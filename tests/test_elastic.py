"""Cluster elasticity (ISSUE 12): slot tombstones + vocab reclamation on
the shrink path, the drain/spot orchestration ladder, targeted node-ADD
queue moves, and the SchedulingElastic workload.

Tier-1 runs the small variants on a FakeClock; the reference-size
SchedulingElastic row is slow-marked."""

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.backend.device_state import DeviceState, caps_for_cluster
from kubernetes_tpu.cache import Snapshot
from kubernetes_tpu.controllers.drain import (
    TAINT_SPOT_RECLAIM,
    TAINT_UNSCHEDULABLE,
    DrainOrchestrator,
)
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.perf import TEST_CASES, run_workload
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock
from kubernetes_tpu.utils.vocab import Vocab


def _bound(store):
    return {p.meta.name: p.spec.node_name
            for p in store.pods.values() if p.spec.node_name}


class TestVocabReclamation:
    def test_release_reuses_id_before_growing(self):
        v = Vocab("t")
        a, b = v.id("a"), v.id("b")
        assert (a, b) == (1, 2)
        assert v.release("a") == 1
        assert v.lookup("a") == 0
        assert v.id("c") == 1  # freed id reused
        assert v.id("d") == 3  # then the table grows
        assert v.live() == 3
        assert v.release("never") is None

    def test_encoder_node_retention_frees_label_values(self):
        from kubernetes_tpu.ops.encode import ClusterEncoder
        from kubernetes_tpu.ops.schema import Capacities

        enc = ClusterEncoder(Capacities(nodes=8, pods=4, value_words=32))
        n0 = make_node("n0").label("zone", "z-only-n0").obj()
        n1 = make_node("n1").label("zone", "z-shared").obj()
        n2 = make_node("n2").label("zone", "z-shared").obj()
        for n in (n0, n1, n2):
            enc.retain_node_values(n.meta.name, n)
            enc.encode_node_row(NodeInfo(n))
        ks = enc.key_vocab.lookup("zone")
        vv = enc.value_vocabs[ks]
        only_id = vv.lookup("z-only-n0")
        assert only_id > 0 and vv.lookup("z-shared") > 0
        # n0 leaves: its unique value frees; the shared one is still pinned
        enc.release_node_values("n0")
        assert vv.lookup("z-only-n0") == 0
        assert vv.lookup("z-shared") > 0
        # one of two sharers leaves: still pinned; the last leaves: freed
        enc.release_node_values("n1")
        assert vv.lookup("z-shared") > 0
        enc.release_node_values("n2")
        assert vv.lookup("z-shared") == 0

    def test_value_free_invalidates_pod_template_cache(self):
        from kubernetes_tpu.ops.encode import ClusterEncoder
        from kubernetes_tpu.ops.schema import Capacities

        enc = ClusterEncoder(Capacities(nodes=8, pods=4, value_words=32))
        node = make_node("n0").label("zone", "zx").obj()
        enc.retain_node_values("n0", node)
        enc.encode_node_row(NodeInfo(node))
        pod = make_pod("p").req({"cpu": "1"}).obj()
        pod.spec.node_selector = {"zone": "zx"}
        enc.encode_pods([pod])
        assert enc._pod_templates  # compiled key set embeds the value id
        enc.release_node_values("n0")  # frees "zx"'s id
        assert not enc._pod_templates, \
            "template cache must clear when a value id is freed"


class TestSlotTombstones:
    def _snap(self, names):
        snap = Snapshot()
        for i, name in enumerate(names):
            snap.node_info_map[name] = NodeInfo(
                make_node(name).capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": 10})
                .label("zone", f"z{i % 2}").obj())
        snap.node_info_list = list(snap.node_info_map.values())
        snap.structure_version += 1
        return snap

    def test_release_generation_guards_inflight_commits(self):
        dev = DeviceState(caps_for_cluster(4))
        dev.sync(self._snap(["a", "b"]))
        slot_a = dev.encoder.node_slots["a"]
        gen0 = dev.encoder.reclaim_gen
        assert not dev.encoder.slot_stale_since(slot_a, gen0)
        dev.sync(self._snap(["b"]))  # a removed: slot tombstoned
        assert dev.encoder.slot_stale_since(slot_a, gen0)
        assert "a" not in dev.encoder.node_slots
        # reuse: the tombstone goes to the newcomer, still stale vs gen0
        dev.sync(self._snap(["b", "c"]))
        assert dev.encoder.node_slots["c"] == slot_a
        assert dev.encoder.slot_reuses == 1
        assert dev.encoder.slot_stale_since(slot_a, gen0)
        assert not dev.encoder.slot_stale_since(slot_a,
                                                dev.encoder.reclaim_gen)

    def test_sustained_churn_capacity_and_vocab_bounded(self):
        """The ISSUE 12 acceptance bound at unit level: remove/add cycling
        2x the initial cluster size leaves row capacity, the hostname value
        vocab, and the node-slot space all at their initial size — and the
        delta path back at zero upload bytes at steady state."""
        n0 = 8
        names = [f"node-{i}" for i in range(n0)]
        dev = DeviceState(caps_for_cluster(n0))
        dev.sync(self._snap(names))
        caps0 = dev.caps.nodes
        ks = dev.encoder.key_vocab.lookup("kubernetes.io/hostname")
        vocab_len0 = (len(dev.encoder.value_vocabs[ks])
                      if ks in dev.encoder.value_vocabs else 0)
        next_i = n0
        for _cycle in range(2 * n0):  # churn 2x the cluster size
            names = names[1:] + [f"node-{next_i}"]
            next_i += 1
            dev.sync(self._snap(names))
        assert dev.caps.nodes == caps0, "row capacity must not grow"
        assert max(dev.encoder.node_slots.values()) < n0, \
            "slots must recycle through the free-list"
        assert dev.encoder.slot_reuses >= 2 * n0
        if ks in dev.encoder.value_vocabs:
            vv = dev.encoder.value_vocabs[ks]
            # hostname ids recycle: live count bounded by the cluster size,
            # table length never exceeds initial + one transient
            assert vv.live() <= n0
            assert len(vv) <= max(vocab_len0, n0 + 2)
        # steady state: an unchanged snapshot uploads zero bytes
        snap = self._snap(names)
        dev.sync(snap)
        dev.sync(snap)
        assert dev.last_upload_bytes == 0

    def _attr_snap(self, gens):
        """Snapshot of nodes publishing UNIQUE string attribute values per
        (name, generation) — the worst case for vocab growth under churn."""
        snap = Snapshot()
        for name, gen in gens:
            snap.node_info_map[name] = NodeInfo(
                make_node(name).capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": 10})
                .device_attrs({"vendor.example/serial": f"sn-{name}-{gen}",
                               "vendor.example/model": f"m-{gen % 3}",
                               "vendor.example/hbm_gb": 16}).obj())
        snap.node_info_list = list(snap.node_info_map.values())
        snap.structure_version += 1
        return snap

    def test_attr_value_vocab_bounded_under_churn(self):
        """ROADMAP item 5 carried follow-up: DRA attribute-value ids were
        append-only — churning 2x the cluster size with fresh per-node
        serial strings grew the vocab (and the int32 id range) without
        bound. With the refcounted free-list, live vocab size and the id
        high-water mark both stay at cluster scale."""
        n0 = 8
        gens = [(f"node-{i}", 0) for i in range(n0)]
        dev = DeviceState(caps_for_cluster(n0))
        dev.sync(self._attr_snap(gens))
        live0 = len(dev.attr_val_ids)
        assert live0 >= n0  # unique serials + shared models
        next_i = n0
        for cycle in range(2 * n0):  # churn 2x the cluster size
            gens = gens[1:] + [(f"node-{next_i}", cycle + 1)]
            next_i += 1
            dev.sync(self._attr_snap(gens))
        # live values bounded by what the LIVE nodes publish
        assert len(dev.attr_val_ids) <= live0 + 3
        # and freed ids were RECYCLED, not burned: the id counter's
        # high-water mark stays at cluster scale instead of growing by
        # one serial per churned node
        assert dev._attr_val_next <= live0 + 4, dev._attr_val_next
        assert max(dev.attr_val_ids.values()) <= live0 + 3
        # refcounts match live publishers exactly (no leak, no double-free)
        serials = {v for v in dev._attr_val_refs if v.startswith("sn-")}
        assert len(serials) == n0

    def test_attr_value_refcount_shared_values(self):
        """A value published by several nodes frees only when the LAST
        publisher leaves; rows re-encode with recycled ids consistently."""
        dev = DeviceState(caps_for_cluster(4))
        snap = Snapshot()
        for name in ("a", "b"):
            snap.node_info_map[name] = NodeInfo(
                make_node(name).capacity({"cpu": "4", "pods": 10})
                .device_attrs({"k": "shared"}).obj())
        snap.node_info_list = list(snap.node_info_map.values())
        snap.structure_version += 1
        dev.sync(snap)
        vid = dev.attr_val_ids["shared"]
        assert dev._attr_val_refs["shared"] == 2
        # one publisher leaves: id stays
        snap2 = Snapshot()
        snap2.node_info_map["a"] = snap.node_info_map["a"]
        snap2.node_info_list = [snap.node_info_map["a"]]
        snap2.structure_version += 1
        dev.sync(snap2)
        assert dev.attr_val_ids["shared"] == vid
        # last publisher leaves: id freed and recycled for the next value
        snap3 = Snapshot()
        snap3.structure_version += 1
        dev.sync(snap3)
        assert "shared" not in dev.attr_val_ids
        assert dev.attr_value_id("fresh") == vid

    def test_tombstoned_row_zeroed_on_device(self):
        dev = DeviceState(caps_for_cluster(4))
        dev.sync(self._snap(["a", "b"]))
        slot_a = dev.encoder.node_slots["a"]
        assert bool(np.asarray(dev.nt.valid)[slot_a])
        dev.sync(self._snap(["b"]))
        assert not bool(np.asarray(dev.nt.valid)[slot_a])
        assert not dev._mirror["valid"][slot_a]


def _cluster(store, n=4, cap="8"):
    for i in range(n):
        store.create_node(make_node(f"n{i}").capacity(
            {"cpu": cap, "memory": "16Gi", "pods": 20}).obj())


class TestDrainOrchestrator:
    def test_cordon_writes_unschedulable_and_taint(self):
        store = ClusterStore()
        _cluster(store, 1)
        d = DrainOrchestrator(store)
        assert d.cordon("n0")
        node = store.nodes["n0"]
        assert node.spec.unschedulable
        assert any(t.key == TAINT_UNSCHEDULABLE and t.effect == "NoSchedule"
                   for t in node.spec.taints)
        assert not d.cordon("n0")  # idempotent
        assert d.uncordon("n0")
        node = store.nodes["n0"]
        assert not node.spec.unschedulable
        assert not any(t.key == TAINT_UNSCHEDULABLE for t in node.spec.taints)

    def test_drain_wave_evicts_whole_gang_atomically(self):
        """A gang member on a draining node drags the WHOLE gang (members
        on healthy nodes included) through the eviction, so the gang
        rebinds as a unit — never a stranded partial quorum."""
        from kubernetes_tpu.api.types import ObjectMeta, PodGroup

        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 4, cap="2")
        sched = Scheduler(store, now_fn=clock)
        store.create_object("PodGroup", PodGroup(
            meta=ObjectMeta(name="g"), min_member=3,
            schedule_timeout_seconds=30))
        for i in range(3):
            store.create_pod(make_pod(f"g-{i}").req({"cpu": "1"})
                             .pod_group("g").obj())
        store.create_pod(make_pod("solo").req({"cpu": "1"}).obj())
        sched.run_until_settled()
        bound = _bound(store)
        assert len(bound) == 4
        gang_nodes = {bound[f"g-{i}"] for i in range(3)}
        assert len(gang_nodes) > 1  # spread over several nodes
        victim_node = bound["g-0"]
        d = DrainOrchestrator(store, metrics=sched.smetrics,
                              queue=sched.queue, now_fn=clock)
        summary = d.drain_wave([victim_node])
        # every gang member evicted (recreated unbound), wherever it was
        for i in range(3):
            p = store.get_pod(f"default/g-{i}")
            assert p is not None and not p.spec.node_name
        # the solo pod is evicted only if it lived on the drained node
        assert summary["gangs"] == 1
        assert sched.smetrics.evicted_pods.labels("drain") >= 3
        # rebind: uncordon and everything lands again, gang whole
        d.uncordon(victim_node)
        clock.advance(11.0)
        sched.run_until_settled()
        bound = _bound(store)
        assert sum(1 for k in bound if k.startswith("g-")) == 3

    def test_spot_reclaim_rides_taint_manager_and_respects_tolerations(self):
        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 2)
        sched = Scheduler(store, now_fn=clock)
        from kubernetes_tpu.api.types import Toleration

        store.create_pod(make_pod("plain").req({"cpu": "1"}).obj())
        shielded = make_pod("shielded").req({"cpu": "1"}).obj()
        shielded.spec.tolerations = (Toleration(
            key=TAINT_SPOT_RECLAIM, operator="Exists",
            effect="NoExecute"),)  # unbounded: survives the reclaim
        store.create_pod(shielded)
        sched.run_until_settled()
        nodes_used = set(_bound(store).values())
        d = DrainOrchestrator(store, metrics=sched.smetrics,
                              queue=sched.queue, now_fn=clock)
        summary = d.spot_reclaim(sorted(store.nodes))
        reclaimed = {n for n in store.nodes
                     if any(t.key == TAINT_SPOT_RECLAIM
                            for t in store.nodes[n].spec.taints)}
        assert reclaimed == set(store.nodes) and nodes_used <= reclaimed
        # the taint manager evicted the non-tolerating pod only
        plain = store.get_pod("default/plain")
        assert plain is not None and not plain.spec.node_name  # recreated
        assert store.get_pod("default/shielded").spec.node_name
        assert summary["evicted"] == 1
        assert sched.smetrics.evicted_pods.labels("spot") == 1
        # the capacity actually vanishes: even the tolerating pod must be
        # evicted (recreated unbound) — a toleration cannot keep a pod on
        # deleted hardware
        d.spot_reclaim(sorted(store.nodes), delete_nodes=True)
        assert not store.nodes
        shielded2 = store.get_pod("default/shielded")
        assert shielded2 is not None and not shielded2.spec.node_name
        assert all(not p.spec.node_name for p in store.pods.values())

    def test_spot_reclaim_defers_to_pdb_budget(self):
        """A PodDisruptionBudget at its budget (disruptionsAllowed == 0)
        DEFERS the spot eviction: the reclaim taint lands, the pod stays,
        and the periodic taint-manager sweep takes it once the disruption
        controller's reconcile shows budget again (ROADMAP item 5
        follow-up, carried from the elastic PR)."""
        import dataclasses

        from kubernetes_tpu.api.types import (
            LabelSelector, ObjectMeta, PodDisruptionBudget)
        from kubernetes_tpu.controllers.nodelifecycle import (
            evict_noexecute_pods)

        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 2)
        sched = Scheduler(store, now_fn=clock)
        store.create_pod(make_pod("guarded").req({"cpu": "1"})
                         .label("app", "db").obj())
        store.create_pod(make_pod("free").req({"cpu": "1"}).obj())
        sched.run_until_settled()
        store.create_pdb(PodDisruptionBudget(
            meta=ObjectMeta(name="db-pdb", namespace="default"),
            selector=LabelSelector(match_labels={"app": "db"}),
            min_available=1, disruptions_allowed=0))  # budget exhausted
        d = DrainOrchestrator(store, metrics=sched.smetrics,
                              queue=sched.queue, now_fn=clock)
        summary = d.spot_reclaim(sorted(store.nodes))
        # the unguarded pod evicted; the PDB-guarded one DEFERRED — still
        # bound, on a node that now carries the reclaim taint
        guarded = store.get_pod("default/guarded")
        assert guarded is not None and guarded.spec.node_name
        free = store.get_pod("default/free")
        assert free is not None and not free.spec.node_name
        assert summary["evicted"] == 1
        node = store.nodes[guarded.spec.node_name]
        assert any(t.key == TAINT_SPOT_RECLAIM for t in node.spec.taints)
        # budget recovers (the disruption controller's reconcile raises
        # disruptionsAllowed): the PERIODIC taint-manager sweep takes the
        # deferred pod through the very same machinery
        pdb = store.pdbs["default/db-pdb"]
        new = dataclasses.replace(pdb, disruptions_allowed=1)
        new.meta = dataclasses.replace(pdb.meta)
        store.update_object("PodDisruptionBudget", new)
        taken = evict_noexecute_pods(
            store, node, clock(), since=None,
            allow_fn=d._pdb_disruption_gate())
        assert [p.meta.name for p in taken] == ["guarded"]

    def test_pdb_gate_charges_budget_within_one_wave(self):
        """One wave can never take more pods from a budget than
        disruptionsAllowed, even before the controller re-reconciles."""
        from kubernetes_tpu.api.types import (
            LabelSelector, ObjectMeta, PodDisruptionBudget)

        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 3)
        sched = Scheduler(store, now_fn=clock)
        for i in range(3):
            store.create_pod(make_pod(f"db-{i}").req({"cpu": "1"})
                             .label("app", "db").obj())
        sched.run_until_settled()
        store.create_pdb(PodDisruptionBudget(
            meta=ObjectMeta(name="db-pdb", namespace="default"),
            selector=LabelSelector(match_labels={"app": "db"}),
            min_available=2, disruptions_allowed=1))
        d = DrainOrchestrator(store, metrics=sched.smetrics,
                              queue=sched.queue, now_fn=clock)
        summary = d.spot_reclaim(sorted(store.nodes))
        still_bound = [p for p in store.pods.values()
                       if p.spec.node_name and p.meta.labels.get("app") == "db"]
        assert len(still_bound) == 2, "wave overdrew the disruption budget"
        assert summary["evicted"] == 1

    def test_nodelifecycle_eviction_uses_shared_taint_manager(self):
        """The unreachable-node path and the spot path are one machinery:
        evict_noexecute_pods judges per actual NoExecute taint, so a
        not-ready-only toleration no longer shields against unreachable."""
        from kubernetes_tpu.api.types import Lease, ObjectMeta, Toleration
        from kubernetes_tpu.client.informer import SharedInformerFactory
        from kubernetes_tpu.controllers.nodelifecycle import (
            NODE_LEASE_NAMESPACE,
            TAINT_UNREACHABLE,
            NodeLifecycleController,
        )
        from kubernetes_tpu.metrics import SchedulerMetrics

        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 1)
        store.create_object("Lease", Lease(
            meta=ObjectMeta(name="n0", namespace=NODE_LEASE_NAMESPACE),
            renew_time=clock()))
        p = make_pod("w").req({"cpu": "1"}).obj()
        p.spec.node_name = "n0"
        store.create_pod(p)
        tol = make_pod("tol").req({"cpu": "1"}).obj()
        tol.spec.node_name = "n0"
        tol.spec.tolerations = (Toleration(
            key=TAINT_UNREACHABLE, operator="Exists", effect="NoExecute"),)
        store.create_pod(tol)
        metrics = SchedulerMetrics()
        ctrl = NodeLifecycleController(
            store, SharedInformerFactory(store), grace_period=40.0,
            now_fn=clock, metrics=metrics)
        clock.advance(60.0)  # lease expires
        ctrl.monitor_node_health()
        node = store.nodes["n0"]
        assert not node.status.ready
        assert any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)
        # admission stamped the 300s DefaultTolerationSeconds pair: both
        # pods ride the toleration window first
        assert store.get_pod("default/w") is not None
        clock.advance(301.0)  # the finite window expires
        ctrl.monitor_node_health()
        assert store.get_pod("default/w") is None  # evicted
        assert store.get_pod("default/tol") is not None  # unbounded: stays
        assert metrics.evicted_pods.labels("taint") == 1


class TestWireRemovalDelta:
    def test_invalidated_then_deleted_node_still_named_in_removed(self):
        """Regression: _invalidate_node pops the node's sent gen (the
        repair idiom); a node DELETED in that window must still be named
        in the next delta's ``removed`` list — previously the removal set
        was computed from _sent_gens, so the service kept a ghost row
        until a full resync."""
        from kubernetes_tpu.backend.service import (
            DeviceService,
            WireScheduler,
            serve,
        )

        service = DeviceService(batch_size=32)
        server, port = serve(service)
        try:
            store = ClusterStore()
            _cluster(store, 2)
            sched = WireScheduler(store,
                                  endpoint=f"http://127.0.0.1:{port}")
            store.create_pod(make_pod("p0").req({"cpu": "1"}).obj())
            sched.run_until_settled()
            assert set(service.infos) == {"n0", "n1"}
            resyncs0 = sched.resyncs
            # the repair idiom fires, then the node leaves
            sched._invalidate_node("n0")
            store.delete_node("n0")
            store.create_pod(make_pod("p1").req({"cpu": "1"}).obj())
            sched.run_until_settled()
            assert "n0" not in service.infos, \
                "removal must ride the delta, not wait for a full resync"
            assert "n0" not in service.device.encoder.node_slots
            assert sched.resyncs == resyncs0
        finally:
            server.shutdown()


class TestNodeAddQueueMove:
    def test_parked_pods_reactivate_when_capacity_arrives(self):
        """ISSUE 12 satellite: a pod parked Unschedulable on resource
        pressure must reactivate on a node ADD (NodeResourcesFit registers
        NODE|ADD) and bind to the new capacity — no unschedulable-timeout
        flush needed."""
        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 1, cap="1")
        sched = Scheduler(store, now_fn=clock, pod_initial_backoff=0.5)
        store.create_pod(make_pod("big").req({"cpu": "4"}).obj())
        sched.run_until_settled()
        pending = sched.queue.pending_pods()
        assert pending["unschedulable"] == 1, pending
        assert sched.smetrics.node_events.labels("add") == 1
        # capacity arrives: the targeted NODE_ADD move reactivates the pod
        store.create_node(make_node("big-node").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
        pending = sched.queue.pending_pods()
        assert pending["unschedulable"] == 0, \
            "NODE_ADD must move the parked pod out of the unschedulable map"
        clock.advance(1.0)  # clear the move's backoff window
        sched.run_until_settled()
        assert _bound(store) == {"big": "big-node"}
        assert sched.smetrics.node_events.labels("add") == 2


class TestSchedulingElasticSmall:
    """The tier-1 variant: tpu backend, FakeClock, 24 nodes — storms,
    drain waves, and spot reclamations rotating over the batched pipeline
    with ring depth 2."""

    def _run(self, **kw):
        tc = TEST_CASES["SchedulingElastic"](
            nodes=24, rounds=6, pods_per_round=12, drain_nodes=3,
            cycles_per_round=40, tick_s=0.05, **kw)
        return run_workload(tc, backend="tpu", now_fn=FakeClock())

    def test_invariants_under_chaos_ladder(self):
        items = self._run()
        (inv,) = [it.data for it in items
                  if it.labels.get("Name") == "ElasticInvariants"]
        assert inv["LostPods"] == 0.0
        assert inv["Oversubscribed"] == 0.0
        assert inv["PendingAtEnd"] == 0.0
        # the shrink direction engaged: nodes removed, rows tombstoned and
        # REUSED (capacity bounded at the initial bucket), evictions rode
        # the drain/spot machinery, and the delta path returned to zero
        assert inv["NodesRemoved"] > 0 and inv["NodesAdded"] > 0
        assert inv["SlotReuses"] > 0
        assert inv["EvictedPods"] > 0
        assert inv["RowCapacity"] == float(caps_for_cluster(24).nodes), \
            "sustained churn must not grow the node axis"
        assert inv["UploadBytesSteady"] == 0.0, \
            "delta elision must recover after the storms"


@pytest.mark.slow
class TestSchedulingElasticLarge:
    def test_reference_size_elastic(self):
        """The reference-size row (kept out of tier-1: slow): 1000 nodes,
        six rounds of storm/drain/spot over the batched pipeline."""
        tc = TEST_CASES["SchedulingElastic"]()
        items = run_workload(tc, backend="tpu")
        (inv,) = [it.data for it in items
                  if it.labels.get("Name") == "ElasticInvariants"]
        assert inv["LostPods"] == 0.0
        assert inv["Oversubscribed"] == 0.0
        assert inv["SlotReuses"] > 0
        assert inv["UploadBytesSteady"] == 0.0
        tput = [it for it in items
                if it.labels.get("Name") == "SchedulingElastic"]
        assert tput and tput[0].data["Average"] > 0
