"""SLO regression fence (ISSUE 7 tentpole piece 4): tools/trend.py's
declared-tolerance comparison of a bench record against the prior
BENCH_r*/TREND history, and the `bench.py --fence` gate wired over it —
exits nonzero on a tolerance-violating regression, 0 when the fence holds.
Pure-host logic: no jax, no cluster."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _trend():
    spec = importlib.util.spec_from_file_location(
        "trend", os.path.join(REPO, "tools", "trend.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _record(value=500.0, p99=1.0, workloads=None, platform="cpu-fallback",
            rnd=None):
    rec = {
        "value": value,
        "platform": platform,
        "attempt_latency_s": {"p50": 0.1, "p90": 0.5, "p99": p99},
        "workloads": workloads or {},
    }
    if rnd is not None:
        rec["_round"] = rnd
    return rec


class TestFenceLogic:
    def test_holds_when_current_matches_baseline(self):
        t = _trend()
        base = _record(500.0, 1.0, {"W": {"pods_per_s": 100.0,
                                          "attempt_p99_s": 0.5}}, rnd=7)
        out = t.fence(_record(495.0, 1.02, {"W": {"pods_per_s": 99.0,
                                                  "attempt_p99_s": 0.51}}),
                      [base])
        assert out["baselineRound"] == 7
        assert out["violations"] == []
        assert out["checked"] == 4

    def test_flags_headline_throughput_regression(self):
        t = _trend()
        out = t.fence(_record(value=200.0), [_record(value=500.0, rnd=7)])
        assert any("headline pods/s" in v for v in out["violations"])

    def test_flags_p99_and_workload_regressions(self):
        t = _trend()
        base = _record(500.0, 1.0, {"W": {"pods_per_s": 100.0,
                                          "attempt_p99_s": 0.5}}, rnd=7)
        cur = _record(500.0, 3.0, {"W": {"pods_per_s": 20.0,
                                         "attempt_p99_s": 0.5}})
        out = t.fence(cur, [base])
        kinds = "\n".join(out["violations"])
        assert "headline attempt p99" in kinds
        assert "workload W pods/s" in kinds

    def test_volatile_workload_gets_its_declared_override(self):
        t = _trend()
        # -60%: beyond the default 40% workload tolerance, inside
        # PreemptionBasic's declared 85% (its history swung 2953->69->243)
        wl_base = {"PreemptionBasic": {"pods_per_s": 1000.0},
                   "Steady": {"pods_per_s": 1000.0}}
        wl_cur = {"PreemptionBasic": {"pods_per_s": 400.0},
                  "Steady": {"pods_per_s": 400.0}}
        out = t.fence(_record(workloads=wl_cur),
                      [_record(workloads=wl_base, rnd=7)])
        assert any("Steady" in v for v in out["violations"])
        assert not any("PreemptionBasic" in v for v in out["violations"])

    def test_errored_and_skipped_rows_are_not_judged(self):
        t = _trend()
        wl_base = {"W": {"pods_per_s": 1000.0},
                   "X": {"skipped": "budget"}}
        wl_cur = {"W": {"error": "timeout"},
                  "X": {"pods_per_s": 1.0}}
        out = t.fence(_record(workloads=wl_cur),
                      [_record(workloads=wl_base, rnd=7)])
        assert not any("workload" in v for v in out["violations"])

    def test_cross_platform_rounds_are_not_a_baseline(self):
        t = _trend()
        out = t.fence(_record(value=10.0, platform="cpu-fallback"),
                      [_record(value=5000.0, platform="tpu", rnd=7)])
        assert out["baselineRound"] is None
        assert out["violations"] == []

    def test_invalid_rounds_excluded_from_baseline(self):
        t = _trend()
        bad_round = sorted(t._INVALID_ROUNDS)[0]
        out = t.fence(_record(value=10.0),
                      [_record(value=5000.0, rnd=bad_round)])
        assert out["baselineRound"] is None

    def test_epoch_boundary_excludes_pre_epoch_baselines(self):
        """A declared PLATFORM_EPOCHS boundary: rounds from the previous
        environment class are not baselines, and the boundary round's
        missing baseline is reported as the documented epoch state (the
        CLI passes on it instead of failing closed)."""
        t = _trend()
        epoch = max(t.PLATFORM_EPOCHS)
        out = t.fence(_record(value=10.0, rnd=epoch),
                      [_record(value=5000.0, rnd=epoch - 2)])
        assert out["baselineRound"] is None
        assert out["violations"] == []
        assert out["epochBoundary"] == t.PLATFORM_EPOCHS[epoch]

    def test_same_epoch_rounds_still_judged(self):
        """Within one epoch the fence bites normally — and prefers the
        newest same-epoch baseline while ignoring pre-epoch rounds."""
        t = _trend()
        epoch = max(t.PLATFORM_EPOCHS)
        out = t.fence(
            _record(value=10.0, rnd=epoch + 2),
            [_record(value=5000.0, rnd=epoch - 2),   # pre-epoch: ignored
             _record(value=100.0, rnd=epoch)])       # same epoch: baseline
        assert out["baselineRound"] == epoch
        assert any("headline pods/s" in v for v in out["violations"])

    def test_fresh_record_belongs_to_the_newest_epoch(self):
        """A record with no round number (an in-flight `--record` run) is
        measured on the current environment, so pre-epoch rounds are not
        its baseline either."""
        t = _trend()
        epoch = max(t.PLATFORM_EPOCHS)
        out = t.fence(_record(value=10.0),
                      [_record(value=5000.0, rnd=epoch - 2)])
        assert out["baselineRound"] is None
        assert out["epochBoundary"] == t.PLATFORM_EPOCHS[epoch]

    def test_repo_history_self_fence_holds(self):
        """The committed rounds pass their own fence (the gate starts
        green): the newest valid round judged against its priors."""
        t = _trend()
        rounds = t._load_rounds()
        valid = [r for r in rounds if r["_round"] not in t._INVALID_ROUNDS]
        if len(valid) < 2:
            pytest.skip("fewer than two valid committed rounds")
        out = t.fence(valid[-1], rounds[:-1])
        assert out["violations"] == [], out


class TestBenchFenceCli:
    def _run(self, args, env=None):
        e = dict(os.environ)
        e.pop("BENCH_FENCE_RECORD", None)
        e.update(env or {})
        return subprocess.run([sys.executable, BENCH, *args],
                              capture_output=True, text=True, timeout=120,
                              cwd=REPO, env=e)

    def test_fence_passes_on_healthy_record(self, tmp_path):
        t = _trend()
        rounds = t._load_rounds()
        valid = [r for r in rounds if r["_round"] not in t._INVALID_ROUNDS]
        if not valid:
            pytest.skip("no valid committed rounds")
        # a record as good as the best prior can never violate
        best = dict(valid[-1])
        best.pop("_round", None)
        path = tmp_path / "rec.json"
        path.write_text(json.dumps(best))
        p = self._run(["--fence", str(path)])
        assert p.returncode == 0, p.stdout + p.stderr
        doc = json.loads(p.stdout.strip().splitlines()[-1])
        assert doc["metric"] == "slo_fence"
        assert doc["violations"] == 0

    def test_fence_fails_on_regressing_record(self, tmp_path):
        t = _trend()
        rounds = t._load_rounds()
        valid = [r for r in rounds if r["_round"] not in t._INVALID_ROUNDS]
        if not valid:
            pytest.skip("no valid committed rounds")
        bad = dict(valid[-1])
        bad.pop("_round", None)
        bad["value"] = (bad.get("value") or 100.0) / 100.0  # -99%
        path = tmp_path / "rec.json"
        path.write_text(json.dumps(bad))
        p = self._run(["--fence", str(path)])
        assert p.returncode == 1, p.stdout + p.stderr
        doc = json.loads(p.stdout.strip().splitlines()[-1])
        assert doc["violations"] >= 1
        assert any("headline pods/s" in v
                   for v in doc["fence"]["violations"])

    def test_fence_without_record_judges_newest_snapshot(self):
        """Bare --fence judges the newest round on disk — and FAILS CLOSED
        (rc 2) when that round is unjudgeable instead of silently judging
        an older one (the r05 parsed:null failure mode: the gate must not
        go green on the very run it cannot see)."""
        import glob as _glob
        import re as _re
        t = _trend()
        rounds = t._load_rounds()
        on_disk = max((int(m.group(1)) for p in
                       _glob.glob(os.path.join(REPO, "BENCH_r*.json"))
                       if (m := _re.search(r"BENCH_r(\d+)\.json$", p))),
                      default=None)
        p = self._run(["--fence"])
        doc = json.loads(p.stdout.strip().splitlines()[-1])
        assert doc["metric"] == "slo_fence"
        if rounds and on_disk == rounds[-1]["_round"]:
            # newest snapshot is judgeable: the committed history holds
            assert p.returncode == 0, p.stdout
        else:
            # newest snapshot dropped by _load_rounds (unrecoverable
            # parsed:null): refusal, not a green pass on stale evidence
            assert p.returncode == 2, p.stdout
            assert "unjudgeable" in doc.get("error", ""), doc

    def test_fence_unreadable_record_is_a_distinct_failure(self, tmp_path):
        p = self._run(["--fence", str(tmp_path / "missing.json")])
        assert p.returncode == 2
        doc = json.loads(p.stdout.strip().splitlines()[-1])
        assert "unreadable" in doc["error"]

    def test_fence_refuses_unjudgeable_parsed_null_wrapper(self, tmp_path):
        """A parsed:null wrapper (the r05 shape) must FAIL the gate with a
        distinct code, never sail through with zero checks performed."""
        path = tmp_path / "rec.json"
        path.write_text(json.dumps({"parsed": None, "rc": 0, "tail": "x"}))
        p = self._run(["--fence", str(path)])
        assert p.returncode == 2, p.stdout + p.stderr
        doc = json.loads(p.stdout.strip().splitlines()[-1])
        assert "no judgeable fields" in doc["error"]

    def test_fence_path_recovers_parsed_null_tail(self, tmp_path):
        """Fencing a parsed:null wrapper BY NAME recovers the record from
        its stdout tail exactly like the no-arg mode's loader — the CI
        recipe must not fail on the very rounds the recovery was built
        for."""
        t = _trend()
        rounds = t._load_rounds()
        valid = [r for r in rounds if r["_round"] not in t._INVALID_ROUNDS]
        if not valid:
            pytest.skip("no valid committed rounds")
        rec = {k: v for k, v in valid[-1].items() if k != "_round"}
        wrapper = {"parsed": None, "rc": 0,
                   "tail": "bench noise line\n" + json.dumps(rec) + "\n"}
        path = tmp_path / "rec.json"
        path.write_text(json.dumps(wrapper))
        p = self._run(["--fence", str(path)])
        assert p.returncode == 0, p.stdout + p.stderr
        doc = json.loads(p.stdout.strip().splitlines()[-1])
        assert doc["violations"] == 0
        assert doc["fence"]["checked"] > 0

    def test_fence_refuses_zero_comparisons(self, tmp_path):
        """checked==0 (e.g. no same-platform baseline) is a refusal (rc 2),
        not a green pass — the gate must never exit 0 having judged
        nothing."""
        path = tmp_path / "rec.json"
        path.write_text(json.dumps({"value": 1.0, "platform": "tpu-v9"}))
        p = self._run(["--fence", str(path)])
        assert p.returncode == 2, p.stdout + p.stderr
        doc = json.loads(p.stdout.strip().splitlines()[-1])
        assert "no comparison performed" in doc["error"]
        assert doc["fence"]["checked"] == 0

    def test_fence_path_naming_a_round_never_self_compares(self, tmp_path):
        """CI fencing the file --record just wrote: a path named
        BENCH_rN.json drops round N from the baseline pool, so the record
        is judged against its PRIORS, not against itself."""
        t = _trend()
        rounds = t._load_rounds()
        valid = [r for r in rounds if r["_round"] not in t._INVALID_ROUNDS]
        if len(valid) < 2:
            pytest.skip("fewer than two valid committed rounds")
        newest = valid[-1]
        if not any(r["_round"] >= t._epoch_start(newest["_round"])
                   for r in valid[:-1]):
            pytest.skip("newest round is a platform-epoch boundary: no "
                        "prior baseline exists to self-compare against")
        # regress the newest round 99% and hand it over under its own name:
        # without self-exclusion the fence would compare it to itself and
        # pass
        bad = {k: v for k, v in newest.items() if k != "_round"}
        bad["value"] = (bad.get("value") or 100.0) / 100.0
        path = tmp_path / f"BENCH_r{newest['_round']:02d}.json"
        path.write_text(json.dumps(bad))
        p = self._run(["--fence", str(path)])
        assert p.returncode == 1, p.stdout + p.stderr
        doc = json.loads(p.stdout.strip().splitlines()[-1])
        assert doc["fence"]["baselineRound"] != newest["_round"]
