"""SchedulingSoak — the multi-tenant production soak (ISSUE 8 tentpole e).

Tier-1 runs the small variant on a FakeClock and asserts the acceptance
SLOs: zero quota oversubscription at every sampled instant, each tenant's
admitted share within 20% of its quota-weighted fair share, and a flooding
tenant unable to push a calm tenant's p99 queue wait above 2x its solo
baseline. The reference-size variant (gangs + claims + preemption + device
flap on the batched path, oracle<->tpu parity) is slow-marked.
"""

import pytest

from kubernetes_tpu.perf import TEST_CASES, run_workload
from kubernetes_tpu.perf.harness import Runner
from kubernetes_tpu.utils.clock import FakeClock


def _items_by_name(items, name):
    return [it for it in items if it.labels.get("Name") == name]


def _invariants(items):
    (inv,) = _items_by_name(items, "SoakInvariants")
    return inv.data


def _tenant_map(items):
    return {it.labels["namespace"]: it.data
            for it in _items_by_name(items, "SoakTenant")}


def _assert_fair_shares(tenants, tol=0.2):
    """Each tenant's admitted share within ``tol`` (relative) of its
    quota-weighted fair share — the ISSUE 8 fairness bound."""
    total = sum(t["Admitted"] for t in tenants.values())
    total_w = sum(t["Weight"] for t in tenants.values())
    assert total > 0 and total_w > 0
    for ns, t in tenants.items():
        fair = t["Weight"] / total_w
        share = t["Admitted"] / total
        # +2/total: integer-granularity slack for the tiny tier-1 variant
        assert abs(share - fair) <= tol * fair + 2 / total, (
            f"{ns}: admitted share {share:.3f} deviates more than "
            f"{tol:.0%} from quota-weighted fair share {fair:.3f}")


class TestSchedulingSoakSmall:
    """The tier-1 variant: oracle backend, FakeClock, 32 nodes."""

    def _run(self, **kw):
        tc = TEST_CASES["SchedulingSoak"](
            nodes=32, rounds=4, scale=6, cycles_per_round=80,
            flap=False, tick_s=0.05, **kw)
        return run_workload(tc, backend="oracle", now_fn=FakeClock())

    def test_zero_oversubscription_and_fairness(self):
        items = self._run()
        inv = _invariants(items)
        # sampled after every cycle and every churn wave: the ledger never
        # exceeded any tenant's hard cap on any dimension, at any instant
        assert inv["OversubscriptionViolations"] == 0.0
        # sustained over-cap arrivals: the gate parked a backlog
        assert inv["GatedAtEnd"] > 0
        tenants = _tenant_map(items)
        assert set(tenants) == {"soak-a", "soak-b", "soak-c"}
        _assert_fair_shares(tenants)

    def test_attempt_latency_slo(self):
        """p99 scheduling-attempt latency SLO over the whole soak (the
        wall-clock histogram, not the FakeClock): the small oracle variant
        must stay under 1s even on a starved CI box."""
        items = self._run()
        atts = [it for it in _items_by_name(
                    items, "scheduling_attempt_duration_seconds")
                if it.labels.get("result") == "scheduled"]
        assert atts, "no scheduled-attempt latency item"
        assert all(it.data["Perc99"] < 1.0 for it in atts)

    def test_flooding_tenant_p99_bound(self):
        """A 10x-flooding tenant cannot push the calm tenant's p99 e2e
        above 2x its solo baseline (deterministic on the FakeClock: every
        cycle ticks 0.05s, so waits count scheduling cycles). The SLO is
        judged from ``scheduler_tenant_e2e_duration_seconds`` read off the
        REGISTRY (the latency ledger's per-tenant histogram — what a real
        alert would scrape from /metrics; closes the ROADMAP item-4 SLO
        fragment); the harness-internal wait accounting stays only as a
        cross-check."""

        def soak(mix):
            clock = FakeClock()
            r = Runner(backend="oracle", now_fn=clock)
            try:
                r.create_nodes(count=32, zones=4)
                r.create_quota(namespace="calm",
                               hard={"pods": 10 ** 6}, weight=2)
                r.create_quota(namespace="flood",
                               hard={"pods": 10 ** 6}, weight=1)
                r.soak_phase(rounds=4, mix=mix, cycles_per_round=80,
                             tick_s=0.05)
                # the registry is the source of truth for the SLO numbers:
                # re-derive the tenant p99 straight off the histogram too,
                # proving the DataItem is a faithful scrape
                hist = r.scheduler.smetrics.registry.get(
                    "scheduler_tenant_e2e_duration_seconds")
                reg_p99 = {ns: hist.percentile(0.99, ns)
                           for (ns,) in hist.label_sets()}
                return _tenant_map(r.data_items), reg_p99
            finally:
                r.close()

        calm = {"namespace": "calm", "count": 10,
                "req": {"cpu": "100m", "memory": "500Mi"}}
        solo, solo_reg = soak([calm])
        flooded, flooded_reg = soak(
            [calm, {"namespace": "flood", "count": 100,
                    "req": {"cpu": "100m", "memory": "500Mi"}}])
        # the SLO bound, judged from the registry metric
        solo_p99 = solo_reg["calm"]
        assert solo_p99 > 0
        assert solo["calm"]["E2eCount"] == solo["calm"]["Admitted"] > 0
        assert flooded["calm"]["Admitted"] == solo["calm"]["Admitted"]
        assert flooded_reg["calm"] <= 2.0 * solo_p99, (
            f'flooded e2e p99 {flooded_reg["calm"]} vs solo {solo_p99}')
        # harness-internal accounting kept as the cross-check: the ledger's
        # registry p99 and the created_at->bound wait p99 must agree on the
        # shared FakeClock (bucket interpolation gives the histogram slack)
        assert flooded["calm"]["WaitP99"] <= 2.0 * solo["calm"]["WaitP99"]
        assert flooded["calm"]["E2eP99"] == flooded_reg["calm"]


class TestSchedulingSoakTPU:
    """The batched path in tier-1: same small shape plus the scripted
    device flap and the cycle-sampled oracle comparer."""

    def test_flap_degrades_and_heals_with_parity(self):
        tc = TEST_CASES["SchedulingSoak"](
            nodes=32, rounds=4, scale=6, cycles_per_round=40, tick_s=0.05)
        items = run_workload(tc, backend="tpu", now_fn=FakeClock(),
                             comparer_every_n=2)
        inv = _invariants(items)
        assert inv["OversubscriptionViolations"] == 0.0
        # the flap fired and was consumed through the real relay-death path
        assert inv["FlapBatches"] > 0
        assert inv["DegradedSeconds"] > 0
        # the soak survived it: tenants kept being admitted, fairly
        tenants = _tenant_map(items)
        assert sum(t["Admitted"] for t in tenants.values()) > 0
        _assert_fair_shares(tenants)
        # oracle<->tpu placement parity maintained across the whole soak
        assert inv["ComparerChecks"] > 0
        assert inv["ComparerMismatches"] == 0.0


@pytest.mark.slow
class TestSchedulingSoakLarge:
    def test_reference_size_mixed_soak(self):
        """The reference-size row (kept out of tier-1: slow): 1000 nodes,
        gangs + DRA claims + preemptors + one scripted device flap on the
        tpu backend, oracle<->tpu parity sampled throughout."""
        tc = TEST_CASES["SchedulingSoak"]()
        items = run_workload(tc, backend="tpu", comparer_every_n=8)
        inv = _invariants(items)
        assert inv["OversubscriptionViolations"] == 0.0
        assert inv["FlapBatches"] > 0
        assert inv["ComparerChecks"] > 0
        assert inv["ComparerMismatches"] == 0.0
        _assert_fair_shares(_tenant_map(items))
        tput = _items_by_name(items, "SchedulingSoak")
        assert tput and tput[0].data["Average"] > 0
