"""Test config: force an 8-device virtual CPU mesh BEFORE jax initializes.

Multi-chip TPU hardware isn't available in CI; sharding tests run on
xla_force_host_platform_device_count=8 per the driver's dryrun contract.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
