"""Test config: force an 8-device virtual CPU mesh BEFORE jax initializes.

Multi-chip TPU hardware isn't available in CI; sharding tests run on
xla_force_host_platform_device_count=8 per the driver's dryrun contract.
"""

import os

# Unconditional: the ambient environment points JAX_PLATFORMS at the real TPU
# (axon), but the test contract is an 8-device virtual CPU mesh.
_platform = os.environ.get("KTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# A pytest plugin may have imported jax already (baking the ambient env into
# jax.config); override programmatically — the backend itself initializes
# lazily on first use, which is after conftest.
try:
    import jax

    jax.config.update("jax_platforms", _platform)
except Exception:  # noqa: BLE001 — jax absent: nothing to force
    pass
