"""Oracle ↔ device-kernel parity for the two hard plugins:
PodTopologySpread and InterPodAffinity (ops/topology.py sig-count kernels).

Batch-of-1 calls isolate the kernels from intra-batch commit effects; the
oracle plugins (pinned to reference semantics by tests/test_oracle_plugins.py)
are ground truth. Intra-batch sequential semantics are covered by the e2e
tests at the bottom (mutually-anti-affine pods, strict spread in one batch).
"""

import dataclasses
import random

import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.api.types import LabelSelector, SCHEDULE_ANYWAY
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.backend.sig_table import SigTable
from kubernetes_tpu.framework.interface import CycleState, NodeScore
from kubernetes_tpu.framework.plugins.interpodaffinity import InterPodAffinity
from kubernetes_tpu.framework.plugins.podtopologyspread import PodTopologySpread
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.ops import filters, topology
from kubernetes_tpu.ops.encode import ClusterEncoder
from kubernetes_tpu.ops.schema import Capacities, TopoBatch

ZONES = ["z0", "z1", "z2"]
RACKS = ["r0", "r1", "r2", "r3"]
APPS = ["web", "db", "cache"]

CAPS = Capacities(nodes=16, pods=4, value_words=32, sigs=32, ex_terms=32,
                  spread_cons=2, ipa_terms=2, ipa_pref=2, label_keys=16)


def sel(app):
    return LabelSelector(match_labels={"app": app})


def random_cluster(rng, n_nodes=16):
    infos = []
    for i in range(n_nodes):
        nw = (
            make_node(f"node-{i}")
            .capacity({"cpu": "64", "memory": "256Gi", "pods": 110})
            .label("zone", rng.choice(ZONES))
        )
        if rng.random() < 0.8:
            nw.label("rack", rng.choice(RACKS))
        ni = NodeInfo(nw.obj())
        for j in range(rng.randint(0, 3)):
            pw = make_pod(f"ex-{i}-{j}").req({"cpu": "100m"}).label("app", rng.choice(APPS))
            r = rng.random()
            if r < 0.25:
                pw.pod_affinity(rng.choice(["zone", "rack"]), sel(rng.choice(APPS)), anti=True)
            elif r < 0.4:
                pw.pod_affinity(rng.choice(["zone", "rack"]), sel(rng.choice(APPS)))
            elif r < 0.5:
                pw.preferred_pod_affinity(rng.randint(1, 50), "zone", sel(rng.choice(APPS)),
                                          anti=rng.random() < 0.5)
            ni.add_pod(pw.obj())
        infos.append(ni)
    return infos


def random_topo_pod(rng, i):
    pw = make_pod(f"pending-{i}").req({"cpu": "100m"}).label("app", rng.choice(APPS))
    r = rng.random()
    if r < 0.35:
        pw.spread_constraint(rng.randint(1, 2), rng.choice(["zone", "rack"]),
                             selector=sel(rng.choice(APPS)))
        if rng.random() < 0.5:
            pw.spread_constraint(rng.randint(1, 3), "zone",
                                 when_unsatisfiable=SCHEDULE_ANYWAY,
                                 selector=sel(rng.choice(APPS)))
    elif r < 0.55:
        pw.pod_affinity(rng.choice(["zone", "rack"]), sel(rng.choice(APPS)))
    elif r < 0.75:
        pw.pod_affinity(rng.choice(["zone", "rack"]), sel(rng.choice(APPS)), anti=True)
    if rng.random() < 0.4:
        pw.preferred_pod_affinity(rng.randint(1, 50), rng.choice(["zone", "rack"]),
                                  sel(rng.choice(APPS)), anti=rng.random() < 0.5)
    return pw.obj()


def encode(infos, pod):
    enc = ClusterEncoder(CAPS)
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    for ni in infos:
        sig.recount_node(enc.node_slots[ni.node.meta.name], ni)
    pb, et = enc.encode_pods([pod])
    tb = sig.encode_topo([pod])
    tc = sig.topo_counts()
    return enc, sig, nt, pb, et, tc, tb


def tb_row(tb: TopoBatch, p=0):
    return {f.name: jnp.asarray(getattr(tb, f.name))[p] for f in dataclasses.fields(TopoBatch)}


def oracle_filter_masks(infos, pod):
    spread = PodTopologySpread(snapshot_fn=lambda: infos)
    ipa = InterPodAffinity(snapshot_fn=lambda: infos, ns_labels_fn=lambda ns: {})
    st_s, st_i = CycleState(), CycleState()
    spread.pre_filter(st_s, pod)
    ipa.pre_filter(st_i, pod)
    m_spread = [spread.filter(st_s, pod, ni).is_success() for ni in infos]
    m_ipa = [ipa.filter(st_i, pod, ni).is_success() for ni in infos]
    return m_spread, m_ipa, (spread, st_s), (ipa, st_i)


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5])
def test_topology_filter_parity(seed):
    rng = random.Random(seed)
    infos = random_cluster(rng)
    for i in range(6):
        pod = random_topo_pod(rng, i)
        enc, sig, nt, pb, et, tc, tb = encode(infos, pod)
        vd = CAPS.value_words * 32
        affinity_ok = np.asarray(filters.filter_node_affinity(pb, et, nt))[0]
        ts = topology.make_static(tc.term_counts, tc.term_key, nt.label_val, nt.valid, vd)
        xs = tb_row(tb)
        k_spread = np.asarray(topology.spread_filter(
            xs, tc.sel_counts, nt.label_val, nt.valid, jnp.asarray(affinity_ok), vd, None))
        aff_ok, anti_ok, exist_ok, _ = topology.ipa_filter(
            xs, tc.sel_counts, ts.seg_exist0, ts.dom_t, nt.label_val, nt.valid, vd, None)
        k_ipa = np.asarray(aff_ok & anti_ok & exist_ok)

        m_spread, m_ipa, _, _ = oracle_filter_masks(infos, pod)
        for ni, want_s, want_i in zip(infos, m_spread, m_ipa):
            slot = enc.node_slots[ni.node.meta.name]
            assert k_spread[slot] == want_s, (seed, i, ni.node.meta.name, "spread", pod.meta.name)
            assert k_ipa[slot] == want_i, (seed, i, ni.node.meta.name, "ipa", pod.meta.name)


@pytest.mark.parametrize("seed", [10, 11, 12, 13])
def test_topology_score_parity(seed):
    rng = random.Random(seed)
    infos = random_cluster(rng)
    for i in range(6):
        pod = random_topo_pod(rng, i)
        enc, sig, nt, pb, et, tc, tb = encode(infos, pod)
        vd = CAPS.value_words * 32
        affinity_ok = jnp.asarray(np.asarray(filters.filter_node_affinity(pb, et, nt))[0])
        ts = topology.make_static(tc.term_counts, tc.term_key, nt.label_val, nt.valid, vd)
        xs = tb_row(tb)

        # feasible = nodes passing both oracle topology filters (capacity ample)
        m_spread, m_ipa, (spread, st_s), (ipa, st_i) = oracle_filter_masks(infos, pod)
        feasible = np.zeros(CAPS.nodes, bool)
        slot_of = {ni.node.meta.name: enc.node_slots[ni.node.meta.name] for ni in infos}
        for ni, fs, fi in zip(infos, m_spread, m_ipa):
            feasible[slot_of[ni.node.meta.name]] = fs and fi
        feas = [ni for ni in infos if feasible[slot_of[ni.node.meta.name]]]
        if not feas:
            continue

        k_spread = np.asarray(topology.spread_score(
            xs, tc.sel_counts, nt.label_val, nt.valid, affinity_ok, jnp.asarray(feasible), vd, None))
        _, _, _, exist_at = topology.ipa_filter(
            xs, tc.sel_counts, ts.seg_exist0, ts.dom_t, nt.label_val, nt.valid, vd, None)
        k_ipa = np.asarray(topology.ipa_score(
            xs, tc.sel_counts, exist_at, nt.label_val, nt.valid, jnp.asarray(feasible), vd, None))

        # oracle scores over the feasible set
        spread.pre_score(st_s, pod, [ni.node for ni in feas])
        scores = []
        for ni in feas:
            s, _ = spread.score_node(st_s, pod, ni)
            scores.append(NodeScore(ni.node.meta.name, s))
        spread.normalize_score(st_s, pod, scores)
        for sc in scores:
            assert abs(k_spread[slot_of[sc.name]] - sc.score) <= 1, (
                seed, i, sc.name, "spread", k_spread[slot_of[sc.name]], sc.score)

        ipa.pre_score(st_i, pod, [ni.node for ni in feas])
        scores = []
        for ni in feas:
            s, _ = ipa.score_node(st_i, pod, ni)
            scores.append(NodeScore(ni.node.meta.name, s))
        ipa.normalize_score(st_i, pod, scores)
        for sc in scores:
            assert abs(k_ipa[slot_of[sc.name]] - sc.score) <= 1, (
                seed, i, sc.name, "ipa", k_ipa[slot_of[sc.name]], sc.score)


# --------------------------------------------------------------------- e2e


def mk_cluster(n_nodes, zones=4):
    from kubernetes_tpu.apiserver import ClusterStore
    from kubernetes_tpu.backend import TPUScheduler

    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=16)
    for i in range(n_nodes):
        store.create_node(
            make_node(f"node-{i}").capacity({"cpu": "16", "memory": "64Gi", "pods": 110})
            .label("zone", f"z{i % zones}").obj())
    return store, sched


def bound(store):
    return {k: p.spec.node_name for k, p in store.pods.items() if p.spec.node_name}


def test_intra_batch_strict_spread():
    """4 DoNotSchedule maxSkew=1 pods in ONE batch must land in 4 distinct
    zones — the in-scan count commits make the batch sequential-equivalent."""
    store, sched = mk_cluster(8, zones=4)
    s = sel("web")
    for i in range(4):
        store.create_pod(make_pod(f"w{i}").label("app", "web").req({"cpu": "1"})
                         .spread_constraint(1, "zone", selector=s).obj())
    sched.run_until_settled()
    b = bound(store)
    assert len(b) == 4
    zones = [store.nodes[n].meta.labels["zone"] for n in b.values()]
    assert sorted(zones) == ["z0", "z1", "z2", "z3"]
    assert sched.fallback_scheduled == 0


def test_intra_batch_anti_affinity():
    """Mutually anti-affine pods in one batch: one per zone, rest unschedulable."""
    store, sched = mk_cluster(8, zones=2)
    s = sel("db")
    for i in range(4):
        store.create_pod(make_pod(f"d{i}").label("app", "db").req({"cpu": "1"})
                         .pod_affinity("zone", s, anti=True).obj())
    sched.run_until_settled()
    b = bound(store)
    assert len(b) == 2
    zones = {store.nodes[n].meta.labels["zone"] for n in b.values()}
    assert zones == {"z0", "z1"}


def test_required_affinity_colocates():
    """Affinity pods follow the seed pod's zone; first-pod case admits the seed."""
    store, sched = mk_cluster(6, zones=3)
    s = sel("cache")
    pods = [make_pod(f"c{i}").label("app", "cache").req({"cpu": "1"})
            .pod_affinity("zone", s).obj() for i in range(3)]
    for p in pods:
        store.create_pod(p)
    sched.run_until_settled()
    b = bound(store)
    assert len(b) == 3
    zones = {store.nodes[n].meta.labels["zone"] for n in b.values()}
    assert len(zones) == 1  # all co-located via self-affinity


def test_first_pod_rule_ignores_keyless_nodes():
    """Matching pods that live only on nodes WITHOUT the term's topology key
    must not defeat the first-pod-in-cluster rule (the oracle never counts
    them — interpodaffinity.py pre_filter skips keyless nodes)."""
    infos = []
    # keyless node hosting a matching pod
    ni = NodeInfo(make_node("keyless").capacity({"cpu": "8", "memory": "16Gi", "pods": 10}).obj())
    ni.add_pod(make_pod("ex").label("app", "cache").req({"cpu": "100m"}).obj())
    infos.append(ni)
    # keyed empty nodes
    for i in range(3):
        infos.append(NodeInfo(
            make_node(f"keyed-{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 10})
            .label("rack", f"r{i}").obj()))
    pod = (make_pod("inc").label("app", "cache").req({"cpu": "1"})
           .pod_affinity("rack", sel("cache")).obj())
    enc, sig, nt, pb, et, tc, tb = encode(infos, pod)
    vd = CAPS.value_words * 32
    ts = topology.make_static(tc.term_counts, tc.term_key, nt.label_val, nt.valid, vd)
    aff_ok, anti_ok, exist_ok, _ = topology.ipa_filter(
        tb_row(tb), tc.sel_counts, ts.seg_exist0, ts.dom_t, nt.label_val, nt.valid, vd, None)
    k_ipa = np.asarray(aff_ok & anti_ok & exist_ok)
    m_spread, m_ipa, _, _ = oracle_filter_masks(infos, pod)
    for ni, want in zip(infos, m_ipa):
        slot = enc.node_slots[ni.node.meta.name]
        assert k_ipa[slot] == want, (ni.node.meta.name, k_ipa[slot], want)
    # the self-matching pod must be admitted on keyed nodes (first-pod rule)
    assert any(k_ipa[enc.node_slots[f"keyed-{i}"]] for i in range(3))


def test_existing_anti_affinity_blocks_incoming():
    """An existing pod's required anti-affinity must repel matching incoming
    pods from its whole zone (the symmetric check, filtering.go:308)."""
    store, sched = mk_cluster(4, zones=2)
    blocker = (make_pod("blocker").label("app", "web").req({"cpu": "1"})
               .pod_affinity("zone", sel("web"), anti=True).obj())
    store.create_pod(blocker)
    sched.run_until_settled()
    assert len(bound(store)) == 1
    blocker_zone = store.nodes[bound(store)["default/blocker"]].meta.labels["zone"]

    for i in range(2):
        store.create_pod(make_pod(f"w{i}").label("app", "web").req({"cpu": "1"}).obj())
    sched.run_until_settled()
    b = bound(store)
    assert len(b) == 3
    for k, n in b.items():
        if k != "default/blocker":
            assert store.nodes[n].meta.labels["zone"] != blocker_zone, (k, n)
