"""Unit tests for cache assume/expire and queue mechanics (the analog of
internal/cache/cache_test.go and internal/queue/scheduling_queue_test.go)."""

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.cache import Cache, Snapshot
from kubernetes_tpu.framework.types import ClusterEvent, NODE, ADD, QueuedPodInfo
from kubernetes_tpu.queue import SchedulingQueue
from kubernetes_tpu.utils.clock import FakeClock


class TestCache:
    def test_assume_confirm(self):
        clock = FakeClock()
        c = Cache(ttl=30, now_fn=clock)
        c.add_node(make_node("n1").capacity({"cpu": "4", "pods": 10}).obj())
        pod = make_pod("p").req({"cpu": "1"}).obj()
        c.assume_pod(pod.clone(), "n1")
        assert c.nodes["n1"].requested.milli_cpu == 1000
        c.finish_binding(pod)
        # informer confirmation before TTL: assumption becomes durable
        bound = pod.clone()
        bound.spec.node_name = "n1"
        c.add_pod(bound)
        clock.advance(60)
        assert c.cleanup() == []
        assert c.nodes["n1"].requested.milli_cpu == 1000

    def test_assume_expiry(self):
        clock = FakeClock()
        c = Cache(ttl=30, now_fn=clock)
        c.add_node(make_node("n1").capacity({"cpu": "4", "pods": 10}).obj())
        pod = make_pod("p").req({"cpu": "1"}).obj()
        c.assume_pod(pod.clone(), "n1")
        c.finish_binding(pod)
        clock.advance(31)
        expired = c.cleanup()
        assert [p.key() for p in expired] == ["default/p"]
        assert c.nodes["n1"].requested.milli_cpu == 0

    def test_forget_rolls_back(self):
        c = Cache()
        c.add_node(make_node("n1").capacity({"cpu": "4", "pods": 10}).obj())
        pod = make_pod("p").req({"cpu": "1"}).obj()
        c.assume_pod(pod.clone(), "n1")
        c.forget_pod(pod)
        assert c.nodes["n1"].requested.milli_cpu == 0

    def test_incremental_snapshot_only_clones_dirty(self):
        c = Cache()
        c.add_node(make_node("n1").capacity({"cpu": "4", "pods": 10}).obj())
        c.add_node(make_node("n2").capacity({"cpu": "4", "pods": 10}).obj())
        snap = Snapshot()
        c.update_snapshot(snap)
        n2_before = snap.node_info_map["n2"]
        c.assume_pod(make_pod("p").req({"cpu": "1"}).obj().clone(), "n1")
        c.update_snapshot(snap)
        assert snap.node_info_map["n2"] is n2_before  # untouched node not re-cloned
        assert snap.node_info_map["n1"].requested.milli_cpu == 1000

    def test_snapshot_node_removal(self):
        c = Cache()
        c.add_node(make_node("n1").capacity({"cpu": "4", "pods": 10}).obj())
        snap = Snapshot()
        c.update_snapshot(snap)
        assert "n1" in snap.node_info_map
        c.remove_node("n1")
        c.update_snapshot(snap)
        assert "n1" not in snap.node_info_map


class TestQueue:
    def mkq(self, clock=None, event_map=None):
        return SchedulingQueue(cluster_event_map=event_map or {}, now_fn=clock or FakeClock())

    def test_priority_pop_order(self):
        q = self.mkq()
        q.add(make_pod("lo").priority(1).obj())
        q.add(make_pod("hi").priority(9).obj())
        assert q.pop().pod.meta.name == "hi"
        assert q.pop().pod.meta.name == "lo"

    def test_backoff_doubling(self):
        q = self.mkq()
        qp = QueuedPodInfo(pod=make_pod("p").obj())
        qp.attempts = 1
        assert q._backoff_duration(qp) == 1.0
        qp.attempts = 3
        assert q._backoff_duration(qp) == 4.0
        qp.attempts = 10
        assert q._backoff_duration(qp) == 10.0  # capped

    def test_event_gated_reactivation(self):
        clock = FakeClock()
        ev_interest = ClusterEvent(NODE, ADD)
        q = self.mkq(clock, {ev_interest: {"NodeResourcesFit"}})
        qp = q_pod = QueuedPodInfo(pod=make_pod("p").obj())
        qp.attempts = 1
        qp.unschedulable_plugins = {"TaintToleration"}  # different plugin
        q.add_unschedulable_if_not_present(qp, 0)
        assert q.move_all_to_active_or_backoff_queue(ClusterEvent(NODE, ADD, "NodeAdd")) == 0
        qp.unschedulable_plugins = {"NodeResourcesFit"}
        assert q.move_all_to_active_or_backoff_queue(ClusterEvent(NODE, ADD, "NodeAdd")) == 1

    def test_update_unknown_pod_falls_through_to_active(self):
        q = self.mkq()
        pod = make_pod("ghost").obj()
        q.update(None, pod)  # never seen before -> activeQ
        assert q.pop().pod.meta.name == "ghost"

    def test_move_request_cycle_race_guard(self):
        clock = FakeClock()
        q = self.mkq(clock)
        q.add(make_pod("p").obj())
        qp = q.pop()  # scheduling_cycle -> 1
        cycle = q.scheduling_cycle
        # a move request fires while the pod's cycle is in flight
        q.move_all_to_active_or_backoff_queue(ClusterEvent(NODE, ADD, "NodeAdd"))
        q.add_unschedulable_if_not_present(qp, cycle)
        # guarded: pod must land in backoff, not unschedulable
        assert q.pending_pods()["backoff"] == 1
        assert q.pending_pods()["unschedulable"] == 0

    def test_flush_unschedulable_leftover(self):
        clock = FakeClock()
        q = self.mkq(clock)
        qp = QueuedPodInfo(pod=make_pod("p").obj(), timestamp=clock())
        qp.attempts = 1
        q.add_unschedulable_if_not_present(qp, 0)
        clock.advance(301)
        q.flush_unschedulable_left_over()
        assert q.pending_pods()["unschedulable"] == 0
        assert q.pop() is not None
