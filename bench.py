"""Benchmark: SchedulingBasic/5000Nodes (scheduler_perf's canonical large
workload — BASELINE.md: 5000 nodes, 1000 init pods, 1000 measured pods).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value        = TPU-batched path throughput (pods scheduled / second, measured
               phase only, end-to-end through queue+cache+bind).
vs_baseline  = speedup over the sequential reference-semantics path (the
               oracle scheduler in this repo — the stand-in for the Go
               kube-scheduler, which cannot run in this image; BASELINE.md
               notes the reference publishes no absolute numbers and its
               harness must be re-run on local hardware to get a baseline).
               The sequential path is measured on a sample and reported as
               pods/s on the same cluster.

Env knobs: BENCH_NODES, BENCH_INIT_PODS, BENCH_PODS, BENCH_SEQ_PODS, BENCH_BATCH.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _probe_platform(timeout_s: float | None = None) -> str:
    """Decide which jax platform this process should use, WITHOUT initializing
    the backend in-process first (a failed/hung init poisons the process).

    Probes the ambient platform (the axon TPU tunnel, if configured) in a
    subprocess with a timeout — round 1 showed backend init can either raise
    (BENCH_r01 rc=1) or hang (MULTICHIP_r01 rc=124).  Retries once, then falls
    back to CPU.  Returns the platform label for the JSON line:
    the real backend name, or "cpu-fallback" when the ambient platform died.
    """
    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "60"))
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return "cpu"
    # Explicit non-cpu platform or auto-selection: probe in a subprocess —
    # either can hang on a broken tunnel.
    probe = "import jax; jax.devices(); print(jax.default_backend())"
    for _attempt in range(2):
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if out.returncode == 0 and out.stdout.strip():
                return out.stdout.strip().splitlines()[-1]
        except subprocess.TimeoutExpired:
            pass
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu-fallback"


def build_cluster(store, n_nodes):
    from kubernetes_tpu.api.wrappers import make_node

    for i in range(n_nodes):
        store.create_node(
            make_node(f"node-{i}")
            .capacity({"cpu": "32", "memory": "128Gi", "pods": 110})
            .label("zone", f"zone-{i % 10}")
            .label("region", f"region-{i % 3}")
            .obj()
        )


def make_pods(store, name_prefix, n):
    from kubernetes_tpu.api.wrappers import make_pod

    for i in range(n):
        store.create_pod(
            make_pod(f"{name_prefix}-{i}")
            .req({"cpu": "900m", "memory": "2Gi"})
            .obj()
        )


def run_tpu(n_nodes, n_init, n_measured, batch):
    from kubernetes_tpu.apiserver import ClusterStore
    from kubernetes_tpu.backend import TPUScheduler

    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=batch)
    build_cluster(store, n_nodes)
    make_pods(store, "init", n_init)
    sched.run_until_settled()  # init phase + jit warmup
    assert sched.metrics["scheduled"] == n_init, sched.metrics

    make_pods(store, "meas", n_measured)
    t0 = time.perf_counter()
    sched.run_until_settled()
    dt = time.perf_counter() - t0
    assert sched.metrics["scheduled"] == n_init + n_measured, sched.metrics
    return n_measured / dt


def run_sequential(n_nodes, n_init, n_measured):
    from kubernetes_tpu.apiserver import ClusterStore
    from kubernetes_tpu.scheduler import Scheduler

    store = ClusterStore()
    sched = Scheduler(store)
    build_cluster(store, n_nodes)
    make_pods(store, "init", n_init)
    sched.run_until_settled()
    make_pods(store, "meas", n_measured)
    t0 = time.perf_counter()
    sched.run_until_settled()
    dt = time.perf_counter() - t0
    assert sched.metrics["scheduled"] == n_init + n_measured, sched.metrics
    return n_measured / dt


def main():
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_init = int(os.environ.get("BENCH_INIT_PODS", 1000))
    n_measured = int(os.environ.get("BENCH_PODS", 1000))
    n_seq = int(os.environ.get("BENCH_SEQ_PODS", 100))
    batch = int(os.environ.get("BENCH_BATCH", 128))

    platform = _probe_platform()
    if platform.startswith("cpu"):
        # Env alone does not stick on relay-tunneled hosts (the platform
        # registration hook can override it); force the config directly.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001
            pass
    record = {
        "metric": "scheduling_throughput SchedulingBasic/5000Nodes",
        "value": 0.0,
        "unit": "pods/s",
        "vs_baseline": 0.0,
        "platform": platform,
        # The sequential path is this repo's Python oracle scheduler, NOT the
        # Go kube-scheduler (no Go toolchain in this image) — it is roughly an
        # order of magnitude slower than the Go scheduler it stands in for.
        "baseline": "python-oracle",
    }
    try:
        tpu_tput = run_tpu(n_nodes, n_init, n_measured, batch)
        seq_tput = run_sequential(n_nodes, min(100, n_init), n_seq)
        record["value"] = round(tpu_tput, 2)
        record["vs_baseline"] = round(tpu_tput / seq_tput, 2)
    except Exception as exc:  # noqa: BLE001 — a number must always be emitted
        if not platform.startswith("cpu"):
            # Backend died mid-run (probe passed but the tunnel dropped):
            # rerun the whole measurement on CPU in a fresh process. CPU runs
            # never re-enter this branch, so the chain is depth-1; the timeout
            # bounds a wedged child (the JSON contract must hold regardless).
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            try:
                out = subprocess.run(
                    [sys.executable, __file__], capture_output=True, text=True,
                    env=env, timeout=float(os.environ.get("BENCH_RERUN_TIMEOUT", "900")),
                )
                line = (out.stdout.strip().splitlines() or [""])[-1]
                rerun = json.loads(line)
                rerun["platform"] = "cpu-fallback"
                print(json.dumps(rerun))
                return
            except (subprocess.SubprocessError, json.JSONDecodeError, TypeError):
                pass
        record["error"] = f"{type(exc).__name__}: {exc}"[:300]
    print(json.dumps(record))


if __name__ == "__main__":
    main()
