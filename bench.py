"""Benchmark: SchedulingBasic/5000Nodes (scheduler_perf's canonical large
workload — BASELINE.md: 5000 nodes, 1000 init pods, 1000 measured pods).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value        = TPU-batched path throughput (pods scheduled / second, measured
               phase only, end-to-end through queue+cache+bind).
vs_baseline  = speedup over the sequential reference-semantics path (the
               oracle scheduler in this repo — the stand-in for the Go
               kube-scheduler, which cannot run in this image; BASELINE.md
               notes the reference publishes no absolute numbers and its
               harness must be re-run on local hardware to get a baseline).
               The sequential path is measured on a sample and reported as
               pods/s on the same cluster.

Env knobs: BENCH_NODES, BENCH_INIT_PODS, BENCH_PODS, BENCH_SEQ_PODS, BENCH_BATCH.
"""

from __future__ import annotations

import json
import os
import time


def build_cluster(store, n_nodes):
    from kubernetes_tpu.api.wrappers import make_node

    for i in range(n_nodes):
        store.create_node(
            make_node(f"node-{i}")
            .capacity({"cpu": "32", "memory": "128Gi", "pods": 110})
            .label("zone", f"zone-{i % 10}")
            .label("region", f"region-{i % 3}")
            .obj()
        )


def make_pods(store, name_prefix, n):
    from kubernetes_tpu.api.wrappers import make_pod

    for i in range(n):
        store.create_pod(
            make_pod(f"{name_prefix}-{i}")
            .req({"cpu": "900m", "memory": "2Gi"})
            .obj()
        )


def run_tpu(n_nodes, n_init, n_measured, batch):
    from kubernetes_tpu.apiserver import ClusterStore
    from kubernetes_tpu.backend import TPUScheduler

    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=batch)
    build_cluster(store, n_nodes)
    make_pods(store, "init", n_init)
    sched.run_until_settled()  # init phase + jit warmup
    assert sched.metrics["scheduled"] == n_init, sched.metrics

    make_pods(store, "meas", n_measured)
    t0 = time.perf_counter()
    sched.run_until_settled()
    dt = time.perf_counter() - t0
    assert sched.metrics["scheduled"] == n_init + n_measured, sched.metrics
    return n_measured / dt


def run_sequential(n_nodes, n_init, n_measured):
    from kubernetes_tpu.apiserver import ClusterStore
    from kubernetes_tpu.scheduler import Scheduler

    store = ClusterStore()
    sched = Scheduler(store)
    build_cluster(store, n_nodes)
    make_pods(store, "init", n_init)
    sched.run_until_settled()
    make_pods(store, "meas", n_measured)
    t0 = time.perf_counter()
    sched.run_until_settled()
    dt = time.perf_counter() - t0
    assert sched.metrics["scheduled"] == n_init + n_measured, sched.metrics
    return n_measured / dt


def main():
    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_init = int(os.environ.get("BENCH_INIT_PODS", 1000))
    n_measured = int(os.environ.get("BENCH_PODS", 1000))
    n_seq = int(os.environ.get("BENCH_SEQ_PODS", 100))
    batch = int(os.environ.get("BENCH_BATCH", 128))

    tpu_tput = run_tpu(n_nodes, n_init, n_measured, batch)
    seq_tput = run_sequential(n_nodes, min(100, n_init), n_seq)

    print(
        json.dumps(
            {
                "metric": "scheduling_throughput SchedulingBasic/5000Nodes",
                "value": round(tpu_tput, 2),
                "unit": "pods/s",
                "vs_baseline": round(tpu_tput / seq_tput, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
