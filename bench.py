"""Benchmark: SchedulingBasic/5000Nodes headline plus the BASELINE.md
workload matrix (SchedulingPodAntiAffinity, TopologySpreading,
SchedulingPodAffinity, PreemptionBasic at reference sizes).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

value        = TPU-batched path throughput (pods scheduled / second, measured
               phase only, end-to-end through queue+cache+bind).
vs_baseline  = speedup over "baseline": the sequential python-oracle path in
               this repo, the stand-in for the Go kube-scheduler (no Go
               toolchain in this image). The oracle is roughly an order of
               magnitude slower than the Go scheduler, so vs_baseline
               overstates the ratio vs the real reference — compare the
               absolute pods/s instead.
attempt_latency_s = p50/p90/p99 of scheduling_attempt_duration_seconds over
               the measured phase (pop → commit per pod; BASELINE's iso-p99).
workloads    = per-workload pods/s + attempt p99 for the matrix rows.

Env knobs: BENCH_NODES, BENCH_INIT_PODS, BENCH_PODS, BENCH_SEQ_PODS,
BENCH_BATCH, BENCH_MATRIX=0, BENCH_BUDGET_S, BENCH_PROBE_TIMEOUT.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _probe_platform(timeout_s: float | None = None) -> tuple[str, dict]:
    """Decide which jax platform this process should use, WITHOUT initializing
    the backend in-process first (a failed/hung init poisons the process).

    Probes the ambient platform (the axon TPU tunnel, if configured) in a
    subprocess with a timeout — round 1 showed backend init can either raise
    (BENCH_r01 rc=1) or hang (MULTICHIP_r01 rc=124).  Retries once, then falls
    back to CPU.  Returns (platform label, probe diagnostic) — the diagnostic
    documents per round whether the chip was reachable (VERDICT r2 missing #1).
    """
    # shared implementation: kubernetes_tpu/utils/relay.py (the relay
    # diagnostics seam); this wrapper only keeps bench.py's public name
    from kubernetes_tpu.utils.relay import probe_platform

    return probe_platform(timeout_s)


def build_cluster(store, n_nodes):
    from kubernetes_tpu.api.wrappers import make_node

    for i in range(n_nodes):
        store.create_node(
            make_node(f"node-{i}")
            .capacity({"cpu": "32", "memory": "128Gi", "pods": 110})
            .label("zone", f"zone-{i % 10}")
            .label("region", f"region-{i % 3}")
            .obj()
        )


def make_pods(store, name_prefix, n):
    from kubernetes_tpu.api.wrappers import make_pod

    for i in range(n):
        store.create_pod(
            make_pod(f"{name_prefix}-{i}")
            .req({"cpu": "900m", "memory": "2Gi"})
            .obj()
        )


# Every span name the package emits on the batch/wire cycle path. This is
# the critical-path attribution table: _critical_path_from_spans buckets
# cycle wall time by these names, and tools/check_metrics.py's span lint
# fails tier-1 when code emits a span that is neither listed here nor
# matched by the lint's explicit ignore list — a new phase span must either
# join the attribution or be consciously ignored, never silently dropped.
CRITICAL_PATH_SPANS = frozenset({
    "scheduling.cycle",
    "device.sync",
    "device.encode",
    "device.encode.pipelined",
    "device.dispatch",
    "device.commit",          # device-service server-side commit
    "device.commit.wait",
    # dispatch-profiler children of device.commit.wait (telemetry.py
    # emit_phase_spans): the wait's dwell/exec/fetch waterfall. Grand-
    # children of scheduling.cycle, so the cycle attribution above never
    # double-counts them; _commit_wait_breakdown consumes them instead.
    "device.dispatch.dwell",
    "device.dispatch.exec",
    "device.dispatch.fetch",
    "device.commit.reconcile",
    "device.commit.backpressure",  # dispatcher blocked on the commit worker
    "host.commit",
    "device.apply_deltas",    # wire: server half of the delta push
    "device.schedule_batch",  # wire: server half of the batch call
})


def _critical_path_from_spans(spans):
    """Span-based critical-path breakdown (ROADMAP PR2 follow-up): per
    scheduling.cycle span, attribute its wall time to child phase spans
    (sync/encode/dispatch + the overlapped previous batch's commit.wait /
    host.commit / commit.reconcile, which land inside the cycle by
    pipelining design) plus an "other" residual, and report which phase
    DOMINATED each cycle. Commit spans outside any cycle (queue-empty
    drains) are aggregated under "drain". The per-phase shares complement
    batch_phase_ms: means say where time goes on average, the dominant
    counts say what the slowest path through a typical cycle actually is."""
    by_id = {s.span_id: s for s in spans}
    cycles = []
    children = {}
    for s in spans:
        parent = by_id.get(s.parent_id) if s.parent_id else None
        if s.name == "scheduling.cycle":
            cycles.append(s)
        elif parent is not None and parent.name == "scheduling.cycle":
            children.setdefault(parent.span_id, []).append(s)
    if not cycles:
        return None
    dominant = {}
    totals = {}
    wall_total = 0.0
    for c in cycles:
        wall = c.duration_s
        wall_total += wall
        phase_ms = {}
        for ch in children.get(c.span_id, ()):
            phase_ms[ch.name] = phase_ms.get(ch.name, 0.0) + ch.duration_s
        other = wall - sum(phase_ms.values())
        if other > 0:
            phase_ms["other"] = other
        for name, dur in phase_ms.items():
            totals[name] = totals.get(name, 0.0) + dur
        if phase_ms:
            top = max(phase_ms, key=phase_ms.get)
            dominant[top] = dominant.get(top, 0) + 1
    # commit-WORKER spans run on their own thread with no cycle parent —
    # the commit data plane's whole point is taking host.commit OFF the
    # cycle's critical path. Bucket them separately from queue-empty
    # drains so the overlap is visible, not mistaken for drain cost.
    worker = {}
    worker_batches = 0
    for s in spans:
        if s.attributes.get("worker") != "commit":
            continue
        worker[s.name] = worker.get(s.name, 0.0) + s.duration_s
        if s.name == "host.commit":
            worker_batches += 1
    # commits that landed outside a cycle (drain at queue-empty / settle end)
    drain = sum(s.duration_s for s in spans
                if s.name.startswith(("device.commit", "host.commit"))
                and (s.parent_id not in by_id
                     or by_id[s.parent_id].name != "scheduling.cycle")
                and s.attributes.get("worker") != "commit")
    # mesh-sharded packed=None commits take the per-array fallback read —
    # a materially different commit-wait shape. Counting the tag keeps the
    # attribution honest on sharded runs instead of silently averaging two
    # different transfer regimes into one "commit.wait" number.
    fallback_commits = sum(
        1 for s in spans
        if s.name in ("device.commit.wait", "device.commit")
        and s.attributes.get("packed") == "fallback")
    out = {
        "cycles": len(cycles),
        "dominant": dict(sorted(dominant.items(), key=lambda kv: -kv[1])),
        "share_pct": {name: round(100.0 * t / max(wall_total, 1e-9), 1)
                      for name, t in sorted(totals.items(), key=lambda kv: -kv[1])},
        "cycle_wall_ms_mean": round(wall_total / len(cycles) * 1000, 2),
        "packed_fallback_commits": fallback_commits,
    }
    if drain > 0:
        out["drain_commit_ms_total"] = round(drain * 1000, 2)
    if worker_batches:
        # commit_plane evidence: per-batch mean of the worker-side commit
        # phases plus the share of cycle wall the async offload hides —
        # overlap_pct near 100 means the host commit fully rides under the
        # next batches' device execution
        wall_worker = sum(worker.values())
        out["commit_plane"] = {
            "async_batches": worker_batches,
            "worker_commit_ms_mean": round(
                worker.get("host.commit", 0.0) / worker_batches * 1000, 2),
            "worker_phase_ms_total": {
                name: round(t * 1000, 2)
                for name, t in sorted(worker.items(), key=lambda kv: -kv[1])},
            "overlap_pct": round(
                100.0 * min(wall_worker, wall_total) / max(wall_total, 1e-9),
                1),
        }
    return out


def _commit_wait_breakdown(spans):
    """Dispatch-profiler waterfall (ROADMAP item 2): decompose the total
    device.commit.wait wall into its dwell / exec / fetch children
    (telemetry.emit_phase_spans window partition — the three phases are
    clamped into the wait window, so their sum tracks the wait total by
    construction; any residual is wait time outside a profiled record,
    e.g. the ready-poll slack before the first record lands)."""
    wait_total = 0.0
    waits = 0
    phase = {"dwell": 0.0, "exec": 0.0, "fetch": 0.0}
    for s in spans:
        if s.name == "device.commit.wait":
            wait_total += s.duration_s
            waits += 1
        elif s.name.startswith("device.dispatch."):
            key = s.name[len("device.dispatch."):]
            if key in phase:
                phase[key] += s.duration_s
    if not waits or wait_total <= 0:
        return None
    return {
        "commit_wait_ms_total": round(wait_total * 1000, 2),
        "batches": waits,
        "phase_ms": {k: round(v * 1000, 2) for k, v in phase.items()},
        "share_pct": {k: round(100.0 * v / wait_total, 1)
                      for k, v in phase.items()},
        "phase_ms_per_batch": {k: round(v / waits * 1000, 3)
                               for k, v in phase.items()},
    }


def _device_program_table(tele, top_n=8):
    """Per-program device-time table from the DispatchLedger running stats
    + cost ledger: where device seconds went by program@bucket, with the
    XLA cost-analysis flops/bytes (and the achieved rates derived from
    them) when the one-shot AOT probe captured them."""
    dump = tele.dispatch_ledger.dump(limit=0)
    programs = dump.get("programs") or {}
    if not programs:
        return None
    rows = sorted(programs.items(), key=lambda kv: -kv[1].get("execS", 0.0))
    out = {}
    for name, st in rows[:top_n]:
        row = {
            "count": st["count"],
            "exec_ms_total": round(st["execS"] * 1000, 2),
            "dwell_ms_total": round(st["dwellS"] * 1000, 2),
            "fetch_ms_total": round(st["fetchS"] * 1000, 2),
            "fetch_bytes": st["fetchBytes"],
        }
        if "flops" in st:
            row["flops"] = st["flops"]
            row["bytes_accessed"] = st.get("bytesAccessed", 0)
            if "achievedFlopsPerS" in st:
                row["achieved_flops_per_s"] = round(st["achievedFlopsPerS"])
            if "achievedBytesPerS" in st:
                row["achieved_bytes_per_s"] = round(st["achievedBytesPerS"])
        out[name] = row
    return out


def run_tpu(n_nodes, n_init, n_measured, batch):
    from kubernetes_tpu.apiserver import ClusterStore
    from kubernetes_tpu.backend import TPUScheduler, telemetry
    from kubernetes_tpu.metrics import latency_ledger
    from kubernetes_tpu.utils import tracing

    store = ClusterStore()
    # comparer on (every 256th placement re-checked by the scalar oracle):
    # the throughput number carries placement-validity evidence (VERDICT r2)
    sched = TPUScheduler(store, batch_size=batch,
                         comparer_every_n=int(os.environ.get("BENCH_COMPARER_N", "256")))
    # device-runtime ledger: XLA compile counts per (program, bucket), HBM
    # stats, per-batch transfer bytes — the bench evidence for ROADMAP items
    # 1/2 (encode is device_put-heavy; 100k-node sharding is HBM-bounded)
    tele = telemetry.enable(sched.smetrics)
    # pod-lifetime latency ledger: per-pod e2e + per-segment attribution —
    # the iso-p99 evidence now covers the WHOLE pod lifetime, not just the
    # winning attempt
    latency_ledger.enable(sched.smetrics, tenant_fn=sched._ns_fair_weight)
    build_cluster(store, n_nodes)
    make_pods(store, "init", n_init)
    sched.run_until_settled()  # init phase + jit warmup
    assert sched.metrics["scheduled"] == n_init, sched.metrics
    assert not sched.settle_abandoned, "init phase abandoned with pods pending"
    # compile every deadline-cutting pod bucket OUTSIDE the measured window
    sched.warm_buckets()

    hist = sched.smetrics.scheduling_attempt_duration
    snap = hist.snapshot("scheduled", "default-scheduler")
    dur = sched.smetrics.device_batch_duration
    phase_names = ("upload", "encode", "compute", "commit",
                   "commit_wait", "commit_host", "commit_reconcile",
                   "commit_backpressure")
    # snapshot sums/counts so phase means cover ONLY the measured phase
    # (the init phase pays the one-off jit compile)
    pre = {ph: (dur.sum(ph), dur.count(ph)) for ph in phase_names}
    # span capture over the measured phase only (in-memory, ~10 spans per
    # batch): feeds the critical-path breakdown below
    own_tracer = tracing.get() is None
    exporter = tracing.enable(tracing.InMemoryExporter()).exporter \
        if own_tracer else None
    e2e_hist = sched.smetrics.pod_e2e_duration
    e2e_snap = e2e_hist.snapshot("scheduled")
    seg_hist = sched.smetrics.pod_latency_segment
    seg_pre = {lv[0]: seg_hist.sum(*lv) for lv in seg_hist.label_sets()}
    stall_pre = sched.smetrics.pipeline_stall_seconds.labels()
    coal = sched.smetrics.commit_coalesced_events
    coal_pre = {k: coal.labels(k)
                for k in ("queue_move", "wal_record", "cache_op", "post_bind")}
    cbd = sched.smetrics.commit_batch_duration
    cbd_stages = ("assume", "reserve", "permit", "pre_bind", "bind",
                  "finish", "total")
    cbd_pre = {st: (cbd.sum(st), cbd.count(st)) for st in cbd_stages}
    # measured-phase deltas of the device-runtime ledger: compiles landing
    # in HERE (after warm_buckets) are exactly the retrace cost the sizer's
    # bucket walk can inflict mid-run
    comp_pre = tele.ledger.total_compilations()
    retrace_pre = tele.ledger.total_retraces()
    xfer_pre = dict(tele.transfer_bytes)
    batches_pre = sched.batch_counter
    make_pods(store, "meas", n_measured)
    t0 = time.perf_counter()
    sched.run_until_settled()
    dt = time.perf_counter() - t0
    critical = None
    commit_wait_breakdown = None
    if exporter is not None:
        critical = _critical_path_from_spans(exporter.spans)
        commit_wait_breakdown = _commit_wait_breakdown(exporter.spans)
        tracing.disable()
    assert sched.metrics["scheduled"] == n_init + n_measured, sched.metrics
    assert not sched.settle_abandoned, "measured phase abandoned with pods pending"
    latency = {
        "p50": round(hist.percentile_since(snap, 0.50, "scheduled", "default-scheduler"), 4),
        "p90": round(hist.percentile_since(snap, 0.90, "scheduled", "default-scheduler"), 4),
        "p99": round(hist.percentile_since(snap, 0.99, "scheduled", "default-scheduler"), 4),
    }
    phases = {ph: round((dur.sum(ph) - pre[ph][0])
                        / max(dur.count(ph) - pre[ph][1], 1) * 1000, 2)
              for ph in phase_names}
    evidence = {
        "comparer_checks": sched.comparer_checks,
        "comparer_mismatches": sched.comparer_mismatches,
        "pipelined_batches": sched.pipelined_batches,
        "fallback_scheduled": sched.fallback_scheduled,
        # iso-p99 machinery (VERDICT r3 item 4): the declared deadline and
        # where the sizer converged — p99 should sit within the deadline
        "batch_deadline_ms": round(sched.sizer.deadline_s * 1000, 1),
        "batch_target_final": sched.sizer.target(),
        # async-commit-pipeline evidence: ring depth, seconds the commit
        # site blocked on device execution over the MEASURED phase only
        # (init/jit-compile waits snapshotted out, like the phase means),
        # and where the stall controller pinned the bucket
        "pipeline_depth": sched.pipeline_depth,
        "pipeline_stall_s": round(
            sched.smetrics.pipeline_stall_seconds.labels() - stall_pre, 3),
        "stall_target_ms": round(sched.sizer.stall_target_s * 1000, 1),
        # device-runtime observability (backend/telemetry.py): process-total
        # XLA compiles + retraces, the measured-phase slice (should be ~0 —
        # warm_buckets exists to keep compiles out of the window), HBM peak
        # (0 on CPU: no memory_stats), and per-batch transfer volume over
        # the measured phase (upload = row sync, fetch = packed block)
        "xla_compilations": tele.ledger.total_compilations(),
        "retraces": tele.ledger.total_retraces(),
        "measured_compilations": tele.ledger.total_compilations() - comp_pre,
        "measured_retraces": tele.ledger.total_retraces() - retrace_pre,
        "retrace_storms": sum(tele.ledger.storms.values()),
        "hbm_bytes_peak": tele.hbm_peak,
    }
    # commit data plane evidence (ROADMAP item 1): engine batch counts, the
    # per-pod deliveries coalesced into batched operations over the measured
    # phase, per-stage engine latencies, and whether the async commit worker
    # ran (platform-aware: accelerators only by default)
    evidence["commit_plane"] = {
        "engine_batches": sched.commit_plane.batches,
        "engine_pods_bound": sched.commit_plane.pods_bound,
        "worker_enabled": sched.commit_worker is not None,
        "coalesced_events": {k: round(coal.labels(k) - coal_pre[k])
                             for k in coal_pre},
        "stage_ms_mean": {
            st: round((cbd.sum(st) - cbd_pre[st][0])
                      / max(cbd.count(st) - cbd_pre[st][1], 1) * 1000, 3)
            for st in cbd_stages},
    }
    # pod-lifetime e2e over the measured phase + where the lifetime went
    # (top segment shares of the measured-phase segment-seconds delta)
    if e2e_hist.count_since(e2e_snap, "scheduled"):
        evidence["e2e_latency_s"] = {
            "p50": round(e2e_hist.percentile_since(e2e_snap, 0.50, "scheduled"), 4),
            "p99": round(e2e_hist.percentile_since(e2e_snap, 0.99, "scheduled"), 4),
        }
        seg_delta = {lv[0]: seg_hist.sum(*lv) - seg_pre.get(lv[0], 0.0)
                     for lv in seg_hist.label_sets()}
        seg_total = sum(v for v in seg_delta.values() if v > 0)
        if seg_total > 0:
            evidence["segment_shares_pct"] = {
                seg: round(100.0 * v / seg_total, 1)
                for seg, v in sorted(seg_delta.items(), key=lambda kv: -kv[1])
                if v > 0}
    meas_batches = max(sched.batch_counter - batches_pre, 1)
    evidence["upload_bytes_per_batch"] = round(
        (tele.transfer_bytes.get("upload", 0) - xfer_pre.get("upload", 0))
        / meas_batches)
    evidence["fetch_bytes_per_batch"] = round(
        (tele.transfer_bytes.get("fetch", 0) - xfer_pre.get("fetch", 0))
        / meas_batches)
    if critical is not None:
        evidence["critical_path"] = critical
    # dispatch-profiler evidence (ROADMAP item 2): the commit-wait
    # waterfall (dwell/exec/fetch shares of device.commit.wait) and the
    # per-program device-time table with cost-ledger flops/bytes
    if commit_wait_breakdown is not None:
        evidence["commit_wait_breakdown"] = commit_wait_breakdown
    device_programs = _device_program_table(tele)
    if device_programs is not None:
        evidence["device_programs"] = device_programs
    # release the module-global ledger so later rows (run_wire's Runner)
    # can own a fresh one against their own registry
    latency_ledger.disable()
    return n_measured / dt, latency, phases, evidence


# BASELINE.md canonical rows (VERDICT r3 item 5: >=9 incl. volume + churn).
# Order matters: if the bench budget runs out, later rows skip — the four
# r3-continuity rows and the newly-required failure/churn/volume rows come
# first, scoring-breadth rows last.
MATRIX_ROWS = ("SchedulingPodAntiAffinity", "TopologySpreading",
               "SchedulingPodAffinity", "PreemptionBasic",
               "Unschedulable", "SchedulingWithChurn",
               "SchedulingSecrets", "SchedulingInTreePVs", "SchedulingCSIPVs",
               "MixedSchedulingBasePod", "SchedulingPreferredPodAffinity",
               "SchedulingPreferredPodAntiAffinity",
               "SchedulingNodeAffinity", "PreferredTopologySpreading",
               "MigratedInTreePVs", "PreemptionPVs",
               "SchedulingRequiredPodAntiAffinityWithNSSelector",
               "SchedulingElastic", "SchedulingSlices", "SchedulingReplay",
               "SchedulingBorrow")


def run_matrix(budget_deadline, platform):
    """Per-workload results (BASELINE.md matrix rows) on the batched path.

    Each row runs in its own subprocess with a hard timeout clipped to the
    remaining budget, so one stalled workload can never block the headline
    JSON line (the one-line contract holds regardless of the matrix)."""
    out = {}
    for name in MATRIX_ROWS:
        remaining = budget_deadline - time.perf_counter()
        if remaining < 30:
            out[name] = {"skipped": "bench time budget exhausted"}
            continue
        env = dict(os.environ, BENCH_MATRIX_CHILD=name,
                   BENCH_PLATFORM_RESOLVED=platform,
                   # per-workload e2e evidence: the child Runner enables
                   # the latency ledger and run_matrix_child lifts its
                   # DataItems into the row
                   KTPU_LEDGER="1")
        if platform.startswith("cpu"):
            env["JAX_PLATFORMS"] = "cpu"
        try:
            p = subprocess.run(
                [sys.executable, __file__], env=env, capture_output=True,
                text=True,
                timeout=min(remaining,
                            float(os.environ.get("BENCH_ROW_TIMEOUT", "1200"))),
            )
            lines = p.stdout.strip().splitlines()
            try:
                row = json.loads(lines[-1]) if lines else None
            except json.JSONDecodeError:
                row = None
            if row is None:  # child died before printing its JSON
                row = {"error": f"rc={p.returncode}: {p.stderr.strip()[-200:]}"}
            out[name] = row
        except subprocess.TimeoutExpired:
            out[name] = {"error": "timeout"}
        except Exception as exc:  # noqa: BLE001 — a bad row must not kill the bench
            out[name] = {"error": f"{type(exc).__name__}: {exc}"[:200]}
    return out


def run_matrix_child(name: str) -> None:
    """One matrix row at the workload factory's reference-default sizes;
    prints a single JSON object."""
    from kubernetes_tpu.perf.harness import run_workload
    from kubernetes_tpu.perf.workloads import TEST_CASES

    entry = {}
    try:
        if name == "SchedulingBorrow":
            # the A/B workload: the row's headline items come from the
            # borrowing-ON arm, and the OFF arm (same caps, same arrivals,
            # no cohort) supplies the baseline for the utilization-lift
            # and lender-p99-delta evidence the fence judges
            items = run_workload(TEST_CASES[name](borrowing=True),
                                 backend="tpu")
            off_items = run_workload(TEST_CASES[name](borrowing=False),
                                     backend="tpu")

            def _one(data_items, label, ns=None):
                for it in data_items:
                    if it.labels.get("Name") == label and (
                            ns is None or it.labels.get("namespace") == ns):
                        return it.data
                return {}

            on_inv = _one(items, "BorrowInvariants")
            off_inv = _one(off_items, "BorrowInvariants")
            on_lender = _one(items, "BorrowTenant", "borrow-lender")
            off_lender = _one(off_items, "BorrowTenant", "borrow-lender")
            entry["borrowing"] = {
                "util_mean_on": round(on_inv.get(
                    "PoolUtilizationMean", 0.0), 4),
                "util_mean_off": round(off_inv.get(
                    "PoolUtilizationMean", 0.0), 4),
                "util_lift": round(
                    on_inv.get("PoolUtilizationMean", 0.0)
                    - off_inv.get("PoolUtilizationMean", 0.0), 4),
                "reclaims": on_inv.get("Reclaims", 0.0),
                "loans_peak": on_inv.get("LoansOutstandingPeak", 0.0),
                "oversubscription": (
                    on_inv.get("OversubscriptionViolations", 0.0)
                    + off_inv.get("OversubscriptionViolations", 0.0)),
                "lender_p99_on_s": round(on_lender.get("E2eP99", 0.0), 4),
                "lender_p99_off_s": round(off_lender.get("E2eP99", 0.0), 4),
                "lender_p99_delta_s": round(
                    on_lender.get("E2eP99", 0.0)
                    - off_lender.get("E2eP99", 0.0), 4),
            }
        else:
            items = run_workload(TEST_CASES[name](), backend="tpu")
        for it in items:
            label = it.labels.get("Name")
            # phase-driven workloads (SchedulingElastic) emit their
            # throughput under the workload's own label, not the measured
            # SchedulingThroughput item
            if label in ("SchedulingThroughput", name):
                entry["pods_per_s"] = round(it.data["Average"], 2)
            elif label == "scheduling_attempt_duration_seconds" \
                    and it.labels.get("result") == "scheduled":
                entry["attempt_p99_s"] = round(it.data["Perc99"], 4)
            elif label == "ElasticInvariants":
                # the elasticity acceptance evidence rides the bench row:
                # zero lost/oversubscribed, bounded capacity, slot reuse,
                # upload back at 0 — judged by eye/tests, not the fence
                entry["elastic"] = {k: it.data[k] for k in (
                    "LostPods", "Oversubscribed", "RowCapacity",
                    "SlotReuses", "UploadBytesSteady", "HbmPeakBytes")}
            elif label == "SliceStats":
                # slice-packing acceptance evidence (ISSUE 16): placement
                # quality + correctness counters ride the bench row; the
                # fence judges wait_p99_s/frag_max, the zero-counters are
                # judged by eye/tests
                entry["slices"] = {
                    "frag_max": round(it.data["FragmentationMax"], 4),
                    "frag_mean": round(it.data["FragmentationMean"], 4),
                    "contiguity_violations": it.data["ContiguityViolations"],
                    "bound_gangs": it.data["BoundSliceGangs"],
                    "rejected": it.data["SliceRejected"],
                    "fallback": it.data["FallbackScheduled"],
                    "wait_p50_s": round(it.data["SliceWaitP50"], 4),
                    "wait_p99_s": round(it.data["SliceWaitP99"], 4),
                }
            elif label == "ReplayInvariants":
                # continuous-rebalancing acceptance evidence (ISSUE 18):
                # the fence judges packing_eff (higher better) and
                # tenant_p99_s (a tenant's e2e SLO must not move); wave/
                # migration counters are judged by eye/tests
                entry["replay"] = {
                    "packing_eff": round(it.data["PackingEff"], 4),
                    "final_entropy": round(it.data["FinalEntropy"], 4),
                    "tenant_p99_s": round(it.data["TenantP99Max"], 4),
                    "waves": it.data["Waves"],
                    "migrations": it.data["Migrations"],
                    "suspended": it.data["Suspended"],
                    "pending_at_end": it.data["PendingAtEnd"],
                }
            elif label == "pod_e2e_duration_seconds" \
                    and it.labels.get("result") == "scheduled":
                # pod-lifetime e2e (latency ledger): the fence's
                # workload_e2e_p99_s tolerance judges this row r11+
                entry["e2e_p50_s"] = round(it.data["Perc50"], 4)
                entry["e2e_p99_s"] = round(it.data["Perc99"], 4)
            elif label == "pod_latency_segments":
                total = sum(v for v in it.data.values() if v > 0)
                if total > 0:
                    shares = sorted(it.data.items(), key=lambda kv: -kv[1])
                    entry["segments_top_pct"] = {
                        seg: round(100.0 * v / total, 1)
                        for seg, v in shares[:4] if v > 0}
    except Exception as exc:  # noqa: BLE001
        entry["error"] = f"{type(exc).__name__}: {exc}"[:200]
    print(json.dumps(entry))


def run_wire(n_nodes=1000, n_init=200, n_measured=500, backend="wire"):
    """Transport-inclusive row: the batched device service behind a real
    localhost socket (SURVEY §5.8 hop 6) — the serialization + wire cost the
    in-process number does not pay. backend="wire" is HTTP/JSON;
    backend="grpc" is the hardened gRPC + template-dedup transport."""
    entry = {"transport": backend}

    def one(depth_env):
        """One measured run; depth_env='' keeps the session default."""
        from kubernetes_tpu.perf.harness import Runner
        from kubernetes_tpu.perf.workloads import scheduling_basic

        prior = os.environ.get("KTPU_WIRE_PIPELINE_DEPTH")
        if depth_env != "":
            os.environ["KTPU_WIRE_PIPELINE_DEPTH"] = depth_env
        try:
            test_case = scheduling_basic(nodes=n_nodes, init_pods=n_init,
                                         measured=n_measured)
            r = Runner(scheduler_config=test_case.get("schedulerConfig"),
                       backend=backend, ledger=True)
            try:
                r.run_ops(test_case["ops"])
                sched = r.scheduler
                out = {
                    "wire_pipeline_depth": getattr(
                        sched, "wire_pipeline_depth", 0),
                    "pipelined_batches": getattr(
                        sched, "pipelined_wire_batches", 0),
                }
                pipeline = getattr(sched, "_wire_pipeline", None)
                if pipeline is not None:
                    out["duplicate_replies"] = pipeline.duplicate_replies
            finally:
                r.close()
            for it in r.data_items:
                if it.labels.get("Name") == "SchedulingThroughput":
                    out["pods_per_s"] = round(it.data["Average"], 2)
                elif (it.labels.get("Name")
                      == "scheduling_attempt_duration_seconds"
                      and it.labels.get("result") == "scheduled"):
                    out["attempt_p99_s"] = round(it.data["Perc99"], 4)
                elif (it.labels.get("Name") == "pod_e2e_duration_seconds"
                      and it.labels.get("result") == "scheduled"):
                    out["e2e_p50_s"] = round(it.data["Perc50"], 4)
                    out["e2e_p99_s"] = round(it.data["Perc99"], 4)
            return out
        finally:
            if depth_env != "":
                if prior is None:
                    os.environ.pop("KTPU_WIRE_PIPELINE_DEPTH", None)
                else:
                    os.environ["KTPU_WIRE_PIPELINE_DEPTH"] = prior

    try:
        # headline row: the pipelined transport at its default depth, plus
        # a SAME-RUN depth-0 control — the box is bimodal across runs
        # (ROADMAP bench caveats), so the pipelining lift is judged at
        # iso-conditions inside one record, not across rounds
        entry.update(one(""))
        sync = one("0")
        entry["sync_pods_per_s"] = sync.get("pods_per_s")
        entry["sync_attempt_p99_s"] = sync.get("attempt_p99_s")
    except Exception as exc:  # noqa: BLE001 — a bad row must not kill the bench
        entry["error"] = f"{type(exc).__name__}: {exc}"[:200]
    return entry


def run_pallas_check():
    """Hardware evidence for the fused Pallas step (VERDICT r2: 'never
    compiled on hardware'): schedule a small cluster with the kernel forced
    on and off; report the mode actually used and placement parity."""
    entry = {}
    try:
        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.apiserver import ClusterStore
        from kubernetes_tpu.backend import TPUScheduler
        from kubernetes_tpu.backend.batch import pallas_mode

        def one(flag):
            os.environ["KTPU_PALLAS"] = flag
            try:
                store = ClusterStore()
                sched = TPUScheduler(store, batch_size=16)
                for i in range(64):
                    store.create_node(
                        make_node(f"n{i}").capacity(
                            {"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
                for i in range(48):
                    store.create_pod(
                        make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
                sched.run_until_settled()
                objs, _rv = store.list_objects("Pod")
                mode = pallas_mode(sched.device.nt, None, sched.device.topo_enabled)
                return {p.meta.name: p.spec.node_name
                        for p in objs if p.spec.node_name}, mode
            finally:
                os.environ.pop("KTPU_PALLAS", None)

        b_pallas, mode = one("auto")
        b_xla, _ = one("0")
        entry["mode"] = mode
        entry["placement_parity"] = b_pallas == b_xla
    except Exception as exc:  # noqa: BLE001
        entry["error"] = f"{type(exc).__name__}: {exc}"[:200]
    return entry


def run_agreement(n_nodes=1000, n_pods=300):
    """Default-config placement agreement (VERDICT r3 item 9): run the
    sequential oracle and the batched path over IDENTICAL clusters at the
    default config (percentageOfNodesToScore=0) and report how often they
    pick the same node. Ties break by different RNG streams (reservoir vs
    jitter), so 100% is not expected even with identical semantics; the
    companion validity signal is the in-run comparer (0 mismatches = every
    batched placement passes the oracle's filters)."""
    entry = {}
    try:
        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.apiserver import ClusterStore
        from kubernetes_tpu.backend import TPUScheduler
        from kubernetes_tpu.scheduler import Scheduler

        def build(store):
            # HETEROGENEOUS cluster + deterministic pre-load: on a uniform
            # empty cluster every node ties and the tie-break lottery (two
            # different RNG streams) makes exact agreement meaningless noise;
            # varied capacity/occupancy gives distinct scores so an argmax
            # divergence is a semantic signal, not luck
            for i in range(n_nodes):
                # near-unique per-node capacity: distinct LeastAllocated/
                # Balanced scores collapse the tie groups, so the tie-break
                # RNG (reservoir vs jitter — random in the reference too)
                # stops dominating the comparison
                cpu = str(8 + (i * 7) % 57)
                mem = f"{32 + (i * 11) % 193}Gi"
                store.create_node(
                    make_node(f"node-{i}")
                    .capacity({"cpu": cpu, "memory": mem, "pods": 110})
                    .label("zone", f"zone-{i % 10}").obj())
            for i in range(n_nodes // 2):  # pre-bound load, identical per run
                store.create_pod(
                    make_pod(f"pre-{i}")
                    .req({"cpu": f"{(i % 7) + 1}", "memory": f"{(i % 5) + 1}Gi"})
                    .node(f"node-{(i * 13) % n_nodes}").obj())

        def run(make_sched):
            store = ClusterStore()
            sched = make_sched(store)
            build(store)
            make_pods(store, "agree", n_pods)
            sched.run_until_settled()
            return {k: p.spec.node_name for k, p in store.pods.items()
                    if p.spec.node_name and k.startswith("default/agree")}

        def agree(a, b):
            common = set(a) & set(b)
            same = sum(1 for k in common if a[k] == b[k])
            return {"pods": len(common),
                    "exact_pct": round(100.0 * same / max(len(common), 1), 2),
                    "both_scheduled": len(common) == n_pods}

        oracle = run(lambda s: Scheduler(s, seed=7))
        # default config: on CPU both paths sample adaptively, but the
        # oracle's rotating window walks the host node list while the device
        # emulation walks slot order (the DOCUMENTED divergence, PARITY
        # §2.7 P2) — they examine different subsets; and under score TIES
        # (integer-floored scores collapse hard) the tie-break RNG streams
        # differ, so exact-match is structurally low for the same reason two
        # runs of the REFERENCE disagree. Report it for transparency...
        batched = run(lambda s: TPUScheduler(s, batch_size=128, seed=7))
        entry = {"default_config_exact": agree(oracle, batched)}
        # ...and pin the real parity claim: ARGMAX-EQUIVALENCE. Replay the
        # batched path's placements pod-by-pod under ORACLE semantics
        # (full evaluation, oracle state evolution) and check each chosen
        # node is feasible and ties the oracle's best score — i.e. every
        # batch decision is one the reference could have made.
        os.environ["KTPU_FULL_BATCH"] = "1"
        try:
            batched_full = run(lambda s: TPUScheduler(s, batch_size=128, seed=7))
            entry["argmax_equivalence"] = _argmax_equivalence(
                build, batched_full, n_pods)
        finally:
            os.environ.pop("KTPU_FULL_BATCH", None)
    except Exception as exc:  # noqa: BLE001
        entry["error"] = f"{type(exc).__name__}: {exc}"[:200]
    return entry


def _argmax_equivalence(build, placements, n_pods):
    """Replay `placements` ({pod key: node}) under oracle semantics: a fresh
    cluster, pods bound in commit order; per pod, the oracle's filter+score
    pass must accept the chosen node with a score equal to the oracle's own
    best (tie-equivalent argmax). Returns the equivalence stats."""
    from kubernetes_tpu.api.types import Binding
    from kubernetes_tpu.api.wrappers import make_pod
    from kubernetes_tpu.apiserver import ClusterStore
    from kubernetes_tpu.framework.interface import CycleState
    from kubernetes_tpu.framework.types import NodeInfo
    from kubernetes_tpu.scheduler import Scheduler

    store = ClusterStore()
    build(store)
    o = Scheduler(store, percentage_of_nodes_to_score=100, seed=7)
    equivalent = infeasible = suboptimal = 0
    for i in range(n_pods):
        key = f"default/agree-{i}"
        chosen = placements.get(key)
        if chosen is None:
            continue
        pod = make_pod(f"agree-{i}").req({"cpu": "900m", "memory": "2Gi"}).obj()
        store.create_pod(pod)
        o.cache.update_snapshot(o.snapshot)
        fwk = o.framework_for_pod(pod)
        state = CycleState()
        fwk.run_pre_filter_plugins(state, pod)
        feasible = []
        for name, ni in o.snapshot.node_info_map.items():
            if ni.node is not None and fwk.run_filter_plugins(state, pod, ni).is_success():
                feasible.append(ni)
        if chosen not in {ni.node.meta.name for ni in feasible}:
            infeasible += 1
        else:
            fwk.run_pre_score_plugins(state, pod, [ni.node for ni in feasible])
            totals = fwk.run_score_plugins(state, pod, feasible)
            if totals.get(chosen) == max(totals.values()):
                equivalent += 1
            else:
                suboptimal += 1
        # ALWAYS mirror the audited run's placement — the replay must track
        # the batched scheduler's actual state, or one early mismatch would
        # cascade spurious classifications onto every later pod
        store.bind(Binding(pod_key=key, node_name=chosen))
    checked = equivalent + infeasible + suboptimal
    return {
        "pods": checked,
        "equivalent_pct": round(100.0 * equivalent / max(checked, 1), 2),
        "infeasible": infeasible,
        "suboptimal": suboptimal,
    }


def run_sequential(n_nodes, n_init, n_measured):
    from kubernetes_tpu.apiserver import ClusterStore
    from kubernetes_tpu.scheduler import Scheduler

    store = ClusterStore()
    sched = Scheduler(store)
    build_cluster(store, n_nodes)
    make_pods(store, "init", n_init)
    sched.run_until_settled()
    make_pods(store, "meas", n_measured)
    t0 = time.perf_counter()
    sched.run_until_settled()
    dt = time.perf_counter() - t0
    assert sched.metrics["scheduled"] == n_init + n_measured, sched.metrics
    return n_measured / dt


def _probe_log_summary() -> dict:
    """Summarize TPU_EVIDENCE/probe_log.jsonl (tools/tpu_watch.py): attempt
    count + outcome histogram + first/last timestamps, so a cpu-fallback
    round carries its own proof of whether the relay was ever reachable."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_EVIDENCE", "probe_log.jsonl")
    summary: dict = {"attempts": 0, "outcomes": {}}
    try:
        with open(path) as f:
            for line in f:
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                summary["attempts"] += 1
                o = str(e.get("outcome", "?"))
                summary["outcomes"][o] = summary["outcomes"].get(o, 0) + 1
                summary.setdefault("first", e.get("t"))
                summary["last"] = e.get("t")
    except OSError:
        summary["missing"] = True
    return summary


def _write_trend(record: dict) -> None:
    """Side-effect artifact: TREND.md/json comparing this run against every
    committed BENCH_r*.json (regressions >20% flagged loudly). Never breaks
    the one-JSON-line stdout contract.

    Write-once guard (VERDICT r4 weak #5): smoke/test invocations of bench.py
    must not clobber the round's recorded trend. TREND.* is only written when
    this run is explicitly the round bench: `--record` argv flag or
    BENCH_RECORD=1 in the environment."""
    if "--record" not in sys.argv and os.environ.get("BENCH_RECORD") != "1":
        return
    try:
        sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))
        from trend import write_trend

        doc = write_trend(record)
        if doc.get("regressions"):
            record["trend_regressions"] = doc["regressions"]
    except Exception:  # noqa: BLE001 — trend is evidence, not a gate
        pass


def run_fence(argv) -> int:
    """SLO regression fence: compare a bench record against the prior
    BENCH_r*.json/TREND history (tools/trend.py declared tolerances) and
    exit nonzero on a violating regression.

    The record under judgment is, in order: an explicit path after
    ``--fence``, ``$BENCH_FENCE_RECORD``, else the NEWEST committed
    BENCH_r*.json (so `bench.py --record && bench.py --fence` is the CI
    gate: measure, snapshot, then refuse the merge if the snapshot
    regressed). Prints one JSON line either way."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    from trend import _load_rounds, fence, recover_record

    import re

    idx = argv.index("--fence")
    path = next((a for a in argv[idx + 1:] if not a.startswith("-")), None)
    path = path or os.environ.get("BENCH_FENCE_RECORD")
    rounds = _load_rounds()
    if path:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            print(json.dumps({"metric": "slo_fence",
                              "error": f"unreadable record {path}: {exc}"}))
            return 2
        # same recovery rule as _load_rounds: parsed, else the record
        # rebuilt from a parsed:null wrapper's stdout tail, else the doc
        # itself (a bare record) — fencing a recoverable snapshot by name
        # must not fail where the no-arg mode would judge it
        current = recover_record(doc) or doc
        # the record under judgment must never be its own baseline: a path
        # naming a committed round (CI fencing the file --record just
        # wrote) drops that round from the prior pool
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.abspath(path))
        if m:
            rounds = [r for r in rounds if r.get("_round") != int(m.group(1))]
    else:
        if not rounds:
            print(json.dumps({"metric": "slo_fence",
                              "error": "no BENCH_r*.json snapshots to judge"}))
            return 2
        # rounds[-1] is the newest RECOVERABLE round; if a newer snapshot
        # exists on disk but was dropped (parsed:null with an unrecoverable
        # tail), judging the older one would green-light the exact run the
        # gate cannot see — refuse instead
        from trend import round_files
        newest = max((n for n, _ in round_files()), default=None)
        if newest is not None and newest != rounds[-1].get("_round"):
            print(json.dumps({"metric": "slo_fence",
                              "error": f"newest snapshot BENCH_r{newest:02d}"
                                       ".json is unjudgeable (parsed:null, "
                                       "unrecoverable tail); refusing to "
                                       "judge an older round in its place"}))
            return 2
        current, rounds = rounds[-1], rounds[:-1]
    if current.get("value") is None:
        # an unjudgeable record (e.g. a parsed:null wrapper) must FAIL the
        # gate distinctly, not sail through with zero checks performed
        print(json.dumps({"metric": "slo_fence",
                          "error": "record carries no judgeable fields "
                                   "(no 'value'); refusing to pass the gate"}))
        return 2
    out = fence(current, rounds)
    out["record"] = path or f"BENCH_r{current.get('_round', '?')}.json"
    if not out["checked"]:
        if out.get("epochBoundary"):
            # a DECLARED platform-epoch boundary (trend.PLATFORM_EPOCHS):
            # earlier rounds exist but were measured on a different
            # environment class, so "no baseline" is the reviewed,
            # committed state — pass with the note, don't fail closed
            print(json.dumps({"metric": "slo_fence", "violations": 0,
                              "fence": out}))
            return 0
        # zero comparisons performed (e.g. no same-platform baseline
        # round): the gate has judged NOTHING and must say so, not pass
        print(json.dumps({"metric": "slo_fence",
                          "error": "no comparison performed "
                                   f"({out.get('note', 'checked=0')}); "
                                   "refusing to pass the gate",
                          "fence": out}))
        return 2
    print(json.dumps({"metric": "slo_fence",
                      "violations": len(out["violations"]), "fence": out}))
    return 1 if out["violations"] else 0


def main():
    if "--fence" in sys.argv:
        raise SystemExit(run_fence(sys.argv))
    child = os.environ.get("BENCH_MATRIX_CHILD")
    if child:
        if os.environ.get("BENCH_PLATFORM_RESOLVED", "").startswith("cpu"):
            from kubernetes_tpu.utils.platform import force_cpu

            force_cpu()
        run_matrix_child(child)
        return

    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_init = int(os.environ.get("BENCH_INIT_PODS", 1000))
    n_measured = int(os.environ.get("BENCH_PODS", 1000))
    n_seq = int(os.environ.get("BENCH_SEQ_PODS", 100))
    batch = int(os.environ.get("BENCH_BATCH", 512))

    platform, probe_diag = _probe_platform()
    if platform.startswith("cpu"):
        from kubernetes_tpu.utils.platform import force_cpu

        force_cpu()
    record = {
        "metric": f"scheduling_throughput SchedulingBasic/{n_nodes}Nodes",
        "value": 0.0,
        "unit": "pods/s",
        "vs_baseline": 0.0,
        "platform": platform,
        # The sequential path is this repo's Python oracle scheduler, NOT the
        # Go kube-scheduler (no Go toolchain in this image) — it is roughly an
        # order of magnitude slower than the Go scheduler it stands in for.
        "baseline": "python-oracle",
        "probe": probe_diag,
        # self-documenting environmental evidence (VERDICT r4 item 2): the
        # continuous watcher's probe-log outcome counts ride in the record
        "probe_log": _probe_log_summary(),
    }
    budget_deadline = time.perf_counter() + float(os.environ.get("BENCH_BUDGET_S", "5400"))
    try:
        tpu_tput, latency, phases, evidence = run_tpu(n_nodes, n_init, n_measured, batch)
        seq_tput = run_sequential(n_nodes, min(100, n_init), n_seq)
        record["value"] = round(tpu_tput, 2)
        record["vs_baseline"] = round(tpu_tput / seq_tput, 2)
        record["attempt_latency_s"] = latency
        record["batch_phase_ms"] = phases
        record["baseline_pods_per_s"] = round(seq_tput, 2)
        record.update(evidence)
        if not platform.startswith("cpu"):
            record["pallas_hw"] = run_pallas_check()
        if os.environ.get("BENCH_AGREEMENT", "1") != "0":
            record["agreement"] = run_agreement()
        if os.environ.get("BENCH_WIRE", "1") != "0":
            record["wire"] = run_wire(min(n_nodes, 1000))
            record["wire_grpc"] = run_wire(min(n_nodes, 1000), backend="grpc")
        if os.environ.get("BENCH_MATRIX", "1") != "0":
            record["workloads"] = run_matrix(budget_deadline, platform)
        _write_trend(record)
    except Exception as exc:  # noqa: BLE001 — a number must always be emitted
        if not platform.startswith("cpu"):
            # Backend died mid-run (probe passed but the tunnel dropped):
            # rerun the whole measurement on CPU in a fresh process. CPU runs
            # never re-enter this branch, so the chain is depth-1; the timeout
            # bounds a wedged child (the JSON contract must hold regardless).
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            try:
                out = subprocess.run(
                    [sys.executable, __file__], capture_output=True, text=True,
                    env=env, timeout=float(os.environ.get("BENCH_RERUN_TIMEOUT", "900")),
                )
                line = (out.stdout.strip().splitlines() or [""])[-1]
                rerun = json.loads(line)
                rerun["platform"] = "cpu-fallback"
                # keep the PARENT's probe evidence + the mid-run error: the
                # child's probe says only "forced-cpu"
                rerun["probe"] = dict(probe_diag, midrun_error=f"{type(exc).__name__}: {exc}"[:200])
                print(json.dumps(rerun))
                return
            except (subprocess.SubprocessError, json.JSONDecodeError, TypeError):
                pass
        record["error"] = f"{type(exc).__name__}: {exc}"[:300]
    print(json.dumps(record))


if __name__ == "__main__":
    main()
