// Native quantity parsing: k8s resource.Quantity strings -> canonical int64.
//
// The framework's host runtime parses resource quantities on every pod/node
// encode (apimachinery pkg/api/resource Quantity semantics). The Python
// implementation (api/resource.py) uses Fraction for exactness; this is the
// same math in exact __int128 integer arithmetic, ~20x faster per call.
//
// Canonical units (must match api/resource.py module doc):
//   class 0: plain integer count, ceil        (pods, extended resources)
//   class 1: millicores, ceil                 (cpu)
//   class 2: KiB, ceil                        (memory)
//   class 3: MiB, ceil                        (ephemeral-storage, hugepages-*)
//
// Exported C ABI (ctypes):
//   int kt_canonical(const char* s, int cls, long long* out)
//     returns 0 on success, nonzero on parse error.
//   long long kt_version()

#include <cstdint>
#include <cstring>

extern "C" {

static const long long KT_ABI_VERSION = 1;

long long kt_version() { return KT_ABI_VERSION; }

// ceil(a / b) for positive b, any-sign a
static __int128 ceil_div(__int128 a, __int128 b) {
    __int128 q = a / b;
    if (a % b != 0 && ((a > 0) == (b > 0))) q += 1;
    return q;
}

int kt_canonical(const char* s, int cls, long long* out) {
    if (!s || !out) return 1;
    // skip leading whitespace
    while (*s == ' ' || *s == '\t') s++;
    int neg = 0;
    if (*s == '+') s++;
    else if (*s == '-') { neg = 1; s++; }

    // mantissa: digits [. digits]; cap significant digits to avoid overflow
    __int128 mant = 0;
    int frac_digits = 0, seen_digit = 0, in_frac = 0, sig = 0;
    for (; *s; s++) {
        char c = *s;
        if (c >= '0' && c <= '9') {
            seen_digit = 1;
            if (sig < 18) {
                mant = mant * 10 + (c - '0');
                sig++;
                if (in_frac) frac_digits++;
            } else if (!in_frac) {
                return 2; // integer part too large to represent
            } else if (c != '0') {
                // A nonzero fractional digit beyond 18 significant digits
                // cannot be represented; silently dropping it can under-shoot
                // the exact ceiling by far more than 1 ulp for large suffixes
                // (e.g. Ei on cpu). Signal failure so the caller falls back to
                // the exact Fraction path in api/resource.py.
                return 7;
            } // trailing fractional zeros beyond 18 sig digits: exactly representable
        } else if (c == '.') {
            if (in_frac) return 3;
            in_frac = 1;
        } else {
            break;
        }
    }
    if (!seen_digit) return 4;

    // suffix: "", Ki..Ei, n/u/m/k/M/G/T/P/E
    __int128 mult_num = 1, mult_den = 1;
    const char* suf = s;
    size_t sl = strlen(suf);
    // trim trailing whitespace
    while (sl > 0 && (suf[sl-1] == ' ' || suf[sl-1] == '\t' || suf[sl-1] == '\n')) sl--;
    if (sl == 2 && suf[1] == 'i') {
        int shift;
        switch (suf[0]) {
            case 'K': shift = 10; break;
            case 'M': shift = 20; break;
            case 'G': shift = 30; break;
            case 'T': shift = 40; break;
            case 'P': shift = 50; break;
            case 'E': shift = 60; break;
            default: return 5;
        }
        mult_num = ((__int128)1) << shift;
    } else if (sl == 1) {
        switch (suf[0]) {
            case 'n': mult_den = 1000000000LL; break;
            case 'u': mult_den = 1000000LL; break;
            case 'm': mult_den = 1000LL; break;
            case 'k': mult_num = 1000LL; break;
            case 'M': mult_num = 1000000LL; break;
            case 'G': mult_num = 1000000000LL; break;
            case 'T': mult_num = 1000000000000LL; break;
            case 'P': mult_num = 1000000000000000LL; break;
            case 'E': mult_num = 1000000000000000000LL; break;
            default: return 5;
        }
    } else if (sl != 0) {
        return 5;
    }

    // unit scale per canonical class
    __int128 un = 1, ud = 1;
    switch (cls) {
        case 0: break;
        case 1: un = 1000; break;                 // cpu -> milli
        case 2: ud = ((__int128)1) << 10; break;  // memory -> KiB
        case 3: ud = ((__int128)1) << 20; break;  // eph/hugepages -> MiB
        default: return 6;
    }

    // 10^frac_digits (frac_digits <= 18)
    __int128 pow10 = 1;
    for (int i = 0; i < frac_digits; i++) pow10 *= 10;

    // result = ceil(mant * mult_num * un / (pow10 * mult_den * ud))
    // overflow guard: mant<=1e18, mult_num<=1e18 -> product <= 1e36; *1000 -> 1e39
    // exceeds int128 (~1.7e38) only for >=15-sig-digit mantissa with E/Ei on cpu;
    // detect and reject that corner rather than wrap.
    __int128 num = mant;
    if (mult_num > 1) {
        if (sig > 18) return 2;
        // mant*mult_num overflow check via division bound
        __int128 lim = (__int128)1;
        lim <<= 126;
        if (mant != 0 && mult_num > lim / (mant ? mant : 1) / (un ? un : 1)) return 2;
        num *= mult_num;
    }
    num *= un;
    __int128 den = pow10 * mult_den * ud;
    __int128 r = ceil_div(neg ? -num : num, den);

    if (r > (__int128)0x7fffffffffffffffLL || r < -(__int128)0x7fffffffffffffffLL) return 2;
    *out = (long long)r;
    return 0;
}

}  // extern "C"
