#!/usr/bin/env python3
"""Vendor the device-service protobuf module without protoc.

Neither ``protoc`` nor ``grpcio-tools`` is in the image, so the gRPC tests
historically skipped (ROADMAP wire hardening).  ``google.protobuf`` (pulled
in by grpcio) is enough, though: a generated ``*_pb2.py`` is just a
serialized FileDescriptorProto handed to the descriptor pool plus the
message-class builder.  This tool parses the subset of proto3 the repo's
wire contracts actually use (top-level messages, scalar/repeated/map/message
fields), builds the FileDescriptorProto by hand, and emits a vendored
module byte-equivalent in behavior to ``protoc --python_out`` output.

    python tools/gen_pb2.py            # (re)generate the vendored module
    python tools/gen_pb2.py --check    # CI gate: exit 1 when the vendored
                                       # module is stale vs the .proto

The drift gate is also registered as the ``pb2-drift`` pass in
tools/ktpu_check.py (``python -m tools.ktpu_check --pass pb2-drift``) —
this CLI stays for direct invocation and regeneration.

The vendored module embeds the source .proto's sha256;
``backend/grpc_service.pb2()`` only trusts it while the hash matches, so a
proto edit without regeneration falls back to protoc (or fails with a
message naming this tool) instead of silently speaking a stale schema.
"""

from __future__ import annotations

import hashlib
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROTO = os.path.join(REPO, "native", "ktpu_device.proto")
OUT = os.path.join(REPO, "kubernetes_tpu", "native", "ktpu_device_pb2.py")

# FieldDescriptorProto.Type values (descriptor.proto) for the scalar subset
SCALARS = {
    "double": 1, "float": 2, "int64": 3, "uint64": 4, "int32": 5,
    "fixed64": 6, "fixed32": 7, "bool": 8, "string": 9, "bytes": 12,
    "uint32": 13, "sfixed32": 15, "sfixed64": 16, "sint32": 17, "sint64": 18,
}
TYPE_MESSAGE = 11
LABEL_OPTIONAL = 1
LABEL_REPEATED = 3


def _strip_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    return re.sub(r"//[^\n]*", "", text)


def parse_proto(text: str):
    """(package, [(msg_name, [field|map-field dict])]) from proto3 source.

    Only the constructs the repo's protos use are accepted; anything else
    (nested messages, enums, oneofs, services) raises so schema drift fails
    loudly here instead of producing a wrong descriptor.
    """
    text = _strip_comments(text)
    m = re.search(r'\bsyntax\s*=\s*"(\w+)"\s*;', text)
    if not m or m.group(1) != "proto3":
        raise ValueError("expected proto3 syntax")
    m = re.search(r"\bpackage\s+([\w.]+)\s*;", text)
    if not m:
        raise ValueError("expected a package statement")
    package = m.group(1)

    messages = []
    body_re = re.compile(r"\bmessage\s+(\w+)\s*\{([^{}]*)\}", flags=re.S)
    consumed = re.sub(r'\bsyntax\s*=\s*"\w+"\s*;|\bpackage\s+[\w.]+\s*;',
                      "", text)
    for m in body_re.finditer(text):
        name, body = m.group(1), m.group(2)
        consumed = consumed.replace(m.group(0), "", 1)
        fields = []
        for stmt in filter(None, (s.strip() for s in body.split(";"))):
            fm = re.fullmatch(
                r"(repeated\s+)?([\w.]+)\s+(\w+)\s*=\s*(\d+)", stmt)
            if fm:
                fields.append({"repeated": bool(fm.group(1)),
                               "type": fm.group(2), "name": fm.group(3),
                               "number": int(fm.group(4))})
                continue
            fm = re.fullmatch(
                r"map\s*<\s*(\w+)\s*,\s*(\w+)\s*>\s*(\w+)\s*=\s*(\d+)", stmt)
            if fm:
                fields.append({"map": (fm.group(1), fm.group(2)),
                               "name": fm.group(3),
                               "number": int(fm.group(4))})
                continue
            raise ValueError(f"unsupported statement in message {name}: "
                             f"{stmt!r}")
        messages.append((name, fields))
    if consumed.strip():
        raise ValueError("unsupported top-level constructs: "
                         f"{consumed.strip()[:120]!r}")
    return package, messages


def _entry_name(field_name: str) -> str:
    # protoc's map-entry naming: CamelCase(field) + "Entry"
    return "".join(p[:1].upper() + p[1:]
                   for p in field_name.split("_")) + "Entry"


def build_file_descriptor(package: str, messages, file_name: str):
    from google.protobuf import descriptor_pb2

    known = {name for name, _fields in messages}

    def set_type(fd, type_name: str, parent: str) -> None:
        if type_name in SCALARS:
            fd.type = SCALARS[type_name]
        elif type_name in known:
            fd.type = TYPE_MESSAGE
            fd.type_name = f".{package}.{type_name}"
        else:
            raise ValueError(f"unknown field type {type_name!r} in {parent}")

    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = file_name
    fdp.package = package
    fdp.syntax = "proto3"
    for msg_name, fields in messages:
        dp = fdp.message_type.add()
        dp.name = msg_name
        for f in fields:
            fd = dp.field.add()
            fd.name = f["name"]
            fd.number = f["number"]
            if "map" in f:
                ktype, vtype = f["map"]
                entry = dp.nested_type.add()
                entry.name = _entry_name(f["name"])
                entry.options.map_entry = True
                for i, (n, t) in enumerate((("key", ktype),
                                            ("value", vtype)), start=1):
                    efd = entry.field.add()
                    efd.name = n
                    efd.number = i
                    efd.label = LABEL_OPTIONAL
                    set_type(efd, t, f"{msg_name}.{entry.name}")
                fd.label = LABEL_REPEATED
                fd.type = TYPE_MESSAGE
                fd.type_name = f".{package}.{msg_name}.{entry.name}"
            else:
                fd.label = LABEL_REPEATED if f["repeated"] else LABEL_OPTIONAL
                set_type(fd, f["type"], msg_name)
    return fdp


TEMPLATE = '''\
# Generated by tools/gen_pb2.py from native/ktpu_device.proto — DO NOT EDIT.
#
# protoc-free equivalent of `protoc --python_out` (neither protoc nor
# grpcio-tools is in the image): the serialized FileDescriptorProto below
# feeds the descriptor pool and the builder materializes the message
# classes, exactly as protoc-generated modules do.  After editing the
# .proto, regenerate with:
#
#     python tools/gen_pb2.py
#
# backend/grpc_service.pb2() only uses this module while PROTO_SHA256
# matches the current .proto source.
"""Vendored protobuf messages for the batched device service wire format."""

from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf.internal import builder as _builder

PROTO_SHA256 = "{sha}"

DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(
    {blob}
)

_globals = globals()
_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, _globals)
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, "ktpu_device_pb2",
                                        _globals)
'''


def _blob_literal(data: bytes, width: int = 70) -> str:
    """The serialized descriptor as an indented parenthesized bytes literal."""
    lines = []
    for i in range(0, len(data), 48):
        chunk = data[i:i + 48]
        lines.append("    " + repr(chunk))
    return "\n".join(lines) if lines else "    b''"


def generate() -> str:
    with open(PROTO, "rb") as f:
        raw = f.read()
    package, messages = parse_proto(raw.decode())
    fdp = build_file_descriptor(package, messages,
                                os.path.basename(PROTO))
    return TEMPLATE.format(sha=hashlib.sha256(raw).hexdigest(),
                           blob=_blob_literal(fdp.SerializeToString()))


def main(argv) -> int:
    content = generate()
    if "--check" in argv:
        try:
            with open(OUT, "r", encoding="utf-8") as f:
                current = f.read()
        except OSError:
            print(f"stale: {OUT} missing; run python tools/gen_pb2.py")
            return 1
        if current != content:
            print(f"stale: {OUT} does not match native/ktpu_device.proto; "
                  "run python tools/gen_pb2.py")
            return 1
        print("ok: vendored ktpu_device_pb2 matches the .proto")
        return 0
    with open(OUT, "w", encoding="utf-8") as f:
        f.write(content)
    print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
