#!/usr/bin/env python3
"""Thin shim over tools/ktpu_check.py (the ``metrics`` + ``spans`` passes).

The dead-metric gate and span-name lint now live in the unified
``ktpu_check`` pass registry; this CLI keeps the historical invocation
(``python tools/check_metrics.py``) and the monkeypatchable module surface
(``PKG``/``METRICS_FILE``/``find_dead_metrics``/...) the tier-1 tests use.
Prefer ``python -m tools.ktpu_check --pass metrics --pass spans``.
"""

from __future__ import annotations

import importlib.util
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(_HERE)
PKG = os.path.join(REPO, "kubernetes_tpu")
METRICS_FILE = os.path.join(PKG, "metrics", "scheduler_metrics.py")
BENCH_FILE = os.path.join(REPO, "bench.py")


def _ktpu_check():
    spec = importlib.util.spec_from_file_location(
        "ktpu_check", os.path.join(_HERE, "ktpu_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_kc = _ktpu_check()
SPAN_IGNORE_PREFIXES = _kc.SPAN_IGNORE_PREFIXES
_MUTATORS = _kc._MUTATORS
registered_metrics = _kc.registered_metrics
helper_map = _kc.helper_map


def find_dead_metrics():
    # reads the module globals at call time so tests can monkeypatch
    # PKG/METRICS_FILE on THIS module and still exercise the real pass
    return _kc.find_dead_metrics(pkg=PKG, metrics_file=METRICS_FILE)


def emitted_span_names(pkg: str = None):
    return _kc.emitted_span_names(pkg or PKG)


def bench_span_table(path: str = None):
    return _kc.bench_span_table(path or BENCH_FILE)


def find_unattributed_spans(pkg: str = None, bench_path: str = None):
    return _kc.find_unattributed_spans(pkg=pkg or PKG,
                                       bench_path=bench_path or BENCH_FILE)


def main() -> int:
    attrs, dead = find_dead_metrics()
    rc = 0
    if dead:
        print(f"DEAD METRICS ({len(dead)}/{len(attrs)}): registered in "
              "SchedulerMetrics but never observed/inc'd/set outside the "
              "definition:")
        for attr in dead:
            print(f"  - {attr}")
        rc = 1
    emitted, unattributed = find_unattributed_spans()
    if unattributed:
        print(f"UNATTRIBUTED SPANS ({len(unattributed)}/{len(emitted)}): "
              "emitted in code but absent from bench.py CRITICAL_PATH_SPANS "
              "and the ignore list:")
        for name in unattributed:
            print(f"  - {name}")
        rc = 1
    if rc == 0:
        print(f"ok: all {len(attrs)} registered scheduler metrics are "
              f"observed; all {len(emitted)} emitted span names attributed")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
