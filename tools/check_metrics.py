#!/usr/bin/env python3
"""Static dead-metric check (tier-1; run by tests/test_check_metrics.py).

Every metric registered in ``SchedulerMetrics.__init__`` must be observed /
incremented / set somewhere in the package outside its definition — either
directly (``smetrics.<attr>.observe(...)``) or through a SchedulerMetrics
helper method that is itself called from outside the metrics module. This
PR fixed a family of defined-but-never-observed metrics
(framework_extension_point_duration, plugin_execution_duration,
queue_incoming_pods, pending_pods, ...); this check keeps them from
reappearing: a new metric that nothing feeds fails tier-1.

Usage: ``python tools/check_metrics.py`` — exits 0 when every metric is
live, 1 with a listing otherwise.
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "kubernetes_tpu")
METRICS_FILE = os.path.join(PKG, "metrics", "scheduler_metrics.py")

# the mutating calls that count as "feeding" a metric
_MUTATORS = ("observe", "inc", "set")


def registered_metrics(tree: ast.Module):
    """Metric attribute names from ``self.<attr> = r.register(...)``
    assignments in SchedulerMetrics.__init__."""
    attrs = []
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and cls.name == "SchedulerMetrics"):
            continue
        for fn in cls.body:
            if not (isinstance(fn, ast.FunctionDef) and fn.name == "__init__"):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                tgt = node.targets[0]
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "register"):
                    attrs.append(tgt.attr)
    return attrs


def helper_map(tree: ast.Module):
    """SchedulerMetrics method name → set of metric attrs it mutates
    (``self.<attr>.<mutator>(...)`` calls inside the method)."""
    out = {}
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and cls.name == "SchedulerMetrics"):
            continue
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef) or fn.name == "__init__":
                continue
            touched = set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS
                        and isinstance(node.func.value, ast.Attribute)
                        and isinstance(node.func.value.value, ast.Name)
                        and node.func.value.value.id == "self"):
                    touched.add(node.func.value.attr)
            if touched:
                out[fn.name] = touched
    return out


def package_sources():
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if f.endswith(".py"):
                path = os.path.join(root, f)
                with open(path, encoding="utf-8") as fh:
                    yield path, fh.read()


def find_dead_metrics():
    tree = ast.parse(open(METRICS_FILE, encoding="utf-8").read())
    attrs = registered_metrics(tree)
    helpers = helper_map(tree)

    outside = []  # package sources excluding the definition module
    for path, text in package_sources():
        if os.path.abspath(path) == os.path.abspath(METRICS_FILE):
            continue
        outside.append(text)
    blob = "\n".join(outside)

    # which helper methods are actually invoked outside the metrics module
    live_helpers = {name for name in helpers
                    if re.search(rf"\.{name}\s*\(", blob)}

    dead = []
    for attr in attrs:
        direct = re.search(
            rf"\.{attr}\.(?:{'|'.join(_MUTATORS)})\s*\(", blob)
        via_helper = any(attr in helpers[h] for h in live_helpers)
        if not direct and not via_helper:
            dead.append(attr)
    return attrs, dead


def main() -> int:
    attrs, dead = find_dead_metrics()
    if dead:
        print(f"DEAD METRICS ({len(dead)}/{len(attrs)}): registered in "
              "SchedulerMetrics but never observed/inc'd/set outside the "
              "definition:")
        for attr in dead:
            print(f"  - {attr}")
        return 1
    print(f"ok: all {len(attrs)} registered scheduler metrics are observed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
