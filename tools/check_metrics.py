#!/usr/bin/env python3
"""Static dead-metric check + span-name lint (tier-1; run by
tests/test_check_metrics.py).

Dead metrics: every metric registered in ``SchedulerMetrics.__init__`` must
be observed / incremented / set somewhere in the package outside its
definition — either directly (``smetrics.<attr>.observe(...)``) or through
a SchedulerMetrics helper method that is itself called from outside the
metrics module. A new metric that nothing feeds fails tier-1.

Span lint: every span name emitted in the package (``tracing.span("...")``
/ ``span_from_remote(..., "...")``) must appear in bench.py's critical-path
attribution table (``CRITICAL_PATH_SPANS``) or match an entry in the
explicit ignore list below. Without this, a new phase span silently falls
into the attribution's "other" bucket and the bench's critical-path story
quietly stops adding up.

Usage: ``python tools/check_metrics.py`` — exits 0 when every metric is
live and every span is attributed, 1 with a listing otherwise.
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "kubernetes_tpu")
METRICS_FILE = os.path.join(PKG, "metrics", "scheduler_metrics.py")
BENCH_FILE = os.path.join(REPO, "bench.py")

# the mutating calls that count as "feeding" a metric
_MUTATORS = ("observe", "inc", "set")

# span names (prefix match) consciously OUTSIDE the bench critical-path
# attribution: the sampled per-extension-point / per-plugin spans are
# latency *exemplars*, not cycle phases
SPAN_IGNORE_PREFIXES = ("framework.", "plugin.")


def registered_metrics(tree: ast.Module):
    """Metric attribute names from ``self.<attr> = r.register(...)``
    assignments in SchedulerMetrics.__init__."""
    attrs = []
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and cls.name == "SchedulerMetrics"):
            continue
        for fn in cls.body:
            if not (isinstance(fn, ast.FunctionDef) and fn.name == "__init__"):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                tgt = node.targets[0]
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "register"):
                    attrs.append(tgt.attr)
    return attrs


def helper_map(tree: ast.Module):
    """SchedulerMetrics method name → set of metric attrs it mutates
    (``self.<attr>.<mutator>(...)`` calls inside the method)."""
    out = {}
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and cls.name == "SchedulerMetrics"):
            continue
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef) or fn.name == "__init__":
                continue
            touched = set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS
                        and isinstance(node.func.value, ast.Attribute)
                        and isinstance(node.func.value.value, ast.Name)
                        and node.func.value.value.id == "self"):
                    touched.add(node.func.value.attr)
            if touched:
                out[fn.name] = touched
    return out


def package_sources():
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if f.endswith(".py"):
                path = os.path.join(root, f)
                with open(path, encoding="utf-8") as fh:
                    yield path, fh.read()


def find_dead_metrics():
    tree = ast.parse(open(METRICS_FILE, encoding="utf-8").read())
    attrs = registered_metrics(tree)
    helpers = helper_map(tree)

    outside = []  # package sources excluding the definition module
    for path, text in package_sources():
        if os.path.abspath(path) == os.path.abspath(METRICS_FILE):
            continue
        outside.append(text)
    blob = "\n".join(outside)

    # which helper methods are actually invoked outside the metrics module
    live_helpers = {name for name in helpers
                    if re.search(rf"\.{name}\s*\(", blob)}

    dead = []
    for attr in attrs:
        direct = re.search(
            rf"\.{attr}\.(?:{'|'.join(_MUTATORS)})\s*\(", blob)
        via_helper = any(attr in helpers[h] for h in live_helpers)
        if not direct and not via_helper:
            dead.append(attr)
    return attrs, dead


# ---------------------------------------------------------------- span lint


def _literal_prefix(node):
    """(value, exact) for a span-name argument: a plain string constant is
    exact; an f-string / ``"prefix" + expr`` concatenation contributes its
    leading literal as a prefix; anything else is unlintable (None)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, True
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                break
        return ("".join(parts), False) if parts else (None, False)
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add)
            and isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)):
        return node.left.value, False
    return None, False


def emitted_span_names(pkg: str = None):
    """(exact names, dynamic prefixes) of every span the package emits:
    ``<anything>.span("name", ...)`` and
    ``<anything>.span_from_remote(tp, "name", ...)`` calls."""
    names, prefixes = set(), set()
    for root, _dirs, files in os.walk(pkg or PKG):
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            try:
                tree = ast.parse(open(path, encoding="utf-8").read())
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                arg = None
                if node.func.attr in ("span", "span_remote") and node.args:
                    arg = node.args[0]
                elif node.func.attr == "span_from_remote" and len(node.args) >= 2:
                    arg = node.args[1]
                if arg is None:
                    continue
                val, exact = _literal_prefix(arg)
                if val is None:
                    continue
                (names if exact else prefixes).add(val)
    return names, prefixes


def bench_span_table(path: str = None):
    """The ``CRITICAL_PATH_SPANS`` literal from bench.py, via AST (importing
    bench.py would drag the whole package + jax into a lint)."""
    tree = ast.parse(open(path or BENCH_FILE, encoding="utf-8").read())
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and tgt.id == "CRITICAL_PATH_SPANS"):
            continue
        consts = [n.value for n in ast.walk(node.value)
                  if isinstance(n, ast.Constant) and isinstance(n.value, str)]
        return set(consts)
    return set()


def find_unattributed_spans(pkg: str = None, bench_path: str = None):
    """(emitted, unattributed): span names/prefixes neither in bench.py's
    attribution table nor matched by SPAN_IGNORE_PREFIXES."""
    names, prefixes = emitted_span_names(pkg)
    table = bench_span_table(bench_path)
    bad = [n for n in sorted(names)
           if n not in table and not n.startswith(SPAN_IGNORE_PREFIXES)]
    for p in sorted(prefixes):
        if p.startswith(SPAN_IGNORE_PREFIXES):
            continue
        if any(t.startswith(p) for t in table):
            continue
        bad.append(p + "*")
    return sorted(names | prefixes), bad


def main() -> int:
    attrs, dead = find_dead_metrics()
    rc = 0
    if dead:
        print(f"DEAD METRICS ({len(dead)}/{len(attrs)}): registered in "
              "SchedulerMetrics but never observed/inc'd/set outside the "
              "definition:")
        for attr in dead:
            print(f"  - {attr}")
        rc = 1
    emitted, unattributed = find_unattributed_spans()
    if unattributed:
        print(f"UNATTRIBUTED SPANS ({len(unattributed)}/{len(emitted)}): "
              "emitted in code but absent from bench.py CRITICAL_PATH_SPANS "
              "and the ignore list:")
        for name in unattributed:
            print(f"  - {name}")
        rc = 1
    if rc == 0:
        print(f"ok: all {len(attrs)} registered scheduler metrics are "
              f"observed; all {len(emitted)} emitted span names attributed")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
