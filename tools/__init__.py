"""Repo tooling (``python -m tools.ktpu_check``, trend/fence, pb2 vendoring)."""
