"""Cross-round bench trend (ROADMAP r3 item 8 / VERDICT r4 item 2).

Compares the current bench record against every committed BENCH_r*.json and
writes TREND.md + TREND.json at the repo root, flagging >20% regressions on
the headline and per-phase metrics so a regression fails loudly at snapshot
time instead of surfacing one round later in a verdict.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_REGRESSION_PCT = 20.0

# rounds whose numbers a verdict invalidated (r2's commit chain was broken —
# VERDICT r2/r3 — so its throughput/latency/commit figures measured a
# scheduler that skipped real adoption); shown in the table, excluded from
# regression baselines
_INVALID_ROUNDS = {1, 2}

# Platform epochs: the first round measured on a NEW execution-environment
# class. Rounds before a boundary are not comparable baselines for rounds
# at/after it — same-box A/B is the only honest comparison across such a
# change. Declared explicitly (like _INVALID_ROUNDS and the per-workload
# tolerance overrides) so the fence never quietly decides on its own that
# a uniform slowdown is "just the box": adding an entry here IS the
# reviewed human judgment, and the fence treats the boundary round's
# missing baseline as a documented state, not an accidental one.
#   r06: bench moved to a 2-core CI container ~4x slower than the r01-r05
#   box (uniform drop across ALL workloads incl. ones the r06 diff never
#   touched; interleaved same-container A/B of the r06 code vs its parent
#   commit showed parity).
PLATFORM_EPOCHS = {6: "2-core CI container (r06+); r01-r05 ran on a "
                      "~4x faster box"}


def _epoch_start(round_no=None) -> int:
    """First round of the platform epoch ``round_no`` belongs to (0 = the
    original epoch). A record with no round number — a fresh, not-yet-
    committed bench run — is by definition measured on the CURRENT
    environment class, i.e. the newest epoch."""
    if not isinstance(round_no, int):
        return max(PLATFORM_EPOCHS, default=0)
    return max((s for s in PLATFORM_EPOCHS if s <= round_no), default=0)


def round_files() -> List[tuple]:
    """Sorted ``(round, path)`` for every committed BENCH_r*.json — the ONE
    place that knows the snapshot naming/location rule (the fence's
    newest-on-disk refusal check must agree with the loader it guards)."""
    out = []
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            out.append((int(m.group(1)), path))
    # numeric, not lexicographic: 'BENCH_r100' sorts before 'BENCH_r99' as
    # a string, and "newest round" must mean the highest number
    return sorted(out)


def recover_record(doc: dict) -> dict:
    """Extract the bench record from a round wrapper: ``parsed`` when
    present, else the one-JSON-line contract recovered from the raw stdout
    tail (some rounds carry parsed=null with the record only in the tail —
    r05's shape). Returns {} when nothing judgeable can be recovered."""
    rec = doc.get("parsed") or {}
    if rec:
        return rec
    tail = doc.get("tail") or ""
    for line in reversed(tail.splitlines() if isinstance(tail, str) else []):
        line = line.strip()
        start = line.find('{"')
        if start < 0:
            continue
        try:
            cand = json.loads(line[start:])
        except json.JSONDecodeError:
            continue
        if isinstance(cand, dict) and "value" in cand:
            return cand
    return {}


def _load_rounds() -> List[dict]:
    rounds = []
    for rno, path in round_files():
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        rec = recover_record(doc)
        if rec:
            rec = dict(rec, _round=rno)
            rounds.append(rec)
    return rounds


def _row(rec: dict) -> dict:
    lat = rec.get("attempt_latency_s") or {}
    ph = rec.get("batch_phase_ms") or {}
    wire = rec.get("wire") or {}
    grpc = rec.get("wire_grpc") or {}
    return {
        "round": rec.get("_round", "now"),
        "platform": rec.get("platform", "?"),
        "pods_per_s": rec.get("value", 0.0),
        "vs_baseline": rec.get("vs_baseline", 0.0),
        "p99_s": lat.get("p99"),
        "commit_ms": ph.get("commit"),
        "wire_pods_per_s": wire.get("pods_per_s"),
        "grpc_pods_per_s": grpc.get("pods_per_s"),
        "comparer_mismatches": rec.get("comparer_mismatches"),
    }


def _flag_regressions(rows: List[dict]) -> List[str]:
    """Compare the newest row against the best prior SAME-PLATFORM row
    (cpu-fallback vs a TPU round is not a regression signal)."""
    if len(rows) < 2:
        return []
    cur = rows[-1]
    fam = "cpu" if str(cur["platform"]).startswith("cpu") else "acc"
    epoch = _epoch_start(cur.get("round"))
    prior = [r for r in rows[:-1]
             if (str(r["platform"]).startswith("cpu")) == (fam == "cpu")
             and r.get("round") not in _INVALID_ROUNDS
             and (not isinstance(r.get("round"), int)
                  or r["round"] >= epoch)]
    if not prior:
        return []
    flags = []

    def worst(metric: str, higher_is_better: bool, fmt: str) -> None:
        vals = [r[metric] for r in prior if r.get(metric)]
        c = cur.get(metric)
        if not vals or not c:
            return
        best = max(vals) if higher_is_better else min(vals)
        ratio = (best - c) / best if higher_is_better else (c - best) / best
        if ratio * 100 > _REGRESSION_PCT:
            flags.append(fmt.format(cur=c, best=best, pct=ratio * 100))

    worst("pods_per_s", True,
          "headline {cur:.1f} pods/s is {pct:.0f}% below best prior {best:.1f}")
    worst("p99_s", False,
          "attempt p99 {cur:.2f}s is {pct:.0f}% above best prior {best:.2f}s")
    worst("commit_ms", False,
          "commit phase {cur:.0f}ms is {pct:.0f}% above best prior {best:.0f}ms")
    worst("wire_pods_per_s", True,
          "wire row {cur:.1f} pods/s is {pct:.0f}% below best prior {best:.1f}")
    worst("grpc_pods_per_s", True,
          "grpc row {cur:.1f} pods/s is {pct:.0f}% below best prior {best:.1f}")
    return flags


# --------------------------------------------------------------- SLO fence
#
# Declared tolerances for `bench.py --fence` (percent). The trend table
# above is *evidence* (flags in a Markdown file); the fence is a *gate*: a
# violation exits nonzero, so CI can refuse a regression instead of a
# verdict discovering it one round later.
FENCE_TOLERANCES = {
    "pods_per_s": 25.0,            # headline throughput: % below baseline
    "p99_s": 50.0,                 # headline attempt p99: % above baseline
    "workload_pods_per_s": 40.0,   # per-workload matrix throughput
    "workload_p99_s": 100.0,       # per-workload attempt p99
    # pod end-to-end p99 (latency ledger, first recorded r11+): e2e spans
    # every attempt — backoff requeues quantize it in ~1s steps and queue
    # dwell scales with arrival burstiness, so the tolerances are one
    # notch looser than the attempt-p99 rows they wrap
    "e2e_p99_s": 100.0,            # headline pod e2e p99
    "workload_e2e_p99_s": 200.0,   # per-workload pod e2e p99
    # SchedulingSlices row (first recorded r15+): slice wait p99 reads
    # from the same ~2x histogram buckets as the other p99 rows; frag_max
    # is a placement-quality score in [0, 1] that shifts with the gang
    # mix, so both fences are loose. check() skips when either round
    # lacks the row (pre-slice baselines, or a budget-skipped matrix).
    "workload_slice_wait_p99_s": 200.0,
    "workload_slice_frag_max": 75.0,
    # dispatch-profiler rows (first recorded r17+): per-batch device time
    # from the commit-wait waterfall (commit_wait_breakdown, bench.py).
    # Exec ms/batch is the XLA program's device run time — it tracks the
    # box's bimodal throughput modes (~2x swings, see the A/A overrides
    # above), so the fence is one notch looser than commit_ms. Fetch
    # ms/batch adds the device->host readback, which on CPU is a memcpy
    # whose cost is mostly scheduling noise — loosest of the family.
    # check() skips when either round lacks the block (pre-profiler
    # baselines, or a run with tracing disabled).
    "device_exec_ms_per_batch": 150.0,
    "device_fetch_ms_per_batch": 250.0,
    # SchedulingReplay row (first recorded r18+): packing efficiency is
    # 1 - mean normalized entropy in [0, 1] — a placement-quality score
    # that shifts with the churned arrival mix, so the fence is loose;
    # the tenant p99 reads from the same ~2x e2e histogram buckets as
    # the other e2e rows. check() skips when either round lacks the row
    # (pre-replay baselines, or a budget-skipped matrix).
    "workload_replay_packing_eff": 40.0,
    "workload_replay_tenant_p99_s": 200.0,
    # SchedulingBorrow row (first recorded r19+): util_lift is the A/B
    # pool-utilization delta (borrowing ON − OFF, in [0, 1]) — the
    # headline "borrowing un-strands lender headroom" number, judged
    # higher-is-better; lender_p99_on_s reads from the same ~2x e2e
    # histogram buckets as the other e2e rows (one bucket step ~100%),
    # and reclaim latency rides the housekeeping sweep cadence, so the
    # fence is loose. check() skips when either round lacks the block
    # (pre-borrowing baselines, or a budget-skipped matrix).
    "workload_borrow_util_lift": 50.0,
    "workload_borrow_lender_p99_s": 200.0,
}
# per-workload overrides for rows whose history is structurally volatile
# (PreemptionBasic swung 2953 -> 69 -> 243 pods/s across r02-r05 as the
# screen/batching strategy changed; a tight fence there would only flap)
FENCE_WORKLOAD_OVERRIDES = {
    "PreemptionBasic": {"workload_pods_per_s": 85.0, "workload_p99_s": 300.0},
    # r07 A/A evidence (two --record runs of the IDENTICAL tree, 40 min
    # apart, on the r06 2-core container): PreemptionPVs 46.9 -> 609.0
    # pods/s (13x) and PreemptionBasic 590.7 -> 35.3 (17x) — the
    # preemption rows share PreemptionBasic's structural volatility on
    # this box. The attempt-p99 rows are read from histogram buckets
    # (~2x spacing: 3.776 -> 7.872), so ONE bucket step reads as
    # ~100-108% and flaps a 100% tolerance.
    "PreemptionPVs": {"workload_pods_per_s": 85.0, "workload_p99_s": 300.0},
    # SchedulingPodAffinity swings across the box's bimodal modes
    # (r06 35.2 vs same-code r07 runs 20.8 / 23.5 pods/s) — a 40%/100%
    # fence there flaps on mode, not on code.
    "SchedulingPodAffinity": {"workload_pods_per_s": 60.0,
                              "workload_p99_s": 200.0},
    # r10 A/A evidence (three runs of the IDENTICAL tree on the r06+
    # container class): SchedulingNodeAffinity 393.9 / 796.7 / 639.5
    # pods/s and SchedulingSecrets 335.9 / 726.1 / 506.5 — ~2x swings on
    # box mode with the standalone runs ABOVE the r09 baseline, so the
    # 40%/100% default flaps on mode, not on code. The p99 rows read from
    # ~2x-spaced histogram buckets, where one bucket step is ~100%.
    "SchedulingNodeAffinity": {"workload_pods_per_s": 60.0,
                               "workload_p99_s": 200.0},
    "SchedulingSecrets": {"workload_pods_per_s": 60.0,
                          "workload_p99_s": 200.0},
    # p99 history 0.256 -> 0.341 -> 0.507 -> 0.127 -> 0.255 across
    # r06-r10: the row bounces between adjacent ~2x histogram buckets
    # (one step = ~100%), with r09 its best-ever bucket — a 100% p99
    # fence against r09 flaps on bucket quantization, not on code
    # (throughput stays inside the default tolerance).
    "SchedulingPreferredPodAffinity": {"workload_p99_s": 200.0},
}


def _same_platform(a: dict, b: dict) -> bool:
    return (str(a.get("platform", "")).startswith("cpu")
            == str(b.get("platform", "")).startswith("cpu"))


def fence(current: dict, rounds: Optional[List[dict]] = None) -> dict:
    """Judge ``current`` against the newest valid same-platform prior round
    (comparing cpu-fallback numbers against a TPU round is noise, not a
    regression signal). Returns {"baselineRound", "checked", "violations",
    "tolerances"}; an empty violations list means the fence holds."""
    if rounds is None:
        rounds = _load_rounds()
    epoch = _epoch_start(current.get("_round"))
    comparable = [r for r in rounds
                  if r.get("_round") not in _INVALID_ROUNDS
                  and _same_platform(r, current)]
    prior = [r for r in comparable if r.get("_round", 0) >= epoch]
    if not prior:
        out = {"baselineRound": None, "checked": 0, "violations": [],
               "tolerances": FENCE_TOLERANCES,
               "note": "no valid same-platform baseline round"}
        if comparable and epoch in PLATFORM_EPOCHS:
            # emptiness is the DECLARED epoch boundary, not accidental
            # baseline loss: earlier rounds exist but were measured on a
            # different environment class (see PLATFORM_EPOCHS)
            out["epochBoundary"] = PLATFORM_EPOCHS[epoch]
            out["note"] = (f"first comparable round of platform epoch "
                           f"r{epoch:02d}: {PLATFORM_EPOCHS[epoch]}")
        return out
    base = prior[-1]
    violations: List[str] = []
    checked = 0

    def check(label: str, cur, ref, tol_pct: float,
              higher_is_better: bool) -> None:
        nonlocal checked
        # a current value of 0 (total collapse) is the WORST regression,
        # not a missing metric — only None/absent (or a zero baseline the
        # ratio can't be computed against) skips the check
        if cur is None or ref is None or not ref:
            return
        checked += 1
        if higher_is_better:
            floor = ref * (1.0 - tol_pct / 100.0)
            if cur < floor:
                violations.append(
                    f"{label}: {cur:.2f} is {100.0 * (ref - cur) / ref:.0f}% "
                    f"below baseline {ref:.2f} (tolerance {tol_pct:.0f}%)")
        else:
            ceil = ref * (1.0 + tol_pct / 100.0)
            if cur > ceil:
                violations.append(
                    f"{label}: {cur:.4f} is {100.0 * (cur - ref) / ref:.0f}% "
                    f"above baseline {ref:.4f} (tolerance {tol_pct:.0f}%)")

    tol = FENCE_TOLERANCES
    check("headline pods/s", current.get("value"), base.get("value"),
          tol["pods_per_s"], True)
    check("headline attempt p99",
          (current.get("attempt_latency_s") or {}).get("p99"),
          (base.get("attempt_latency_s") or {}).get("p99"),
          tol["p99_s"], False)
    # pod e2e p99 (latency ledger): judged only when BOTH rounds recorded
    # it — pre-ledger baselines skip the check rather than fake a pass
    check("headline e2e p99",
          (current.get("e2e_latency_s") or {}).get("p99"),
          (base.get("e2e_latency_s") or {}).get("p99"),
          tol["e2e_p99_s"], False)
    # dispatch-profiler waterfall (skip-when-absent: rounds before the
    # profiler, or runs without span capture, carry no breakdown block)
    cur_cwb = ((current.get("commit_wait_breakdown") or {})
               .get("phase_ms_per_batch") or {})
    base_cwb = ((base.get("commit_wait_breakdown") or {})
                .get("phase_ms_per_batch") or {})
    check("device exec ms/batch", cur_cwb.get("exec"), base_cwb.get("exec"),
          tol["device_exec_ms_per_batch"], False)
    check("device fetch ms/batch", cur_cwb.get("fetch"), base_cwb.get("fetch"),
          tol["device_fetch_ms_per_batch"], False)
    cur_wl = current.get("workloads") or {}
    base_wl = base.get("workloads") or {}
    for name in sorted(set(cur_wl) & set(base_wl)):
        c, b = cur_wl[name], base_wl[name]
        if not isinstance(c, dict) or not isinstance(b, dict):
            continue
        if "error" in c or "skipped" in c or "error" in b or "skipped" in b:
            continue
        over = FENCE_WORKLOAD_OVERRIDES.get(name, {})
        check(f"workload {name} pods/s", c.get("pods_per_s"),
              b.get("pods_per_s"),
              over.get("workload_pods_per_s", tol["workload_pods_per_s"]),
              True)
        check(f"workload {name} attempt p99", c.get("attempt_p99_s"),
              b.get("attempt_p99_s"),
              over.get("workload_p99_s", tol["workload_p99_s"]), False)
        check(f"workload {name} e2e p99", c.get("e2e_p99_s"),
              b.get("e2e_p99_s"),
              over.get("workload_e2e_p99_s", tol["workload_e2e_p99_s"]),
              False)
        # slice-packing rows only (skip-when-absent via check()'s None
        # guard: non-slice workloads carry no "slices" block)
        check(f"workload {name} slice wait p99",
              (c.get("slices") or {}).get("wait_p99_s"),
              (b.get("slices") or {}).get("wait_p99_s"),
              over.get("workload_slice_wait_p99_s",
                       tol["workload_slice_wait_p99_s"]), False)
        check(f"workload {name} slice frag max",
              (c.get("slices") or {}).get("frag_max"),
              (b.get("slices") or {}).get("frag_max"),
              over.get("workload_slice_frag_max",
                       tol["workload_slice_frag_max"]), False)
        # trace-replay rows only (same skip-when-absent): packing
        # efficiency must not decay, and rebalancing must never cost a
        # tenant its e2e p99 — the ISSUE 18 acceptance pair
        check(f"workload {name} replay packing eff",
              (c.get("replay") or {}).get("packing_eff"),
              (b.get("replay") or {}).get("packing_eff"),
              over.get("workload_replay_packing_eff",
                       tol["workload_replay_packing_eff"]), True)
        check(f"workload {name} replay tenant p99",
              (c.get("replay") or {}).get("tenant_p99_s"),
              (b.get("replay") or {}).get("tenant_p99_s"),
              over.get("workload_replay_tenant_p99_s",
                       tol["workload_replay_tenant_p99_s"]), False)
        # cohort-borrowing rows only (same skip-when-absent): the A/B
        # utilization lift must not decay, and funding the lender's
        # wake-up by reclaim must never cost the lender its e2e p99 —
        # the ISSUE 19 acceptance pair
        check(f"workload {name} borrow util lift",
              (c.get("borrowing") or {}).get("util_lift"),
              (b.get("borrowing") or {}).get("util_lift"),
              over.get("workload_borrow_util_lift",
                       tol["workload_borrow_util_lift"]), True)
        check(f"workload {name} borrow lender p99",
              (c.get("borrowing") or {}).get("lender_p99_on_s"),
              (b.get("borrowing") or {}).get("lender_p99_on_s"),
              over.get("workload_borrow_lender_p99_s",
                       tol["workload_borrow_lender_p99_s"]), False)
    return {"baselineRound": base.get("_round"), "checked": checked,
            "violations": violations, "tolerances": FENCE_TOLERANCES}


def write_trend(current: Optional[dict] = None) -> dict:
    rows = [_row(r) for r in _load_rounds()]
    if current is not None:
        rows.append(_row(dict(current, _round="now")))
    flags = _flag_regressions(rows)
    doc = {"rows": rows, "regressions": flags,
           "threshold_pct": _REGRESSION_PCT}
    with open(os.path.join(REPO, "TREND.json"), "w") as f:
        json.dump(doc, f, indent=2)
    lines = [
        "# Bench trend (generated by bench.py via tools/trend.py)",
        "",
        "| round | platform | pods/s | vs_base | p99 s | commit ms | wire | grpc | cmp miss |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            "| {round} | {platform} | {pods_per_s} | {vs_baseline} | {p99_s} "
            "| {commit_ms} | {wire_pods_per_s} | {grpc_pods_per_s} "
            "| {comparer_mismatches} |".format(
                **{k: ("—" if v is None else v) for k, v in r.items()}))
    lines.append("")
    if flags:
        lines.append(f"## REGRESSIONS (> {_REGRESSION_PCT:.0f}% vs best same-platform prior)")
        lines += [f"- {f}" for f in flags]
    else:
        lines.append("No regressions beyond threshold against prior rounds.")
    lines.append("")
    with open(os.path.join(REPO, "TREND.md"), "w") as f:
        f.write("\n".join(lines))
    return doc


if __name__ == "__main__":
    doc = write_trend()
    print(json.dumps({"rows": len(doc["rows"]),
                      "regressions": doc["regressions"]}))
