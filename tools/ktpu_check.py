#!/usr/bin/env python3
"""ktpu-check: the unified static-analysis driver for this repo.

One registry of analysis passes, one CLI, one exit code — the
``hack/verify-*`` + ``go vet`` + race-discipline role Kubernetes gets from
its toolchain, rebuilt for this Python/JAX port. Every pass is a pure
function over the source tree returning findings; the driver runs them all
(``--all``) or selectively (``--pass NAME``), prints a listing, and exits
nonzero on any finding.

Passes
======

``metrics``    dead-metric gate: every metric registered in
               SchedulerMetrics must be fed outside its definition.
``spans``      span-name lint: every emitted span name must be in bench.py's
               critical-path attribution table (or the ignore list).
``markers``    perf-scale tests must carry ``@pytest.mark.slow``.
``pb2-drift``  the vendored ktpu_device_pb2 module must match the .proto.
``locks``      lock-discipline: per class, attributes accessed under
               ``with self._lock`` must not be touched unguarded elsewhere.
``jit``        jit-boundary: functions reachable from the jitted entry
               points must not host-sync traced values (int()/float()/
               bool()/.item()/np.asarray), branch on them in Python, or
               declare unhashable static args.
``errors``     error taxonomy: ``backend/`` raises use the typed taxonomy
               (backend/errors.py); broad ``except Exception`` handlers
               reclassify or carry a reviewed justification.
``suppress``   suppression hygiene: every ``# ktpu: *-ok(...)`` marker
               carries a non-empty reason (an exception without a reason is
               itself a finding — every suppression is a reviewed decision).

Suppression grammar (all per-line, reason mandatory)
====================================================

    # ktpu: unguarded-ok(reason)      silence one locks finding
    # ktpu: host-sync-ok(reason)      silence one jit finding
    # ktpu: taxonomy-ok(reason)       silence one errors raise finding
    # ktpu: broad-except-ok(reason)   justify one broad except handler
    # ktpu: locked                    on a ``def`` line: the function runs
                                      with its class lock held by contract
                                      (callers acquire it) — its accesses
                                      count as guarded

Usage
=====

    python -m tools.ktpu_check --all            # every pass, exit 1 on any
    python -m tools.ktpu_check --pass locks     # one pass
    python -m tools.ktpu_check --all --json     # machine-readable (trends)
    python -m tools.ktpu_check --list           # registry

The old CLIs (``tools/check_metrics.py``, ``tools/check_markers.py``,
``tools/gen_pb2.py --check``) remain as thin shims over this registry.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "kubernetes_tpu")
TESTS = os.path.join(REPO, "tests")
METRICS_FILE = os.path.join(PKG, "metrics", "scheduler_metrics.py")
BENCH_FILE = os.path.join(REPO, "bench.py")


class Finding(NamedTuple):
    path: str
    line: int
    message: str

    def render(self) -> str:
        rel = os.path.relpath(self.path, REPO) if os.path.isabs(self.path) else self.path
        return f"{rel}:{self.line} {self.message}"


# --------------------------------------------------------------- registry

PASSES: "Dict[str, tuple]" = {}


def register(name: str, description: str):
    def deco(fn):
        PASSES[name] = (fn, description)
        return fn

    return deco


def _walk_py(root: str):
    for dirpath, _dirs, files in os.walk(root):
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as fh:
        return fh.read()


# ----------------------------------------------------------- suppressions

_SUPPRESS_RE = re.compile(
    r"#\s*ktpu:\s*(unguarded-ok|host-sync-ok|taxonomy-ok|broad-except-ok"
    r"|dispatch-ok)"
    r"\s*\(([^)]*)\)")
_LOCKED_RE = re.compile(r"#\s*ktpu:\s*locked\b")
_ANY_MARKER_RE = re.compile(r"#\s*ktpu:\s*([\w-]+)")


class _Suppressions:
    """Per-file ``# ktpu:`` marker index. A finding at line L is suppressed
    when L (or the statement's first line) carries the matching marker WITH
    a non-empty reason; empty reasons are surfaced by the ``suppress``
    pass, not honored here."""

    def __init__(self, src: str):
        self.by_line: Dict[int, List[Tuple[str, str]]] = {}
        self.locked_lines: Set[int] = set()
        for i, line in enumerate(src.splitlines(), start=1):
            for m in _SUPPRESS_RE.finditer(line):
                self.by_line.setdefault(i, []).append(
                    (m.group(1), m.group(2).strip()))
            if _LOCKED_RE.search(line):
                self.locked_lines.add(i)

    def silences(self, marker: str, *lines: int) -> bool:
        for ln in lines:
            for kind, reason in self.by_line.get(ln, ()):
                if kind == marker and reason:
                    return True
        return False


def _suppression_files():
    yield from _walk_py(PKG)
    yield BENCH_FILE
    for f in sorted(os.listdir(os.path.join(REPO, "tools"))):
        if f.endswith(".py"):
            yield os.path.join(REPO, "tools", f)


@register("suppress", "every # ktpu marker is well-formed and carries a reason")
def pass_suppress(files=None) -> List[Finding]:
    known = {"unguarded-ok", "host-sync-ok", "taxonomy-ok", "broad-except-ok",
             "dispatch-ok", "locked"}
    out: List[Finding] = []
    for path in (files if files is not None else _suppression_files()):
        try:
            src = _read(path)
        except OSError:
            continue
        if "ktpu:" not in src:
            continue
        for i, line in enumerate(src.splitlines(), start=1):
            m = _ANY_MARKER_RE.search(line)
            if not m:
                continue
            kind = m.group(1)
            if kind not in known:
                out.append(Finding(path, i, f"unknown ktpu marker {kind!r} "
                                   f"(known: {sorted(known)})"))
                continue
            if kind == "locked":
                continue
            sm = _SUPPRESS_RE.search(line)
            if sm is None:
                out.append(Finding(
                    path, i, f"malformed suppression '# ktpu: {kind}': "
                    "expected '(reason)'"))
            elif not sm.group(2).strip():
                out.append(Finding(
                    path, i, f"suppression '# ktpu: {kind}()' has no reason "
                    "— every exception is a reviewed decision"))
    return out


# ===================================================================== metrics
# (absorbed from tools/check_metrics.py — the PR-2 dead-metric gate)

_MUTATORS = ("observe", "inc", "set")


def registered_metrics(tree: ast.Module):
    """Metric attribute names from ``self.<attr> = r.register(...)``
    assignments in SchedulerMetrics.__init__."""
    attrs = []
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and cls.name == "SchedulerMetrics"):
            continue
        for fn in cls.body:
            if not (isinstance(fn, ast.FunctionDef) and fn.name == "__init__"):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                tgt = node.targets[0]
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "register"):
                    attrs.append(tgt.attr)
    return attrs


def helper_map(tree: ast.Module):
    """SchedulerMetrics method name → set of metric attrs it mutates."""
    out = {}
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and cls.name == "SchedulerMetrics"):
            continue
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef) or fn.name == "__init__":
                continue
            touched = set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS
                        and isinstance(node.func.value, ast.Attribute)
                        and isinstance(node.func.value.value, ast.Name)
                        and node.func.value.value.id == "self"):
                    touched.add(node.func.value.attr)
            if touched:
                out[fn.name] = touched
    return out


def find_dead_metrics(pkg: str = None, metrics_file: str = None):
    pkg = pkg or PKG
    metrics_file = metrics_file or METRICS_FILE
    tree = ast.parse(_read(metrics_file))
    attrs = registered_metrics(tree)
    helpers = helper_map(tree)

    outside = []
    for path in _walk_py(pkg):
        if os.path.abspath(path) == os.path.abspath(metrics_file):
            continue
        outside.append(_read(path))
    blob = "\n".join(outside)

    live_helpers = {name for name in helpers
                    if re.search(rf"\.{name}\s*\(", blob)}
    dead = []
    for attr in attrs:
        direct = re.search(rf"\.{attr}\.(?:{'|'.join(_MUTATORS)})\s*\(", blob)
        via_helper = any(attr in helpers[h] for h in live_helpers)
        if not direct and not via_helper:
            dead.append(attr)
    return attrs, dead


@register("metrics", "registered SchedulerMetrics are observed somewhere")
def pass_metrics() -> List[Finding]:
    attrs, dead = find_dead_metrics()
    return [Finding(METRICS_FILE, 0,
                    f"dead metric: {a} is registered but never "
                    "observed/inc'd/set outside its definition")
            for a in dead]


# ======================================================================= spans
# (absorbed from tools/check_metrics.py — the PR-7 span-name lint)

SPAN_IGNORE_PREFIXES = ("framework.", "plugin.")


def _literal_prefix(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, True
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                break
        return ("".join(parts), False) if parts else (None, False)
    if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add)
            and isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)):
        return node.left.value, False
    return None, False


def emitted_span_names(pkg: str = None):
    names, prefixes = set(), set()
    for path in _walk_py(pkg or PKG):
        try:
            tree = ast.parse(_read(path))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            arg = None
            if node.func.attr in ("span", "span_remote") and node.args:
                arg = node.args[0]
            elif node.func.attr == "span_from_remote" and len(node.args) >= 2:
                arg = node.args[1]
            elif node.func.attr == "emit" and node.args:
                # tracing.emit(name, start_ns, end_ns): explicit-timestamp
                # finished-span export (dispatch-profiler child spans)
                arg = node.args[0]
            if arg is None:
                continue
            val, exact = _literal_prefix(arg)
            if val is None:
                continue
            (names if exact else prefixes).add(val)
    return names, prefixes


def bench_span_table(path: str = None):
    tree = ast.parse(_read(path or BENCH_FILE))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and tgt.id == "CRITICAL_PATH_SPANS"):
            continue
        return {n.value for n in ast.walk(node.value)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)}
    return set()


def find_unattributed_spans(pkg: str = None, bench_path: str = None):
    names, prefixes = emitted_span_names(pkg)
    table = bench_span_table(bench_path)
    bad = [n for n in sorted(names)
           if n not in table and not n.startswith(SPAN_IGNORE_PREFIXES)]
    for p in sorted(prefixes):
        if p.startswith(SPAN_IGNORE_PREFIXES):
            continue
        if any(t.startswith(p) for t in table):
            continue
        bad.append(p + "*")
    return sorted(names | prefixes), bad


@register("spans", "emitted span names appear in bench.py's attribution table")
def pass_spans() -> List[Finding]:
    _emitted, bad = find_unattributed_spans()
    return [Finding(BENCH_FILE, 0,
                    f"unattributed span: {n} is emitted but absent from "
                    "CRITICAL_PATH_SPANS and the ignore list")
            for n in bad]


# ====================================================================== events
# Flight-recorder event-kind lint (the span-name lint's twin, PR-7 follow-
# up): every LITERAL kind passed to ``telemetry.event(...)`` or
# ``<...>.flight.record(...)`` inside the package must appear in the
# declared registry frozenset ``backend/telemetry.py EVENT_KINDS`` — a new
# lifecycle event cannot ship without joining the documented vocabulary.

TELEMETRY_FILE = os.path.join(PKG, "backend", "telemetry.py")


def declared_event_kinds(path: str = None) -> Set[str]:
    """The EVENT_KINDS frozenset literal from backend/telemetry.py."""
    tree = ast.parse(_read(path or TELEMETRY_FILE))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and tgt.id == "EVENT_KINDS"):
            continue
        return {c.value for c in ast.walk(node.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)}
    return set()


def emitted_event_kinds(pkg: str = None) -> List[Tuple[str, int, str]]:
    """(path, line, kind) for every literal event-kind emission site:
    calls whose attribute is ``event`` (``telemetry.event`` and
    ``DeviceTelemetry.event`` call-throughs) or ``record`` on a
    flight-recorder receiver (``self.flight.record`` / ``flight.record``).
    Non-literal first args (pass-through helpers) are skipped — they
    forward kinds already checked at their own literal call sites."""
    out: List[Tuple[str, int, str]] = []
    for path in _walk_py(pkg or PKG):
        try:
            tree = ast.parse(_read(path))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.args):
                continue
            attr = node.func.attr
            if attr == "record":
                recv = node.func.value
                recv_name = (recv.attr if isinstance(recv, ast.Attribute)
                             else recv.id if isinstance(recv, ast.Name)
                             else "")
                if recv_name != "flight":
                    continue
            elif attr != "event":
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((path, node.lineno, arg.value))
    return out


def find_undeclared_events(pkg: str = None,
                           telemetry_path: str = None) -> List[Finding]:
    declared = declared_event_kinds(telemetry_path)
    if not declared:
        return [Finding(telemetry_path or TELEMETRY_FILE, 0,
                        "EVENT_KINDS registry frozenset not found — the "
                        "events lint has nothing to check against")]
    return [Finding(path, line,
                    f"undeclared flight-recorder event kind {kind!r}: add "
                    "it to backend/telemetry.py EVENT_KINDS (the declared "
                    "postmortem vocabulary) or rename to a declared kind")
            for path, line, kind in emitted_event_kinds(pkg)
            if kind not in declared]


@register("events", "emitted flight-recorder event kinds are declared in "
                    "telemetry.EVENT_KINDS")
def pass_events() -> List[Finding]:
    return find_undeclared_events()


# ==================================================================== dispatch
# Device-dispatch attribution lint (the events lint's sibling, PR-17): a
# jitted entry point invoked OUTSIDE a ``telemetry.dispatch(...)`` context
# manager produces device time the DispatchLedger can never attribute — it
# shows up as unexplained commit-wait dwell in the waterfall. Two rules:
# every literal program name handed to the dispatch/cost-probe family must
# appear in the declared ``backend/telemetry.py PROGRAM_NAMES`` registry,
# and every call of a discovered jit entry (tools-wide ``_collect_jit_
# functions`` — the same discovery the jit pass trusts) must sit lexically
# under a ``with <...>.dispatch(...)`` block. Exemptions: calls in the
# entry's own defining module (composition inside the profiled boundary —
# batch.py assembling schedule_batch from its cores), calls from inside
# another jit entry (traced composition never blocks on device), and
# reviewed ``# ktpu: dispatch-ok(reason)`` sites.

_DISPATCH_PROGRAM_ATTRS = ("dispatch", "cost_probe", "dispatch_window",
                           "dispatch_phases", "record_window",
                           "record_phases")


def declared_program_names(path: str = None) -> Set[str]:
    """The PROGRAM_NAMES frozenset literal from backend/telemetry.py."""
    tree = ast.parse(_read(path or TELEMETRY_FILE))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and tgt.id == "PROGRAM_NAMES"):
            continue
        return {c.value for c in ast.walk(node.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)}
    return set()


def dispatch_program_sites(pkg: str = None) -> List[Tuple[str, int, str]]:
    """(path, line, program) for every literal program name handed to the
    dispatch-attribution family (``telemetry.dispatch`` / ``cost_probe`` /
    ``dispatch_window`` / ``dispatch_phases`` and the DispatchLedger
    ``record_window`` / ``record_phases`` methods). Non-literal first args
    are pass-through helpers, checked at their own literal sites."""
    out: List[Tuple[str, int, str]] = []
    for path in _walk_py(pkg or PKG):
        try:
            tree = ast.parse(_read(path))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _DISPATCH_PROGRAM_ATTRS
                    and node.args):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((path, node.lineno, arg.value))
    return out


def _jit_entry_aliases(pkg: str) -> Dict[str, str]:
    """Every name a jit entry is callable under -> its defining file:
    decorated function names plus the assignment targets of
    ``x = jit(f, ...)`` bindings (callers invoke the TARGET name)."""
    aliases: Dict[str, str] = {}
    fns, entries, _sites = _collect_jit_functions(pkg)
    for name in entries:
        info = fns.get(name)
        if info is not None:
            aliases[name] = info.path
    for path in _walk_py(pkg):
        try:
            tree = ast.parse(_read(path))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            if (_callable_name(call.func) == "jit" and call.args
                    and isinstance(call.args[0], ast.Name)):
                aliases[node.targets[0].id] = path
    return aliases


def _is_dispatch_with(withnode: ast.With) -> bool:
    for item in withnode.items:
        ctx = item.context_expr
        if (isinstance(ctx, ast.Call) and isinstance(ctx.func, ast.Attribute)
                and ctx.func.attr == "dispatch"):
            return True
    return False


def find_unattributed_dispatches(pkg: str = None,
                                 telemetry_path: str = None) -> List[Finding]:
    pkg = pkg or PKG
    declared = declared_program_names(telemetry_path)
    if not declared:
        return [Finding(telemetry_path or TELEMETRY_FILE, 0,
                        "PROGRAM_NAMES registry frozenset not found — the "
                        "dispatch lint has nothing to check against")]
    findings = [
        Finding(path, line,
                f"undeclared dispatch program {prog!r}: add it to "
                "backend/telemetry.py PROGRAM_NAMES (the declared device-"
                "time attribution vocabulary) or rename to a declared one")
        for path, line, prog in dispatch_program_sites(pkg)
        if prog not in declared]
    aliases = _jit_entry_aliases(pkg)
    entry_names = set(aliases)
    for path in _walk_py(pkg):
        src = _read(path)
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        sup = _Suppressions(src)

        def walk(node, in_dispatch, in_entry):
            if isinstance(node, ast.With) and _is_dispatch_with(node):
                in_dispatch = True
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a fresh function body is a fresh lexical scope: an
                # enclosing `with dispatch` does NOT cover calls made when
                # the nested function runs later
                in_dispatch = False
                in_entry = node.name in entry_names
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in entry_names
                    and not in_dispatch and not in_entry
                    and aliases[node.func.id] != path
                    and not sup.silences("dispatch-ok", node.lineno)):
                findings.append(Finding(
                    path, node.lineno,
                    f"unattributed dispatch: jitted entry {node.func.id}() "
                    "called outside 'with telemetry.dispatch(...)' — its "
                    "device time lands in no program's ledger; wrap the "
                    "call or suppress with '# ktpu: dispatch-ok(reason)'"))
            for child in ast.iter_child_nodes(node):
                walk(child, in_dispatch, in_entry)

        walk(tree, False, False)
    return findings


@register("dispatch", "jit-entry calls run under telemetry.dispatch with a "
                      "declared PROGRAM_NAMES program")
def pass_dispatch() -> List[Finding]:
    return find_unattributed_dispatches()


# ===================================================================== markers
# (absorbed from tools/check_markers.py — the PR-4 slow-marker lint)

PERF_SCALE_NODES = 1000
SOAK_SCALE = 16
SOAK_ROUNDS = 16


def _is_slow_mark(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        node = node.func
    return (isinstance(node, ast.Attribute) and node.attr == "slow"
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "mark")


def _has_slow(decorators) -> bool:
    return any(_is_slow_mark(d) for d in decorators)


def _module_marked_slow(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "pytestmark":
                    for cand in ast.walk(node.value):
                        if _is_slow_mark(cand):
                            return True
    return False


def _test_cases_key(call: ast.Call):
    if not (isinstance(call.func, ast.Subscript)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "TEST_CASES"):
        return None
    sl = call.func.slice
    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
        return sl.value
    return ""


def _int_kw(call: ast.Call, name: str):
    for k in call.keywords:
        if (k.arg == name and isinstance(k.value, ast.Constant)
                and isinstance(k.value.value, int)):
            return k.value.value
    return None


def _is_perf_scale(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        kw_names = {k.arg for k in node.keywords}
        for k in node.keywords:
            if (k.arg == "nodes" and isinstance(k.value, ast.Constant)
                    and isinstance(k.value.value, int)
                    and k.value.value >= PERF_SCALE_NODES):
                return True
        key = _test_cases_key(node)
        if key is not None and "nodes" not in kw_names:
            return True
        if key == "SchedulingSoak":
            scale, rounds = _int_kw(node, "scale"), _int_kw(node, "rounds")
            if (scale is None or scale >= SOAK_SCALE
                    or rounds is None or rounds >= SOAK_ROUNDS):
                return True
    return False


def find_unmarked(paths=None) -> List[Tuple[str, int, str]]:
    violations = []
    paths = paths or sorted(
        os.path.join(TESTS, f) for f in os.listdir(TESTS)
        if f.startswith("test_") and f.endswith(".py"))
    for path in paths:
        tree = ast.parse(_read(path))
        if _module_marked_slow(tree):
            continue
        scopes = [(tree.body, False)]
        for cls in tree.body:
            if isinstance(cls, ast.ClassDef):
                scopes.append((cls.body, _has_slow(cls.decorator_list)))
        for body, class_slow in scopes:
            for fn in body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                if not fn.name.startswith("test_"):
                    continue
                if class_slow or _has_slow(fn.decorator_list):
                    continue
                if _is_perf_scale(fn):
                    violations.append((path, fn.lineno, fn.name))
    return violations


@register("markers", "perf-scale tests carry @pytest.mark.slow")
def pass_markers() -> List[Finding]:
    return [Finding(path, line,
                    f"perf-scale test {name} (>= {PERF_SCALE_NODES} nodes or "
                    "TEST_CASES defaults) lacks @pytest.mark.slow")
            for path, line, name in find_unmarked()]


# =================================================================== pb2 drift
# (absorbed from ``tools/gen_pb2.py --check``)


@register("pb2-drift", "vendored ktpu_device_pb2 matches native/ktpu_device.proto")
def pass_pb2_drift() -> List[Finding]:
    import importlib.util

    tool = os.path.join(REPO, "tools", "gen_pb2.py")
    out_path = os.path.join(PKG, "native", "ktpu_device_pb2.py")
    try:
        spec = importlib.util.spec_from_file_location("_ktpu_gen_pb2", tool)
        gen = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(gen)
        content = gen.generate()
    except ImportError:
        return []  # google.protobuf absent: the vendored module is unusable
        # anyway and the grpc suites skip — nothing to gate
    try:
        current = _read(out_path)
    except OSError:
        return [Finding(out_path, 0, "vendored pb2 module missing; run "
                        "python tools/gen_pb2.py")]
    if current != content:
        return [Finding(out_path, 0, "vendored pb2 module is stale vs "
                        "native/ktpu_device.proto; run python tools/gen_pb2.py")]
    return []


# ======================================================================= locks
# Lock-discipline AST pass: per class, learn which ``self.<x>`` attributes
# are accessed under ``with self.<lock>`` and flag unguarded accesses to the
# same attributes elsewhere in the class.
#
# Scope rules (kept deliberately intraprocedural — no cross-function lock
# state):
#   * a class participates when some method assigns ``self.<name> = Lock()/
#     RLock()/Condition()/locktrace.make_lock()/make_rlock()``;
#   * an attribute is a CANDIDATE when it is (a) accessed at least once
#     inside a with-lock block anywhere in the class AND (b) mutated outside
#     ``__init__`` (rebinding, augmented assignment, ``self.x[k] = / del``,
#     or a mutating method call like ``self.x.pop(...)``) — config fields
#     assigned once at construction are exempt;
#   * guarded contexts: ``with self.<lock>:`` bodies, ``__init__`` (no
#     concurrent aliases exist yet), methods decorated ``@_locked``, and
#     methods whose ``def`` line carries ``# ktpu: locked`` (the reviewed
#     "caller holds the lock" contract);
#   * nested functions/lambdas are UNGUARDED even when defined under the
#     lock (they escape the critical section);
#   * ``# ktpu: unguarded-ok(reason)`` silences one line.

_LOCK_CTOR_NAMES = {"Lock", "RLock", "Condition",
                    "make_lock", "make_rlock", "make_condition"}
_MUTATING_CALLS = {"add", "append", "appendleft", "clear", "discard",
                   "extend", "insert", "pop", "popitem", "popleft", "remove",
                   "setdefault", "update", "sort", "reverse", "notify",
                   "notify_all"}


def _callable_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """X for a ``self.X`` attribute node."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _Access(NamedTuple):
    attr: str
    line: int
    write: bool
    guarded: bool
    method: str
    # True only for rebinding writes (assign/augassign/subscript-store/del):
    # candidacy keys off these — a ``.pop()``/``.append()`` call mutates the
    # CONTENTS (often of a sub-object with its own lock) and stays an access
    # but does not by itself make the attribute lock-owned
    rebind: bool = False


def _scan_class(cls: ast.ClassDef, sup: _Suppressions) -> List[_Access]:
    lock_attrs: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            attr = _self_attr(node.targets[0])
            if (attr and isinstance(node.value, ast.Call)
                    and _callable_name(node.value.func) in _LOCK_CTOR_NAMES):
                lock_attrs.add(attr)
    if not lock_attrs:
        return []

    accesses: List[_Access] = []

    def is_lock_with(withnode: ast.With) -> bool:
        for item in withnode.items:
            a = _self_attr(item.context_expr)
            if a in lock_attrs:
                return True
        return False

    def record(attr: Optional[str], node: ast.AST, write: bool,
               guarded: bool, method: str, rebind: bool = False):
        if attr and attr not in lock_attrs:
            accesses.append(_Access(attr, node.lineno, write, guarded,
                                    method, rebind))

    def walk(node: ast.AST, guarded: bool, method: str):
        if isinstance(node, ast.With) and is_lock_with(node):
            for item in node.items:
                walk(item.context_expr, guarded, method)
            for child in node.body:
                walk(child, True, method)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # nested defs/lambdas escape the critical section — unless the
            # nested def itself carries the reviewed '# ktpu: locked'
            # contract (e.g. commit closures run by a locked helper)
            nested_locked = (not isinstance(node, ast.Lambda)
                             and (node.lineno in sup.locked_lines
                                  or node.name.endswith("_locked")))
            for child in ast.iter_child_nodes(node):
                walk(child, nested_locked, method)
            return
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                _record_target(tgt, guarded, method)
            walk(node.value, guarded, method)
            return
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            _record_target(node.target, guarded, method)
            if getattr(node, "value", None) is not None:
                walk(node.value, guarded, method)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                _record_target(tgt, guarded, method)
            return
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in _MUTATING_CALLS):
                recv = _self_attr(fn.value)
                if recv:
                    record(recv, fn.value, True, guarded, method)
                    for arg in list(node.args) + [k.value for k in node.keywords]:
                        walk(arg, guarded, method)
                    return
            for child in ast.iter_child_nodes(node):
                walk(child, guarded, method)
            return
        attr = _self_attr(node)
        if attr is not None:
            record(attr, node, isinstance(node.ctx, (ast.Store, ast.Del)),
                   guarded, method)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, guarded, method)

    def _record_target(tgt: ast.AST, guarded: bool, method: str):
        attr = _self_attr(tgt)
        if attr is not None:
            record(attr, tgt, True, guarded, method, rebind=True)
            return
        if isinstance(tgt, ast.Subscript):
            attr = _self_attr(tgt.value)
            if attr is not None:
                record(attr, tgt.value, True, guarded, method, rebind=True)
                walk(tgt.slice, guarded, method)
                return
        walk(tgt, guarded, method)

    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        locked = (
            fn.name == "__init__"
            # the *_locked naming convention IS the caller-holds-the-lock
            # contract this codebase already uses (_clear_unschedulable_
            # locked, _flush_waiting_locked, _drop_service_locked, ...)
            or fn.name.endswith("_locked")
            or fn.lineno in sup.locked_lines
            or any(ln in sup.locked_lines
                   for ln in range(fn.lineno,
                                   (fn.body[0].lineno if fn.body else fn.lineno)))
            or any(_callable_name(d) in ("_locked", "locked")
                   for d in fn.decorator_list))
        for stmt in fn.body:
            walk(stmt, locked, fn.name)
    return accesses


def find_lock_violations(pkg: str = None) -> List[Finding]:
    out: List[Finding] = []
    for path in _walk_py(pkg or PKG):
        src = _read(path)
        if "Lock(" not in src and "make_lock" not in src \
                and "make_rlock" not in src and "Condition(" not in src:
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        sup = _Suppressions(src)
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            accesses = _scan_class(cls, sup)
            if not accesses:
                continue
            # __init__ is exempt from flagging (no concurrent alias exists
            # yet) but is NOT evidence of lock discipline
            guarded_attrs = {a.attr for a in accesses
                             if a.guarded and a.method != "__init__"}
            mutated = {a.attr for a in accesses
                       if a.rebind and a.method != "__init__"}
            candidates = guarded_attrs & mutated
            for a in accesses:
                if a.guarded or a.attr not in candidates:
                    continue
                if sup.silences("unguarded-ok", a.line):
                    continue
                verb = "write to" if a.write else "read of"
                out.append(Finding(
                    path, a.line,
                    f"unguarded {verb} {cls.name}.{a.attr} in {a.method}(): "
                    f"this attribute is accessed under the class lock "
                    f"elsewhere — guard it, mark the method '# ktpu: locked' "
                    f"if callers hold the lock, or suppress with "
                    f"'# ktpu: unguarded-ok(reason)'"))
    return out


@register("locks", "lock-guarded attributes are never accessed unguarded")
def pass_locks() -> List[Finding]:
    return find_lock_violations()


# ========================================================================= jit
# Jit-boundary / device-sync pass.
#
# Discovers the jitted entry points (``@jax.jit``, ``@functools.partial(
# jax.jit, static_argnames=...)``, ``x = jax.jit(f)``), walks the call graph
# over the package's module-level functions, propagates which parameters are
# STATIC (non-traced) through call sites, and flags host syncs and retrace
# hazards inside the traced region:
#
#   J1  int()/float()/bool() of a traced value      (implicit device sync)
#   J2  .item() on a traced value                   (implicit device sync)
#   J3  np.asarray()/np.array()/... of a traced value (host materialization)
#   J4  Python if/while/ternary on a traced value   (ConcretizationError —
#       or worse, a silent retrace per distinct value via static fallback)
#   J5  unhashable (list/dict/set) defaults for declared static args
#
# Shape/metadata access is static (``x.shape[0]``, ``x.ndim``, ``len(x)``),
# ``is (not) None`` tests are fine (tracers are never None), and values
# derived only from static parameters stay static. Suppress one line with
# ``# ktpu: host-sync-ok(reason)``.

_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype", "nbytes"}
_NP_HOST_FNS = {"asarray", "array", "ascontiguousarray", "copy", "frombuffer",
                "save", "tolist"}


class _FnInfo(NamedTuple):
    path: str
    module: str          # module basename, e.g. "batch"
    node: ast.FunctionDef
    params: Tuple[str, ...]
    imports: Dict[str, str]   # local alias -> module basename (or func name)


def _param_names(fn: ast.FunctionDef) -> Tuple[str, ...]:
    a = fn.args
    names = [p.arg for p in getattr(a, "posonlyargs", [])] + \
            [p.arg for p in a.args] + [p.arg for p in a.kwonlyargs]
    return tuple(names)


def _module_imports(tree: ast.Module) -> Dict[str, str]:
    """alias -> imported module basename or imported function name."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for al in node.names:
                out[al.asname or al.name.split(".")[0]] = \
                    al.name.rsplit(".", 1)[-1]
        elif isinstance(node, ast.ImportFrom):
            for al in node.names:
                out[al.asname or al.name] = al.name
    return out


def _jit_static_names(dec: ast.AST) -> Optional[Set[str]]:
    """The static_argnames set when ``dec`` is a jit decorator, else None."""
    if isinstance(dec, ast.Attribute) and dec.attr == "jit":
        return set()
    if isinstance(dec, ast.Name) and dec.id == "jit":
        return set()
    if isinstance(dec, ast.Call):
        fname = _callable_name(dec.func)
        if fname == "jit":
            inner = None
        elif fname == "partial":
            if not (dec.args and _callable_name(dec.args[0]) == "jit"):
                return None
            inner = dec
        else:
            return None
        statics: Set[str] = set()
        for kw in (inner or dec).keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(c.value, str):
                        statics.add(c.value)
        return statics
    return None


def _collect_jit_functions(pkg: str):
    """(functions by name, entry -> static names, jit decl sites)."""
    fns: Dict[str, _FnInfo] = {}
    entries: Dict[str, Set[str]] = {}
    jit_sites: List[Tuple[str, ast.AST, Set[str], str]] = []
    for path in _walk_py(pkg):
        try:
            tree = ast.parse(_read(path))
        except SyntaxError:
            continue
        module = os.path.splitext(os.path.basename(path))[0]
        imports = _module_imports(tree)
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                # first definition wins on name collision; module-level only
                fns.setdefault(node.name, _FnInfo(
                    path, module, node, _param_names(node), imports))
                for dec in node.decorator_list:
                    statics = _jit_static_names(dec)
                    if statics is not None:
                        entries[node.name] = statics
                        jit_sites.append((path, node, statics, node.name))
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = node.value
                if (_callable_name(call.func) == "jit" and call.args
                        and isinstance(call.args[0], ast.Name)):
                    statics = {c.value for kw in call.keywords
                               if kw.arg in ("static_argnames",)
                               for c in ast.walk(kw.value)
                               if isinstance(c, ast.Constant)
                               and isinstance(c.value, str)}
                    entries[call.args[0].id] = statics
                    jit_sites.append((path, call, statics, call.args[0].id))
    return fns, entries, jit_sites


def _expr_names(node: ast.AST) -> Set[str]:
    """Name leaves that could carry tracedness: prunes static subtrees
    (shape/dtype metadata, len(), ``x is None`` operands are NOT pruned
    here — branch rule handles those)."""
    out: Set[str] = set()

    def rec(n: ast.AST):
        if isinstance(n, ast.Attribute):
            if n.attr in _SHAPE_ATTRS:
                return  # metadata: static regardless of the base
            rec(n.value)
            return
        if isinstance(n, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
            return  # identity tests yield host bools (tracers aren't None)
        if isinstance(n, ast.Call):
            fname = _callable_name(n.func)
            if fname in ("len", "isinstance", "getattr", "hasattr", "type"):
                return  # static metadata/introspection
            for child in ast.iter_child_nodes(n):
                rec(child)
            return
        if isinstance(n, ast.Name):
            out.add(n.id)
            return
        for child in ast.iter_child_nodes(n):
            rec(child)

    rec(node)
    return out


def _strip_none_tests(node: ast.AST) -> List[ast.AST]:
    """Operands of a branch test that remain relevant after dropping
    ``x is None`` / ``x is not None`` comparisons."""
    if isinstance(node, ast.BoolOp):
        out = []
        for v in node.values:
            out.extend(_strip_none_tests(v))
        return out
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
        return _strip_none_tests(node.operand)
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return []
        return [node]
    return [node]


class _TracedScan:
    """Per-function traced-name flow + rule application."""

    def __init__(self, info: _FnInfo, traced_params: Set[str],
                 sup: _Suppressions, findings: List[Finding],
                 np_aliases: Set[str]):
        self.info = info
        self.traced: Set[str] = set(traced_params)
        self.sup = sup
        self.findings = findings
        self.np_aliases = np_aliases
        self.calls: List[ast.Call] = []

    def is_traced(self, node: ast.AST) -> bool:
        return bool(_expr_names(node) & self.traced)

    def flag(self, node: ast.AST, msg: str):
        if self.sup.silences("host-sync-ok", node.lineno,
                             getattr(node, "end_lineno", node.lineno)):
            return
        self.findings.append(Finding(
            self.info.path, node.lineno,
            f"{msg} in traced function {self.info.node.name}() — "
            "suppress with '# ktpu: host-sync-ok(reason)' if reviewed"))

    def run(self):
        # two passes so later-defined helpers feeding earlier names settle
        for _ in range(2):
            for node in ast.walk(self.info.node):
                self._propagate(node)
        for node in ast.walk(self.info.node):
            self._apply_rules(node)

    def _propagate(self, node: ast.AST):
        if isinstance(node, ast.Assign):
            if self.is_traced(node.value):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            self.traced.add(n.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if getattr(node, "value", None) is not None \
                    and self.is_traced(node.value) \
                    and isinstance(node.target, ast.Name):
                self.traced.add(node.target.id)
        elif isinstance(node, (ast.For,)):
            if self.is_traced(node.iter):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        self.traced.add(n.id)
        elif isinstance(node, ast.NamedExpr):
            if self.is_traced(node.value) and isinstance(node.target, ast.Name):
                self.traced.add(node.target.id)
        elif isinstance(node, (ast.FunctionDef, ast.Lambda)):
            # nested function (scan body / vmapped inner): its params are
            # traced operands
            if node is not self.info.node:
                a = node.args
                for p in list(getattr(a, "posonlyargs", [])) + list(a.args) \
                        + list(a.kwonlyargs):
                    self.traced.add(p.arg)

    def _apply_rules(self, node: ast.AST):
        if isinstance(node, ast.Call):
            self.calls.append(node)
            fname = _callable_name(node.func)
            if (isinstance(node.func, ast.Name)
                    and fname in ("int", "float", "bool", "complex")
                    and node.args and self.is_traced(node.args[0])):
                self.flag(node, f"{fname}() on a traced value forces a "
                          "blocking device sync")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "item"
                  and self.is_traced(node.func.value)):
                self.flag(node, ".item() on a traced value forces a "
                          "blocking device sync")
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id in self.np_aliases
                  and node.func.attr in _NP_HOST_FNS
                  and node.args and self.is_traced(node.args[0])):
                self.flag(node, f"np.{node.func.attr}() on a traced value "
                          "materializes it on host")
        elif isinstance(node, (ast.If, ast.While)):
            for operand in _strip_none_tests(node.test):
                if self.is_traced(operand):
                    self.flag(node, "Python branch on a traced value (use "
                              "jnp.where/lax.cond, or make the input a "
                              "static arg)")
                    break
        elif isinstance(node, ast.IfExp):
            for operand in _strip_none_tests(node.test):
                if self.is_traced(operand):
                    self.flag(node, "Python conditional expression on a "
                              "traced value (use jnp.where)")
                    break
        elif isinstance(node, ast.Assert):
            for operand in _strip_none_tests(node.test):
                if self.is_traced(operand):
                    self.flag(node, "assert on a traced value forces a "
                              "blocking device sync")
                    break


def find_jit_violations(pkg: str = None) -> List[Finding]:
    pkg = pkg or PKG
    fns, entries, jit_sites = _collect_jit_functions(pkg)
    findings: List[Finding] = []
    src_cache: Dict[str, _Suppressions] = {}
    np_alias_cache: Dict[str, Set[str]] = {}

    def sup_for(path: str) -> _Suppressions:
        if path not in src_cache:
            src_cache[path] = _Suppressions(_read(path))
        return src_cache[path]

    def np_aliases_for(path: str) -> Set[str]:
        if path not in np_alias_cache:
            aliases = {"np", "numpy"}
            try:
                tree = ast.parse(_read(path))
                for node in ast.walk(tree):
                    if isinstance(node, ast.Import):
                        for al in node.names:
                            if al.name == "numpy":
                                aliases.add(al.asname or "numpy")
            except SyntaxError:
                pass
            np_alias_cache[path] = aliases
        return np_alias_cache[path]

    # J5: unhashable defaults for declared static args, at the jit site
    for path, node, statics, name in jit_sites:
        info = fns.get(name)
        if info is None or not statics:
            continue
        fn = info.node
        a = fn.args
        pos = list(getattr(a, "posonlyargs", [])) + list(a.args)
        defaults = list(a.defaults)
        pairs = list(zip(pos[len(pos) - len(defaults):], defaults)) + [
            (p, d) for p, d in zip(a.kwonlyargs, a.kw_defaults) if d is not None]
        for p, d in pairs:
            if p.arg in statics and isinstance(d, (ast.List, ast.Dict,
                                                   ast.Set)):
                sup = sup_for(info.path)
                if not sup.silences("host-sync-ok", d.lineno):
                    findings.append(Finding(
                        info.path, d.lineno,
                        f"static arg {p.arg!r} of jitted {name}() defaults "
                        "to an unhashable literal — jit static args must "
                        "hash (use a tuple)"))

    # traced-param fixed point over the call graph
    traced_params: Dict[str, Set[str]] = {}
    for name, statics in entries.items():
        info = fns.get(name)
        if info is None:
            continue
        traced_params[name] = {p for p in info.params if p not in statics}

    for _ in range(12):  # bounded fixed point
        changed = False
        scans: Dict[str, _TracedScan] = {}
        for name, tp in list(traced_params.items()):
            info = fns.get(name)
            if info is None:
                continue
            scan = _TracedScan(info, tp, sup_for(info.path), [],
                               np_aliases_for(info.path))
            scan.run()
            scans[name] = scan
            for call in scan.calls:
                callee = _callable_name(call.func)
                # resolve `from x import f` aliasing and module-attr calls
                target = None
                if isinstance(call.func, ast.Name) and callee in fns:
                    target = callee
                elif isinstance(call.func, ast.Attribute) and \
                        isinstance(call.func.value, ast.Name):
                    mod_alias = call.func.value.id
                    mod = info.imports.get(mod_alias)
                    if mod is not None and callee in fns \
                            and fns[callee].module == mod:
                        target = callee
                if target is None:
                    continue
                if target in entries and target != name:
                    # a nested call into another jit entry: that entry's
                    # declared static_argnames are authoritative — caller
                    # tracedness must not overwrite its static surface
                    continue
                tinfo = fns[target]
                tparams = traced_params.setdefault(target, set())
                before = len(tparams)
                for i, arg in enumerate(call.args):
                    if i < len(tinfo.params) and scan.is_traced(arg):
                        tparams.add(tinfo.params[i])
                for kw in call.keywords:
                    if kw.arg in tinfo.params and scan.is_traced(kw.value):
                        tparams.add(kw.arg)
                if len(tparams) != before:
                    changed = True
        if not changed:
            break

    # final scan with settled traced sets
    for name, tp in traced_params.items():
        info = fns.get(name)
        if info is None:
            continue
        scan = _TracedScan(info, tp, sup_for(info.path), findings,
                           np_aliases_for(info.path))
        scan.run()
    return findings


@register("jit", "no host syncs / retrace hazards reachable from jitted entries")
def pass_jit() -> List[Finding]:
    return find_jit_violations()


# ====================================================================== errors
# Error-taxonomy pass over backend/: raises use the typed taxonomy; broad
# ``except Exception`` handlers reclassify into it or carry a reviewed
# justification comment.

_UNTYPED_RAISES = {"RuntimeError", "Exception", "BaseException", "OSError",
                   "IOError", "SystemError", "StandardError"}
_TAXONOMY = {"DeviceServiceError", "TransientDeviceError",
             "PermanentDeviceError", "StaleEpochError", "ConflictError",
             "CapacityError"}


def _handler_reclassifies(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True  # bare re-raise: the original type propagates
            name = _callable_name(node.exc if not isinstance(node.exc, ast.Call)
                                  else node.exc.func)
            if name in _TAXONOMY:
                return True
    return False


def _line_has_justification(src_lines: List[str], lineno: int) -> bool:
    """True when the except line carries an explanatory comment — either a
    ``# ktpu: broad-except-ok(reason)`` marker or a prose comment with
    content beyond a bare lint pragma (the established
    ``# noqa: BLE001 — reason`` idiom)."""
    line = src_lines[lineno - 1] if lineno - 1 < len(src_lines) else ""
    if "#" not in line:
        return False
    comment = line.split("#", 1)[1]
    m = _SUPPRESS_RE.search(line)
    if m:
        return m.group(1) == "broad-except-ok" and bool(m.group(2).strip())
    stripped = re.sub(r"noqa(:\s*[\w,]+)?", "", comment)
    stripped = stripped.strip(" #—-:\t")
    return bool(stripped)


def find_error_violations(backend: str = None) -> List[Finding]:
    backend = backend or os.path.join(PKG, "backend")
    out: List[Finding] = []
    for path in _walk_py(backend):
        src = _read(path)
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        sup = _Suppressions(src)
        src_lines = src.splitlines()
        for node in ast.walk(tree):
            if isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                name = _callable_name(exc.func) if isinstance(exc, ast.Call) \
                    else None
                if name in _UNTYPED_RAISES:
                    if not sup.silences("taxonomy-ok", node.lineno,
                                        getattr(node, "end_lineno",
                                                node.lineno)):
                        out.append(Finding(
                            path, node.lineno,
                            f"untyped raise {name}(...) on the device path — "
                            "use the backend/errors.py taxonomy (Transient/"
                            "Permanent/StaleEpoch/Conflict) or suppress with "
                            "'# ktpu: taxonomy-ok(reason)'"))
            elif isinstance(node, ast.ExceptHandler):
                broad = (node.type is None
                         or (isinstance(node.type, ast.Name)
                             and node.type.id in ("Exception", "BaseException")))
                if not broad:
                    continue
                if _handler_reclassifies(node):
                    continue
                if _line_has_justification(src_lines, node.lineno):
                    continue
                out.append(Finding(
                    path, node.lineno,
                    "broad 'except Exception' without reclassification into "
                    "the typed taxonomy or a justification comment "
                    "('# reason' / '# ktpu: broad-except-ok(reason)')"))
    return out


@register("errors", "backend/ raises are typed; broad excepts justify themselves")
def pass_errors() -> List[Finding]:
    return find_error_violations()


# ========================================================================= CLI


def run_passes(names: Sequence[str]) -> Dict[str, List[Finding]]:
    results: Dict[str, List[Finding]] = {}
    for name in names:
        fn, _desc = PASSES[name]
        results[name] = fn()
    return results


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if "--list" in argv:
        for name, (_fn, desc) in PASSES.items():
            print(f"{name:12s} {desc}")
        return 0
    names: List[str] = []
    if "--all" in argv:
        names = list(PASSES)
    i = 0
    while i < len(argv):
        if argv[i] == "--pass":
            if i + 1 >= len(argv) or argv[i + 1] not in PASSES:
                print(f"usage: --pass <{'|'.join(PASSES)}>", file=sys.stderr)
                return 2
            names.append(argv[i + 1])
            i += 2
        elif argv[i] == "--all":
            i += 1
        else:
            print(f"unknown argument {argv[i]!r} "
                  "(try --all, --pass NAME, --list, --json)", file=sys.stderr)
            return 2
    if not names:
        names = list(PASSES)
    seen = set()
    names = [n for n in names if not (n in seen or seen.add(n))]

    results = run_passes(names)
    total = sum(len(v) for v in results.values())
    if as_json:
        print(json.dumps({
            "passes": {
                name: {
                    "findings": [
                        {"path": os.path.relpath(f.path, REPO)
                         if os.path.isabs(f.path) else f.path,
                         "line": f.line, "message": f.message}
                        for f in findings],
                    "count": len(findings),
                } for name, findings in results.items()},
            "total": total,
        }, indent=2))
        return 1 if total else 0
    for name, findings in results.items():
        if findings:
            print(f"FAIL {name} ({len(findings)}):")
            for f in findings:
                print(f"  - {f.render()}")
        else:
            print(f"ok   {name}: clean")
    if total:
        print(f"\n{total} finding(s) across "
              f"{sum(1 for v in results.values() if v)} pass(es)")
    return 1 if total else 0


if __name__ == "__main__":
    raise SystemExit(main())
