"""TPU relay watcher (VERDICT r3 item 1: relay-resilient probing).

The axon tunnel is flaky for long stretches; three rounds of bench fell back
to CPU because the probe window (2x60s back-to-back at bench time) missed
every healthy period. This watcher spreads probe attempts across the whole
round: every PROBE_INTERVAL_S it subprocess-probes jax.devices(); on the
first success it runs the on-chip evidence suite and writes artifacts under
TPU_EVIDENCE/ (probe log + headline bench + KTPU_SPEC on/off delta), then
keeps probing so later healthy windows refresh the evidence.

Usage: nohup python tools/tpu_watch.py &   (stops itself after MAX_HOURS)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EVIDENCE_DIR = os.path.join(REPO, "TPU_EVIDENCE")
LOG = os.path.join(EVIDENCE_DIR, "probe_log.jsonl")

PROBE_INTERVAL_S = float(os.environ.get("TPU_WATCH_INTERVAL", "600"))
PROBE_TIMEOUT_S = float(os.environ.get("TPU_WATCH_TIMEOUT", "90"))
MAX_HOURS = float(os.environ.get("TPU_WATCH_HOURS", "11"))
BENCH_TIMEOUT_S = float(os.environ.get("TPU_WATCH_BENCH_TIMEOUT", "2400"))


def log(entry: dict) -> None:
    os.makedirs(EVIDENCE_DIR, exist_ok=True)
    entry["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(LOG, "a") as f:
        f.write(json.dumps(entry) + "\n")
    print(entry, flush=True)


def probe() -> tuple[bool, dict]:
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    t0 = time.perf_counter()
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print(jax.default_backend())"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S, env=env)
        dur = round(time.perf_counter() - t0, 1)
        if out.returncode == 0 and out.stdout.strip():
            platform = out.stdout.strip().splitlines()[-1]
            return platform not in ("cpu",), {"outcome": "ok",
                                              "platform": platform,
                                              "duration_s": dur}
        return False, {"outcome": f"rc={out.returncode}", "duration_s": dur,
                       "stderr": out.stderr.strip()[-200:]}
    except subprocess.TimeoutExpired:
        return False, {"outcome": "timeout", "duration_s": PROBE_TIMEOUT_S}


def run_evidence(tag: str) -> None:
    """On-chip evidence: headline bench (its own probe will now pass) and
    the KTPU_SPEC=1 vs 0 delta on a reduced headline config."""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    runs = [
        ("bench", dict(env), [sys.executable, os.path.join(REPO, "bench.py")]),
        ("spec_on", dict(env, KTPU_SPEC="1", BENCH_MATRIX="0", BENCH_WIRE="0",
                         BENCH_PODS="2000"),
         [sys.executable, os.path.join(REPO, "bench.py")]),
        ("spec_off", dict(env, KTPU_SPEC="0", BENCH_MATRIX="0", BENCH_WIRE="0",
                          BENCH_PODS="2000"),
         [sys.executable, os.path.join(REPO, "bench.py")]),
    ]
    for name, renv, cmd in runs:
        t0 = time.perf_counter()
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=BENCH_TIMEOUT_S, env=renv, cwd=REPO)
            line = (out.stdout.strip().splitlines() or [""])[-1]
            try:
                payload = json.loads(line)
            except json.JSONDecodeError:
                payload = {"error": f"rc={out.returncode}",
                           "stderr": out.stderr.strip()[-300:]}
        except subprocess.TimeoutExpired:
            payload = {"error": "timeout"}
        payload["_run"] = name
        payload["_wall_s"] = round(time.perf_counter() - t0, 1)
        path = os.path.join(EVIDENCE_DIR, f"{tag}_{name}.json")
        with open(path, "w") as f:
            json.dump(payload, f, indent=2)
        log({"evidence": name, "path": path,
             "platform": payload.get("platform"),
             "value": payload.get("value"), "error": payload.get("error")})


def main() -> None:
    deadline = time.time() + MAX_HOURS * 3600
    evidence_runs = 0
    attempt = 0
    while time.time() < deadline:
        attempt += 1
        up, diag = probe()
        diag["attempt"] = attempt
        log(diag)
        if up and evidence_runs < int(os.environ.get("TPU_WATCH_MAX_RUNS", "3")):
            evidence_runs += 1
            tag = time.strftime("tpu_%H%M%S")
            log({"event": "chip-up: running evidence suite", "tag": tag})
            run_evidence(tag)
        time.sleep(PROBE_INTERVAL_S)


if __name__ == "__main__":
    main()
