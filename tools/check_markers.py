#!/usr/bin/env python3
"""Marker lint (tier-1; run by tests/test_check_metrics.py): a perf-scale
test must carry ``@pytest.mark.slow``.

Tier-1 runs ``-m 'not slow'`` under a hard timeout; one unmarked
reference-scale workload test (5000 nodes on the CPU fallback) blows the
whole gate. A test function counts as perf-scale when it

  * passes ``nodes=<constant >= 1000>`` to any call, or
  * invokes a ``TEST_CASES[...](...)`` workload factory WITHOUT a ``nodes``
    override — the factory defaults are the reference 5000Nodes sizes, or
  * invokes ``TEST_CASES["SchedulingSoak"](...)`` at soak scale: the soak's
    cost grows with ``rounds``x``scale``x``cycles_per_round``, not node
    count, so a "small-nodes" soak with reference-size soak knobs
    (``scale >= 16`` or ``rounds >= 16``, or either left at its default)
    still must be slow-marked.

A test is "marked slow" when the function, its class, or the module-level
``pytestmark`` carries ``pytest.mark.slow``.

Usage: ``python tools/check_markers.py`` — exits 0 when clean, 1 with a
listing otherwise.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.join(REPO, "tests")

PERF_SCALE_NODES = 1000
# soak knobs at/above these are reference-size regardless of node count
SOAK_SCALE = 16
SOAK_ROUNDS = 16


def _is_slow_mark(node: ast.AST) -> bool:
    """True for ``pytest.mark.slow`` (bare or called)."""
    if isinstance(node, ast.Call):
        node = node.func
    return (isinstance(node, ast.Attribute) and node.attr == "slow"
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "mark")


def _has_slow(decorators) -> bool:
    return any(_is_slow_mark(d) for d in decorators)


def _module_marked_slow(tree: ast.Module) -> bool:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "pytestmark":
                    for cand in ast.walk(node.value):
                        if _is_slow_mark(cand):
                            return True
    return False


def _test_cases_key(call: ast.Call):
    """The workload name of a ``TEST_CASES["X"](...)`` call, else None."""
    if not (isinstance(call.func, ast.Subscript)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "TEST_CASES"):
        return None
    sl = call.func.slice
    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
        return sl.value
    return ""  # dynamic key: still a TEST_CASES call


def _int_kw(call: ast.Call, name: str):
    for k in call.keywords:
        if (k.arg == name and isinstance(k.value, ast.Constant)
                and isinstance(k.value.value, int)):
            return k.value.value
    return None


def _is_perf_scale(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        kw_names = {k.arg for k in node.keywords}
        for k in node.keywords:
            if (k.arg == "nodes" and isinstance(k.value, ast.Constant)
                    and isinstance(k.value.value, int)
                    and k.value.value >= PERF_SCALE_NODES):
                return True
        # TEST_CASES["X"](...) with the reference-size defaults
        key = _test_cases_key(node)
        if key is not None and "nodes" not in kw_names:
            return True
        # the soak scales with its arrival knobs, not node count: a small-
        # nodes call with default (or reference-size) scale/rounds is still
        # the large variant
        if key == "SchedulingSoak":
            scale, rounds = _int_kw(node, "scale"), _int_kw(node, "rounds")
            if (scale is None or scale >= SOAK_SCALE
                    or rounds is None or rounds >= SOAK_ROUNDS):
                return True
    return False


def find_unmarked(paths=None):
    violations = []
    paths = paths or sorted(
        os.path.join(TESTS, f) for f in os.listdir(TESTS)
        if f.startswith("test_") and f.endswith(".py"))
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        if _module_marked_slow(tree):
            continue
        scopes = [(tree.body, False)]
        for cls in tree.body:
            if isinstance(cls, ast.ClassDef):
                scopes.append((cls.body, _has_slow(cls.decorator_list)))
        for body, class_slow in scopes:
            for fn in body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                if not fn.name.startswith("test_"):
                    continue
                if class_slow or _has_slow(fn.decorator_list):
                    continue
                if _is_perf_scale(fn):
                    violations.append(
                        f"{os.path.relpath(path, REPO)}:{fn.lineno} "
                        f"{fn.name}")
    return violations


def main() -> int:
    violations = find_unmarked()
    if violations:
        print(f"UNMARKED PERF-SCALE TESTS ({len(violations)}): "
              f">= {PERF_SCALE_NODES} nodes (or TEST_CASES defaults) "
              "without @pytest.mark.slow:")
        for v in violations:
            print(f"  - {v}")
        return 1
    print("ok: every perf-scale test carries the slow marker")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
