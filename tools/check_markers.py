#!/usr/bin/env python3
"""Thin shim over tools/ktpu_check.py (the ``markers`` pass).

The slow-marker lint lives in the unified ``ktpu_check`` registry; this CLI
keeps the historical invocation (``python tools/check_markers.py``) and the
``find_unmarked(paths)`` surface the tier-1 tests call. Prefer
``python -m tools.ktpu_check --pass markers``.
"""

from __future__ import annotations

import importlib.util
import os

_HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(_HERE)
TESTS = os.path.join(REPO, "tests")


def _ktpu_check():
    spec = importlib.util.spec_from_file_location(
        "ktpu_check", os.path.join(_HERE, "ktpu_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_kc = _ktpu_check()
PERF_SCALE_NODES = _kc.PERF_SCALE_NODES
SOAK_SCALE = _kc.SOAK_SCALE
SOAK_ROUNDS = _kc.SOAK_ROUNDS


def find_unmarked(paths=None):
    """Violations as the historical ``"path:line name"`` strings."""
    return [f"{os.path.relpath(path, REPO)}:{line} {name}"
            for path, line, name in _kc.find_unmarked(paths)]


def main() -> int:
    violations = find_unmarked()
    if violations:
        print(f"UNMARKED PERF-SCALE TESTS ({len(violations)}): "
              f">= {PERF_SCALE_NODES} nodes (or TEST_CASES defaults) "
              "without @pytest.mark.slow:")
        for v in violations:
            print(f"  - {v}")
        return 1
    print("ok: every perf-scale test carries the slow marker")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
