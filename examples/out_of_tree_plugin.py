"""Out-of-tree scheduler plugin registration — the ``app.WithPlugin``
analog (reference: cmd/kube-scheduler/app/server.go:293).

The reference lets vendors ship plugins outside the kubernetes tree:

    command := app.NewSchedulerCommand(
        app.WithPlugin("ZoneWeight", zoneweight.New),
    )

and enable them per profile in KubeSchedulerConfiguration. This framework's
equivalent is the ``out_of_tree_registry`` argument of
``kubernetes_tpu.config.scheduler_from_config``: a ``{name: factory}`` map
merged with the in-tree registry (config/factory.py; a name collision with
an in-tree plugin raises). The factory signature matches the in-tree ones:

    factory(handle_ctx, args) -> Plugin instance

* ``handle_ctx`` is the framework Handle (snapshot/listers/client seams);
  most out-of-tree plugins only need ``args``.
* ``args`` is the profile's pluginConfig args block for this plugin,
  already decoded to a plain dict.

The plugin below implements two extension points (Filter + Score) the way
an in-tree plugin does; enable it through a config profile, including
MultiPoint shorthand. Pods scheduled through a profile carrying a
non-default plugin set take the host (sequential) path automatically —
TPUScheduler only batches profiles whose compiled program matches the
default set (backend/tpu_scheduler.py _framework_batchable), so out-of-tree
plugins are always honored.

Run me:  python examples/out_of_tree_plugin.py
"""

from __future__ import annotations

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config import scheduler_from_config
from kubernetes_tpu.framework.interface import (
    FilterPlugin,
    ScorePlugin,
    Status,
)


class ZoneWeight(FilterPlugin, ScorePlugin):
    """Filter out forbidden zones; score the rest by configured weights.

    Args (pluginConfig):
        forbidden: [zone, ...]        zones no pod may land in
        weights:   {zone: 0..100}     preference per zone (default 50)
    """

    NAME = "ZoneWeight"
    ZONE_LABEL = "zone"

    def __init__(self, handle, args: dict):
        self.handle = handle
        self.forbidden = set(args.get("forbidden", ()))
        self.weights = dict(args.get("weights", {}))

    def name(self) -> str:
        return self.NAME

    # -- Filter extension point
    def filter(self, state, pod, node_info) -> Status:
        zone = node_info.node.meta.labels.get(self.ZONE_LABEL, "")
        if zone in self.forbidden:
            return Status.unschedulable(
                f"zone {zone!r} is forbidden").with_plugin(self.NAME)
        return Status()

    # -- Score extension point (the runtime calls score_node with the
    #    NodeInfo and expects ``(raw_score, Status)``)
    def score(self, state, pod, node_name):
        raise NotImplementedError  # the runtime drives score_node

    def score_node(self, state, pod, node_info):
        zone = node_info.node.meta.labels.get(self.ZONE_LABEL, "")
        return int(self.weights.get(zone, 50)), Status()


def main() -> None:
    store = ClusterStore()
    for i in range(6):
        store.create_node(
            make_node(f"node-{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
            .label("zone", f"z{i % 3}")
            .obj())

    # KubeSchedulerConfiguration (raw v1beta3-shaped dict): enable the
    # plugin on a dedicated profile; z2 forbidden, z1 preferred
    raw = {
        "apiVersion": "kubescheduler.config.k8s.io/v1beta3",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{
            "schedulerName": "zoned-scheduler",
            "plugins": {
                "filter": {"enabled": [{"name": ZoneWeight.NAME}]},
                "score": {"enabled": [{"name": ZoneWeight.NAME, "weight": 5}]},
            },
            "pluginConfig": [{
                "name": ZoneWeight.NAME,
                "args": {"forbidden": ["z2"], "weights": {"z1": 100, "z0": 10}},
            }],
        }],
    }
    sched = scheduler_from_config(
        store, raw=raw,
        out_of_tree_registry={ZoneWeight.NAME: ZoneWeight},
    )

    for i in range(4):
        pw = make_pod(f"pod-{i}").req({"cpu": "500m", "memory": "512Mi"})
        pw.scheduler_name("zoned-scheduler")
        store.create_pod(pw.obj())
    sched.run_until_settled()

    for key, pod in store.pods.items():
        node = store.nodes[pod.spec.node_name]
        print(f"{key} -> {pod.spec.node_name} (zone {node.meta.labels['zone']})")


if __name__ == "__main__":
    main()
