"""Slice-topology packing: contiguous torus placement for gangs on device.

A slice gang (a PodGroup whose pods carry the ``ktpu.dev/slice`` marker
label) must land on a *contiguous* run of torus-adjacent hosts inside one
superpod — the multi-host TPU contract the flat gang assigner (ops/gang.py)
cannot express. The planner here runs INSIDE the batch program's jit,
before the commit scan: it picks one window of ``k`` adjacent free cells
per gang, and the scan is then pinned to those choices through a per-pod
feasibility mask, so slice verdicts ride the packed result block with zero
extra device dispatch per batch.

Coordinate model: every node carries ``(topo_sp, topo_pos)`` — the superpod
id and a LINEAR position inside that superpod's torus (ops/encode.py parses
them from the well-known labels, or synthesizes them from the node slot).
The torus is linearized: contiguity means consecutive ``topo_pos`` values
within one superpod, the 1-D snake order a real (x, y, z) torus walk
induces. Windows never span superpods and never wrap.

Scoring (best fit + anti-fragmentation): among feasible windows the planner
minimizes ``left_run + right_run`` — the free cells the placement strands
on either side. A hole of exactly ``k`` scores 0 (perfect fit), so small
jobs prefer already-shredded capacity; a pristine superpod-wide run scores
``P - k`` and is only split when no tighter hole exists. Ties break to the
lowest superpod id, then the lowest start position — reproduced exactly by
``slice_assign_host``, the greedy numpy oracle the parity tests and the
host SlicePacking plugin share.

Cross-gang consistency inside one batch: gangs plan sequentially
(``lax.scan``) against a shared taken-cell bitmap, so two gangs in one
batch can never be planned onto overlapping windows.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .encode import TOPO_SLOT_LABEL, TOPO_SUPERPOD_LABEL  # noqa: F401 — re-export

# marker label: a PodGroup whose pods carry it is slice-placed (contiguous
# torus window) instead of flat gang-assigned
SLICE_LABEL = "ktpu.dev/slice"


def is_slice_pod(pod) -> bool:
    return bool(pod.meta.labels.get(SLICE_LABEL))


def _row_runs(fg: jax.Array) -> jax.Array:
    """[S, P] bool -> [S, P] int32: length of the free run ENDING at each
    cell (0 where blocked). Scan-free: distance to the last blocked cell,
    via a cummax over blocked positions."""
    p = fg.shape[1]
    iota = jnp.arange(p, dtype=jnp.int32)[None, :]
    last_blocked = lax.cummax(jnp.where(fg, np.int32(-1), iota), axis=1)
    return jnp.where(fg, iota - last_blocked, 0)


def plan_slices(nt, req: jax.Array, member_idx: jax.Array,
                member_valid: jax.Array,
                slice_grid: Tuple[int, int]) -> Tuple[jax.Array, jax.Array]:
    """Plan every slice gang of a batch onto contiguous torus windows.

    ``nt``: NodeTensors (pre-batch state). ``req``: [P, R] int32 per-pod
    requests (pb.req). ``member_idx``: [G, M] int32 rows into the pod axis
    (-1 pad); ``member_valid``: [G, M] bool. ``slice_grid``: static
    (superpods, slots-per-superpod). Returns (targets [G, M] int32 node
    slots, -1 for padding/rejected; ok [G] bool all-or-nothing verdicts).

    Per-gang request = elementwise max over active members — exact for the
    homogeneous gangs slice jobs are, conservative otherwise. Feasibility is
    valid & schedulable & resource fit at batch start; non-slice pods the
    scan places mid-batch are invisible to the plan (their capacity charge
    lands in-scan, where a collision turns into a whole-gang miss, never a
    partial placement).
    """
    s_pods, ps = slice_grid
    cells = s_pods * ps
    g, m = member_idx.shape
    p = req.shape[0]
    n = nt.valid.shape[0]

    safe = jnp.clip(member_idx, 0, p - 1)
    mreq = jnp.where(member_valid[..., None], req[safe], 0)      # [G, M, R]
    req_g = jnp.max(mreq, axis=1)                                 # [G, R]
    want = jnp.sum(member_valid, axis=1).astype(jnp.int32)        # [G]

    # node -> linearized grid cell; nodes without (in-range) coordinates
    # land in a spill cell past the grid and never participate
    has_coord = (nt.topo_sp >= 0) & (nt.topo_sp < s_pods) \
        & (nt.topo_pos >= 0) & (nt.topo_pos < ps) & nt.valid
    cell = jnp.where(has_coord, nt.topo_sp * ps + nt.topo_pos, cells)
    grid_node = jnp.full(cells + 1, -1, jnp.int32).at[cell].set(
        jnp.arange(n, dtype=jnp.int32))[:cells]
    node_of_cell = jnp.clip(grid_node, 0, n - 1)

    free = nt.allocatable - nt.requested                          # [N, R]
    ok_node = nt.valid & ~nt.unschedulable                        # [N]
    iota_ps = jnp.arange(ps, dtype=jnp.int32)
    iota_cells = jnp.arange(cells, dtype=jnp.int32)
    big = np.int32(2 ** 31 - 1)

    def place(taken, xs):
        rg, k, mv = xs
        # `req == 0 always fits` sentinel, same trick as the batch scan
        gate = jnp.where(rg == 0, jnp.int32(-(2 ** 30)), rg)
        fits = jnp.all(free >= gate[None, :], axis=-1) & ok_node  # [N]
        feas = (grid_node >= 0) & fits[node_of_cell] & ~taken     # [cells]
        fg = feas.reshape(s_pods, ps)

        # window feasibility for dynamic length k via row prefix sums:
        # window [b, b+k) is free iff csum[b+k-1] - csum[b-1] == k
        csum = jnp.cumsum(fg.astype(jnp.int32), axis=1)
        hi_idx = jnp.clip(iota_ps + k - 1, 0, ps - 1)
        hi = jnp.take(csum, hi_idx, axis=1)
        lo = jnp.pad(csum, ((0, 0), (1, 0)))[:, :-1]
        win_ok = (k > 0) & (iota_ps[None, :] + k <= ps) & (hi - lo == k)

        # fragmentation term: free run stranded left of b plus right of
        # b+k-1 — best fit minimizes the leftover
        run_end = _row_runs(fg)
        run_start = jnp.flip(_row_runs(jnp.flip(fg, axis=1)), axis=1)
        left = jnp.pad(run_end, ((0, 0), (1, 0)))[:, :-1]
        right_idx = jnp.clip(iota_ps + k, 0, ps - 1)
        right = jnp.where((iota_ps[None, :] + k) < ps,
                          jnp.take(run_start, right_idx, axis=1), 0)
        leftover = left + right

        # encoded preference: leftover, then superpod, then start — one
        # argmin, identical to slice_assign_host's (leftover, s, b) tuple
        score = jnp.where(win_ok, leftover * cells
                          + iota_cells.reshape(s_pods, ps), big).reshape(-1)
        best = jnp.argmin(score).astype(jnp.int32)
        okg = score[best] < big

        off = jnp.cumsum(mv.astype(jnp.int32)) - 1                # [M]
        tcell = jnp.clip(best + off, 0, cells - 1)
        tnode = jnp.where(mv & okg, grid_node[tcell], jnp.int32(-1))
        taken = taken | (okg & (iota_cells >= best) & (iota_cells < best + k))
        return taken, (tnode, okg)

    taken0 = jnp.zeros(cells, bool)
    _taken, (targets, ok) = lax.scan(
        place, taken0, (req_g, want, member_valid))
    return targets, ok


def slice_assign_host(topo_sp, topo_pos, valid, fits, want,
                      slice_grid: Tuple[int, int],
                      taken_cells=None) -> Tuple[List[List[int]], List[bool]]:
    """Host oracle of ``plan_slices`` (parity tests + the SlicePacking
    plugin): the same greedy best-fit walk in plain Python. ``fits`` is
    [G, N] bool (node currently fits gang g's request and is schedulable),
    ``want`` [G] member counts. ``taken_cells`` optionally seeds the
    taken-cell bitmap (the plugin's live-plan reservations). Returns
    (per-gang node-slot lists — empty when rejected, ok flags)."""
    s_pods, ps = slice_grid
    cells = s_pods * ps
    grid_node = np.full(cells, -1, np.int64)
    for nidx in range(len(topo_sp)):
        sp, pos = int(topo_sp[nidx]), int(topo_pos[nidx])
        if valid[nidx] and 0 <= sp < s_pods and 0 <= pos < ps:
            grid_node[sp * ps + pos] = nidx
    taken = np.zeros(cells, bool)
    if taken_cells is not None:
        for c in taken_cells:
            if 0 <= c < cells:
                taken[c] = True
    out_targets: List[List[int]] = []
    out_ok: List[bool] = []
    for gi in range(len(want)):
        k = int(want[gi])
        best, best_score = -1, None
        if k > 0:
            feas = np.array([
                grid_node[c] >= 0 and bool(fits[gi][grid_node[c]])
                and not taken[c] for c in range(cells)])
            fg = feas.reshape(s_pods, ps)
            for s in range(s_pods):
                row = fg[s]
                for b in range(ps - k + 1):
                    if not row[b:b + k].all():
                        continue
                    left = 0
                    q = b - 1
                    while q >= 0 and row[q]:
                        left += 1
                        q -= 1
                    right = 0
                    q = b + k
                    while q < ps and row[q]:
                        right += 1
                        q += 1
                    cand = (left + right, s, b)
                    if best_score is None or cand < best_score:
                        best_score, best = cand, s * ps + b
        if best < 0:
            out_targets.append([])
            out_ok.append(False)
            continue
        out_targets.append([int(grid_node[best + o]) for o in range(k)])
        taken[best:best + k] = True
        out_ok.append(True)
    return out_targets, out_ok


def fragmentation_host(topo_sp, topo_pos, valid, node_free,
                       slice_grid: Tuple[int, int]) -> List[dict]:
    """Per-superpod fragmentation accounting (host-side, numpy over the
    device mirror — no device sync). ``node_free`` [N] bool marks nodes
    whose full chip complement is available for slice use. Returns one dict
    per superpod that has any mapped node: {sp, free, used, largest_run,
    frag} where frag = 1 - largest_free_run / free_count (0.0 when nothing
    is free — an exhausted superpod is full, not fragmented)."""
    s_pods, ps = slice_grid
    rows: List[dict] = []
    free_grid = np.zeros((s_pods, ps), bool)
    present = np.zeros((s_pods, ps), bool)
    for nidx in range(len(topo_sp)):
        sp, pos = int(topo_sp[nidx]), int(topo_pos[nidx])
        if valid[nidx] and 0 <= sp < s_pods and 0 <= pos < ps:
            present[sp, pos] = True
            free_grid[sp, pos] = bool(node_free[nidx])
    for s in range(s_pods):
        if not present[s].any():
            continue
        free = int(free_grid[s].sum())
        used = int(present[s].sum()) - free
        largest = run = 0
        for cell_free in free_grid[s]:
            run = run + 1 if cell_free else 0
            largest = max(largest, run)
        frag = 0.0 if free == 0 else 1.0 - largest / free
        rows.append({"sp": s, "free": free, "used": used,
                     "largest_run": largest, "frag": frag})
    return rows
