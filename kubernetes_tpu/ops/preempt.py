"""Batched preemption screen + candidate ranking on device.

The reference's second hot loop is ``DryRunPreemption`` — a parallel per-node
simulation that removes lower-priority victims and re-runs the Filters
(preemption.go:546, defaultpreemption/default_preemption.go:226). The device
equivalent buckets each node's pods by priority class (NodeTensors.class_req)
and computes, per (pending pod, node):

  * ``k_needed`` — the minimal number of priority classes (ascending priority
    order) whose full eviction makes the pod fit: a prefix-sum fit check,
    exact for the resource/pod-count dimensions;
  * a candidate ranking key approximating ``pickOneNodeForPreemption``
    (preemption.go:397): no-victims first, then lowest max victim priority,
    then smallest victim-priority sum, then fewest victims.

The host then runs the EXACT victim selection (reprieve order, filters) only
on the device-chosen candidate, falling back to the ranked screen mask when
verification fails — the "device proposes, host verifies" contract.

Documented divergences from the reference (host verify bounds their effect):
  * victims are whole priority classes in the screen; the host reprieve still
    trims to the minimal set on the chosen node;
  * PDB-violation counts are not modeled on device — clusters WITH PDBs take
    the host path wholesale (defaultpreemption.py gates on the PDB lister);
  * the earliest-start-time tiebreak (criterion 5) is not modeled.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .schema import COL_PODS, NodeTensors, PodBatch

_BIG = np.float32(1e18)


class PreemptResult(NamedTuple):
    screen: jax.Array     # [P, N] bool — pod could fit after evicting lower-prio classes
    best: jax.Array       # [P] int32 — top-ranked candidate slot (-1 = none)


def screen_prefix(pb, nt, static_masks, failed_prefix):
    """Pad an [n]-bool per-pod failure prefix to pb.capacity and run the
    screen — the ONE construction every caller (batch commit, wire service,
    bucket warmup) must share, so a signature or mask-convention change
    lands everywhere at once.

    The padding happens EAGERLY in numpy, outside the jit: the prefix is a
    host-side numpy bool array, and assigning it into a numpy buffer inside
    a traced function raises TracerArrayConversionError (this silently
    disabled the batched preemption hints for every caller that caught the
    exception — VERDICT r4 weak #4's 4s preemption p99)."""
    failed = np.zeros(pb.capacity, bool)
    failed[: len(failed_prefix)] = np.asarray(failed_prefix, bool)
    return _screen_jit(pb, nt, static_masks, failed)


@functools.partial(jax.jit, static_argnames=())
def _screen_jit(pb, nt, static_masks, failed):
    return preempt_screen(pb, nt, static_masks, failed)


def preempt_screen(pb: PodBatch, nt: NodeTensors, static_masks,
                   failed: jax.Array) -> PreemptResult:
    """``static_masks``: the batch's static filter masks [P,N] (unschedulable,
    node name, taints, affinity) — eviction cannot fix those, matching
    nodesWherePreemptionMightHelp's skip of unresolvable nodes. ANDed here,
    inside the jit (eager ops each cost a relay round-trip)."""
    static_ok = pb.valid[:, None] & nt.valid[None, :]
    for m in static_masks.values():
        static_ok = static_ok & m
    alloc = nt.allocatable          # [N, R]
    req = nt.requested              # [N, R]
    class_req = nt.class_req        # [N, C, R]
    class_prio = nt.class_prio      # [C]
    P = pb.capacity
    N, C, R = class_req.shape

    order = jnp.argsort(class_prio)                      # ascending priority
    cprio = class_prio[order]                            # [C]
    creq = jnp.take(class_req, order, axis=1)            # [N, C, R]
    cum = jnp.cumsum(creq, axis=1)                       # [N, C, R]

    # deficit per resource: how much must be freed for pod p on node n
    deficit = pb.req[:, None, :] - (alloc - req)[None, :, :]      # [P, N, R]

    # classes-needed per resource: deficit ≤ 0 → 0; else 1 + index of the
    # first prefix whose cumulative freed amount covers the deficit (= 1 +
    # count of insufficient prefixes). Loop over the small static R axis to
    # keep intermediates [P, N, C].
    k_needed = jnp.zeros((P, N), jnp.int32)
    for r in range(R):
        d = deficit[:, :, r]
        cnt = jnp.sum(cum[None, :, :, r] < d[:, :, None], axis=-1).astype(jnp.int32)
        k_r = jnp.where(d > 0, cnt + 1, 0)
        k_needed = jnp.maximum(k_needed, k_r)

    # eligible prefix length per pod: classes with priority < pod priority
    # (a PREFIX of the ascending order)
    elig = jnp.sum(cprio[None, :] < pb.priority[:, None], axis=-1)  # [P]
    viable = (k_needed <= elig[:, None]) & static_ok & nt.valid[None, :]

    # ranking stats from the evicted prefix (k = k_needed)
    cum_cnt = jnp.cumsum(creq[..., COL_PODS], axis=1)              # [N, C]
    cum_psum = jnp.cumsum(
        creq[..., COL_PODS].astype(jnp.float32) * cprio[None, :].astype(jnp.float32),
        axis=1)                                                     # [N, C]
    k = k_needed  # [P, N]
    k_idx = jnp.clip(k - 1, 0, C - 1)
    victims = jnp.where(k > 0, jnp.take_along_axis(
        jnp.broadcast_to(cum_cnt[None], (P, N, C)), k_idx[:, :, None], axis=2)[:, :, 0], 0)
    psum = jnp.where(k > 0, jnp.take_along_axis(
        jnp.broadcast_to(cum_psum[None], (P, N, C)), k_idx[:, :, None], axis=2)[:, :, 0], 0.0)
    maxprio = jnp.where(k > 0, cprio[k_idx].astype(jnp.float32), -_BIG)

    # staged masked argmin = exact lexicographic selection over
    # (max victim priority, victim priority sum, victim count) — pickOneNode
    # criteria 2-4; criterion 1 (PDBs) is host-gated, criterion 5 (start
    # time) not modeled. float32 packing would lose the low-order criteria.
    def _pick(mask_row, keys):
        for key_row in keys:
            masked = jnp.where(mask_row, key_row, _BIG)
            mask_row = mask_row & (masked == jnp.min(masked))
        return jnp.argmax(mask_row).astype(jnp.int32)

    # greedy claim diversification: pods in the same failed batch must not
    # all converge on the identical best node (the host nominates them one
    # by one, and a node already claimed by an earlier preemptor fails the
    # later pods' exact verification, pushing them onto the slow full scan).
    # Each FAILED pod prefers unclaimed viable nodes; claimed ones remain a
    # fallback when nothing else is viable. Scheduled pods neither claim nor
    # consume hints (their rows would only steer real preemptors away from
    # their cheapest victims).
    victims_f = victims.astype(jnp.float32)

    def claim_step(claimed, xs):
        v_row, mp_row, ps_row, vc_row, is_failed = xs
        prefer = v_row & ~claimed
        row = jnp.where(jnp.any(prefer), prefer, v_row)
        idx = _pick(row, (mp_row, ps_row, vc_row))
        ok = jnp.any(v_row) & is_failed
        claimed = claimed | (jnp.arange(N) == idx) & ok
        return claimed, jnp.where(ok, idx, -1)

    _, best = jax.lax.scan(
        claim_step, jnp.zeros((N,), bool),
        (viable, maxprio, psum, victims_f, failed))
    return PreemptResult(screen=viable, best=best)
