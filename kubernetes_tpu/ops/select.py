"""Host selection: weighted score sum + masked argmax with uniform tie-break.

selectHost (schedule_one.go:709) picks argmax with a reservoir-sampled uniform
tie-break.  The kernel equivalent adds U(0, 0.5) jitter to integer-valued
scores (gap ≥ 1 between distinct totals), which is exactly "uniform among the
maxima" — and deterministic under a fixed PRNG key for parity tests.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

# Python literal, NOT jnp.float32(...): a module-level jax scalar is a
# device buffer that gets closure-captured into every jitted program using
# it, and the axon TPU relay re-fetches captured buffer constants on every
# while-loop iteration — one such scalar inside the commit scan measured
# ~2000x slower (68ms vs 0.03ms per batch). Literals lower to HLO constants.
NEG_INF = -(2.0**30)


def weighted_total(scores: Dict[str, jax.Array], weights: Dict[str, float]) -> jax.Array:
    """Σ_plugin weight · normalized-score (runtime/framework.go:951-966)."""
    total = None
    for name, s in scores.items():
        w = weights.get(name, 1.0)
        total = w * s if total is None else total + w * s
    return total


def select_host(total: jax.Array, feasible: jax.Array, key: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-pod winner: (node_idx [P] int32, best_score [P], any_feasible [P]).
    node_idx is -1 for pods with no feasible node."""
    jitter = jax.random.uniform(key, total.shape, jnp.float32, 0.0, 0.5)
    eff = jnp.where(feasible, total + jitter, NEG_INF)
    idx = jnp.argmax(eff, axis=1).astype(jnp.int32)
    any_feasible = jnp.any(feasible, axis=1)
    best = jnp.take_along_axis(total, idx[:, None], axis=1)[:, 0]
    return jnp.where(any_feasible, idx, -1), best, any_feasible
