"""Host-side compiler: API objects → dense device tensors.

The ClusterEncoder owns every vocabulary (label keys, per-key value vocabs,
ports, images, scalar resources, node slots) and produces:

  * per-node rows (``encode_node_row``) / full snapshots (``encode_snapshot``)
    following the NodeTensors schema;
  * compiled pod batches (``encode_pods``): a deduplicated ExprTable (the
    batch's unique selector expressions) plus per-pod programs indexing it.

String semantics compiled here, evaluated on device (SURVEY.md §7 "hard parts"
#1):
  - label selector expressions → (op, key-slot, value-id-set bitset);
  - nodeSelector maps → AND-combined single-value IN exprs;
  - metadata.name matchFields → OP_NODE_NAME on the node-slot axis;
  - tolerations → (key-id, value-id, op, effect) rows;
  - host ports → exact wildcard-IP conflict semantics with two vocab bits per
    used port: ("*", proto, port) marks "any IP uses proto/port", and the
    concrete (ip, proto, port) bit preserves IP-specific matching
    (framework/types.go HostPortInfo).

Vocab ids are append-only; id 0 = absent everywhere.  Encoders raise
CapacityError when a static capacity is exceeded — callers re-encode with
``Capacities.grow_*`` (the recompilation policy lives in backend/, not here).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import resource as resource_api
from ..api.types import (
    EXISTS,
    DOES_NOT_EXIST,
    GT,
    IN,
    LT,
    NOT_IN,
    Pod,
    Requirement,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE,
    Taint,
    TOLERATION_OP_EXISTS,
)
from ..framework.types import NodeInfo, nonzero_request
from ..utils.vocab import Vocab
from . import schema
from .schema import Capacities, INT_NONE

_EFFECT_CODE = {
    "": schema.EFFECT_NONE,
    TAINT_NO_SCHEDULE: schema.EFFECT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE: schema.EFFECT_PREFER_NO_SCHEDULE,
    TAINT_NO_EXECUTE: schema.EFFECT_NO_EXECUTE,
}

_UNSCHEDULABLE_TAINT = Taint(key="node.kubernetes.io/unschedulable", effect=TAINT_NO_SCHEDULE)

# well-known TPU torus labels (GKE `cloud.google.com/gke-tpu-topology`-style
# keys): the superpod a host belongs to and its linear position inside that
# superpod's torus. Nodes without both labels fall back to slot-derived
# synthetic coordinates (the harness's simulated torus).
TOPO_SUPERPOD_LABEL = "cloud.google.com/gke-tpu-superpod"
TOPO_SLOT_LABEL = "cloud.google.com/gke-tpu-slot"


class CapacityError(Exception):
    """A static tensor capacity was exceeded; re-encode with larger Capacities."""

    def __init__(self, dimension: str, needed: int, capacity: int):
        self.dimension = dimension
        self.needed = needed
        self.capacity = capacity
        super().__init__(f"capacity exceeded: {dimension} needs {needed} > {capacity}")


_NEVER = "__never__"  # expr-key sentinel: term matches nothing


@dataclass
class _PodTemplate:
    """Builder-independent encode of one pod-spec shape.

    Thousands of workload pods share a handful of spec shapes (the
    scheduler_perf pod templates), so the expensive per-pod work — quantity
    canonicalization, toleration/selector/affinity compilation — is done once
    per shape. Expr *keys* (not batch-local slots) are stored; they are
    re-interned into each batch's ExprTable, which dedups by key. Vocab ids
    inside keys/arrays are append-only and therefore stable for the life of
    the encoder (growth rebuilds the encoder, resetting this cache)."""

    priority: int
    req: np.ndarray
    nzreq: np.ndarray
    tol_key: np.ndarray
    tol_val: np.ndarray
    tol_op: np.ndarray
    tol_effect: np.ndarray
    tol_prefer: np.ndarray
    tolerates_unsched: bool
    sel_keys: Tuple
    term_keys: Tuple            # ((expr_key | _NEVER, ...), ...)
    pref_terms: Tuple           # ((weight, (expr_key | _NEVER, ...)), ...)
    port_wanted: Tuple[int, ...]
    n_containers: int


class ClusterEncoder:
    def __init__(self, caps: Capacities):
        self.caps = caps
        self.key_vocab = Vocab("label-keys")          # key string -> key slot (1-based, < K)
        self.value_vocabs: Dict[int, Vocab] = {}      # key slot -> value vocab
        self.port_vocab = Vocab("ports")              # (ip|'*', proto, port) -> id
        self.image_vocab = Vocab("images")
        self.scalar_vocab = Vocab("scalar-resources")
        # priority-class vocab (batched preemption): distinct pod priority
        # values -> class id; id 0 reserved (class_prio INT_MAX = never
        # evictable padding)
        self.prio_vocab: Dict[int, int] = {}
        self.node_slots: Dict[str, int] = {}          # node name -> slot
        self.slot_names: Dict[int, str] = {}          # live reverse map
        self._free_slots: List[int] = []
        # slot-reclamation bookkeeping (elastic clusters): a released slot is
        # a TOMBSTONE until reused — ``reclaim_gen`` is a monotonic release
        # counter and ``slot_release_gen[slot]`` the gen at the slot's last
        # release, so an in-flight batch (which captured reclaim_gen at
        # dispatch) can prove at commit time that a winner slot still names
        # the node the kernel judged (slot_stale_since). ``slot_reuses``
        # counts free-list pops (the scheduler_device_slot_reuse_total feed).
        self.reclaim_gen = 0
        self.slot_release_gen: Dict[int, int] = {}
        self.slot_reuses = 0
        # node-retained vocab refcounts: (key, value) string pairs each LIVE
        # node's labels/taints pin in the per-key value vocabs. Release at
        # refcount zero frees the id for reuse (bounded vocab consumption
        # under node churn); any free invalidates the pod-template cache,
        # whose compiled expr keys embed value ids.
        self._value_refs: Dict[Tuple[str, str], int] = {}
        self._node_value_pairs: Dict[str, frozenset] = {}
        self._pod_templates: Dict[Tuple, _PodTemplate] = {}
        self.last_has_ports = False                   # set by encode_pods
        self._template_cap = 4096                     # runaway-shape guard
        # node-STATIC row fields (labels/taints/images/allocatable) keyed by
        # (name, resourceVersion): only pod-dependent fields re-encode when a
        # row is dirty from commits alone — the reconcile hot path re-encodes
        # every committed row each batch
        self._static_rows: Dict[str, Tuple[int, Dict[str, np.ndarray]]] = {}

    # ------------------------------------------------------------- vocab plumbing

    def key_slot(self, key: str) -> int:
        slot = self.key_vocab.id(key)
        if slot >= self.caps.label_keys:
            raise CapacityError("label_keys", slot + 1, self.caps.label_keys)
        return slot

    def value_id(self, key: str, value: str) -> int:
        ks = self.key_slot(key)
        vv = self.value_vocabs.setdefault(ks, Vocab(f"values[{key}]"))
        vid = vv.id(value)
        if vid >= self.caps.value_words * 32:
            raise CapacityError(f"value vocab for {key!r}", vid + 1, self.caps.value_words * 32)
        return vid

    def scalar_col(self, resource: str) -> int:
        col = schema.N_FIXED_COLS + self.scalar_vocab.id(resource) - 1
        if col >= self.caps.resources:
            raise CapacityError("resources", col + 1, self.caps.resources)
        return col

    def resource_col(self, resource: str) -> int:
        fixed = {
            resource_api.CPU: schema.COL_CPU,
            resource_api.MEMORY: schema.COL_MEM,
            resource_api.EPHEMERAL_STORAGE: schema.COL_EPH,
            resource_api.PODS: schema.COL_PODS,
        }
        if resource in fixed:
            return fixed[resource]
        return self.scalar_col(resource)

    def port_id(self, ip: str, proto: str, port: int) -> int:
        pid = self.port_vocab.id((ip, proto, port))
        if pid >= self.caps.port_words * 32:
            raise CapacityError("ports vocab", pid + 1, self.caps.port_words * 32)
        return pid

    def image_id(self, name: str) -> int:
        iid = self.image_vocab.id(name)
        if iid >= self.caps.images:
            raise CapacityError("image vocab", iid + 1, self.caps.images)
        return iid

    def prio_class_id(self, priority: int) -> int:
        cid = self.prio_vocab.get(priority)
        if cid is None:
            cid = len(self.prio_vocab) + 1  # 0 reserved
            if cid >= self.caps.prio_classes:
                raise CapacityError("prio_classes", cid + 1, self.caps.prio_classes)
            self.prio_vocab[priority] = cid
        return cid

    def class_prio_array(self) -> np.ndarray:
        """[C] int32: priority value per class id; reserved/unused rows get
        INT_MAX so `class_prio < pod_priority` is never true for them."""
        arr = np.full(self.caps.prio_classes, 2**31 - 1, np.int32)
        for prio, cid in self.prio_vocab.items():
            arr[cid] = prio
        return arr

    def node_slot(self, name: str) -> int:
        slot = self.node_slots.get(name)
        if slot is None:
            reused = bool(self._free_slots)
            slot = self._free_slots.pop() if self._free_slots else len(self.node_slots)
            # slots are dense; a freed slot is reused before extending
            used = set(self.node_slots.values())
            if slot in used:  # freed-list raced with dense growth; find a hole
                slot = next(i for i in range(self.caps.nodes + 1) if i not in used)
            if slot >= self.caps.nodes:
                raise CapacityError("nodes", slot + 1, self.caps.nodes)
            if reused:
                self.slot_reuses += 1
            self.node_slots[name] = slot
            self.slot_names[slot] = name
        return slot

    def release_node_slot(self, name: str) -> Optional[int]:
        """Tombstone a removed node's slot: the row index goes to the
        free-list for reuse, the release generation is stamped so in-flight
        commits naming it get a typed rejection, and the node's vocab
        retentions are dropped (value ids free at refcount zero)."""
        slot = self.node_slots.pop(name, None)
        self._static_rows.pop(name, None)
        self.release_node_values(name)
        if slot is not None:
            self.slot_names.pop(slot, None)
            self._free_slots.append(slot)
            self.reclaim_gen += 1
            self.slot_release_gen[slot] = self.reclaim_gen
        return slot

    def slot_stale_since(self, slot: int, gen: int) -> bool:
        """True iff ``slot`` was released (tombstoned/reused) after an
        observer captured ``reclaim_gen == gen`` — the commit-time guard for
        placements decided before the release."""
        return self.slot_release_gen.get(slot, 0) > gen

    # ------------------------------------------------- node vocab retention

    @staticmethod
    def _node_pairs(node) -> frozenset:
        pairs = {(k, v) for k, v in node.meta.labels.items()}
        pairs.update((t.key, t.value) for t in node.spec.taints)
        return frozenset(pairs)

    def retain_node_values(self, name: str, node) -> None:
        """Refcount the (key, value) label/taint pairs ``node`` pins in the
        value vocabs (called per dirty row from DeviceState.sync — the same
        walk that encodes the row, so every retained pair is interned)."""
        new = self._node_pairs(node) if node is not None else frozenset()
        old = self._node_value_pairs.get(name, frozenset())
        if new == old:
            return
        for pair in new - old:
            self._value_refs[pair] = self._value_refs.get(pair, 0) + 1
        freed = False
        for pair in old - new:
            freed |= self._drop_value_ref(pair)
        if new:
            self._node_value_pairs[name] = new
        else:
            self._node_value_pairs.pop(name, None)
        if freed:
            # cached templates embed value ids; a freed id may be recycled
            # for a different string, so every compiled key set is suspect
            self._pod_templates.clear()

    def release_node_values(self, name: str) -> None:
        old = self._node_value_pairs.pop(name, None)
        if not old:
            return
        freed = False
        for pair in old:
            freed |= self._drop_value_ref(pair)
        if freed:
            self._pod_templates.clear()

    def _drop_value_ref(self, pair: Tuple[str, str]) -> bool:
        """Decrement one (key, value) retention; free the vocab id at zero.
        Returns True when an id was actually freed."""
        left = self._value_refs.get(pair, 0) - 1
        if left > 0:
            self._value_refs[pair] = left
            return False
        self._value_refs.pop(pair, None)
        ks = self.key_vocab.lookup(pair[0])
        vv = self.value_vocabs.get(ks)
        return vv is not None and vv.release(pair[1]) is not None

    def release_image(self, name: str) -> None:
        """Free an image vocab id once no node reports the image (driven by
        DeviceState._track_images' global refcount). Image ids are looked up
        per encode (never cached in templates), so no cache invalidation."""
        self.image_vocab.release(name)

    # ------------------------------------------------------------- resources

    def resource_vec(self, m: Dict[str, int]) -> np.ndarray:
        v = np.zeros(self.caps.resources, np.int32)
        for rname, val in m.items():
            v[self.resource_col(rname)] = min(val, 2**31 - 1)
        return v

    # ------------------------------------------------------------- node rows

    def _encode_static_fields(self, ni: NodeInfo) -> Dict[str, np.ndarray]:
        """Row fields derived from the Node OBJECT alone (labels, taints,
        images, allocatable) — cacheable by (name, resourceVersion) since
        pod commits never change them."""
        caps = self.caps
        node = ni.node
        row: Dict[str, np.ndarray] = {}
        row["valid"] = np.array(node is not None)
        row["unschedulable"] = np.array(bool(node and node.spec.unschedulable))
        row["allocatable"] = self.resource_vec(ni.allocatable.as_map())
        from .tiebreak import name_hash as _name_hash

        row["name_hash"] = np.array(
            _name_hash(node.meta.name) if node is not None else 0, np.uint32)

        label_val = np.zeros(caps.label_keys, np.int32)
        label_num = np.full(caps.label_keys, INT_NONE, np.int32)
        if node is not None:
            for k, v in node.meta.labels.items():
                ks = self.key_slot(k)
                label_val[ks] = self.value_id(k, v)
                try:
                    label_num[ks] = np.int32(int(v))
                except (ValueError, OverflowError):
                    pass
        row["label_val"] = label_val
        row["label_num"] = label_num

        tkey = np.zeros(caps.taints, np.int32)
        tval = np.zeros(caps.taints, np.int32)
        teff = np.zeros(caps.taints, np.int32)
        taints = node.spec.taints if node is not None else ()
        if len(taints) > caps.taints:
            raise CapacityError("taints", len(taints), caps.taints)
        for i, t in enumerate(taints):
            tkey[i] = self.key_slot(t.key)
            tval[i] = self.value_id(t.key, t.value)
            teff[i] = _EFFECT_CODE[t.effect]
        row["taint_key"], row["taint_val"], row["taint_effect"] = tkey, tval, teff

        ibits = np.zeros(caps.image_words, np.uint32)
        for name in ni.image_states:
            iid = self.image_id(name)
            ibits[iid >> 5] |= np.uint32(1 << (iid & 31))
        row["image_bits"] = ibits

        # torus coordinates: labeled nodes are authoritative; unlabeled ones
        # take slot-derived synthetic coords (slots are stable for a node's
        # lifetime and this cached row is dropped on release_node_slot, so
        # the slot dependence cannot go stale while cached)
        sp = pos = -1
        if node is not None:
            sp_s = node.meta.labels.get(TOPO_SUPERPOD_LABEL)
            pos_s = node.meta.labels.get(TOPO_SLOT_LABEL)
            if sp_s is not None and pos_s is not None:
                try:
                    sp, pos = int(sp_s), int(pos_s)
                except (ValueError, OverflowError):
                    sp = pos = -1
            if sp < 0 or pos < 0:
                slot = self.node_slots.get(node.meta.name)
                if slot is not None:
                    sp, pos = slot // caps.sp_slots, slot % caps.sp_slots
            if sp >= caps.superpods:
                raise CapacityError("superpods", sp + 1, caps.superpods)
            if pos >= caps.sp_slots:
                raise CapacityError("sp_slots", pos + 1, caps.sp_slots)
        row["topo_sp"] = np.array(sp, np.int32)
        row["topo_pos"] = np.array(pos, np.int32)
        return row

    def encode_dynamic_fields(self, ni: NodeInfo) -> Dict[str, np.ndarray]:
        """Row fields that pod commits change (requested/nonzero/ports/
        class_req) — the reconcile hot path re-encodes ONLY these."""
        row: Dict[str, np.ndarray] = {}
        req = ni.requested.as_map()
        req[resource_api.PODS] = len(ni.pods)
        row["requested"] = self.resource_vec(req)
        nzreq = ni.non_zero_requested.as_map()
        nzreq[resource_api.PODS] = len(ni.pods)
        row["nonzero_requested"] = self.resource_vec(nzreq)

        pbits = np.zeros(self.caps.port_words, np.uint32)
        for (ip, proto, port) in ni.used_ports:
            for pid in (self.port_id(ip, proto, port), self.port_id("*", proto, port)):
                pbits[pid >> 5] |= np.uint32(1 << (pid & 31))
        row["port_bits"] = pbits

        # priority-class-bucketed request sums (batched preemption screen),
        # from NodeInfo's incremental buckets — O(distinct priorities), not
        # O(pods on node) (this runs per dirty row on sync AND reconcile)
        creq = np.zeros((self.caps.prio_classes, self.caps.resources), np.int32)
        for prio, bucket in ni.prio_requested.items():
            cid = self.prio_class_id(prio)
            creq[cid] += self.resource_vec(bucket)
        row["class_req"] = creq
        return row

    def encode_node_row(self, ni: NodeInfo) -> Dict[str, np.ndarray]:
        """One NodeTensors row (no slot assignment here)."""
        node = ni.node
        static = None
        if node is not None:
            key = node.meta.name
            # keyed by OBJECT IDENTITY with the reference held (so the id
            # can never be recycled while cached): any replaced Node object
            # re-encodes, store-bumped or not
            cached = self._static_rows.get(key)
            if cached is not None and cached[0] is node:
                static = cached[1]
            else:
                static = self._encode_static_fields(ni)
                for arr in static.values():
                    arr.flags.writeable = False  # aliased into rows: freeze
                self._static_rows[key] = (node, static)
        else:
            static = self._encode_static_fields(ni)
        row: Dict[str, np.ndarray] = dict(static)
        row.update(self.encode_dynamic_fields(ni))
        return row

    def image_vocab_arrays(self, node_infos: Sequence[NodeInfo]) -> Tuple[np.ndarray, np.ndarray]:
        sizes = np.zeros(self.caps.images, np.int32)
        num_nodes = np.zeros(self.caps.images, np.int32)
        for ni in node_infos:
            for name, size in ni.image_states.items():
                iid = self.image_id(name)
                if num_nodes[iid] == 0:  # first occurrence wins, even a 0 size
                    sizes[iid] = min(size, 2**31 - 1)  # (cache.addNodeImageStates)
                num_nodes[iid] += 1
        return sizes, num_nodes

    def encode_snapshot(self, node_infos: Sequence[NodeInfo]) -> "schema.NodeTensors":
        """Full-snapshot encode (tests / resync path; the incremental path is
        backend/device_state.py)."""
        import jax.numpy as jnp

        caps = self.caps
        if len(node_infos) > caps.nodes:
            raise CapacityError("nodes", len(node_infos), caps.nodes)
        rows = []
        for ni in node_infos:
            self.node_slot(ni.node.meta.name)  # assign slots in order
            rows.append(self.encode_node_row(ni))

        def stack(field, dtype, shape_tail):
            out = np.zeros((caps.nodes,) + shape_tail, dtype)
            if field == "label_num":
                out[:] = INT_NONE
            elif field in ("topo_sp", "topo_pos"):
                out[:] = -1  # padding rows carry no topology
            for i, r in enumerate(rows):
                out[self.node_slots[node_infos[i].node.meta.name]] = r[field]
            return out

        sizes, num_nodes = self.image_vocab_arrays(node_infos)
        nt = schema.NodeTensors(
            valid=jnp.asarray(stack("valid", bool, ())),
            unschedulable=jnp.asarray(stack("unschedulable", bool, ())),
            allocatable=jnp.asarray(stack("allocatable", np.int32, (caps.resources,))),
            requested=jnp.asarray(stack("requested", np.int32, (caps.resources,))),
            nonzero_requested=jnp.asarray(stack("nonzero_requested", np.int32, (caps.resources,))),
            label_val=jnp.asarray(stack("label_val", np.int32, (caps.label_keys,))),
            label_num=jnp.asarray(stack("label_num", np.int32, (caps.label_keys,))),
            taint_key=jnp.asarray(stack("taint_key", np.int32, (caps.taints,))),
            taint_val=jnp.asarray(stack("taint_val", np.int32, (caps.taints,))),
            taint_effect=jnp.asarray(stack("taint_effect", np.int32, (caps.taints,))),
            port_bits=jnp.asarray(stack("port_bits", np.uint32, (caps.port_words,))),
            image_bits=jnp.asarray(stack("image_bits", np.uint32, (caps.image_words,))),
            image_sizes=jnp.asarray(sizes),
            image_num_nodes=jnp.asarray(num_nodes),
            class_req=jnp.asarray(stack("class_req", np.int32, (caps.prio_classes, caps.resources))),
            class_prio=jnp.asarray(self.class_prio_array()),
            name_hash=jnp.asarray(stack("name_hash", np.uint32, ())),
            topo_sp=jnp.asarray(stack("topo_sp", np.int32, ())),
            topo_pos=jnp.asarray(stack("topo_pos", np.int32, ())),
        )
        return nt

    # ------------------------------------------------------------- expressions

    def _expr_from_requirement(self, r: Requirement, builder: "_ExprBuilder") -> int:
        ks = self.key_slot(r.key)
        if r.operator == IN:
            ids = frozenset(self.value_id(r.key, v) for v in r.values)
            return builder.slot((schema.OP_IN, ks, 0, ids))
        if r.operator == NOT_IN:
            ids = frozenset(self.value_id(r.key, v) for v in r.values)
            return builder.slot((schema.OP_NOT_IN, ks, 0, ids))
        if r.operator == EXISTS:
            return builder.slot((schema.OP_EXISTS, ks, 0, frozenset()))
        if r.operator == DOES_NOT_EXIST:
            return builder.slot((schema.OP_NOT_EXISTS, ks, 0, frozenset()))
        if r.operator in (GT, LT):
            try:
                rhs = int(r.values[0])
            except (ValueError, IndexError):
                # unparseable Gt/Lt never matches (labels.NewRequirement errors)
                return builder.slot((schema.OP_IN, ks, 0, frozenset()))
            op = schema.OP_GT if r.operator == GT else schema.OP_LT
            return builder.slot((op, ks, rhs, frozenset()))
        raise ValueError(f"unknown operator {r.operator}")

    # ------------------------------------------------------------- pod batch

    def _pod_sig(self, pod: Pod) -> Optional[Tuple]:
        """Hashable signature of every spec field the template encodes, or
        None when the pod is uncacheable (matchFields terms embed the current
        node-slot mapping, which churns)."""
        spec = pod.spec
        a = spec.affinity
        terms: Sequence = ()
        prefs: Sequence = ()
        if a and a.node_affinity:
            if a.node_affinity.required:
                terms = a.node_affinity.required.terms
            prefs = tuple(a.node_affinity.preferred)
        for t in terms:
            if t.match_fields_name is not None:
                return None
        for wt in prefs:
            if wt.preference.match_fields_name is not None:
                return None

        def reqs(c):
            return tuple(sorted((r, str(q)) for r, q in c.requests.items()))

        def exprs(term):
            return tuple((r.key, r.operator, tuple(r.values))
                         for r in term.match_expressions)

        try:
            return (
                tuple(reqs(c) for c in spec.containers),
                tuple(reqs(c) for c in spec.init_containers),
                tuple(sorted((r, str(q)) for r, q in spec.overhead.items())),
                spec.priority,
                tuple((t.key, t.operator, t.value, t.effect) for t in spec.tolerations),
                tuple(spec.node_selector.items()),
                tuple(exprs(t) for t in terms),
                tuple((wt.weight, exprs(wt.preference)) for wt in prefs),
                tuple((cp.host_ip, cp.protocol, cp.host_port) for cp in pod.host_ports()),
                len(spec.containers),
            )
        except TypeError:  # unhashable field value: just skip caching
            return None

    def _build_template(self, pod: Pod) -> _PodTemplate:
        caps = self.caps
        kb = _KeyBuilder()

        r = dict(pod.resource_request())  # copy: resource_request() is cached
        r[resource_api.PODS] = 1
        nz = nonzero_request(pod.resource_request())
        nz[resource_api.PODS] = 1

        tols = pod.spec.tolerations
        if len(tols) > caps.tolerations:
            raise CapacityError("tolerations", len(tols), caps.tolerations)
        tol_key = np.zeros(caps.tolerations, np.int32)
        tol_val = np.zeros(caps.tolerations, np.int32)
        tol_op = np.zeros(caps.tolerations, np.int32)
        tol_effect = np.zeros(caps.tolerations, np.int32)
        tol_prefer = np.zeros(caps.tolerations, bool)
        for i, t in enumerate(tols):
            tol_key[i] = self.key_slot(t.key) if t.key else 0
            tol_op[i] = schema.TOL_EXISTS if t.operator == TOLERATION_OP_EXISTS else schema.TOL_EQUAL
            if t.key and tol_op[i] == schema.TOL_EQUAL:
                tol_val[i] = self.value_id(t.key, t.value)
            tol_effect[i] = _EFFECT_CODE[t.effect]
            tol_prefer[i] = t.effect in ("", TAINT_PREFER_NO_SCHEDULE)

        # nodeSelector map → AND of single-value IN exprs
        sel = list(pod.spec.node_selector.items())
        if len(sel) > caps.sel_exprs:
            raise CapacityError("sel_exprs", len(sel), caps.sel_exprs)
        sel_keys = tuple(
            self._expr_from_requirement(Requirement(k, IN, (v,)), kb) for k, v in sel)

        def term_key_row(term):
            n_exprs = len(term.match_expressions) + (term.match_fields_name is not None)
            if n_exprs > caps.term_exprs:
                raise CapacityError("term_exprs", n_exprs, caps.term_exprs)
            if not term.match_expressions and term.match_fields_name is None:
                # empty term matches nothing (nodeaffinity semantics)
                return (kb.never_slot(),)
            row = [self._expr_from_requirement(r_, kb) for r_ in term.match_expressions]
            if term.match_fields_name is not None:
                tgt = self.node_slots.get(term.match_fields_name, -2)
                row.append((schema.OP_NODE_NAME, 0, tgt, frozenset()))
            return tuple(row)

        a = pod.spec.affinity
        terms: Sequence = ()
        if a and a.node_affinity and a.node_affinity.required:
            terms = a.node_affinity.required.terms
        if len(terms) > caps.terms:
            raise CapacityError("terms", len(terms), caps.terms)
        term_keys = tuple(term_key_row(t) for t in terms)

        prefs = tuple(a.node_affinity.preferred) if a and a.node_affinity else ()
        if len(prefs) > caps.pref_terms:
            raise CapacityError("pref_terms", len(prefs), caps.pref_terms)
        pref_terms = tuple((wt.weight, term_key_row(wt.preference)) for wt in prefs)

        # host ports: specific IP wants (ip,…) OR (0.0.0.0,…); wildcard wants ("*",…)
        wanted: List[int] = []
        for cp in pod.host_ports():
            ip = cp.host_ip or "0.0.0.0"
            if ip == "0.0.0.0":
                wanted.append(self.port_id("*", cp.protocol, cp.host_port))
            else:
                wanted.append(self.port_id(ip, cp.protocol, cp.host_port))
                wanted.append(self.port_id("0.0.0.0", cp.protocol, cp.host_port))
        wanted = list(dict.fromkeys(wanted))  # dedupe (repeat hostPorts across containers)
        if len(wanted) > caps.ports:
            raise CapacityError("ports", len(wanted), caps.ports)
        if len(pod.spec.containers) > caps.containers:
            raise CapacityError("containers", len(pod.spec.containers), caps.containers)

        return _PodTemplate(
            priority=pod.spec.priority,
            req=self.resource_vec(r),
            nzreq=self.resource_vec(nz),
            tol_key=tol_key, tol_val=tol_val, tol_op=tol_op,
            tol_effect=tol_effect, tol_prefer=tol_prefer,
            tolerates_unsched=any(t.tolerates(_UNSCHEDULABLE_TAINT) for t in tols),
            sel_keys=sel_keys,
            term_keys=term_keys,
            pref_terms=pref_terms,
            port_wanted=tuple(wanted),
            n_containers=len(pod.spec.containers),
        )

    def _template_for(self, pod: Pod) -> _PodTemplate:
        sig = self._pod_sig(pod)
        if sig is None:
            return self._build_template(pod)
        tmpl = self._pod_templates.get(sig)
        if tmpl is None:
            tmpl = self._build_template(pod)
            if len(self._pod_templates) >= self._template_cap:
                self._pod_templates.clear()
            self._pod_templates[sig] = tmpl
        return tmpl

    def encode_pods(self, pods: Sequence[Pod], capacity: Optional[int] = None,
                    tie_seeds: Optional[Sequence[int]] = None,
                    ) -> Tuple["schema.PodBatch", "schema.ExprTable"]:
        """``capacity`` pads the pod axis to a smaller bucket than caps.pods:
        the compiled program's step count (and the speculative rounds' [P,N]
        width) is the PADDED size, so deadline-cut batches must compile at a
        matching bucket or they pay the full-capacity program anyway."""
        import jax.numpy as jnp

        from ..framework.plugins.imagelocality import normalized_image_name

        caps = self.caps
        P = caps.pods if capacity is None else min(int(capacity), caps.pods)
        if len(pods) > caps.pods:
            raise CapacityError("pods", len(pods), caps.pods)
        assert len(pods) <= P, "bucket smaller than the batch"
        builder = _ExprBuilder(caps)

        valid = np.zeros(P, bool)
        priority = np.zeros(P, np.int32)
        req = np.zeros((P, caps.resources), np.int32)
        nzreq = np.zeros((P, caps.resources), np.int32)
        node_name = np.full(P, -1, np.int32)
        nominated = np.full(P, -1, np.int32)
        tol_key = np.zeros((P, caps.tolerations), np.int32)
        tol_val = np.zeros((P, caps.tolerations), np.int32)
        tol_op = np.zeros((P, caps.tolerations), np.int32)
        tol_effect = np.zeros((P, caps.tolerations), np.int32)
        tol_prefer = np.zeros((P, caps.tolerations), bool)
        tolerates_unsched = np.zeros(P, bool)
        sel_idx = np.zeros((P, caps.sel_exprs), np.int32)
        term_idx = np.zeros((P, caps.terms, caps.term_exprs), np.int32)
        term_valid = np.zeros((P, caps.terms), bool)
        pref_idx = np.zeros((P, caps.pref_terms, caps.term_exprs), np.int32)
        pref_weight = np.zeros((P, caps.pref_terms), np.int32)
        port_ids = np.zeros((P, caps.ports), np.int32)
        image_ids = np.zeros((P, caps.containers), np.int32)
        num_containers = np.zeros(P, np.int32)

        for p, pod in enumerate(pods):
            tmpl = self._template_for(pod)
            valid[p] = True
            priority[p] = tmpl.priority
            req[p] = tmpl.req
            nzreq[p] = tmpl.nzreq
            tol_key[p] = tmpl.tol_key
            tol_val[p] = tmpl.tol_val
            tol_op[p] = tmpl.tol_op
            tol_effect[p] = tmpl.tol_effect
            tol_prefer[p] = tmpl.tol_prefer
            tolerates_unsched[p] = tmpl.tolerates_unsched
            for i, k in enumerate(tmpl.sel_keys):
                sel_idx[p, i] = builder.slot(k)
            for t_i, keys in enumerate(tmpl.term_keys):
                term_valid[p, t_i] = True
                for e_i, k in enumerate(keys):
                    term_idx[p, t_i, e_i] = builder.slot(k)
            for t_i, (w, keys) in enumerate(tmpl.pref_terms):
                pref_weight[p, t_i] = w
                for e_i, k in enumerate(keys):
                    pref_idx[p, t_i, e_i] = builder.slot(k)
            port_ids[p, : len(tmpl.port_wanted)] = tmpl.port_wanted
            num_containers[p] = tmpl.n_containers
            # per-pod (never cached): node-slot binding + image-vocab lookup
            # (slots churn with nodes; the image vocab grows as nodes report)
            if pod.spec.node_name:
                node_name[p] = self.node_slots.get(pod.spec.node_name, -2)  # -2: unknown ⇒ never matches
            if pod.status.nominated_node_name:
                nominated[p] = self.node_slots.get(pod.status.nominated_node_name, -1)
            imgs = [self.image_vocab.lookup(normalized_image_name(c.image))
                    for c in pod.spec.containers]
            image_ids[p, : len(imgs)] = imgs

        # host copies of the commit-relevant arrays: DeviceState.adopt_commits
        # advances its host mirror from these without a device→host read of
        # the PodBatch (each read is a relay round-trip on this TPU)
        prio_class = np.zeros(P, np.int32)
        for p, pod in enumerate(pods):
            prio_class[p] = self.prio_class_id(pod.spec.priority)
        from .tiebreak import pod_seed

        tie_seed = np.zeros(P, np.uint32)
        if tie_seeds is not None:
            tie_seed[: len(tie_seeds)] = np.asarray(tie_seeds, np.uint32)[:P]
        else:
            for p, pod in enumerate(pods):
                tie_seed[p] = pod_seed(pod.key(), 0)
        self.last_host_pb = {"req": req, "nonzero_req": nzreq,
                             "port_ids": port_ids, "prio_class": prio_class}
        # trace-time ports gate: when NO pod in the batch wants a host port,
        # the dispatched program skips the [N, Wport] conflict pass and the
        # port-carry update entirely (batch.py ports_enabled)
        self.last_has_ports = bool(port_ids.any())
        batch = schema.PodBatch(
            valid=jnp.asarray(valid),
            priority=jnp.asarray(priority),
            prio_class=jnp.asarray(prio_class),
            req=jnp.asarray(req),
            nonzero_req=jnp.asarray(nzreq),
            node_name=jnp.asarray(node_name),
            nominated=jnp.asarray(nominated),
            tol_key=jnp.asarray(tol_key),
            tol_val=jnp.asarray(tol_val),
            tol_op=jnp.asarray(tol_op),
            tol_effect=jnp.asarray(tol_effect),
            tol_prefer=jnp.asarray(tol_prefer),
            tolerates_unschedulable=jnp.asarray(tolerates_unsched),
            sel_idx=jnp.asarray(sel_idx),
            term_idx=jnp.asarray(term_idx),
            term_valid=jnp.asarray(term_valid),
            pref_idx=jnp.asarray(pref_idx),
            pref_weight=jnp.asarray(pref_weight),
            port_ids=jnp.asarray(port_ids),
            image_ids=jnp.asarray(image_ids),
            num_containers=jnp.asarray(num_containers),
            tie_seed=jnp.asarray(tie_seed),
        )
        return batch, builder.table()


class _KeyBuilder:
    """Builder shim for template construction: returns expr KEYS, deferring
    slot interning to the per-batch _ExprBuilder."""

    @staticmethod
    def slot(key: Tuple) -> Tuple:
        return key

    @staticmethod
    def never_slot() -> Tuple:
        return (schema.OP_IN, 0, 0, frozenset())


class _ExprBuilder:
    """Dedup unique expressions into ExprTable slots. Slot 0 = OP_TRUE."""

    def __init__(self, caps: Capacities):
        self.caps = caps
        self._slots: Dict[Tuple, int] = {(schema.OP_TRUE, 0, 0, frozenset()): 0}

    def slot(self, key: Tuple) -> int:
        s = self._slots.get(key)
        if s is None:
            s = len(self._slots)
            if s >= self.caps.exprs:
                raise CapacityError("exprs", s + 1, self.caps.exprs)
            self._slots[key] = s
        return s

    def never_slot(self) -> int:
        # IN with an empty value set matches nothing
        return self.slot((schema.OP_IN, 0, 0, frozenset()))

    def table(self) -> "schema.ExprTable":
        import jax.numpy as jnp

        E = self.caps.exprs
        op = np.zeros(E, np.int32)
        key = np.zeros(E, np.int32)
        val = np.zeros(E, np.int32)
        bits = np.zeros((E, self.caps.value_words), np.uint32)
        for (o, k, v, ids), s in self._slots.items():
            op[s], key[s], val[s] = o, k, v
            for vid in ids:
                bits[s, vid >> 5] |= np.uint32(1 << (vid & 31))
        return schema.ExprTable(op=jnp.asarray(op), key=jnp.asarray(key), val=jnp.asarray(val), bits=jnp.asarray(bits))
