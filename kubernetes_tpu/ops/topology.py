"""Segment-reduction kernels for the two "hard" plugins (SURVEY.md §7 step 5):
PodTopologySpread and InterPodAffinity on the batched device path.

Topology domains are label-value-id buckets: a constraint/term's per-domain
pod counts are one scatter-add of TopoCounts rows over ``label_val[:, key]``,
and per-node reads are one gather back — the tensorization of the reference's
``map[{topologyKey,value}]int`` bookkeeping (podtopologyspread/filtering.go:40
preFilterState, interpodaffinity/filtering.go:155 topologyToMatchedTermCount).

Everything here runs INSIDE the commit scan of backend/batch.py: counts evolve
as batch pods commit, so pod k sees exactly the topology state the reference's
serial loop would (anti-affinity violations within one batch are impossible by
construction, SURVEY.md §7 hard-part 4).

Sharding: scatters run over the local node shard, then one psum merges the
per-shard segment tables; reads stay shard-local. seg_exist (existing-term
domain counts) is replicated and updated on every shard via a psum'd
commit-domain broadcast.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

# Python literal, not jnp.int32(...): module-level jax scalars become
# captured device-buffer constants, which the axon relay re-fetches every
# scan iteration (see ops/select.py NEG_INF note).
INT_MAX = 2**31 - 1


def _gsum(x, axis_name):
    return x if axis_name is None else lax.psum(x, axis_name)


def _gmax(x, axis_name):
    return x if axis_name is None else lax.pmax(x, axis_name)


def _gmin(x, axis_name):
    return x if axis_name is None else lax.pmin(x, axis_name)


class TopoStatic(NamedTuple):
    """Per-batch static context (node labels cannot change intra-batch)."""

    dom_t: jax.Array      # [T, N] domain id of node n under term t's topology key
    seg_exist0: jax.Array  # [T, Vd] per-domain counts of pods carrying term t


def make_static(term_counts: jax.Array, term_key: jax.Array, label_val: jax.Array,
                valid: jax.Array, vd: int, axis_name: Optional[str] = None) -> TopoStatic:
    T = term_counts.shape[0]
    dom_t = label_val[:, term_key].T                                  # [T, N]
    add = jnp.where(valid[None, :] & (dom_t > 0), term_counts, 0)
    t_iota = jnp.arange(T, dtype=jnp.int32)[:, None]
    seg = jnp.zeros((T, vd), jnp.int32).at[t_iota, dom_t].add(add)
    return TopoStatic(dom_t=dom_t, seg_exist0=_gsum(seg, axis_name))


def _seg_sum(values: jax.Array, dom: jax.Array, vd: int, axis_name):
    """[C, N] values segment-summed by domain id → [C, Vd] (psum'd global).
    For compact domains the scatter becomes a one-hot contraction — TPU
    scatters cost ~200µs of fixed overhead EACH inside the commit scan,
    while the [C, N, Vd] one-hot matmul rides the MXU and fuses; counts stay
    exact in f32 (< 2^24)."""
    C = dom.shape[0]
    if vd <= 256:
        onehot = (dom[:, :, None] == jnp.arange(vd, dtype=dom.dtype)[None, None, :])
        seg = jnp.einsum("cn,cnv->cv", values.astype(jnp.float32),
                         onehot.astype(jnp.float32)).astype(jnp.int32)
    else:
        c_iota = jnp.arange(C, dtype=jnp.int32)[:, None]
        seg = jnp.zeros((C, vd), jnp.int32).at[c_iota, dom].add(values)
    return _gsum(seg, axis_name)


def _seg_counts(sig: jax.Array, key: jax.Array, sel_counts: jax.Array,
                label_val: jax.Array, elig: jax.Array, vd: int, axis_name):
    """Shared segment reduction: per-domain sums of sel_counts[sig] over
    eligible nodes. sig/key [C]; elig [C, N] or [N]. Returns (dom [C,N],
    has_key [C,N], seg [C,Vd] global, cnt_at [C,N])."""
    dom = label_val[:, key].T                                          # [C, N]
    has_key = dom > 0
    if elig.ndim == 1:
        elig = jnp.broadcast_to(elig[None, :], dom.shape)
    cnts = sel_counts[sig]                                             # [C, N]
    # nodes lacking the topology key are never counted (the reference skips
    # them: tv == None). Keeps segment column 0 empty so whole-table sums
    # (the first-pod-in-cluster check) match the oracle.
    add = jnp.where(elig & has_key, cnts, 0)
    seg = _seg_sum(add, dom, vd, axis_name)
    cnt_at = jnp.take_along_axis(seg, dom, axis=1)                     # [C, N]
    return dom, has_key, seg, cnt_at


# ----------------------------------------------------------------- filters


def spread_filter(xs, sel_counts, label_val, valid, affinity_ok, vd, axis_name):
    """PodTopologySpread Filter (filtering.go:335): per DoNotSchedule
    constraint, matchNum + selfMatch − minMatchNum ≤ maxSkew over domains of
    eligible nodes (nodes matching the pod's node affinity AND carrying every
    constraint's topology key). Returns [N] bool."""
    sf_valid, sf_sig, sf_key, sf_skew, sf_self, sf_min_dom = (
        xs["sf_valid"], xs["sf_sig"], xs["sf_key"], xs["sf_skew"], xs["sf_self"], xs["sf_min_domains"],
    )
    dom = label_val[:, sf_key].T                                       # [C, N]
    has_key = dom > 0
    has_all = jnp.all(jnp.where(sf_valid[:, None], has_key, True), axis=0)   # [N]
    elig = valid & affinity_ok & has_all
    _, _, seg, cnt_at = _seg_counts(sf_sig, sf_key, sel_counts, label_val, elig, vd, axis_name)

    pres = _seg_sum(jnp.broadcast_to(elig[None, :], dom.shape).astype(jnp.int32),
                    dom, vd, axis_name) > 0                            # [C, Vd]
    minm = jnp.min(jnp.where(pres, seg, INT_MAX), axis=1)              # [C]
    any_pres = jnp.any(pres, axis=1)
    minm = jnp.where(any_pres, minm, 0)
    ndom = jnp.sum(pres, axis=1)
    minm = jnp.where((sf_min_dom >= 0) & (ndom < sf_min_dom), 0, minm)

    ok_c = has_key & (cnt_at + sf_self[:, None].astype(jnp.int32) - minm[:, None] <= sf_skew[:, None])
    return jnp.all(jnp.where(sf_valid[:, None], ok_c, True), axis=0)


def ipa_filter(xs, sel_counts, seg_exist, dom_t, label_val, valid, vd, axis_name):
    """InterPodAffinity Filter's three checks (filtering.go:377-387).
    Returns (aff_ok, anti_ok, exist_ok, exist_at) — exist_at [T, N] is the
    per-node existing-term domain count matrix, reused by the score path."""
    # 1. incoming pod's required affinity (+ first-pod-in-cluster case)
    ia_valid, ia_sig, ia_key = xs["ia_valid"], xs["ia_sig"], xs["ia_key"]
    _, has_key, seg, cnt_at = _seg_counts(ia_sig, ia_key, sel_counts, label_val, valid, vd, axis_name)
    # reference counts only pods on nodes that carry the key (tv != None)
    exist = cnt_at > 0
    pods_exist = jnp.all(jnp.where(ia_valid[:, None], exist, True), axis=0)
    all_keys = jnp.all(jnp.where(ia_valid[:, None], has_key, True), axis=0)
    total = jnp.sum(jnp.where(ia_valid[:, None], seg, 0))
    first_ok = (total == 0) & xs["ia_self_all"]
    has_terms = jnp.any(ia_valid)
    aff_ok = ~has_terms | (all_keys & (pods_exist | first_ok))

    # 2. incoming pod's required anti-affinity
    an_valid, an_sig, an_key = xs["ianti_valid"], xs["ianti_sig"], xs["ianti_key"]
    _, an_has_key, _, an_cnt = _seg_counts(an_sig, an_key, sel_counts, label_val, valid, vd, axis_name)
    viol = jnp.any(an_valid[:, None] & an_has_key & (an_cnt > 0), axis=0)
    anti_ok = ~viol

    # 3. existing pods' required anti-affinity vs the incoming pod
    exist_at = jnp.where(dom_t > 0, jnp.take_along_axis(seg_exist, dom_t, axis=1), 0)  # [T, N]
    viol_cnt = jnp.einsum("t,tn->n", xs["term_filter_match"].astype(jnp.int32), exist_at)
    exist_ok = viol_cnt == 0
    return aff_ok, anti_ok, exist_ok, exist_at


# ----------------------------------------------------------------- scores


def spread_score(xs, sel_counts, label_val, valid, affinity_ok, feasible, vd, axis_name):
    """PodTopologySpread Score+Normalize (scoring.go:196-271). Returns [N]
    normalized float scores (ignored/infeasible nodes 0)."""
    ss_valid, ss_sig, ss_key, ss_skew, ss_host = (
        xs["ss_valid"], xs["ss_sig"], xs["ss_key"], xs["ss_skew"], xs["ss_hostname"],
    )
    require_all = xs["ss_require_all"]
    has_cons = jnp.any(ss_valid)

    dom = label_val[:, ss_key].T                                       # [C, N]
    has_key = dom > 0
    has_all = jnp.all(jnp.where(ss_valid[:, None], has_key, True), axis=0)
    ignored = require_all & ~has_all                                   # [N]
    base = feasible & ~ignored

    # domain sizes over filtered non-ignored nodes; hostname uses node count
    pres = _seg_sum(jnp.broadcast_to(base[None, :], dom.shape).astype(jnp.int32),
                    dom, vd, axis_name) > 0
    sz = jnp.sum(pres, axis=1)                                          # [C]
    n_base = _gsum(jnp.sum(base.astype(jnp.int32)), axis_name)
    sz = jnp.where(ss_host, n_base, sz)
    w = jnp.log(sz.astype(jnp.float32) + 2.0)                           # [C]

    # counts over eligible nodes (affinity match + require-all key rule)
    elig = valid & affinity_ok & jnp.where(require_all, has_all, True)
    _, _, _, cnt_at = _seg_counts(ss_sig, ss_key, sel_counts, label_val, elig, vd, axis_name)
    cnt = jnp.where(ss_host[:, None], sel_counts[ss_sig], cnt_at).astype(jnp.float32)

    contrib = jnp.where(
        ss_valid[:, None] & has_key,
        cnt * w[:, None] + (ss_skew[:, None].astype(jnp.float32) - 1.0),
        0.0,
    )
    raw = jnp.floor(jnp.sum(contrib, axis=0) + 0.5)                     # math.Round, ≥0

    mx = _gmax(jnp.max(jnp.where(base, raw, -jnp.inf)), axis_name)
    mn = _gmin(jnp.min(jnp.where(base, raw, jnp.inf)), axis_name)
    any_base = _gmax(jnp.any(base), axis_name)
    norm = jnp.where(
        mx == 0, 100.0, jnp.floor(100.0 * (mx + mn - raw) / jnp.maximum(mx, 1.0))
    )
    norm = jnp.where(ignored | ~any_base, 0.0, norm)
    return jnp.where(has_cons, norm, 0.0)


def ipa_score(xs, sel_counts, exist_at, label_val, valid, feasible, vd, axis_name):
    """InterPodAffinity Score+Normalize (scoring.go): incoming preferred terms
    vs existing pods + symmetric existing-term weights, normalized over the
    feasible set with min/max floored/ceiled at 0. Returns [N] float."""
    ip_valid, ip_sig, ip_key, ip_w = xs["ip_valid"], xs["ip_sig"], xs["ip_key"], xs["ip_w"]
    _, has_key, _, cnt_at = _seg_counts(ip_sig, ip_key, sel_counts, label_val, valid, vd, axis_name)
    pref = jnp.sum(
        jnp.where(ip_valid[:, None] & has_key, ip_w[:, None].astype(jnp.float32) * cnt_at, 0.0),
        axis=0,
    )
    sym = jnp.einsum("t,tn->n", xs["term_score_w"], exist_at.astype(jnp.float32))
    raw = pref + sym

    mx = jnp.maximum(_gmax(jnp.max(jnp.where(feasible, raw, -jnp.inf)), axis_name), 0.0)
    mn = jnp.minimum(_gmin(jnp.min(jnp.where(feasible, raw, jnp.inf)), axis_name), 0.0)
    diff = mx - mn
    return jnp.where(diff > 0, jnp.floor(100.0 * (raw - mn) / jnp.maximum(diff, 1.0)), 0.0)


# ----------------------------------------------------------------- commit


# ------------------------------------------------------- hostname fast path
#
# ``kubernetes.io/hostname`` is the dominant topology key in the reference's
# benchmark configs (SchedulingPodAntiAffinity/Affinity,
# performance-config.yaml:23-50) and it degenerates: every node is its own
# domain, so the [C, Vd] segment scatters collapse to direct per-node count
# reads. A batch whose every involved key is hostname takes these paths —
# no scatters in the scan at all (measured 1.4s → ~0.1s per 512-pod batch
# on v5e).


def spread_filter_host(xs, sel_counts, hostkey_ok, valid, affinity_ok, axis_name):
    """Spread filter with hostname domains: matchNum at node n is simply
    sel_counts[sig, n]; minMatchNum is the min over eligible nodes."""
    sf_valid, sf_sig, sf_skew, sf_self, sf_min_dom = (
        xs["sf_valid"], xs["sf_sig"], xs["sf_skew"], xs["sf_self"], xs["sf_min_domains"],
    )
    elig = valid & affinity_ok & hostkey_ok
    cnt = sel_counts[sf_sig]                                           # [C, N]
    minm = _gmin(jnp.min(jnp.where(elig[None, :], cnt, INT_MAX), axis=1), axis_name)
    ndom = _gsum(jnp.sum(elig.astype(jnp.int32)), axis_name)
    any_pres = ndom > 0
    minm = jnp.where(any_pres, minm, 0)
    minm = jnp.where((sf_min_dom >= 0) & (ndom < sf_min_dom), 0, minm)
    ok_c = hostkey_ok[None, :] & (
        cnt + sf_self[:, None].astype(jnp.int32) - minm[:, None] <= sf_skew[:, None])
    return jnp.all(jnp.where(sf_valid[:, None], ok_c, True), axis=0)


def ipa_filter_host(xs, sel_counts, term_cnt, hostkey_ok, valid, axis_name):
    """InterPodAffinity filter with hostname domains: cnt_at == the node's
    own sel_counts row; exist_at == the carried per-node term counts."""
    ia_valid, ia_sig = xs["ia_valid"], xs["ia_sig"]
    cnt_at = sel_counts[ia_sig]                                        # [A, N]
    exist = hostkey_ok[None, :] & (cnt_at > 0)
    pods_exist = jnp.all(jnp.where(ia_valid[:, None], exist, True), axis=0)
    all_keys = jnp.all(jnp.where(ia_valid[:, None], hostkey_ok[None, :], True), axis=0)
    total = _gsum(jnp.sum(jnp.where(ia_valid[:, None] & valid[None, :] & hostkey_ok[None, :],
                                    cnt_at, 0)), axis_name)
    first_ok = (total == 0) & xs["ia_self_all"]
    has_terms = jnp.any(ia_valid)
    aff_ok = ~has_terms | (all_keys & (pods_exist | first_ok))

    an_valid, an_sig = xs["ianti_valid"], xs["ianti_sig"]
    an_cnt = sel_counts[an_sig]                                        # [A, N]
    viol = jnp.any(an_valid[:, None] & hostkey_ok[None, :] & (an_cnt > 0), axis=0)
    anti_ok = ~viol

    exist_at = jnp.where(hostkey_ok[None, :], term_cnt, 0)             # [T, N]
    viol_cnt = jnp.einsum("t,tn->n", xs["term_filter_match"].astype(jnp.int32), exist_at)
    exist_ok = viol_cnt == 0
    return aff_ok, anti_ok, exist_ok, exist_at


def spread_score_host(xs, sel_counts, hostkey_ok, valid, affinity_ok, feasible, axis_name):
    """Spread score with hostname domains (scoring.go:196-271): size = count
    of non-ignored nodes, counts read directly per node."""
    ss_valid, ss_sig, ss_skew = xs["ss_valid"], xs["ss_sig"], xs["ss_skew"]
    require_all = xs["ss_require_all"]
    has_cons = jnp.any(ss_valid)
    ignored = require_all & ~hostkey_ok
    base = feasible & ~ignored
    n_base = _gsum(jnp.sum(base.astype(jnp.int32)), axis_name)
    w = jnp.log(n_base.astype(jnp.float32) + 2.0)
    cnt = sel_counts[ss_sig].astype(jnp.float32)                        # [C, N]
    contrib = jnp.where(
        ss_valid[:, None] & hostkey_ok[None, :],
        cnt * w + (ss_skew[:, None].astype(jnp.float32) - 1.0),
        0.0,
    )
    raw = jnp.floor(jnp.sum(contrib, axis=0) + 0.5)
    mx = _gmax(jnp.max(jnp.where(base, raw, -jnp.inf)), axis_name)
    mn = _gmin(jnp.min(jnp.where(base, raw, jnp.inf)), axis_name)
    any_base = _gmax(jnp.any(base), axis_name)
    norm = jnp.where(mx == 0, 100.0, jnp.floor(100.0 * (mx + mn - raw) / jnp.maximum(mx, 1.0)))
    norm = jnp.where(ignored | ~any_base, 0.0, norm)
    return jnp.where(has_cons, norm, 0.0)


def ipa_score_host(xs, sel_counts, exist_at, hostkey_ok, feasible, axis_name):
    ip_valid, ip_sig, ip_w = xs["ip_valid"], xs["ip_sig"], xs["ip_w"]
    cnt_at = sel_counts[ip_sig]                                         # [PT, N]
    pref = jnp.sum(
        jnp.where(ip_valid[:, None] & hostkey_ok[None, :],
                  ip_w[:, None].astype(jnp.float32) * cnt_at.astype(jnp.float32), 0.0),
        axis=0,
    )
    sym = jnp.einsum("t,tn->n", xs["term_score_w"], exist_at.astype(jnp.float32))
    raw = pref + sym
    mx = jnp.maximum(_gmax(jnp.max(jnp.where(feasible, raw, -jnp.inf)), axis_name), 0.0)
    mn = jnp.minimum(_gmin(jnp.min(jnp.where(feasible, raw, jnp.inf)), axis_name), 0.0)
    diff = mx - mn
    return jnp.where(diff > 0, jnp.floor(100.0 * (raw - mn) / jnp.maximum(diff, 1.0)), 0.0)


def commit_update_host(sel_counts, term_cnt, local_idx, commit, mine,
                       pod_sig_mask, pod_term_mask):
    """Hostname-mode commit: both tables are [*, N] and take a single-column
    add at the winning node — no domain broadcast needed (each shard owns
    its columns). One-hot elementwise instead of scatters (per-step scatter
    overhead, see _seg_sum)."""
    col = ((jnp.arange(sel_counts.shape[1], dtype=jnp.int32) == local_idx)
           & commit & mine).astype(jnp.int32)                           # [N]
    sel_counts = sel_counts + pod_sig_mask.astype(jnp.int32)[:, None] * col[None, :]
    term_cnt = term_cnt + pod_term_mask.astype(jnp.int32)[:, None] * col[None, :]
    return sel_counts, term_cnt


def commit_update(sel_counts, seg_exist, dom_t, local_idx, commit, mine,
                  pod_sig_mask, pod_term_mask, axis_name):
    """Apply a committed pod's membership to the evolving count tables:
    sel_counts[:, node] += pod_sig_mask on the owning shard; seg_exist gets the
    pod's carried terms added at the winning node's domains on EVERY shard
    (replicated table — the winner broadcasts its domain column via psum)."""
    col = ((jnp.arange(sel_counts.shape[1], dtype=jnp.int32) == local_idx)
           & commit & mine).astype(jnp.int32)                           # [N]
    sel_counts = sel_counts + pod_sig_mask.astype(jnp.int32)[:, None] * col[None, :]
    dom_col = dom_t[:, local_idx]                                       # [T] local
    if axis_name is not None:
        dom_col = _gsum(jnp.where(mine, dom_col, 0), axis_name)
    add = jnp.where(commit & (dom_col > 0), pod_term_mask.astype(jnp.int32), 0)
    # elementwise one-hot add instead of a scatter (fuses; scatters carry
    # ~200µs fixed overhead per scan step on TPU)
    vd = seg_exist.shape[1]
    onehot = (jnp.arange(vd, dtype=dom_col.dtype)[None, :] == dom_col[:, None])
    seg_exist = seg_exist + add[:, None] * onehot.astype(jnp.int32)
    return sel_counts, seg_exist
