"""Pallas TPU kernel: the fused per-pod scheduling step.

One kernel invocation does everything the commit-phase scan step needs for a
non-topology pod (the common case): resource fit + port conflict over the
node axis, the dynamic resource scores (LeastAllocated + BalancedAllocation),
DefaultNormalizeScore of the static taint/affinity raws over the feasible
set, weighted total, jittered masked argmax, and the winner's resource/port
commit — a single VMEM-resident fusion replacing ~30 XLA ops per scan step
(see /opt/skills/guides/pallas_guide.md for the tiling model).

Layout: node axis in lanes (last dim, multiple of 128), resource/port-word
axes in sublanes — [R, N] / [W, N] transposed from the NodeTensors layout.
The commit updates the winner's lane in-place via input/output aliasing, so
the scan carry never leaves VMEM between pods.

Used when: single shard, topology path disabled, N ≤ MAX_PALLAS_NODES (VMEM
budget) — otherwise the XLA path in backend/batch.py runs unchanged.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

try:  # pallas needs a TPU-capable lowering; import is cheap and safe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # noqa: BLE001 — environment without pallas
    HAVE_PALLAS = False

NEG_INF = -(2.0 ** 30)  # plain float: jnp constants cannot be captured by the kernel
VMEM_BUDGET_BYTES = 12 * 1024 * 1024  # keep resident buffers under ~16MB VMEM


def shapes_supported(n_nodes: int, n_resources: int, n_port_words: int,
                     axis_name, topo_enabled: bool) -> bool:
    """Structural eligibility for the fused kernel (shared by compiled and
    interpret modes): single shard, no topology path, lane-aligned node axis,
    and ALL resident buffers within the VMEM budget — the [R,N] triples grow
    with the extended-resource vocabulary, not just N."""
    if not HAVE_PALLAS or axis_name is not None or topo_enabled:
        return False
    if n_nodes % 128 != 0:
        return False
    resident = (
        4 * n_resources * n_nodes * 4      # alloc + req + nz (+1 copy headroom)
        + 2 * n_port_words * n_nodes * 4   # port bits + copy
        + 8 * n_nodes * 4                  # [1,N] vectors
    )
    return resident <= VMEM_BUDGET_BYTES


def compile_supported() -> bool:
    return HAVE_PALLAS and jax.default_backend() in ("tpu", "axon")


def _step_kernel(
    # inputs
    alloc_ref,      # [R, N] i32 (static allocatable, transposed)
    req_ref,        # [R, N] i32 (dynamic requested; aliased to output 0)
    nz_ref,         # [R, N] i32 (dynamic nonzero-requested; aliased 1)
    port_ref,       # [W, N] u32 (dynamic port bitsets; aliased 2)
    preq_ref,       # [R, 1] i32 pod request
    pnz_ref,        # [R, 1] i32 pod nonzero request
    pbits_ref,      # [W, 1] u32 pod wanted-port bits
    static_ref,     # [1, N] bool (static filters AND node valid)
    taint_ref,      # [1, N] f32 raw taint score
    aff_ref,        # [1, N] f32 raw node-affinity score
    img_ref,        # [1, N] f32 image-locality score (pre-normalized)
    jitter_ref,     # [1, N] f32 tie-break jitter in [0, 0.5)
    pvalid_ref,     # [1, 1] i32 pod-valid flag
    weights_ref,    # [1, 8] f32 plugin weights (fit, balanced, taint, aff, img)
    # outputs
    req_out,        # [R, N] i32 (alias of req_ref)
    nz_out,         # [R, N] i32 (alias of nz_ref)
    port_out,       # [W, N] u32 (alias of port_ref)
    idx_out,        # [1, 1] i32 winner slot (-1 none)
    best_out,       # [1, 1] f32 winner total (no jitter)
    anyf_out,       # [1, 1] i32 any-feasible flag
    fit_out,        # [1, N] bool
    ports_out,      # [1, N] bool
):
    alloc = alloc_ref[:]
    req = req_ref[:]
    nz = nz_ref[:]
    ports = port_ref[:]
    p_req = preq_ref[:]
    p_nz = pnz_ref[:]
    p_bits = pbits_ref[:]
    static_ok = static_ref[:]
    w = weights_ref[:]

    # ---- dynamic filters
    free = alloc - req                                     # [R, N]
    fit2d = (p_req <= free) | (p_req == 0)
    fit = jnp.all(fit2d, axis=0, keepdims=True)            # [1, N]
    conflict = jnp.any((ports & p_bits) != 0, axis=0, keepdims=True)
    ports_ok = ~conflict
    feasible = static_ok & fit & ports_ok                  # [1, N]

    # ---- resource scores over the evolving requested state (cpu=row0, mem=row1)
    nz_req0 = nz[0:1, :].astype(jnp.float32) + p_nz[0, 0].astype(jnp.float32)
    nz_req1 = nz[1:2, :].astype(jnp.float32) + p_nz[1, 0].astype(jnp.float32)
    cap0 = alloc[0:1, :].astype(jnp.float32)
    cap1 = alloc[1:2, :].astype(jnp.float32)
    la0 = jnp.where((cap0 == 0) | (nz_req0 > cap0), 0.0,
                    jnp.floor((cap0 - nz_req0) * 100.0 / jnp.maximum(cap0, 1.0)))
    la1 = jnp.where((cap1 == 0) | (nz_req1 > cap1), 0.0,
                    jnp.floor((cap1 - nz_req1) * 100.0 / jnp.maximum(cap1, 1.0)))
    least_alloc = jnp.floor((la0 + la1) / 2.0)
    f0 = jnp.where(cap0 == 0, 1.0, jnp.minimum(1.0, nz_req0 / jnp.maximum(cap0, 1.0)))
    f1 = jnp.where(cap1 == 0, 1.0, jnp.minimum(1.0, nz_req1 / jnp.maximum(cap1, 1.0)))
    balanced = jnp.floor((1.0 - jnp.abs(f0 - f1) / 2.0) * 100.0)

    # ---- DefaultNormalizeScore over this pod's feasible set
    taint = taint_ref[:]
    aff = aff_ref[:]
    t_mx = jnp.max(jnp.where(feasible, taint, 0.0))
    t_scaled = jnp.floor(taint * 100.0 / jnp.maximum(t_mx, 1.0))
    taint_norm = jnp.where(t_mx == 0, 100.0, 100.0 - t_scaled)
    a_mx = jnp.max(jnp.where(feasible, aff, 0.0))
    a_scaled = jnp.floor(aff * 100.0 / jnp.maximum(a_mx, 1.0))
    aff_norm = jnp.where(a_mx == 0, 0.0, a_scaled)

    total = (
        w[0, 0] * least_alloc
        + w[0, 1] * balanced
        + w[0, 2] * taint_norm
        + w[0, 3] * aff_norm
        + w[0, 4] * img_ref[:]
    )                                                      # [1, N]

    # ---- winner: jittered masked argmax (first-max tie order == XLA path)
    eff = jnp.where(feasible, total + jitter_ref[:], NEG_INF)
    idx = jnp.argmax(eff[0, :]).astype(jnp.int32)
    any_feasible = jnp.any(feasible) & (pvalid_ref[:][0, 0] > 0)
    commit = any_feasible

    idx_out[:] = jnp.where(any_feasible, idx, -1).reshape(1, 1)
    best_out[:] = jnp.sum(jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, total.shape, 1) == idx, total, 0.0)
    ).reshape(1, 1)
    anyf_out[:] = any_feasible.astype(jnp.int32).reshape(1, 1)
    fit_out[:] = fit
    ports_out[:] = ports_ok

    # ---- commit the winner's lane (outputs alias the dynamic inputs)
    lane = jax.lax.broadcasted_iota(jnp.int32, req.shape, 1) == idx
    add_req = jnp.where(lane & commit, p_req, 0)
    add_nz = jnp.where(lane & commit, p_nz, 0)
    req_out[:] = req + add_req
    nz_out[:] = nz + add_nz
    lane_w = jax.lax.broadcasted_iota(jnp.int32, ports.shape, 1) == idx
    port_out[:] = jnp.where(lane_w & commit, ports | p_bits, ports)


def fused_step(alloc_t, req_t, nz_t, port_t, p_req, p_nz, p_bits,
               static_ok, taint, aff, img, jitter, p_valid, weights,
               interpret: bool = False) -> Tuple:
    """Invoke the fused kernel. Shapes as per _step_kernel; returns
    (req_t', nz_t', port_t', idx, best, any_feasible, fit_ok, ports_ok).
    ``interpret=True`` runs the Python interpreter lowering (CPU tests)."""
    R, N = alloc_t.shape
    W = port_t.shape[0]
    out_shape = (
        jax.ShapeDtypeStruct((R, N), jnp.int32),
        jax.ShapeDtypeStruct((R, N), jnp.int32),
        jax.ShapeDtypeStruct((W, N), jnp.uint32),
        jax.ShapeDtypeStruct((1, 1), jnp.int32),
        jax.ShapeDtypeStruct((1, 1), jnp.float32),
        jax.ShapeDtypeStruct((1, 1), jnp.int32),
        jax.ShapeDtypeStruct((1, N), jnp.bool_),
        jax.ShapeDtypeStruct((1, N), jnp.bool_),
    )
    return pl.pallas_call(
        _step_kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 14,
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 8),
        input_output_aliases={1: 0, 2: 1, 3: 2},  # req/nz/port update in place
        interpret=interpret,
    )(alloc_t, req_t, nz_t, port_t, p_req, p_nz, p_bits,
      static_ok, taint, aff, img, jitter, p_valid, weights)
