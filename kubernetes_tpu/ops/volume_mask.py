"""Host-vectorized volume-bindability pre-pass for the batched scheduler
(VERDICT r4 item 4; reference: volumebinding/binder.go FindPodVolumes,
volumezone/volume_zone.go, nodevolumelimits/*).

Volume-bearing pods used to take the sequential oracle path wholesale
(batch_supported=False), paying O(nodes × PVs) host Python per pod — the
40 pods/s InTreePVs/CSIPVs rows. This module computes a [P, N] boolean
bindability mask per batch instead:

  * bound claims  → the PV's admitted-node set (node-affinity label terms,
    vectorized over the node slot table; a PV with no affinity admits all)
  * delayed (WaitForFirstConsumer) claims → per-(class) free-PV node counts
    must cover the pod's per-class claim count (Hall's condition is only
    approximated — see below)
Attach limits (nodevolumelimits) are NOT screened here — per-type/driver
limits vary by CSINode and cluster config, and any fixed bound would
under-admit; the exact limit plugins run in the commit-path host verify.

The mask is deliberately ONE-SIDED: it may over-admit (attach-limit races
inside a batch, multi-claim matching subtleties) but never under-admits a
node the oracle would accept. The commit path re-runs the exact volume
filter plugins on the CHOSEN node only (host verify, O(PVs) once per pod
instead of per node); an over-admitted choice fails there and the pod
retries — crash-only, same shape as the preemption screen's
"device proposes, host verifies".
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api.types import BINDING_WAIT_FOR_FIRST_CONSUMER

class VolumeMaskBuilder:
    """Per-scheduler cache of PV → admitted-slot sets, keyed by the encoder's
    slot table version (slots churn with node add/remove)."""

    def __init__(self, client):
        self.client = client
        self._pv_slots: Dict[str, Tuple[int, Optional[np.ndarray]]] = {}
        self._label_index_key = None
        self._label_index: Dict[Tuple[str, str], List[int]] = {}

    # -- helpers

    def batchable(self, pod) -> bool:
        """Cheap per-pod gate: every claim resolvable and either bound or
        delayed-binding (immediate-unbound pods go to the oracle, which
        rejects them exactly — volume_binding.go:207)."""
        for claim in pod.spec.volumes:
            pvc = self.client.get_pvc(f"{pod.meta.namespace}/{claim}")
            if pvc is None:
                return False
            if not pvc.bound_pv:
                sc = self.client.get_storage_class(pvc.storage_class)
                if sc is None or sc.volume_binding_mode != BINDING_WAIT_FOR_FIRST_CONSUMER:
                    return False
        return True

    def _node_label_index(self, snapshot, version) -> Dict[Tuple[str, str], List[int]]:
        if self._label_index_key != version:
            self._label_index = {}
            for ni in snapshot.node_info_list:
                node = ni.node
                slot = self._slot_of.get(node.meta.name)
                if slot is None:
                    continue
                for k, v in node.meta.labels.items():
                    self._label_index.setdefault((k, v), []).append(slot)
            self._label_index_key = version
        return self._label_index

    # zone/region label keys a bound PV constrains (volume_zone.go:88)
    _ZONE_KEYS = (
        "topology.kubernetes.io/zone",
        "topology.kubernetes.io/region",
        "failure-domain.beta.kubernetes.io/zone",
        "failure-domain.beta.kubernetes.io/region",
    )

    def _pv_admitted(self, pv, snapshot, version, n_cap) -> Optional[np.ndarray]:
        """[N] bool of slots this PV admits: node-affinity label terms AND
        the VolumeZone rule (PV zone/region labels must match the node's;
        `__`-separated multi-zone values allowed). None = all nodes."""
        constraints = list(pv.node_affinity.items())
        for key in self._ZONE_KEYS:
            val = pv.meta.labels.get(key)
            if val is not None:
                constraints.append((key, tuple(val.split("__"))))
        if not constraints:
            return None
        cache_key = (version, pv.meta.resource_version)
        cached = self._pv_slots.get(pv.meta.name)
        if cached is not None and cached[0] == cache_key:
            return cached[1]
        idx = self._node_label_index(snapshot, version)
        mask = np.zeros(n_cap, bool)
        first = True
        for key, allowed in constraints:
            term = np.zeros(n_cap, bool)
            for v in allowed:
                for slot in idx.get((key, v), ()):
                    term[slot] = True
            mask = term if first else (mask & term)
            first = False
        self._pv_slots[pv.meta.name] = (cache_key, mask)
        return mask

    # -- the batch mask

    def build(self, qps, snapshot, encoder, n_cap: int,
              pad_to: int) -> Optional[np.ndarray]:
        """[pad_to, n_cap] bool; None when no pod in the batch has volumes.
        Rows for volume-less (and padding) pods are all-True."""
        if not any(qp.pod.spec.volumes for qp in qps):
            return None
        self._slot_of = encoder.node_slots
        version = (len(encoder.node_slots),
                   getattr(snapshot, "structure_version", -1),
                   getattr(snapshot, "node_object_version", -1))
        mask = np.ones((pad_to, n_cap), bool)

        # delayed-binding pools: per storage class, free-PV counts per node
        free_by_class: Dict[str, np.ndarray] = {}

        for p, qp in enumerate(qps):
            pod = qp.pod
            if not pod.spec.volumes:
                continue
            row = mask[p]
            delayed_needs: Dict[str, int] = {}
            for claim in pod.spec.volumes:
                pvc = self.client.get_pvc(f"{pod.meta.namespace}/{claim}")
                if pvc is None:
                    # batchable() should have routed this to the oracle;
                    # admit-all keeps the one-sided contract if it races
                    continue
                if pvc.bound_pv:
                    pv = self.client.get_pv(pvc.bound_pv)
                    if pv is None:
                        continue  # dangling bind: the oracle filters skip it too
                    admitted = self._pv_admitted(pv, snapshot, version, n_cap)
                    if admitted is not None:
                        row &= admitted
                else:
                    delayed_needs[pvc.storage_class] = (
                        delayed_needs.get(pvc.storage_class, 0) + 1)
            for cls, need in delayed_needs.items():
                free = free_by_class.get(cls)
                if free is None:
                    free = np.zeros(n_cap, np.int32)
                    for pv in self.client.list_pvs():
                        if pv.bound_pvc or pv.storage_class != cls:
                            continue
                        admitted = self._pv_admitted(pv, snapshot, version, n_cap)
                        if admitted is None:
                            free += 1
                        else:
                            free += admitted.astype(np.int32)
                    free_by_class[cls] = free
                row &= free >= need
        return mask
