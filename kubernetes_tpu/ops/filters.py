"""Batched filter kernels: [P pods, N nodes] boolean predicates.

Each kernel mirrors one oracle Filter plugin (framework/plugins/*) evaluated
for the whole pod batch × node snapshot at once — the tensorization of the
reference's per-(pod,node) Filter calls (schedule_one.go:449
findNodesThatPassFilters runs them node-parallel; here pod×node-parallel).

All kernels are shape-polymorphic pure functions of (PodBatch, ExprTable,
NodeTensors); everything is gather-based — no O(P·N·V) intermediates.
Filter short-circuit semantics become mask ANDs (SURVEY.md §8 last bullet):
the accept set is identical; first-failing-plugin attribution is
reconstructed host-side from the per-plugin masks when a pod fails.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import schema
from .schema import ExprTable, NodeTensors, PodBatch


def eval_exprs(et: ExprTable, nt: NodeTensors) -> jax.Array:
    """Evaluate the batch's unique selector expressions → [E, N] bool."""
    vals = nt.label_val[:, et.key].T       # [E, N] value-id of node for expr's key
    nums = nt.label_num[:, et.key].T       # [E, N]
    # IN-set membership: bit `vals` of et.bits[e]
    word = jnp.take_along_axis(et.bits, (vals >> 5).astype(jnp.int32), axis=1)
    in_set = ((word >> (vals & 31).astype(jnp.uint32)) & 1).astype(bool)

    has_key = vals > 0
    has_num = nums != schema.INT_NONE
    op = et.op[:, None]
    val = et.val[:, None]
    n_idx = jnp.arange(nt.capacity, dtype=jnp.int32)[None, :]

    out = jnp.ones_like(in_set)  # OP_TRUE
    out = jnp.where(op == schema.OP_IN, in_set, out)
    out = jnp.where(op == schema.OP_NOT_IN, ~in_set, out)
    out = jnp.where(op == schema.OP_EXISTS, has_key, out)
    out = jnp.where(op == schema.OP_NOT_EXISTS, ~has_key, out)
    out = jnp.where(op == schema.OP_GT, has_num & (nums > val), out)
    out = jnp.where(op == schema.OP_LT, has_num & (nums < val), out)
    out = jnp.where(op == schema.OP_NODE_NAME, n_idx == val, out)
    return out


def eval_and_program(expr_match: jax.Array, idx: jax.Array) -> jax.Array:
    """AND over expr slots (slot 0 = TRUE is the neutral pad). idx [P,S] → [P,N]."""
    return jnp.all(expr_match[idx], axis=1)


def eval_term_program(expr_match: jax.Array, term_idx: jax.Array, term_valid: jax.Array) -> jax.Array:
    """OR over valid terms of AND over each term's exprs; no valid terms ⇒ True.
    term_idx [P,T,E'] → [P,N]. (NodeSelector term OR-semantics.)"""
    per_term = jnp.all(expr_match[term_idx], axis=2)          # [P, T, N]
    any_term = jnp.any(per_term & term_valid[:, :, None], axis=1)
    has_terms = jnp.any(term_valid, axis=1)
    return jnp.where(has_terms[:, None], any_term, True)


# --------------------------------------------------------------------- filters


def filter_node_resources_fit(pb: PodBatch, nt: NodeTensors) -> jax.Array:
    """NodeResourcesFit (noderesources/fit.go:252 fitsRequest): per resource
    `req ≤ allocatable − requested`, zero requests skip the check (the pod-count
    column always requests 1, giving the `len(pods)+1 > allowed` check)."""
    free = nt.allocatable - nt.requested                       # [N, R]
    req = pb.req[:, None, :]                                   # [P, 1, R]
    ok = (req <= free[None]) | (req == 0)
    return jnp.all(ok, axis=-1)


def filter_node_name(pb: PodBatch, nt: NodeTensors) -> jax.Array:
    n_idx = jnp.arange(nt.capacity, dtype=jnp.int32)[None, :]
    want = pb.node_name[:, None]
    return (want == -1) | (want == n_idx)


def filter_unschedulable(pb: PodBatch, nt: NodeTensors) -> jax.Array:
    return (~nt.unschedulable)[None, :] | pb.tolerates_unschedulable[:, None]


def _taint_tolerated(pb: PodBatch, nt: NodeTensors, tol_mask: jax.Array) -> jax.Array:
    """tolerated[p, n, t] = any toleration (restricted by tol_mask [P,L])
    tolerates node n's taint t (Toleration.ToleratesTaint semantics)."""
    tk = nt.taint_key[None, :, :, None]      # [1, N, T, 1]
    tv = nt.taint_val[None, :, :, None]
    te = nt.taint_effect[None, :, :, None]
    lk = pb.tol_key[:, None, None, :]        # [P, 1, 1, L]
    lv = pb.tol_val[:, None, None, :]
    lo = pb.tol_op[:, None, None, :]
    le = pb.tol_effect[:, None, None, :]
    key_ok = (lk == 0) | (lk == tk)
    eff_ok = (le == schema.EFFECT_NONE) | (le == te)
    val_ok = (lo == schema.TOL_EXISTS) | ((lo == schema.TOL_EQUAL) & (lv == tv) & (lk == tk))
    live = (lo != 0) & tol_mask[:, None, None, :]
    return jnp.any(key_ok & eff_ok & val_ok & live, axis=-1)   # [P, N, T]


def filter_taints(pb: PodBatch, nt: NodeTensors) -> jax.Array:
    """TaintToleration Filter: every NoSchedule/NoExecute taint tolerated."""
    all_tols = jnp.ones_like(pb.tol_prefer)
    tolerated = _taint_tolerated(pb, nt, all_tols)
    relevant = (nt.taint_effect == schema.EFFECT_NO_SCHEDULE) | (
        nt.taint_effect == schema.EFFECT_NO_EXECUTE
    )                                                          # [N, T]
    bad = relevant[None] & (nt.taint_key > 0)[None] & ~tolerated
    return ~jnp.any(bad, axis=-1)


def filter_node_affinity(pb: PodBatch, et: ExprTable, nt: NodeTensors, expr_match=None) -> jax.Array:
    """NodeAffinity Filter: nodeSelector map AND required terms."""
    if expr_match is None:
        expr_match = eval_exprs(et, nt)
    sel_ok = eval_and_program(expr_match, pb.sel_idx)
    aff_ok = eval_term_program(expr_match, pb.term_idx, pb.term_valid)
    return sel_ok & aff_ok


def filter_node_ports(pb: PodBatch, nt: NodeTensors) -> jax.Array:
    """NodePorts: no wanted-port vocab bit set on the node (wildcard-exact)."""
    ids = pb.port_ids                                          # [P, MP]
    word = nt.port_bits[:, ids >> 5]                           # [N, P, MP]
    bit = ((word >> (ids & 31).astype(jnp.uint32)) & 1).astype(bool)
    conflict = jnp.any(bit & (ids > 0)[None], axis=-1)         # [N, P]
    return ~conflict.T


def run_all_filters(pb: PodBatch, et: ExprTable, nt: NodeTensors) -> dict:
    """All per-(pod,node) filter masks + the combined feasibility mask.
    Returned per plugin so host code can attribute failures in config order
    (Diagnosis.NodeToStatusMap reconstruction)."""
    expr_match = eval_exprs(et, nt)
    masks = {
        "NodeUnschedulable": filter_unschedulable(pb, nt),
        "NodeName": filter_node_name(pb, nt),
        "TaintToleration": filter_taints(pb, nt),
        "NodeAffinity": filter_node_affinity(pb, et, nt, expr_match),
        "NodePorts": filter_node_ports(pb, nt),
        "NodeResourcesFit": filter_node_resources_fit(pb, nt),
    }
    feasible = nt.valid[None, :] & pb.valid[:, None]
    for m in masks.values():
        feasible = feasible & m
    return {"masks": masks, "feasible": feasible, "expr_match": expr_match}
