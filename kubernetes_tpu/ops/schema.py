"""Dense tensor schemas for the device-resident cluster mirror.

Design notes (why this is NOT a transliteration of NodeInfo):

* Nodes live in fixed **slots** (stable indices into the N axis); all arrays are
  padded to static capacities so one compiled program serves the whole run.
  Slot 0 is always invalid padding (vocab-id convention), real slots start at 1?
  No — slots are 0-based with an explicit ``valid`` mask; id-like *vocab*
  columns reserve 0 for "absent".

* Labels are encoded as a dense per-registered-key table instead of bitsets:
  ``label_val[N, K]`` holds the value-id of node n for key k (per-key value
  vocab, 0 = absent) and ``label_num[N, K]`` its integer parse (INT_MIN when
  not numeric).  Selector matching then becomes *gathers*, not giant bit
  intersections: each scheduling batch compiles its unique selector
  expressions into an ExprTable evaluated once as an [E, N] bool matrix.

* Resource vectors are int32 with canonical units (api/resource.py):
  col 0 = cpu milli, 1 = memory KiB, 2 = ephemeral MiB, 3 = pod count /
  allowed pods, 4.. = scalar resources by scalar-vocab slot.

Reference mapping: framework/types.go:363 NodeInfo → NodeTensors row;
snapshot (internal/cache/snapshot.go) → the whole NodeTensors value.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

INT_NONE = np.int32(-(2**31))  # sentinel for "absent" numeric label

# TPU torus geometry: chips per schedulable host (a v4/v5 host exposes one
# 4-chip board to the control plane; slice sizes quoted in chips are
# node-count * CHIPS_PER_NODE)
CHIPS_PER_NODE = 4

# resource columns
COL_CPU = 0
COL_MEM = 1
COL_EPH = 2
COL_PODS = 3
N_FIXED_COLS = 4

# expression opcodes (the selector VM)
OP_TRUE = 0       # constant true (slot 0 of every ExprTable; AND-neutral padding)
OP_IN = 1         # label_val[n, key] ∈ value-id set (bitset over the key's value vocab)
OP_NOT_IN = 2     # absent key matches (labels.Requirement semantics)
OP_EXISTS = 3
OP_NOT_EXISTS = 4
OP_GT = 5         # int(label) > val; absent/non-numeric never matches
OP_LT = 6
OP_NODE_NAME = 7  # node slot == val (compiled metadata.name matchFields)

# taint effects
EFFECT_NONE = 0
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3

# toleration operators
TOL_EQUAL = 1
TOL_EXISTS = 2


def pytree_dataclass(cls):
    cls = dataclasses.dataclass(cls)
    fields = [f.name for f in dataclasses.fields(cls)]
    jax.tree_util.register_dataclass(cls, data_fields=fields, meta_fields=[])
    return cls


@pytree_dataclass
class NodeTensors:
    """Device-resident per-node state, [N]-padded. The TPU mirror of the
    scheduler cache snapshot."""

    valid: jax.Array          # [N] bool
    unschedulable: jax.Array  # [N] bool
    allocatable: jax.Array    # [N, R] int32 (col PODS = allowed pod count)
    requested: jax.Array      # [N, R] int32 (col PODS = current pod count)
    nonzero_requested: jax.Array  # [N, R] int32 (scoring-path requests)
    label_val: jax.Array      # [N, K] int32 value-id (0 absent)
    label_num: jax.Array      # [N, K] int32 numeric parse (INT_NONE absent)
    taint_key: jax.Array      # [N, T] int32 key-id (0 = no taint in slot)
    taint_val: jax.Array      # [N, T] int32 value-id in key's vocab
    taint_effect: jax.Array   # [N, T] int32 effect code
    port_bits: jax.Array      # [N, Wport] uint32 bitset over the port vocab
    image_bits: jax.Array     # [N, Wimg] uint32 bitset over the image vocab
    image_sizes: jax.Array    # [Vimg] int32 bytes (vocab-level, not per node)
    image_num_nodes: jax.Array  # [Vimg] int32 (ImageStateSummary.NumNodes)
    # priority-class-bucketed requested sums: the device side of batched
    # preemption (preemption.go:546 DryRunPreemption's fit check becomes a
    # prefix-sum over classes sorted by priority). class 0 is reserved
    # padding with class_prio INT_MAX (never evictable).
    class_req: jax.Array      # [N, C, R] int32 requested by pods of class c
    class_prio: jax.Array     # [C] int32 priority value of class c (vocab)
    name_hash: jax.Array      # [N] uint32 fnv1a(node name) — seeded tie-break
    # torus topology axis (slice packing): superpod id and linear position
    # inside the superpod's torus, parsed from well-known node labels (or the
    # synthetic slot-derived fallback); -1 = node carries no topology
    topo_sp: jax.Array        # [N] int32 superpod id (-1 absent)
    topo_pos: jax.Array       # [N] int32 torus slot within superpod (-1 absent)

    @property
    def capacity(self) -> int:
        return self.valid.shape[0]


@pytree_dataclass
class ExprTable:
    """Batch-level deduplicated selector expressions, evaluated once per batch
    to an [E, N] match matrix. Slot 0 is OP_TRUE."""

    op: jax.Array      # [E] int32 opcode
    key: jax.Array     # [E] int32 label-key slot
    val: jax.Array     # [E] int32 (GT/LT compare value or NODE_NAME slot)
    bits: jax.Array    # [E, Wv] uint32 value-id set for IN/NOT_IN


@pytree_dataclass
class PodBatch:
    """A micro-batch of pending pods, [P]-padded, with compiled programs
    pointing into the batch ExprTable."""

    valid: jax.Array        # [P] bool
    priority: jax.Array     # [P] int32
    prio_class: jax.Array   # [P] int32 priority-class vocab id (preemption)
    req: jax.Array          # [P, R] int32 (filter-path request; col PODS == 1)
    nonzero_req: jax.Array  # [P, R] int32 (scoring-path request)
    node_name: jax.Array    # [P] int32 target slot or -1 (pod.spec.nodeName)
    nominated: jax.Array    # [P] int32 nominatedNodeName slot or -1
    tol_key: jax.Array      # [P, L] int32 (0 = wildcard key)
    tol_val: jax.Array      # [P, L] int32
    tol_op: jax.Array       # [P, L] int32 (0 = empty slot)
    tol_effect: jax.Array   # [P, L] int32 (EFFECT_NONE = matches all effects)
    tol_prefer: jax.Array   # [P, L] bool: effect ∈ {"", PreferNoSchedule} (taint Score path)
    tolerates_unschedulable: jax.Array  # [P] bool (precompiled for NodeUnschedulable)
    # node selector + required affinity: AND(sel_idx) AND OR_t(AND_e(term))
    sel_idx: jax.Array      # [P, S] int32 expr slots, AND-combined (0 = true)
    term_idx: jax.Array     # [P, TERM, EXPR] int32 expr slots
    term_valid: jax.Array   # [P, TERM] bool (no valid terms ⇒ affinity passes)
    # preferred affinity (weights; invalid slots have weight 0)
    pref_idx: jax.Array     # [P, PTERM, EXPR] int32
    pref_weight: jax.Array  # [P, PTERM] int32
    port_ids: jax.Array     # [P, MP] int32 wanted-port vocab ids (0 = empty)
    image_ids: jax.Array    # [P, C] int32 container image vocab ids (0 = empty)
    num_containers: jax.Array  # [P] int32
    tie_seed: jax.Array     # [P] uint32 per-(pod, attempt) tie-break seed

    @property
    def capacity(self) -> int:
        return self.valid.shape[0]


@pytree_dataclass
class TopoCounts:
    """Device-resident pod-set count tables — the incremental tensorization of
    the O(pods) scans in PodTopologySpread.PreFilter (filtering.go:238) and
    InterPodAffinity.PreFilter (filtering.go:86-135).

    ``sel_counts[s, n]`` = number of pods currently on node n matching
    registered pod-set signature s (a (namespace-spec, label-selector) pair —
    the unit both plugins count by). ``term_counts[t, n]`` = number of pods on
    node n *carrying* registered (anti-)affinity term t (the symmetric
    direction: existing pods' terms evaluated against the incoming pod).
    Both are maintained host-side per node generation and updated in-scan as
    batch pods commit."""

    sel_counts: jax.Array   # [S, N] int32
    term_counts: jax.Array  # [T, N] int32
    term_key: jax.Array     # [T] int32 topology-key slot of term t (0 = unused row)


@pytree_dataclass
class TopoBatch:
    """Per-batch compiled topology programs: spread constraints and
    inter-pod-affinity terms of the batch pods, pointing into TopoCounts rows.
    All index fields are 0 where invalid (row 0 of each table is a zero row)."""

    # PodTopologySpread DoNotSchedule constraints (filter), [P, C]
    sf_valid: jax.Array        # bool
    sf_sig: jax.Array          # int32 sig row
    sf_key: jax.Array          # int32 topology-key slot
    sf_skew: jax.Array         # int32 maxSkew
    sf_self: jax.Array         # bool: incoming pod matches the constraint selector
    sf_min_domains: jax.Array  # int32, -1 = unset
    # PodTopologySpread ScheduleAnyway constraints (score), [P, C]
    ss_valid: jax.Array        # bool
    ss_sig: jax.Array
    ss_key: jax.Array
    ss_skew: jax.Array
    ss_hostname: jax.Array     # bool: topologyKey == kubernetes.io/hostname
    ss_require_all: jax.Array  # [P] bool (pod-specified or non-system defaults)
    # incoming pod's required pod-affinity terms, [P, A]
    ia_valid: jax.Array
    ia_sig: jax.Array
    ia_key: jax.Array
    ia_self_all: jax.Array     # [P] bool: pod matches ALL its own affinity terms
    # incoming pod's required pod-anti-affinity terms, [P, A]
    ianti_valid: jax.Array
    ianti_sig: jax.Array
    ianti_key: jax.Array
    # incoming pod's preferred (anti-)affinity terms, [P, PT]
    ip_valid: jax.Array
    ip_sig: jax.Array
    ip_key: jax.Array
    ip_w: jax.Array            # int32 signed weight (negative = anti)
    # existing-term interactions, [P, T]
    term_filter_match: jax.Array  # bool: ANTI_REQ term t matches incoming pod p
    term_score_w: jax.Array       # float32 symmetric score weight of term t for pod p
    # commit updates (what a committing pod adds to the node it lands on)
    pod_sig_mask: jax.Array    # [P, S] bool
    pod_term_mask: jax.Array   # [P, T] bool


def round_node_capacity(n: int, floor: int = 128) -> int:
    """Node-axis padding bucket: powers of two up to 1024, then multiples of
    1024. Pow2 all the way up wastes real bandwidth — every per-step tensor
    in the batch scan is [N,·], so padding 5000 nodes to 8192 paid +64%
    memory traffic per scheduling step; 5120 pays +2.4%. Multiples of 1024
    keep the lane/sublane tiling XLA wants on TPU (and change nothing on
    CPU), while still bucketing growth so the executable cache stays small."""
    cap = max(128, floor)
    while cap < n and cap < 1024:
        cap *= 2
    if cap < n:
        cap = ((n + 1023) // 1024) * 1024
    return cap


@dataclasses.dataclass(frozen=True)
class Capacities:
    """Static padding sizes; one compiled executable per Capacities value."""

    nodes: int = 128          # N
    pods: int = 64            # P
    resources: int = 6        # R (4 fixed + scalar slots)
    label_keys: int = 16      # K
    taints: int = 4           # T per node
    tolerations: int = 4      # L per pod
    exprs: int = 64           # E per batch
    sel_exprs: int = 8        # S per pod
    terms: int = 4            # affinity terms per pod
    term_exprs: int = 4       # exprs per term
    pref_terms: int = 4       # preferred terms per pod
    value_words: int = 32     # Wv: value-vocab bitset words (per-key vocab ≤ 32*Wv)
    port_words: int = 16      # Wport
    ports: int = 8            # MP wanted ports per pod
    image_words: int = 16     # Wimg
    images: int = 1 + 16 * 32  # Vimg (vocab capacity = image_words*32, +0 slot)
    containers: int = 4       # C per pod
    sigs: int = 8             # S: registered pod-set signatures (row 0 reserved)
    ex_terms: int = 8         # T: registered existing-pod terms (row 0 reserved)
    spread_cons: int = 2      # C: topology-spread constraints per pod per kind
    ipa_terms: int = 2        # A: required (anti-)affinity terms per pod
    ipa_pref: int = 2         # PT: preferred terms per pod (both signs combined)
    prio_classes: int = 32    # distinct pod priority values (+ reserved row 0)
    superpods: int = 16       # S: torus superpods the grid axis can hold
    sp_slots: int = 16        # P: node positions per superpod torus

    def grow_nodes(self, n: int) -> "Capacities":
        return dataclasses.replace(self, nodes=round_node_capacity(n, self.nodes))
