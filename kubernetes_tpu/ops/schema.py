"""Dense tensor schemas for the device-resident cluster mirror.

Design notes (why this is NOT a transliteration of NodeInfo):

* Nodes live in fixed **slots** (stable indices into the N axis); all arrays are
  padded to static capacities so one compiled program serves the whole run.
  Slot 0 is always invalid padding (vocab-id convention), real slots start at 1?
  No — slots are 0-based with an explicit ``valid`` mask; id-like *vocab*
  columns reserve 0 for "absent".

* Labels are encoded as a dense per-registered-key table instead of bitsets:
  ``label_val[N, K]`` holds the value-id of node n for key k (per-key value
  vocab, 0 = absent) and ``label_num[N, K]`` its integer parse (INT_MIN when
  not numeric).  Selector matching then becomes *gathers*, not giant bit
  intersections: each scheduling batch compiles its unique selector
  expressions into an ExprTable evaluated once as an [E, N] bool matrix.

* Resource vectors are int32 with canonical units (api/resource.py):
  col 0 = cpu milli, 1 = memory KiB, 2 = ephemeral MiB, 3 = pod count /
  allowed pods, 4.. = scalar resources by scalar-vocab slot.

Reference mapping: framework/types.go:363 NodeInfo → NodeTensors row;
snapshot (internal/cache/snapshot.go) → the whole NodeTensors value.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

INT_NONE = np.int32(-(2**31))  # sentinel for "absent" numeric label

# resource columns
COL_CPU = 0
COL_MEM = 1
COL_EPH = 2
COL_PODS = 3
N_FIXED_COLS = 4

# expression opcodes (the selector VM)
OP_TRUE = 0       # constant true (slot 0 of every ExprTable; AND-neutral padding)
OP_IN = 1         # label_val[n, key] ∈ value-id set (bitset over the key's value vocab)
OP_NOT_IN = 2     # absent key matches (labels.Requirement semantics)
OP_EXISTS = 3
OP_NOT_EXISTS = 4
OP_GT = 5         # int(label) > val; absent/non-numeric never matches
OP_LT = 6
OP_NODE_NAME = 7  # node slot == val (compiled metadata.name matchFields)

# taint effects
EFFECT_NONE = 0
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3

# toleration operators
TOL_EQUAL = 1
TOL_EXISTS = 2


def pytree_dataclass(cls):
    cls = dataclasses.dataclass(cls)
    fields = [f.name for f in dataclasses.fields(cls)]
    jax.tree_util.register_dataclass(cls, data_fields=fields, meta_fields=[])
    return cls


@pytree_dataclass
class NodeTensors:
    """Device-resident per-node state, [N]-padded. The TPU mirror of the
    scheduler cache snapshot."""

    valid: jax.Array          # [N] bool
    unschedulable: jax.Array  # [N] bool
    allocatable: jax.Array    # [N, R] int32 (col PODS = allowed pod count)
    requested: jax.Array      # [N, R] int32 (col PODS = current pod count)
    nonzero_requested: jax.Array  # [N, R] int32 (scoring-path requests)
    label_val: jax.Array      # [N, K] int32 value-id (0 absent)
    label_num: jax.Array      # [N, K] int32 numeric parse (INT_NONE absent)
    taint_key: jax.Array      # [N, T] int32 key-id (0 = no taint in slot)
    taint_val: jax.Array      # [N, T] int32 value-id in key's vocab
    taint_effect: jax.Array   # [N, T] int32 effect code
    port_bits: jax.Array      # [N, Wport] uint32 bitset over the port vocab
    image_bits: jax.Array     # [N, Wimg] uint32 bitset over the image vocab
    image_sizes: jax.Array    # [Vimg] int32 bytes (vocab-level, not per node)
    image_num_nodes: jax.Array  # [Vimg] int32 (ImageStateSummary.NumNodes)

    @property
    def capacity(self) -> int:
        return self.valid.shape[0]


@pytree_dataclass
class ExprTable:
    """Batch-level deduplicated selector expressions, evaluated once per batch
    to an [E, N] match matrix. Slot 0 is OP_TRUE."""

    op: jax.Array      # [E] int32 opcode
    key: jax.Array     # [E] int32 label-key slot
    val: jax.Array     # [E] int32 (GT/LT compare value or NODE_NAME slot)
    bits: jax.Array    # [E, Wv] uint32 value-id set for IN/NOT_IN


@pytree_dataclass
class PodBatch:
    """A micro-batch of pending pods, [P]-padded, with compiled programs
    pointing into the batch ExprTable."""

    valid: jax.Array        # [P] bool
    priority: jax.Array     # [P] int32
    req: jax.Array          # [P, R] int32 (filter-path request; col PODS == 1)
    nonzero_req: jax.Array  # [P, R] int32 (scoring-path request)
    node_name: jax.Array    # [P] int32 target slot or -1 (pod.spec.nodeName)
    tol_key: jax.Array      # [P, L] int32 (0 = wildcard key)
    tol_val: jax.Array      # [P, L] int32
    tol_op: jax.Array       # [P, L] int32 (0 = empty slot)
    tol_effect: jax.Array   # [P, L] int32 (EFFECT_NONE = matches all effects)
    tol_prefer: jax.Array   # [P, L] bool: effect ∈ {"", PreferNoSchedule} (taint Score path)
    tolerates_unschedulable: jax.Array  # [P] bool (precompiled for NodeUnschedulable)
    # node selector + required affinity: AND(sel_idx) AND OR_t(AND_e(term))
    sel_idx: jax.Array      # [P, S] int32 expr slots, AND-combined (0 = true)
    term_idx: jax.Array     # [P, TERM, EXPR] int32 expr slots
    term_valid: jax.Array   # [P, TERM] bool (no valid terms ⇒ affinity passes)
    # preferred affinity (weights; invalid slots have weight 0)
    pref_idx: jax.Array     # [P, PTERM, EXPR] int32
    pref_weight: jax.Array  # [P, PTERM] int32
    port_ids: jax.Array     # [P, MP] int32 wanted-port vocab ids (0 = empty)
    image_ids: jax.Array    # [P, C] int32 container image vocab ids (0 = empty)
    num_containers: jax.Array  # [P] int32

    @property
    def capacity(self) -> int:
        return self.valid.shape[0]


@dataclasses.dataclass(frozen=True)
class Capacities:
    """Static padding sizes; one compiled executable per Capacities value."""

    nodes: int = 128          # N
    pods: int = 64            # P
    resources: int = 6        # R (4 fixed + scalar slots)
    label_keys: int = 16      # K
    taints: int = 4           # T per node
    tolerations: int = 4      # L per pod
    exprs: int = 64           # E per batch
    sel_exprs: int = 8        # S per pod
    terms: int = 4            # affinity terms per pod
    term_exprs: int = 4       # exprs per term
    pref_terms: int = 4       # preferred terms per pod
    value_words: int = 32     # Wv: value-vocab bitset words (per-key vocab ≤ 32*Wv)
    port_words: int = 16      # Wport
    ports: int = 8            # MP wanted ports per pod
    image_words: int = 16     # Wimg
    images: int = 1 + 16 * 32  # Vimg (vocab capacity = image_words*32, +0 slot)
    containers: int = 4       # C per pod

    def grow_nodes(self, n: int) -> "Capacities":
        cap = self.nodes
        while cap < n:
            cap *= 2
        return dataclasses.replace(self, nodes=cap)
