"""Device-side namespace-quota screen: the over-quota verdict column.

The host gate (framework/plugins/quota.py pre_filter, run at pop time)
is authoritative, but it judges usage as of the POP — on the pipelined
device path several batches are in flight at once, and on the wire path
peer replicas charge the same namespaces concurrently, so a winner can be
over its namespace's (possibly borrowed) headroom by the time its batch
lands. The screen here replays the batch's winners IN BATCH ORDER against
a per-namespace usage/limit tensor pair synced into DeviceState, flagging
every winner whose charge would cross the limit — an extra verdict column
riding the packed result block, zero extra dispatch, zero extra reads.

The commit side treats a flagged winner exactly like a gang-surrendered
member: reject + requeue + invalidate the adopted device row. Because
commit-time host revalidation (Reserve's atomic charge) stays
authoritative, tensor staleness can only REJECT a pod the host would have
admitted (it requeues and retries), never admit one the host would
reject — the screen cannot oversubscribe.

Charging runs as a ``lax.scan`` over the batch so two same-namespace
winners in one batch see each other's charges, mirroring the sequential
order the host commit applies them in (the host oracle twin below is the
parity contract, pinned by tests/test_quota_screen.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..api.types import QUOTA_DIM_ORDER

# the fixed dimension order of the [NS, Q] usage/limit tensors and the
# [P, Q] per-pod request block (api/types.py is the one source of truth,
# shared with the ledger's device_quota_table export)
QUOTA_DIMS = len(QUOTA_DIM_ORDER)

# per-pod quota verdict word (the packed block's trailing quota column):
# bit 0 = the pod was screened (its namespace has a row in the tensor
# pair), bit 1 = the charge fit under the synced limit. A screened winner
# with bit 1 clear is over quota on decision-time state — the commit
# rejects it before bind. Unscreened pods carry word 0.
QUOTA_SCREEN_BIT = 1
QUOTA_OK_BIT = 2

# unlisted-namespace sentinel for the limit tensor: never flags
QUOTA_NO_LIMIT = np.int32(2**31 - 1)


def quota_screen(node_idx: jax.Array, ns_idx: jax.Array, req: jax.Array,
                 used: jax.Array, limit: jax.Array) -> jax.Array:
    """[P] int32 verdict words for one batch. ``node_idx`` [P] (the core's
    placements: < 0 never charges), ``ns_idx`` [P] int32 row into the
    namespace axis (-1 = unquota'd/exempt), ``req`` [P, Q] int32 per-pod
    charge vectors, ``used``/``limit`` [NS, Q] int32 the synced tensors.
    Traced into the batch program (schedule_batch's jit) — no dispatch of
    its own."""
    ns_n = used.shape[0]

    def step(u, xs):
        nidx, ns, r = xs
        screened = ns >= 0
        safe = jnp.clip(ns, 0, ns_n - 1)
        fits = jnp.all(u[safe] + r <= limit[safe])
        # only a PLACED, screened, fitting pod charges the evolving usage
        charge = screened & (nidx >= 0) & fits
        u = u.at[safe].add(jnp.where(charge, r, jnp.zeros_like(r)))
        # unplaced pods read as ok: there is nothing to reject, and the
        # commit's verdict ladder only consults the word for winners
        word = jnp.where(
            screened,
            np.int32(QUOTA_SCREEN_BIT)
            | jnp.where(fits | (nidx < 0), np.int32(QUOTA_OK_BIT), 0),
            0).astype(jnp.int32)
        return u, word

    _u, words = lax.scan(step, used, (node_idx, ns_idx, req))
    return words


def quota_screen_host(node_idx, ns_idx, req, used, limit) -> np.ndarray:
    """Host oracle twin of ``quota_screen`` (numpy, same walk): the parity
    contract the oracle path and the tests judge the device column by."""
    used = np.array(used, dtype=np.int64, copy=True)
    limit = np.asarray(limit, dtype=np.int64)
    p = len(node_idx)
    words = np.zeros(p, np.int32)
    for i in range(p):
        ns = int(ns_idx[i])
        if ns < 0:
            continue
        r = np.asarray(req[i], dtype=np.int64)
        fits = bool(np.all(used[ns] + r <= limit[ns]))
        word = QUOTA_SCREEN_BIT
        if fits or int(node_idx[i]) < 0:
            word |= QUOTA_OK_BIT
        if fits and int(node_idx[i]) >= 0:
            used[ns] += r
        words[i] = word
    return words


def quota_request_row(pod) -> np.ndarray:
    """[Q] int32 charge vector for one pod, in QUOTA_DIM_ORDER — the
    encode-side twin of the ledger's pod_quota_request."""
    from ..framework.plugins.quota import pod_quota_request

    req = pod_quota_request(pod)
    return np.array([min(int(req.get(d, 0)), int(QUOTA_NO_LIMIT))
                     for d in QUOTA_DIM_ORDER], np.int32)


def build_quota_batch_args(pods, device, table: Optional[dict] = None,
                           pad_to: Optional[int] = None):
    """(ns_idx [P] int32, req [P, Q] int32) for one batch against
    ``device``'s namespace-quota table, or (None, None) when no pod in the
    batch belongs to a screened namespace — the common case, whose batch
    program is unchanged. ``pad_to`` pads the pod axis to the batch's
    bucketed capacity (padding rows are exempt: ns_idx -1). ``table``
    (ns -> (used, limit) rows) is applied to the device first when given,
    so the screen judges the freshest host ledger view. Shared by the
    in-process dispatch and the wire server so both transports screen
    identically."""
    if table is not None:
        device.set_ns_quota(table)
    if not device.nsq_slots:
        return None, None
    p = max(pad_to or 0, len(pods))
    ns_idx = np.full(p, -1, np.int32)
    req = np.zeros((p, QUOTA_DIMS), np.int32)
    any_screened = False
    for i, pod in enumerate(pods):
        slot = device.nsq_slots.get(pod.meta.namespace)
        if slot is None:
            continue
        ns_idx[i] = slot
        req[i] = quota_request_row(pod)
        any_screened = True
    if not any_screened:
        return None, None
    return ns_idx, req
