"""Deterministic seeded tie-break, shared bit-for-bit by the Python oracle
and the device batch programs (SURVEY §8; the reference's reservoir uniform
tie-break is pkg/scheduler/schedule_one.go:709-730).

The reference breaks score ties with an unseeded uniform draw, which makes
exact-replay parity between two schedulers unmeasurable. Here both paths
derive the SAME per-(pod, attempt, node) 32-bit key:

    key(p, n) = mix32(pod_seed(pod_key, attempts) ^ fnv1a32(node_name))

and pick the tied node with the LARGEST key — a uniform choice over the tie
set (mix32 is a bijective avalanche permutation), but reproducible. The
device adds the same key, scaled into [0, 0.5), onto each node's score as
jitter: for exactly-tied scores argmax-by-jitter == max-by-key, so the
batched path and the oracle land the same node.

This also replaces the jax.random threefry draw of a [P, N] uniform table —
~40 u32 rounds per element and the single most expensive block of the batch
program on CPU — with an 8-pass integer hash. Node keys hash the node NAME
(not the slot), so values are identical across shard layouts and topology
modes (sharded-vs-single-device parity is automatic).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)
_GOLDEN = np.uint32(0x9E3779B9)
_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)

# jitter strictly below 0.5: integer plugin scores differ by ≥ 1, so the
# tie-break can never flip a non-tie (same bound the old uniform draw used)
JITTER_SCALE = np.float32(0.5 / (1 << 24))


def fnv1a32(s: str) -> np.uint32:
    """FNV-1a over the UTF-8 bytes — stable across processes (unlike hash())."""
    h = int(_FNV_OFFSET)
    prime = int(_FNV_PRIME)
    for b in s.encode("utf-8"):
        h = ((h ^ b) * prime) & 0xFFFFFFFF
    return np.uint32(h)


def mix32(x):
    """Murmur3 finalizer (avalanche bijection) — scalar or ndarray. Scalars
    run in masked Python ints (numpy warns on intended u32 wraparound)."""
    if np.ndim(x) == 0:
        v = int(x) & 0xFFFFFFFF
        v ^= v >> 16
        v = (v * int(_M1)) & 0xFFFFFFFF
        v ^= v >> 13
        v = (v * int(_M2)) & 0xFFFFFFFF
        v ^= v >> 16
        return np.uint32(v)
    x = x.astype(np.uint32)
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint32(16))
        x = x * _M1
        x = x ^ (x >> np.uint32(13))
        x = x * _M2
        x = x ^ (x >> np.uint32(16))
    return x


def pod_seed(pod_key: str, attempts: int = 0) -> np.uint32:
    """Per-(pod, scheduling attempt) seed: fresh tie-break draw each retry,
    exactly reproducible by anyone holding (pod key, attempt count)."""
    return mix32(int(fnv1a32(pod_key)) ^ ((attempts * int(_GOLDEN)) & 0xFFFFFFFF))


def name_hash(node_name: str) -> np.uint32:
    return fnv1a32(node_name)


def tie_key(seed: np.uint32, node_name_hash: np.uint32) -> int:
    """Oracle-side scalar: the tied node with the largest key wins."""
    return int(mix32(np.uint32(seed) ^ np.uint32(node_name_hash)))


def jitter_table(tie_seed, node_name_hash):
    """Device-side [P, N] float32 jitter in [0, 0.5): monotone in tie_key, so
    score-tied argmax == oracle's max-by-key. jnp in, jnp out.

    Precision bound: only the top 24 hash bits survive the float32 mantissa,
    and adding jitter onto a score total of magnitude ~10² leaves ~14-16
    effective bits — among a K-node pure-tie set the device argmax can
    disagree with the oracle's full-32-bit max with probability ≈ K/2¹⁶
    (≈ 7% at K = 5000, < 0.5% at K ≤ 256). That bounds exact-replay
    agreement below 100% on degenerate all-identical clusters; acceptable
    against the ≥ 90% target (SURVEY §8), and the argmax-equivalence metric
    is unaffected (any max-scoring node is equivalent)."""
    import jax.numpy as jnp

    x = tie_seed[:, None].astype(jnp.uint32) ^ node_name_hash[None, :].astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(_M1)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(_M2)
    x = x ^ (x >> 16)
    return (x >> 8).astype(jnp.float32) * JITTER_SCALE


def seeds_for(qps) -> Optional[np.ndarray]:
    """[len(qps)] uint32 seed vector from QueuedPodInfos (key + attempts)."""
    return np.asarray([pod_seed(qp.pod.key(), qp.attempts) for qp in qps],
                      np.uint32)
