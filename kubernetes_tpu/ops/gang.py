"""Batched gang placement: greedy all-or-nothing assignment on device.

The pods axis the reference never batches (SURVEY §2 P8): a gang is a set
of pods that must place together or not at all. This kernel takes the
per-pod feasibility masks the batch program already computed (a node is
feasible for a member iff its first-fail id is 0 at that pod's decision
time) and greedily assigns every member of a gang to a DISTINCT feasible
node in one vmapped pass — the multi-host TPU contract, one worker per
host. The result is either a full assignment or a whole-gang miss; no
partial assignment ever escapes the kernel, which is exactly the property
the host commit needs to never strand a half-placed gang.

Greedy order: members in batch (= queue) order; each member takes its
preferred node (the batch program's own choice) when it is feasible and
untaken, else the first feasible untaken slot. With the program's choices
as preferences, a gang the program fully placed on distinct nodes
reproduces those placements bit for bit — the kernel only "repairs" when
preferences collide, and reports infeasibility when no distinct cover
exists.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def gang_assign(feasible: jax.Array, prefer: jax.Array,
                active: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One gang: ``feasible`` [M, N] bool, ``prefer`` [M] int32 (-1 = no
    preference), ``active`` [M] bool (False = padding member). Returns
    (idx [M] int32, ok scalar bool); idx is all -1 unless every active
    member got a distinct feasible node (all-or-nothing)."""
    n = feasible.shape[1]

    def step(taken, xs):
        feas, pref, act = xs
        avail = feas & ~taken
        pref_c = jnp.clip(pref, 0, n - 1)
        has_pref = (pref >= 0) & avail[pref_c]
        # argmax over bool picks the FIRST available slot — deterministic,
        # and irrelevant to parity (preferences win whenever they can)
        fallback = jnp.argmax(avail).astype(jnp.int32)
        any_avail = jnp.any(avail)
        choice = jnp.where(has_pref, pref_c.astype(jnp.int32),
                           jnp.where(any_avail, fallback, jnp.int32(-1)))
        choice = jnp.where(act, choice, jnp.int32(-1))
        taken = jnp.where(choice >= 0,
                          taken.at[jnp.clip(choice, 0, n - 1)].set(True),
                          taken)
        return taken, choice

    taken0 = jnp.zeros((n,), bool)
    _taken, idx = lax.scan(step, taken0, (feasible, prefer, active))
    ok = jnp.all((idx >= 0) | ~active)
    return jnp.where(ok, idx, jnp.int32(-1)), ok


# [G, M, N] feasibility, [G, M] preferences, [G, M] active
# -> ([G, M] assignment, [G] ok): every gang in the batch in one pass
assign_gangs = jax.vmap(gang_assign)


def gang_assign_host(feasible, prefer, active) -> Tuple[list, bool]:
    """Host oracle of ``gang_assign`` (parity tests): same greedy walk in
    plain Python over one gang's numpy masks."""
    taken = set()
    out = []
    for m in range(len(feasible)):
        if not active[m]:
            out.append(-1)
            continue
        pref = int(prefer[m])
        if pref >= 0 and bool(feasible[m][pref]) and pref not in taken:
            choice = pref
        else:
            choice = -1
            for slot in range(len(feasible[m])):
                if bool(feasible[m][slot]) and slot not in taken:
                    choice = slot
                    break
        if choice < 0:
            return [-1] * len(feasible), False
        taken.add(choice)
        out.append(choice)
    return out, True
