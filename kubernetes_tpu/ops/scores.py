"""Batched score kernels: [P, N] float32 per plugin + normalization.

Raw-score and NormalizeScore formulas per SURVEY.md §8.  The reference
computes int64 scores; these kernels use float32 (TPU-native) with floor()
where the reference floor-divides — parity tests allow ±1 on score values
(float32 mantissa vs int64 exactness; the winner-selection impact is confined
to exact ties, which selectHost breaks randomly anyway).

Normalization runs over the *feasible* node set only (prioritizeNodes scores
only filtered nodes, schedule_one.go:605).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from . import schema
from .filters import _taint_tolerated, eval_exprs
from .schema import ExprTable, NodeTensors, PodBatch

MAX_NODE_SCORE = 100.0

# default scoring resources: cpu + memory, weight 1 each (resource cols)
DEFAULT_SCORE_COLS: Tuple[Tuple[int, float], ...] = ((schema.COL_CPU, 1.0), (schema.COL_MEM, 1.0))


def _requested_with_pod(pb: PodBatch, nt: NodeTensors, col: int) -> jax.Array:
    """node NonZeroRequested + incoming pod's nonzero request → [P, N] f32."""
    return (
        nt.nonzero_requested[None, :, col].astype(jnp.float32)
        + pb.nonzero_req[:, None, col].astype(jnp.float32)
    )


def score_least_allocated(pb: PodBatch, nt: NodeTensors, cols=DEFAULT_SCORE_COLS) -> jax.Array:
    """least_allocated.go:29: Σ w·floor((cap−req)·100/cap) / Σ w (0 when
    req>cap or cap=0)."""
    num = 0.0
    den = 0.0
    for col, w in cols:
        cap = nt.allocatable[None, :, col].astype(jnp.float32)
        req = _requested_with_pod(pb, nt, col)
        s = jnp.floor((cap - req) * MAX_NODE_SCORE / jnp.maximum(cap, 1.0))
        s = jnp.where((cap == 0) | (req > cap), 0.0, s)
        num = num + w * s
        den += w
    return jnp.floor(num / den)


def score_most_allocated(pb: PodBatch, nt: NodeTensors, cols=DEFAULT_SCORE_COLS) -> jax.Array:
    num = 0.0
    den = 0.0
    for col, w in cols:
        cap = nt.allocatable[None, :, col].astype(jnp.float32)
        req = _requested_with_pod(pb, nt, col)
        s = jnp.floor(req * MAX_NODE_SCORE / jnp.maximum(cap, 1.0))
        s = jnp.where((cap == 0) | (req > cap), 0.0, s)
        num = num + w * s
        den += w
    return jnp.floor(num / den)


def score_balanced_allocation(pb: PodBatch, nt: NodeTensors, cols=DEFAULT_SCORE_COLS) -> jax.Array:
    """balanced_allocation.go: (1 − std(fractions)) · 100, truncated."""
    fracs = []
    for col, _w in cols:
        cap = nt.allocatable[None, :, col].astype(jnp.float32)
        req = _requested_with_pod(pb, nt, col)
        f = jnp.where(cap == 0, 1.0, jnp.minimum(1.0, req / jnp.maximum(cap, 1.0)))
        fracs.append(f)
    f = jnp.stack(fracs, axis=-1)                        # [P, N, C]
    if f.shape[-1] == 2:
        std = jnp.abs(f[..., 0] - f[..., 1]) / 2.0
    else:
        mean = jnp.mean(f, axis=-1, keepdims=True)
        std = jnp.sqrt(jnp.mean((f - mean) ** 2, axis=-1))
    return jnp.floor((1.0 - std) * MAX_NODE_SCORE)


def score_taint_toleration(pb: PodBatch, nt: NodeTensors) -> jax.Array:
    """Raw score: count of PreferNoSchedule taints NOT tolerated by the pod's
    {empty, PreferNoSchedule}-effect tolerations (taint_toleration.go:147)."""
    tolerated = _taint_tolerated(pb, nt, pb.tol_prefer)  # [P, N, T]
    prefer = (nt.taint_effect == schema.EFFECT_PREFER_NO_SCHEDULE)[None]
    bad = prefer & (nt.taint_key > 0)[None] & ~tolerated
    return jnp.sum(bad, axis=-1).astype(jnp.float32)


def score_node_affinity(pb: PodBatch, et: ExprTable, nt: NodeTensors, expr_match=None) -> jax.Array:
    """Σ weights of matching preferred terms (node_affinity.go:260)."""
    if expr_match is None:
        expr_match = eval_exprs(et, nt)
    per_term = jnp.all(expr_match[pb.pref_idx], axis=2)  # [P, PT, N]
    w = pb.pref_weight[:, :, None].astype(jnp.float32)
    return jnp.sum(per_term * w, axis=1)


_MB = 1024.0 * 1024.0
_MIN_THRESHOLD = 23.0 * _MB
_MAX_CONTAINER_THRESHOLD = 1000.0 * _MB


def score_image_locality(pb: PodBatch, nt: NodeTensors, total_nodes=None) -> jax.Array:
    """imagelocality: Σ_present size·numNodes/totalNodes, clamped+scaled.
    ``total_nodes`` is injectable so the sharded path can psum it globally."""
    ids = pb.image_ids                                   # [P, C]
    word = nt.image_bits[:, ids >> 5]                    # [N, P, C]
    present = ((word >> (ids & 31).astype(jnp.uint32)) & 1).astype(jnp.float32)
    present = jnp.transpose(present, (1, 0, 2))          # [P, N, C]
    if total_nodes is None:
        total_nodes = jnp.maximum(jnp.sum(nt.valid), 1)
    total_nodes = jnp.asarray(total_nodes, jnp.float32)
    spread = nt.image_num_nodes[ids].astype(jnp.float32) / total_nodes  # [P, C]
    contrib = jnp.floor(nt.image_sizes[ids].astype(jnp.float32) * spread)
    sum_scores = jnp.sum(present * contrib[:, None, :], axis=-1)        # [P, N]
    max_threshold = _MAX_CONTAINER_THRESHOLD * jnp.maximum(pb.num_containers, 1)[:, None].astype(jnp.float32)
    clamped = jnp.clip(sum_scores, _MIN_THRESHOLD, max_threshold)
    return jnp.floor(MAX_NODE_SCORE * (clamped - _MIN_THRESHOLD) / (max_threshold - _MIN_THRESHOLD))


def normalize_default(raw: jax.Array, feasible: jax.Array, reverse: bool) -> jax.Array:
    """helper.DefaultNormalizeScore over the feasible set per pod:
    scale to [0,100], flip when reverse; all-zero max ⇒ 100s when reversed."""
    masked = jnp.where(feasible, raw, 0.0)
    max_score = jnp.max(masked, axis=1, keepdims=True)
    scaled = jnp.floor(raw * MAX_NODE_SCORE / jnp.maximum(max_score, 1.0))
    if reverse:
        out = jnp.where(max_score == 0, MAX_NODE_SCORE, MAX_NODE_SCORE - scaled)
    else:
        out = jnp.where(max_score == 0, 0.0, scaled)
    return out
