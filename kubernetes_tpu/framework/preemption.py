"""Preemption engine — the generic Evaluator behind the DefaultPreemption
PostFilter plugin.

Analog of pkg/scheduler/framework/preemption/preemption.go:
  * Preempt (:138): eligibility check → find candidates (parallel dry-runs)
    → select one node (5-criteria lexicographic) → prepare (delete victims,
    clear lower nominations) → return the nominated node name.
  * DryRunPreemption (:546): per candidate node, clone NodeInfo+CycleState,
    remove lower-priority victims (via the PreFilter RemovePod extensions),
    check the pod fits, then reprieve victims highest-priority-first —
    PDB-non-violating pods get reprieved before PDB-violating ones
    (defaultpreemption/default_preemption.go:226 selectVictimsOnNode).
  * pickOneNodeForPreemption (:397): fewest PDB violations → lowest max
    victim priority → smallest priority sum → fewest victims → earliest
    highest-priority-victim start time → first in list.
  * Candidate count limit: minCandidateNodesPercentage (10%) /
    minCandidateNodesAbsolute (100), with a rotating offset for fairness
    (:172 GetOffsetAndNumCandidates).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

from ..api.types import Pod, PodDisruptionBudget
from . import interface as fw
from .interface import CycleState, Status
from .types import Diagnosis, NodeInfo

POLICY_NEVER = "Never"


class Candidate:
    __slots__ = ("node_name", "victims", "num_pdb_violations")

    def __init__(self, node_name: str, victims: List[Pod], num_pdb_violations: int):
        self.node_name = node_name
        self.victims = victims
        self.num_pdb_violations = num_pdb_violations


def more_important(a: Pod, b: Pod) -> bool:
    """util.MoreImportantPod: higher priority, then earlier start time."""
    if a.spec.priority != b.spec.priority:
        return a.spec.priority > b.spec.priority
    return a.status.start_time < b.status.start_time


def pdbs_for_pod(pod: Pod, pdbs: Sequence[PodDisruptionBudget]) -> List[PodDisruptionBudget]:
    return [
        p
        for p in pdbs
        if p.meta.namespace == pod.meta.namespace
        and p.selector is not None
        and p.selector.matches(pod.meta.labels)
    ]


class Evaluator:
    """One preemption attempt per unschedulable pod (Evaluator, :117)."""

    def __init__(
        self,
        plugin_name: str,
        framework,
        pdb_lister,
        state: CycleState,
        min_candidate_nodes_percentage: int = 10,
        min_candidate_nodes_absolute: int = 100,
        rng: Optional[random.Random] = None,
        screen_fn=None,
        preferred_node: Optional[str] = None,
    ):
        self.plugin_name = plugin_name
        self.fwk = framework
        self.pdb_lister = pdb_lister
        self.state = state
        self.min_pct = min_candidate_nodes_percentage
        self.min_abs = min_candidate_nodes_absolute
        self.rng = rng or random.Random(0)
        self.prescreen_skips = 0  # candidates rejected by the max-free bound
        # device-computed hints (ops/preempt.py): screen_fn(name) -> bool
        # replaces the host _max_free_prescreen; preferred_node is the
        # device's top-ranked candidate, verified EXACTLY before use
        self.screen_fn = screen_fn
        self.preferred_node = preferred_node

    # ------------------------------------------------------------- top level

    def preempt(self, pod: Pod, status_map: Dict[str, Status], node_infos: List[NodeInfo]) -> Tuple[Optional[str], Status]:
        """(:138) returns (nominated node name, status)."""
        by_name = {ni.node.meta.name: ni for ni in node_infos if ni.node is not None}

        if not self._pod_eligible_to_preempt_others(pod, by_name):
            return None, Status.unschedulable("preemption is not helpful for scheduling")

        # device-proposed candidate: run the EXACT victim selection on just
        # that node; only on verification failure pay the full candidate scan
        # ("device proposes, host verifies" — VERDICT r2 next-step 7)
        if self.preferred_node is not None and self.preferred_node in by_name:
            pdbs = list(self.pdb_lister() if callable(self.pdb_lister) else self.pdb_lister)
            victims, n_viol, ok = self.select_victims_on_node(
                pod, by_name[self.preferred_node], pdbs)
            if ok:
                cand = Candidate(self.preferred_node, victims, n_viol)
                cands = self._call_extenders(pod, [cand])
                if cands:
                    status = self.prepare_candidate(cands[0], pod)
                    if not status.is_success():
                        return None, status
                    return cands[0].node_name, fw.OK

        candidates, diagnosis = self.find_candidates(pod, status_map, node_infos)
        if not candidates:
            # mirror FitError-style reporting for observability (:205)
            return None, Status.unschedulable(
                "preemption: 0/{} nodes are available".format(len(node_infos)),
                *sorted(diagnosis),
            )

        best = self.select_candidate(candidates)
        if best is None:
            return None, Status.unschedulable("no candidate node for preemption")

        status = self.prepare_candidate(best, pod)
        if not status.is_success():
            return None, status
        return best.node_name, fw.OK

    # ------------------------------------------------------------- eligibility

    def _pod_eligible_to_preempt_others(self, pod: Pod, by_name: Dict[str, NodeInfo]) -> bool:
        """PodEligibleToPreemptOthers (:319): Never-policy pods can't preempt;
        a pod already nominated somewhere waits while a lower-priority victim
        on that node is still terminating."""
        if pod.spec.preemption_policy == POLICY_NEVER:
            return False
        nominated = pod.status.nominated_node_name
        if nominated and nominated in by_name:
            for p in by_name[nominated].pods:
                if p.meta.deletion_timestamp > 0 and p.spec.priority < pod.spec.priority:
                    return False
        return True

    # ------------------------------------------------------------- candidates

    def _nodes_where_preemption_might_help(
        self, node_infos: List[NodeInfo], status_map: Dict[str, Status]
    ) -> List[NodeInfo]:
        """(:363) skip nodes whose filter status was UnschedulableAndUnresolvable."""
        out = []
        for ni in node_infos:
            if ni.node is None:
                continue
            st = status_map.get(ni.node.meta.name)
            if st is not None and st.code == fw.UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue
            out.append(ni)
        return out

    def _offset_and_num_candidates(self, num_nodes: int) -> Tuple[int, int]:
        """(:172) rotate a random offset; candidate count = max(pct·N, abs)."""
        n = num_nodes * self.min_pct // 100
        if n < self.min_abs:
            n = self.min_abs
        if n > num_nodes:
            n = num_nodes
        return self.rng.randrange(num_nodes) if num_nodes else 0, n

    @staticmethod
    def _max_free_prescreen(pod: Pod, potential: List[NodeInfo]) -> List[bool]:
        """Vectorized candidate pre-screen (the batched-tensor analog of
        DryRunPreemption's first fit check): a node where the pod does not
        fit even with EVERY lower-priority pod removed can never survive the
        full dry run — pod removal cannot free more than their requests.
        Exact for the resource dimension, conservative overall."""
        from ..api import resource as resource_api

        preq = pod.resource_request()
        p_cpu = preq.get(resource_api.CPU, 0)
        p_mem = preq.get(resource_api.MEMORY, 0)
        p_eph = preq.get(resource_api.EPHEMERAL_STORAGE, 0)
        out = []
        for ni in potential:
            free_cpu = ni.allocatable.milli_cpu - ni.requested.milli_cpu
            free_mem = ni.allocatable.memory - ni.requested.memory
            free_eph = ni.allocatable.ephemeral_storage - ni.requested.ephemeral_storage
            n_lower = 0
            for p in ni.pods:
                if p.spec.priority < pod.spec.priority:
                    r = p.resource_request()
                    free_cpu += r.get(resource_api.CPU, 0)
                    free_mem += r.get(resource_api.MEMORY, 0)
                    free_eph += r.get(resource_api.EPHEMERAL_STORAGE, 0)
                    n_lower += 1
            pods_free = ni.allocatable.allowed_pod_number - len(ni.pods) + n_lower
            out.append(
                p_cpu <= free_cpu
                and p_mem <= free_mem
                and p_eph <= free_eph
                and pods_free >= 1
            )
        return out

    def find_candidates(
        self, pod: Pod, status_map: Dict[str, Status], node_infos: List[NodeInfo]
    ) -> Tuple[List[Candidate], List[str]]:
        potential = self._nodes_where_preemption_might_help(node_infos, status_map)
        if not potential:
            return [], ["no node is eligible for preemption"]
        offset, num = self._offset_and_num_candidates(len(potential))
        pdbs = list(self.pdb_lister() if callable(self.pdb_lister) else self.pdb_lister)
        if self.screen_fn is not None:
            feasible_bound = [self.screen_fn(ni.node.meta.name) for ni in potential]
        else:
            feasible_bound = self._max_free_prescreen(pod, potential)

        candidates: List[Candidate] = []
        diagnosis: List[str] = []
        for i in range(len(potential)):
            k = (offset + i) % len(potential)
            ni = potential[k]
            if not feasible_bound[k]:
                self.prescreen_skips += 1
                continue
            victims, n_viol, ok = self.select_victims_on_node(pod, ni, pdbs)
            if ok:
                candidates.append(Candidate(ni.node.meta.name, victims, n_viol))
                if len(candidates) >= num:
                    break
            else:
                diagnosis.append(f"{ni.node.meta.name}: preemption would not make pod schedulable")
        candidates = self._call_extenders(pod, candidates)
        return candidates, diagnosis

    def _call_extenders(self, pod: Pod, candidates: List[Candidate]) -> List[Candidate]:
        """(:241) preemption-aware extenders may veto/trim the victim map;
        ignorable extender errors drop the extender."""
        extenders = [
            e for e in self.fwk.handle_ctx.get("extenders", []) if e.supports_preemption() and e.is_interested(pod)
        ]
        if not extenders or not candidates:
            return candidates
        victims_by_node = {c.node_name: list(c.victims) for c in candidates}
        by_node = {c.node_name: c for c in candidates}
        for ext in extenders:
            try:
                victims_by_node = ext.process_preemption(pod, victims_by_node, None)
            except Exception:  # noqa: BLE001
                if ext.is_ignorable():
                    continue
                return []
        return [
            Candidate(n, v, by_node[n].num_pdb_violations)
            for n, v in victims_by_node.items()
            if n in by_node
        ]

    # ------------------------------------------------------------- dry run

    def select_victims_on_node(
        self, pod: Pod, node_info: NodeInfo, pdbs: Sequence[PodDisruptionBudget]
    ) -> Tuple[List[Pod], int, bool]:
        """selectVictimsOnNode (defaultpreemption/default_preemption.go:226).

        Returns (victims sorted most-important-first, num PDB violations, ok).
        """
        ni = node_info.clone()
        state = self.state.clone()

        remove = [p for p in ni.pods if p.spec.priority < pod.spec.priority]
        if not remove and not self._fits(state, pod, ni):
            return [], 0, False
        for victim in list(remove):
            ni.remove_pod(victim)
            self.fwk.run_remove_pod_extensions(state, pod, victim, ni)
        if not self._fits(state, pod, ni):
            return [], 0, False

        # filterPodsWithPDBViolation (defaultpreemption): a pod violates iff
        # any matching PDB has no remaining disruption budget — budgets are
        # the controller-maintained live disruptionsAllowed, consumed as
        # earlier victims claim them
        violating, non_violating = [], []
        consumed: Dict[str, int] = {}
        for p in remove:
            matching = pdbs_for_pod(p, pdbs)
            is_viol = any(
                pdb.disruptions_allowed - consumed.get(pdb.meta.key(), 0) <= 0
                for pdb in matching
            )
            if not is_viol:
                for pdb in matching:
                    k = pdb.meta.key()
                    consumed[k] = consumed.get(k, 0) + 1
            (violating if is_viol else non_violating).append(p)
        violating.sort(key=lambda p: (-p.spec.priority, p.status.start_time))
        non_violating.sort(key=lambda p: (-p.spec.priority, p.status.start_time))

        victims: List[Pod] = []
        num_violating = 0

        def reprieve(p: Pod) -> bool:
            ni.add_pod(p)
            self.fwk.run_add_pod_extensions(state, pod, p, ni)
            if self._fits(state, pod, ni):
                return True
            ni.remove_pod(p)
            self.fwk.run_remove_pod_extensions(state, pod, p, ni)
            victims.append(p)
            return False

        for p in violating:
            if not reprieve(p):
                num_violating += 1
        for p in non_violating:
            reprieve(p)

        victims.sort(key=lambda p: (-p.spec.priority, p.status.start_time))
        return victims, num_violating, True

    def _fits(self, state: CycleState, pod: Pod, ni: NodeInfo) -> bool:
        return self.fwk.run_filter_plugins_with_nominated_pods(state, pod, ni).is_success()

    # ------------------------------------------------------------- selection

    def select_candidate(self, candidates: List[Candidate]) -> Optional[Candidate]:
        """pickOneNodeForPreemption (:397), lexicographic on 5 criteria."""
        if not candidates:
            return None
        if len(candidates) == 1:
            return candidates[0]

        def keys(c: Candidate):
            if not c.victims:
                # a no-victim candidate wins everything (:404)
                return (0, -(1 << 62), -(1 << 62), 0, float("-inf"))
            highest = max(p.spec.priority for p in c.victims)
            total = sum(p.spec.priority for p in c.victims)
            # earliest start time of the highest-priority victim (:466)
            hp_start = min(
                p.status.start_time for p in c.victims if p.spec.priority == highest
            )
            # later start = more recently started = preferred victim set
            return (c.num_pdb_violations, highest, total, len(c.victims), -hp_start)

        return min(candidates, key=keys)

    # ------------------------------------------------------------- prepare

    def prepare_candidate(self, c: Candidate, pod: Pod) -> Status:
        """(:331) delete victims via the API; clear nominations of lower-
        priority pods nominated to this node (they must re-evaluate)."""
        client = self.fwk.handle_ctx.get("client")
        metrics = self.fwk.handle_ctx.get("metrics")
        if metrics is not None and c.victims:
            metrics.preemption_victims.observe(len(c.victims))
        for victim in c.victims:
            if victim.meta.deletion_timestamp > 0:
                continue  # already terminating
            try:
                client.delete_pod(victim.key())
            except Exception as e:  # noqa: BLE001 — victim already gone is fine
                if "NotFound" not in type(e).__name__:
                    return Status.error(f"deleting victim {victim.key()}: {e}")
        nominator = self.fwk.nominator
        for p in list(nominator.nominated_pods_for_node(c.node_name)):
            if p.spec.priority < pod.spec.priority:
                nominator.delete_nominated_pod_if_exists(p)
                try:
                    client.update_pod_nominated_node(p.key(), "")
                except Exception:  # noqa: BLE001 — pod vanished meanwhile
                    pass
        return fw.OK
