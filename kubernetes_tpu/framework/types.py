"""Framework data types: Resource, NodeInfo, QueuedPodInfo, ClusterEvent.

Analog of pkg/scheduler/framework/types.go — the de-facto snapshot row schema
the tensor encoder (ops/encode.py) flattens onto the device.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api import resource as resource_api
from ..api.types import ContainerPort, Node, Pod

# ---------------------------------------------------------------------------
# Resource (framework/types.go:414 Resource)


class Resource:
    """Canonical-int resource vector: milli_cpu, memory(KiB), ephemeral(MiB),
    allowed_pod_number, plus scalar resources by name."""

    __slots__ = ("milli_cpu", "memory", "ephemeral_storage", "allowed_pod_number", "scalars")

    def __init__(self):
        self.milli_cpu = 0
        self.memory = 0
        self.ephemeral_storage = 0
        self.allowed_pod_number = 0
        self.scalars: Dict[str, int] = {}

    @classmethod
    def from_map(cls, m: Dict[str, int]) -> "Resource":
        r = cls()
        for name, v in m.items():
            r.set(name, v)
        return r

    def set(self, name: str, v: int) -> None:
        if name == resource_api.CPU:
            self.milli_cpu = v
        elif name == resource_api.MEMORY:
            self.memory = v
        elif name == resource_api.EPHEMERAL_STORAGE:
            self.ephemeral_storage = v
        elif name == resource_api.PODS:
            self.allowed_pod_number = v
        else:
            self.scalars[name] = v

    def get(self, name: str) -> int:
        if name == resource_api.CPU:
            return self.milli_cpu
        if name == resource_api.MEMORY:
            return self.memory
        if name == resource_api.EPHEMERAL_STORAGE:
            return self.ephemeral_storage
        if name == resource_api.PODS:
            return self.allowed_pod_number
        return self.scalars.get(name, 0)

    def add(self, m: Dict[str, int], sign: int = 1) -> None:
        for name, v in m.items():
            self.set(name, self.get(name) + sign * v)

    def clone(self) -> "Resource":
        r = Resource()
        r.milli_cpu = self.milli_cpu
        r.memory = self.memory
        r.ephemeral_storage = self.ephemeral_storage
        r.allowed_pod_number = self.allowed_pod_number
        r.scalars = dict(self.scalars)
        return r

    def as_map(self) -> Dict[str, int]:
        m = {
            resource_api.CPU: self.milli_cpu,
            resource_api.MEMORY: self.memory,
            resource_api.EPHEMERAL_STORAGE: self.ephemeral_storage,
            resource_api.PODS: self.allowed_pod_number,
        }
        m.update(self.scalars)
        return m


def nonzero_request(req: Dict[str, int]) -> Dict[str, int]:
    """GetNonzeroRequests (pkg/scheduler/util): scoring-path request with
    nominal defaults for cpu/memory when unset."""
    out = dict(req)
    if out.get(resource_api.CPU, 0) == 0:
        out[resource_api.CPU] = resource_api.DEFAULT_MILLI_CPU_REQUEST
    if out.get(resource_api.MEMORY, 0) == 0:
        out[resource_api.MEMORY] = resource_api.DEFAULT_MEMORY_REQUEST_KIB
    return out


# ---------------------------------------------------------------------------
# NodeInfo (framework/types.go:363)

_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


class NodeInfo:
    """Aggregated per-node scheduling state; monotonic ``generation`` drives
    both the host incremental snapshot (cache.go:198 UpdateSnapshot) and the
    device delta uploads."""

    def __init__(self, node: Optional[Node] = None):
        self.node: Optional[Node] = node
        self.pods: List[Pod] = []
        self.pods_with_affinity: List[Pod] = []
        self.pods_with_required_anti_affinity: List[Pod] = []
        self.used_ports: Set[Tuple[str, str, int]] = set()  # (hostIP, proto, port)
        self.requested = Resource()
        self.non_zero_requested = Resource()
        self.allocatable = Resource()
        # priority-bucketed request sums (incl. a synthetic "pods" count per
        # bucket): incremental source for the device class_req rows (batched
        # preemption screen) so encode never rescans ni.pods
        self.prio_requested: Dict[int, Dict[str, int]] = {}
        self.pvc_ref_counts: Dict[str, int] = {}
        self.image_states: Dict[str, int] = {}  # image name -> size bytes
        self.generation = next_generation()
        if node is not None:
            self.allocatable = Resource.from_map(node.allocatable_canonical())
            for img in node.status.images:
                for name in img.names:
                    self.image_states[name] = img.size_bytes

    def set_node(self, node: Node) -> None:
        self.node = node
        self.allocatable = Resource.from_map(node.allocatable_canonical())
        self.image_states = {}
        for img in node.status.images:
            for name in img.names:
                self.image_states[name] = img.size_bytes
        self.generation = next_generation()

    @staticmethod
    def _has_affinity(pod: Pod) -> bool:
        a = pod.spec.affinity
        return a is not None and (a.pod_affinity is not None or a.pod_anti_affinity is not None)

    @staticmethod
    def _has_required_anti_affinity(pod: Pod) -> bool:
        a = pod.spec.affinity
        return a is not None and a.pod_anti_affinity is not None and bool(a.pod_anti_affinity.required)

    def add_pod(self, pod: Pod) -> None:
        self.pods.append(pod)
        if self._has_affinity(pod):
            self.pods_with_affinity.append(pod)
        if self._has_required_anti_affinity(pod):
            self.pods_with_required_anti_affinity.append(pod)
        req = pod.resource_request()
        self.requested.add(req)
        self.requested.allowed_pod_number = 0  # pods tracked via len(self.pods)
        self.non_zero_requested.add(nonzero_request(req))
        self.non_zero_requested.allowed_pod_number = 0
        bucket = self.prio_requested.setdefault(pod.spec.priority, {})
        for r, v in req.items():
            if r != resource_api.PODS:  # pods tracked as the +1 below
                bucket[r] = bucket.get(r, 0) + v
        bucket[resource_api.PODS] = bucket.get(resource_api.PODS, 0) + 1
        for p in pod.host_ports():
            self.used_ports.add((p.host_ip or "0.0.0.0", p.protocol, p.host_port))
        for claim in pod.spec.volumes:
            key = f"{pod.meta.namespace}/{claim}"
            self.pvc_ref_counts[key] = self.pvc_ref_counts.get(key, 0) + 1
        self.generation = next_generation()

    def remove_pod(self, pod: Pod) -> bool:
        for i, p in enumerate(self.pods):
            if p.key() == pod.key():
                self.pods.pop(i)
                break
        else:
            return False
        self.pods_with_affinity = [p for p in self.pods_with_affinity if p.key() != pod.key()]
        self.pods_with_required_anti_affinity = [
            p for p in self.pods_with_required_anti_affinity if p.key() != pod.key()
        ]
        req = pod.resource_request()
        self.requested.add(req, sign=-1)
        self.non_zero_requested.add(nonzero_request(req), sign=-1)
        bucket = self.prio_requested.get(pod.spec.priority)
        if bucket is not None:
            for r, v in req.items():
                if r != resource_api.PODS:
                    bucket[r] = bucket.get(r, 0) - v
            bucket[resource_api.PODS] = bucket.get(resource_api.PODS, 0) - 1
            if bucket[resource_api.PODS] <= 0:
                del self.prio_requested[pod.spec.priority]
        for p in pod.host_ports():
            self.used_ports.discard((p.host_ip or "0.0.0.0", p.protocol, p.host_port))
        for claim in pod.spec.volumes:
            key = f"{pod.meta.namespace}/{claim}"
            n = self.pvc_ref_counts.get(key, 0) - 1
            if n <= 0:
                self.pvc_ref_counts.pop(key, None)
            else:
                self.pvc_ref_counts[key] = n
        self.generation = next_generation()
        return True

    def clone(self) -> "NodeInfo":
        ni = NodeInfo()
        ni.node = self.node
        ni.pods = list(self.pods)
        ni.pods_with_affinity = list(self.pods_with_affinity)
        ni.pods_with_required_anti_affinity = list(self.pods_with_required_anti_affinity)
        ni.used_ports = set(self.used_ports)
        ni.requested = self.requested.clone()
        ni.non_zero_requested = self.non_zero_requested.clone()
        ni.allocatable = self.allocatable.clone()
        ni.prio_requested = {p: dict(b) for p, b in self.prio_requested.items()}
        ni.pvc_ref_counts = dict(self.pvc_ref_counts)
        ni.image_states = dict(self.image_states)
        ni.generation = self.generation
        return ni


def ports_conflict(used: Set[Tuple[str, str, int]], wanted: Tuple[ContainerPort, ...]) -> bool:
    """HostPortInfo conflict semantics (framework/types.go HostPortInfo):
    0.0.0.0 conflicts with every IP on the same (proto, port)."""
    for w in wanted:
        wip = w.host_ip or "0.0.0.0"
        for (ip, proto, port) in used:
            if proto == w.protocol and port == w.host_port:
                if wip == "0.0.0.0" or ip == "0.0.0.0" or ip == wip:
                    return True
    return False


# ---------------------------------------------------------------------------
# queue types (framework/types.go:94 QueuedPodInfo; :42 ClusterEvent)


@dataclass
class QueuedPodInfo:
    pod: Pod
    timestamp: float = field(default_factory=time.monotonic)
    attempts: int = 0
    initial_attempt_timestamp: float = field(default_factory=time.monotonic)
    unschedulable_plugins: Set[str] = field(default_factory=set)
    gated: bool = False


# ActionType bitmask (framework/types.go:42-85)
ADD = 1
DELETE = 1 << 1
UPDATE_NODE_ALLOCATABLE = 1 << 2
UPDATE_NODE_LABEL = 1 << 3
UPDATE_NODE_TAINT = 1 << 4
UPDATE_NODE_CONDITION = 1 << 5
UPDATE = UPDATE_NODE_ALLOCATABLE | UPDATE_NODE_LABEL | UPDATE_NODE_TAINT | UPDATE_NODE_CONDITION
ALL = ADD | DELETE | UPDATE


@dataclass(frozen=True)
class GVK:
    name: str

    def __str__(self):
        return self.name


POD = GVK("Pod")
NODE = GVK("Node")
PVC = GVK("PersistentVolumeClaim")
PV = GVK("PersistentVolume")
STORAGE_CLASS = GVK("StorageClass")
CSI_NODE = GVK("CSINode")
RESOURCE_CLAIM = GVK("ResourceClaim")
RESOURCE_CLASS = GVK("ResourceClass")
POD_SCHEDULING_CONTEXT = GVK("PodSchedulingContext")
POD_GROUP = GVK("PodGroup")
SCHEDULING_QUOTA = GVK("SchedulingQuota")
WILDCARD = GVK("*")


@dataclass(frozen=True)
class ClusterEvent:
    resource: GVK
    action_type: int
    label: str = ""

    def is_wildcard(self) -> bool:
        return self.resource == WILDCARD and self.action_type == ALL

    def match(self, other: "ClusterEvent") -> bool:
        """Does a registered interest ``self`` cover a fired event ``other``."""
        if self.is_wildcard():
            return True
        return self.resource == other.resource and (self.action_type & other.action_type) != 0


WILDCARD_EVENT = ClusterEvent(WILDCARD, ALL, "UnschedulableTimeout")


@dataclass
class Diagnosis:
    """FitError detail (framework/types.go:215): per-node failure status map +
    the set of plugins that voted Unschedulable (drives queue reactivation)."""

    node_to_status: Dict[str, "Status"] = field(default_factory=dict)  # noqa: F821
    unschedulable_plugins: Set[str] = field(default_factory=set)


class FitError(Exception):
    def __init__(self, pod: Pod, num_all_nodes: int, diagnosis: Diagnosis):
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.diagnosis = diagnosis
        super().__init__(self.message())

    def message(self) -> str:
        reasons: Dict[str, int] = {}
        for status in self.diagnosis.node_to_status.values():
            for r in status.reasons:
                reasons[r] = reasons.get(r, 0) + 1
        detail = ", ".join(f"{n} {r}" for r, n in sorted(reasons.items()))
        return f"0/{self.num_all_nodes} nodes are available: {detail}."
