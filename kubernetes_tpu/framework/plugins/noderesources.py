"""NodeResourcesFit + the resource scoring strategies + BalancedAllocation.

Oracle implementations of noderesources/{fit,least_allocated,most_allocated,
requested_to_capacity_ratio,resource_allocation,balanced_allocation}.go.
Exact formulas documented in SURVEY.md §8.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ...api import resource as resource_api
from ...api.types import Pod
from ..interface import (
    CycleState,
    FilterPlugin,
    OK,
    PreFilterExtensions,
    PreFilterPlugin,
    PreFilterResult,
    ScorePlugin,
    Status,
    MAX_NODE_SCORE,
)
from ..types import ADD, DELETE, NODE, POD, UPDATE_NODE_ALLOCATABLE, ClusterEvent, NodeInfo, nonzero_request
from . import names

# scoring strategy names (apis/config types_pluginargs.go ScoringStrategyType)
LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"
REQUESTED_TO_CAPACITY_RATIO = "RequestedToCapacityRatio"

DEFAULT_RESOURCES: Tuple[Tuple[str, int], ...] = ((resource_api.CPU, 1), (resource_api.MEMORY, 1))


@dataclass
class InsufficientResource:
    resource_name: str
    reason: str
    requested: int
    used: int
    capacity: int


class _FitState:
    """preFilterState (fit.go:142): the pod's canonical-int resource request."""

    __slots__ = ("request",)

    def __init__(self, request: Dict[str, int]):
        self.request = request

    def clone(self) -> "_FitState":
        return _FitState(dict(self.request))


def fits_request(
    request: Dict[str, int],
    node_info: NodeInfo,
    ignored_extended: frozenset = frozenset(),
) -> List[InsufficientResource]:
    """fitsRequest (fit.go:252): per-resource `req ≤ allocatable − requested`,
    plus the pod-count check; returns every insufficiency (not just first)."""
    out: List[InsufficientResource] = []
    allowed = node_info.allocatable.allowed_pod_number
    if len(node_info.pods) + 1 > allowed:
        out.append(InsufficientResource(resource_api.PODS, "Too many pods", 1, len(node_info.pods), allowed))

    core = {k: v for k, v in request.items() if k != resource_api.PODS}
    if all(v == 0 for v in core.values()):
        return out

    for rname, rq in core.items():
        if rq == 0:
            continue
        if resource_api.is_extended(rname) and rname in ignored_extended:
            continue
        free = node_info.allocatable.get(rname) - node_info.requested.get(rname)
        if rq > free:
            out.append(
                InsufficientResource(rname, f"Insufficient {rname}", rq, node_info.requested.get(rname), node_info.allocatable.get(rname))
            )
    return out


class Fit(PreFilterPlugin, FilterPlugin, ScorePlugin, PreFilterExtensions):
    """noderesources/fit.go — the CPU-reference predicate, plus the configured
    scoring strategy (default LeastAllocated)."""

    STATE_KEY = "PreFilter/NodeResourcesFit"

    def __init__(
        self,
        strategy: str = LEAST_ALLOCATED,
        resources: Tuple[Tuple[str, int], ...] = DEFAULT_RESOURCES,
        shape: Tuple[Tuple[int, int], ...] = (),
        ignored_extended: frozenset = frozenset(),
    ):
        self.strategy = strategy
        self.resources = resources
        self.shape = shape or ((0, 0), (100, 10))  # RequestedToCapacityRatio default
        self.ignored_extended = ignored_extended

    def name(self) -> str:
        return names.NODE_RESOURCES_FIT

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(POD, DELETE), ClusterEvent(NODE, ADD | UPDATE_NODE_ALLOCATABLE)]

    # -- PreFilter

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        state.write(self.STATE_KEY, _FitState(pod.resource_request()))
        return None, OK

    def pre_filter_extensions(self):
        return self

    def add_pod(self, state, pod, to_add, node_info) -> Status:
        return OK  # fit state is pod-side only; node side comes from NodeInfo

    def remove_pod(self, state, pod, to_remove, node_info) -> Status:
        return OK

    # -- Filter

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: _FitState = state.read(self.STATE_KEY)
        insufficient = fits_request(s.request, node_info, self.ignored_extended)
        if insufficient:
            return Status.unschedulable(*[i.reason for i in insufficient])
        return OK

    # -- Score (resource_allocation.go scorer shared by the strategies)

    def score_node(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        req = nonzero_request(pod.resource_request())
        if self.strategy == REQUESTED_TO_CAPACITY_RATIO:
            # requested_to_capacity_ratio.go:41-54: weight counted only when the
            # resource scores > 0; result rounded, not floored.
            num, den = 0, 0
            for rname, weight in self.resources:
                alloc = node_info.allocatable.get(rname)
                requested = node_info.non_zero_requested.get(rname) + req.get(rname, 0)
                rscore = self._rtcr_score(requested, alloc)
                if rscore > 0:
                    num += weight * rscore
                    den += weight
            return (round(num / den) if den else 0), OK
        num, den = 0, 0
        for rname, weight in self.resources:
            alloc = node_info.allocatable.get(rname)
            requested = node_info.non_zero_requested.get(rname) + req.get(rname, 0)
            num += weight * self._score_one(requested, alloc)
            den += weight
        if den == 0:
            return 0, OK
        return num // den, OK

    def _score_one(self, requested: int, capacity: int) -> int:
        if self.strategy == LEAST_ALLOCATED:
            # least_allocated.go:29: ((capacity − requested) · MaxNodeScore) / capacity
            if capacity == 0 or requested > capacity:
                return 0
            return (capacity - requested) * MAX_NODE_SCORE // capacity
        # most_allocated.go:29
        if capacity == 0 or requested > capacity:
            return 0
        return requested * MAX_NODE_SCORE // capacity

    def _rtcr_score(self, requested: int, capacity: int) -> int:
        """resourceScoringFunction: shape scores are pre-scaled ×(100/10)
        BEFORE interpolation (requested_to_capacity_ratio.go:66), and
        over-capacity/zero-capacity evaluates the shape at 100% utilization."""
        util = 100 if (capacity == 0 or requested > capacity) else requested * 100 // capacity
        scaled = tuple((x, y * (MAX_NODE_SCORE // 10)) for x, y in self.shape)
        return piecewise_linear(util, scaled)

    def score(self, state: CycleState, pod: Pod, node_name: str):
        raise NotImplementedError

    def score_extensions(self):
        return None


def piecewise_linear(x: int, shape: Tuple[Tuple[int, int], ...]) -> int:
    """FunctionShape interpolation (helper.BuildBrokenLinearFunction), shape
    points are (utilization%, score 0-10); scaling to 0-100 happens in caller."""
    if x <= shape[0][0]:
        return shape[0][1]
    for (x0, y0), (x1, y1) in zip(shape, shape[1:]):
        if x <= x1:
            return y0 + (y1 - y0) * (x - x0) // (x1 - x0)
    return shape[-1][1]


class BalancedAllocation(ScorePlugin):
    """noderesources/balanced_allocation.go: score = (1 − std(fractions)) · 100
    over the configured resources' utilization fractions (incoming pod included,
    nonzero requests)."""

    def __init__(self, resources: Tuple[Tuple[str, int], ...] = DEFAULT_RESOURCES):
        self.resources = resources

    def name(self) -> str:
        return names.NODE_RESOURCES_BALANCED_ALLOCATION

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(POD, DELETE), ClusterEvent(NODE, ADD | UPDATE_NODE_ALLOCATABLE)]

    def score_node(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        req = nonzero_request(pod.resource_request())
        fractions: List[float] = []
        for rname, _w in self.resources:
            alloc = node_info.allocatable.get(rname)
            if alloc == 0:
                fractions.append(1.0)
                continue
            requested = node_info.non_zero_requested.get(rname) + req.get(rname, 0)
            fractions.append(min(1.0, requested / alloc))
        if len(fractions) == 2:
            std = abs(fractions[0] - fractions[1]) / 2.0
        else:
            mean = sum(fractions) / len(fractions)
            std = math.sqrt(sum((f - mean) ** 2 for f in fractions) / len(fractions))
        return int((1 - std) * MAX_NODE_SCORE), OK

    def score(self, state: CycleState, pod: Pod, node_name: str):
        raise NotImplementedError

    def score_extensions(self):
        return None
