"""ImageLocality score plugin (imagelocality/image_locality.go).

score_node raw value = Σ over the pod's container images present on the node of
``size · numNodesWithImage / totalNodes``, clamped to
[23 MB, 1000 MB · numContainers] and scaled to [0, 100].

The per-image node spread (ImageStateSummary.NumNodes, computed by the cache in
the reference) is derived here at PreScore from the snapshot's node list.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ...api.types import Pod
from ..interface import CycleState, OK, PreScorePlugin, ScorePlugin, Status, MAX_NODE_SCORE
from ..types import NodeInfo
from . import names

MB = 1024 * 1024
MIN_THRESHOLD = 23 * MB
MAX_CONTAINER_THRESHOLD = 1000 * MB


def normalized_image_name(name: str) -> str:
    """parsers.NormalizeImageRef-lite: append :latest when no tag/digest."""
    if "@" in name:
        return name
    last = name.rsplit("/", 1)[-1]
    if ":" not in last:
        return name + ":latest"
    return name


class _SpreadState:
    __slots__ = ("num_nodes_with_image", "sizes", "total_nodes")

    def __init__(self, num_nodes_with_image: Dict[str, int], sizes: Dict[str, int], total_nodes: int):
        self.num_nodes_with_image = num_nodes_with_image
        # one global size per image name, first occurrence wins — mirrors the
        # scheduler cache's imageStates map (internal/cache/cache.go
        # addNodeImageStates), which the summary Size comes from
        self.sizes = sizes
        self.total_nodes = total_nodes

    def clone(self):
        return self


class ImageLocality(PreScorePlugin, ScorePlugin):
    STATE_KEY = "PreScore/ImageLocality"

    def __init__(self, snapshot_fn=None):
        # snapshot_fn: () -> List[NodeInfo]; injected by the framework runtime
        self.snapshot_fn = snapshot_fn

    def name(self) -> str:
        return names.IMAGE_LOCALITY

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        spread: Dict[str, int] = {}
        sizes: Dict[str, int] = {}
        # without a snapshot there is no image-spread information: score 0s
        node_infos: List[NodeInfo] = self.snapshot_fn() if self.snapshot_fn else []
        for ni in node_infos:
            for img, size in ni.image_states.items():
                spread[img] = spread.get(img, 0) + 1
                sizes.setdefault(img, size)
        state.write(self.STATE_KEY, _SpreadState(spread, sizes, max(1, len(node_infos))))
        return OK

    def score_node(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        s: _SpreadState = state.read(self.STATE_KEY)
        total = 0
        for c in pod.spec.containers:
            img = normalized_image_name(c.image)
            if img not in node_info.image_states and c.image not in node_info.image_states:
                continue
            size = s.sizes.get(img, s.sizes.get(c.image, 0))
            total += size * s.num_nodes_with_image.get(img, s.num_nodes_with_image.get(c.image, 0)) // s.total_nodes
        return self._calculate_priority(total, len(pod.spec.containers)), OK

    @staticmethod
    def _calculate_priority(sum_scores: int, num_containers: int) -> int:
        max_threshold = MAX_CONTAINER_THRESHOLD * num_containers
        sum_scores = min(max(sum_scores, MIN_THRESHOLD), max_threshold)
        return MAX_NODE_SCORE * (sum_scores - MIN_THRESHOLD) // (max_threshold - MIN_THRESHOLD)

    def score(self, state: CycleState, pod: Pod, node_name: str):
        raise NotImplementedError

    def score_extensions(self):
        return None
