"""DefaultBinder (defaultbinder/default_binder.go): POST pods/{name}/binding."""

from __future__ import annotations

from ...api.types import Binding, Pod
from ..interface import BindPlugin, CycleState, OK, Status
from . import names


class DefaultBinder(BindPlugin):
    def __init__(self, client=None):
        self.client = client  # apiserver.Client

    def name(self) -> str:
        return names.DEFAULT_BINDER

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        try:
            self.client.bind(Binding(pod_key=pod.key(), node_name=node_name))
        except Exception as e:  # noqa: BLE001 — surfaced as Status like AsStatus(err)
            return Status.error(str(e))
        return OK
