"""PodTopologySpread plugin oracle (podtopologyspread/{filtering,scoring}.go).

Filter: for each DoNotSchedule constraint, over "eligible" nodes (nodes that
match the incoming pod's nodeSelector/required node affinity AND carry every
constraint's topology key), count matching pods per topology domain; a node
passes iff ``matchNum + selfMatch − minMatchNum ≤ maxSkew``.

Score: for each ScheduleAnyway constraint, raw(node) = Σ_i scoreForCount
(= cnt·ln(size_i+2) + (maxSkew_i−1)); NormalizeScore inverts via
``100·(max+min−raw)/max`` with ignored nodes scored 0.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ...api.types import (
    DO_NOT_SCHEDULE,
    MATCH_NOTHING,
    SCHEDULE_ANYWAY,
    LabelSelector,
    Pod,
    TopologySpreadConstraint,
)
from ..interface import (
    CycleState,
    FilterPlugin,
    NodeScore,
    OK,
    PreFilterExtensions,
    PreFilterPlugin,
    PreFilterResult,
    PreScorePlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
    MAX_NODE_SCORE,
)
from ..types import ADD, DELETE, NODE, POD, UPDATE, UPDATE_NODE_LABEL, ClusterEvent, NodeInfo
from . import names

ERR_REASON_CONSTRAINTS = "node(s) didn't match pod topology spread constraints"
ERR_REASON_LABEL = ERR_REASON_CONSTRAINTS + " (missing required label)"

HOSTNAME_KEY = "kubernetes.io/hostname"


def _selector_of(c: TopologySpreadConstraint) -> LabelSelector:
    return c.label_selector if c.label_selector is not None else MATCH_NOTHING


def _pod_matches_node_affinity(pod: Pod, node) -> bool:
    """GetRequiredNodeAffinity.Match: nodeSelector map AND required terms."""
    if any(node.meta.labels.get(k) != v for k, v in pod.spec.node_selector.items()):
        return False
    a = pod.spec.affinity
    if a and a.node_affinity and a.node_affinity.required:
        return a.node_affinity.required.matches(node)
    return True


def count_pods_match_selector(pods, selector: LabelSelector, ns: str) -> int:
    return sum(
        1 for p in pods if p.meta.namespace == ns and selector.matches(p.meta.labels)
    )


@dataclass
class _PreFilterState:
    constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    tp_pair_to_match_num: Dict[Tuple[str, str], int] = field(default_factory=dict)
    tp_key_to_domains_num: Dict[str, int] = field(default_factory=dict)

    def clone(self) -> "_PreFilterState":
        return _PreFilterState(
            list(self.constraints),
            dict(self.tp_pair_to_match_num),
            dict(self.tp_key_to_domains_num),
        )

    def min_match_num(self, tp_key: str, min_domains: Optional[int]) -> int:
        vals = [n for (k, _v), n in self.tp_pair_to_match_num.items() if k == tp_key]
        m = min(vals) if vals else 0
        if min_domains is not None and self.tp_key_to_domains_num.get(tp_key, 0) < min_domains:
            return 0  # fewer eligible domains than minDomains ⇒ global min is 0
        return m

    def update(self, pod: Pod, node, delta: int, incoming_ns: str) -> None:
        """AddPod/RemovePod extension (filtering.go:166,177 updateWithPod);
        only nodes carrying every constraint's topology key were counted at
        PreFilter, so only those may be updated (nodeLabelsMatchSpreadConstraints)."""
        if pod.meta.namespace != incoming_ns:
            return
        if any(c.topology_key not in node.meta.labels for c in self.constraints):
            return
        for c in self.constraints:
            if not _selector_of(c).matches(pod.meta.labels):
                continue
            if c.topology_key not in node.meta.labels:
                continue
            pair = (c.topology_key, node.meta.labels[c.topology_key])
            self.tp_pair_to_match_num[pair] = self.tp_pair_to_match_num.get(pair, 0) + delta


@dataclass
class _PreScoreState:
    constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    ignored_nodes: Set[str] = field(default_factory=set)
    topology_pair_to_pod_counts: Dict[Tuple[str, str], int] = field(default_factory=dict)
    topology_normalizing_weight: List[float] = field(default_factory=list)

    def clone(self):
        return self


class PodTopologySpread(PreFilterPlugin, FilterPlugin, PreScorePlugin, ScorePlugin, ScoreExtensions, PreFilterExtensions):
    PREFILTER_KEY = "PreFilter/PodTopologySpread"
    PRESCORE_KEY = "PreScore/PodTopologySpread"

    def __init__(self, snapshot_fn=None, default_constraints: Tuple[TopologySpreadConstraint, ...] = (),
                 system_defaulted: bool = False):
        self.snapshot_fn = snapshot_fn  # () -> List[NodeInfo]
        self.default_constraints = default_constraints
        # True only when default_constraints are the built-in system defaults
        # (plugin.go systemDefaulted) — relaxes the require-all-topologies rule
        self.system_defaulted = system_defaulted

    def name(self) -> str:
        return names.POD_TOPOLOGY_SPREAD

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(POD, ADD | DELETE), ClusterEvent(NODE, ADD | UPDATE_NODE_LABEL)]

    def _constraints(self, pod: Pod, when: str) -> List[TopologySpreadConstraint]:
        if pod.spec.topology_spread_constraints:
            return [c for c in pod.spec.topology_spread_constraints if c.when_unsatisfiable == when]
        return [c for c in self.default_constraints if c.when_unsatisfiable == when]

    # -- PreFilter (filtering.go:238 calPreFilterState)

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        constraints = self._constraints(pod, DO_NOT_SCHEDULE)
        s = _PreFilterState(constraints=constraints)
        if constraints:
            all_nodes: List[NodeInfo] = self.snapshot_fn() if self.snapshot_fn else []
            for ni in all_nodes:
                node = ni.node
                if node is None or not _pod_matches_node_affinity(pod, node):
                    continue
                if any(c.topology_key not in node.meta.labels for c in constraints):
                    continue
                for c in constraints:
                    pair = (c.topology_key, node.meta.labels[c.topology_key])
                    cnt = count_pods_match_selector(ni.pods, _selector_of(c), pod.meta.namespace)
                    s.tp_pair_to_match_num[pair] = s.tp_pair_to_match_num.get(pair, 0) + cnt
            for (k, _v) in s.tp_pair_to_match_num:
                s.tp_key_to_domains_num[k] = s.tp_key_to_domains_num.get(k, 0) + 1
        state.write(self.PREFILTER_KEY, s)
        return None, OK

    def pre_filter_extensions(self):
        return self

    def add_pod(self, state: CycleState, pod: Pod, to_add: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState = state.read(self.PREFILTER_KEY)
        if s.constraints and node_info.node is not None and _pod_matches_node_affinity(pod, node_info.node):
            s.update(to_add, node_info.node, 1, pod.meta.namespace)
        return OK

    def remove_pod(self, state: CycleState, pod: Pod, to_remove: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState = state.read(self.PREFILTER_KEY)
        if s.constraints and node_info.node is not None and _pod_matches_node_affinity(pod, node_info.node):
            s.update(to_remove, node_info.node, -1, pod.meta.namespace)
        return OK

    # -- Filter (filtering.go:335)

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState = state.read(self.PREFILTER_KEY)
        if not s.constraints:
            return OK
        node = node_info.node
        for c in s.constraints:
            if c.topology_key not in node.meta.labels:
                return Status.unresolvable(ERR_REASON_LABEL)
            min_match = s.min_match_num(c.topology_key, c.min_domains)
            self_match = 1 if _selector_of(c).matches(pod.meta.labels) else 0
            pair = (c.topology_key, node.meta.labels[c.topology_key])
            match_num = s.tp_pair_to_match_num.get(pair, 0)
            if match_num + self_match - min_match > c.max_skew:
                return Status.unschedulable(ERR_REASON_CONSTRAINTS)
        return OK

    # -- Score (scoring.go)

    def pre_score(self, state: CycleState, pod: Pod, filtered_nodes) -> Status:
        constraints = self._constraints(pod, SCHEDULE_ANYWAY)
        s = _PreScoreState(constraints=constraints)
        state.write(self.PRESCORE_KEY, s)
        if not constraints:
            return OK
        require_all = bool(pod.spec.topology_spread_constraints) or not self.system_defaulted

        topo_size = [0] * len(constraints)
        seen_pairs: Set[Tuple[str, str]] = set()
        for node in filtered_nodes:
            if require_all and any(c.topology_key not in node.meta.labels for c in constraints):
                s.ignored_nodes.add(node.meta.name)
                continue
            for i, c in enumerate(constraints):
                if c.topology_key == HOSTNAME_KEY:
                    continue
                pair = (c.topology_key, node.meta.labels.get(c.topology_key, ""))
                if pair not in seen_pairs:
                    seen_pairs.add(pair)
                    s.topology_pair_to_pod_counts[pair] = 0
                    topo_size[i] += 1

        for i, c in enumerate(constraints):
            sz = topo_size[i]
            if c.topology_key == HOSTNAME_KEY:
                sz = len(filtered_nodes) - len(s.ignored_nodes)
            s.topology_normalizing_weight.append(math.log(sz + 2))

        all_nodes: List[NodeInfo] = self.snapshot_fn() if self.snapshot_fn else []
        for ni in all_nodes:
            node = ni.node
            if node is None or not _pod_matches_node_affinity(pod, node):
                continue
            if require_all and any(c.topology_key not in node.meta.labels for c in constraints):
                continue
            for c in constraints:
                pair = (c.topology_key, node.meta.labels.get(c.topology_key, ""))
                if pair in s.topology_pair_to_pod_counts:
                    s.topology_pair_to_pod_counts[pair] += count_pods_match_selector(
                        ni.pods, _selector_of(c), pod.meta.namespace
                    )
        return OK

    def score_node(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        s: _PreScoreState = state.read(self.PRESCORE_KEY)
        node = node_info.node
        if not s.constraints or node.meta.name in s.ignored_nodes:
            return 0, OK
        score = 0.0
        for i, c in enumerate(s.constraints):
            if c.topology_key not in node.meta.labels:
                continue
            if c.topology_key == HOSTNAME_KEY:
                cnt = count_pods_match_selector(node_info.pods, _selector_of(c), pod.meta.namespace)
            else:
                cnt = s.topology_pair_to_pod_counts.get((c.topology_key, node.meta.labels[c.topology_key]), 0)
            score += cnt * s.topology_normalizing_weight[i] + (c.max_skew - 1)
        return round(score), OK

    def score(self, state: CycleState, pod: Pod, node_name: str):
        raise NotImplementedError

    def score_extensions(self):
        return self

    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> Status:
        s: _PreScoreState = state.read(self.PRESCORE_KEY)
        if not s.constraints:
            return OK
        valid = [sc.score for sc in scores if sc.name not in s.ignored_nodes]
        if not valid:
            return OK
        min_score, max_score = min(valid), max(valid)
        for sc in scores:
            if sc.name in s.ignored_nodes:
                sc.score = 0
            elif max_score == 0:
                sc.score = MAX_NODE_SCORE
            else:
                sc.score = MAX_NODE_SCORE * (max_score + min_score - sc.score) // max_score
        return OK
