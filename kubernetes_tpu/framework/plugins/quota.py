"""QuotaAdmission — scheduler-side multi-tenant admission over
SchedulingQuota (scheduling.x-k8s.io/v1alpha1).

The Kueue/ElasticQuota analog collapsed onto the scheduling framework: a
namespace's SchedulingQuota caps what the scheduler may *admit* (assume +
bind), not what the apiserver may store — an over-quota tenant's pods exist
but park GATED in the unschedulable pool, where they cost no scheduling
cycles and no device batch slots. The plugin is a PreEnqueue/PreFilter pair
plus a Reserve-time charge:

  * PreEnqueue: the queue-admission gate. Every transition toward activeQ
    re-runs it, so a reactivation wave (assigned-pod delete, gang teardown,
    unschedulable-timeout flush) can never flood the active queue with pods
    whose namespace is still over quota (the reactivation-thrash guard).
  * PreFilter: the in-cycle re-check (usage may have grown between enqueue
    and pop — a batched frontend pops hundreds of pods per cycle).
    Over-quota is UnschedulableAndUnresolvable: evicting node-capacity
    victims cannot raise a namespace's quota, so no preemption dry-run
    fans out.
  * Reserve: the authoritative charge, atomically with the assume on the
    single-threaded scheduling loop — usage can never oversubscribe ``hard``
    because the charge IS the admission. Unreserve releases.

Release (unreserve, bound-pod delete) fires a targeted quota-release queue
move for the namespace: only gated/quota-failed pods whose request now fits
(tracked against a shadow ledger, so one freed slot admits one pod, not the
whole parked backlog) re-enter the queue.

The ledger is in-memory and seeded per namespace from the store's bound
pods on first touch, so a restarted scheduler resumes with true usage.

Fair share: the queue's deficit-round-robin layer asks ``weight_for(ns)``
— namespaces with a SchedulingQuota are tenants served in proportion to
``spec.weight``; namespaces without one share the default bucket.

Cohort borrowing (the elastic-headroom layer)
---------------------------------------------

Quotas carrying ``spec.cohort`` pool their *unused guaranteed* capacity: a
tenant over its own hard cap may still admit by charging the cohort's idle
headroom — a **loan**. The invariants the ledger keeps at every instant:

  * Per-dimension cohort capacity is the sum of member hard caps; total
    member usage (own + borrowed) never exceeds it, so only unused
    guaranteed quota is ever lent — never another borrower's loans
    (headroom = Σhard − Σused already nets loans out).
  * Gangs admit atomically: the first uncharged member's fits check prices
    the gang's remaining ``minMember`` aggregate, so a PodGroup whose tail
    cannot fit never charges its head (no half-admitted gangs; the Permit
    quorum + unreserve cascade covers mid-flight races).
  * Loans are RECLAIMABLE. A lender's own pod that fits its guarantee but
    finds the cohort exhausted records reclaim demand; the periodic
    reclaim pass (``run_reclaim``, driven from the scheduler's housekeeping
    sweep) evicts borrower pods newest-loan-first — whole gangs via the
    drain orchestrator's gang closure — until the lender's demand fits.
    A per-cohort cooldown plus an SLO circuit breaker (the PR-17
    rebalance pattern: trip → ``reclaim_suspended`` event, heal through
    the half-open probe) guard against reclaim storms.

The device screen half lives in ops/quota.py: ``device_quota_table()``
exports this ledger as the per-namespace used/limit tensor rows the batch
program screens winners against (limit = own hard + borrowable headroom,
so screen staleness can only reject-and-retry, never oversubscribe).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ...api.types import (
    Pod,
    QUOTA_CLAIMS,
    QUOTA_CPU,
    QUOTA_DIM_ORDER,
    QUOTA_MEMORY,
    QUOTA_PODS,
    SchedulingQuota,
)
from ...api import resource as resource_api
from ..interface import (
    CycleState,
    OK,
    PreEnqueuePlugin,
    PreFilterPlugin,
    PreFilterResult,
    ReservePlugin,
    Status,
)
from ..types import ALL, ClusterEvent, SCHEDULING_QUOTA
from . import names

ERR_REASON_QUOTA_EXCEEDED = "QuotaExceeded"

# int32 tensor ceiling for the device-table rows (ops/quota.py sentinel)
_NO_LIMIT = 2**31 - 1

# reclaim-pass pacing: a cohort is reclaimed at most once per cooldown, and
# the SLO breaker opens after ``threshold`` guard-judged bad waves, healing
# through a half-open probe after ``reset`` (the PR-17 rebalance pattern)
DEFAULT_RECLAIM_COOLDOWN_S = 5.0
RECLAIM_BREAKER_THRESHOLD = 2
RECLAIM_BREAKER_RESET_S = 30.0


def pod_quota_request(pod: Pod) -> Dict[str, int]:
    """The SchedulingQuota dimensions one pod consumes (canonical ints,
    api/resource.py): max(containers)+init+overhead cpu/memory via the
    cached resource_request(), one pod slot, and its resource.k8s.io claim
    count."""
    req = pod.resource_request()
    return {
        QUOTA_PODS: 1,
        QUOTA_CPU: req.get(resource_api.CPU, 0),
        QUOTA_MEMORY: req.get(resource_api.MEMORY, 0),
        QUOTA_CLAIMS: len(pod.spec.resource_claims),
    }


def quota_precheck_status(fwk, pod: Pod) -> Optional[Status]:
    """Host-side stand-in for QuotaAdmission's PreFilter on the batched
    paths (the compiled device program does not model namespace quota):
    returns the non-success Status the pod should fail with before
    dispatch, or None when it may ride the batch."""
    plugin = fwk.plugin(names.QUOTA_ADMISSION)
    if plugin is None:
        return None
    _r, st = plugin.pre_filter(CycleState(), pod)
    return None if st.is_success() else st


class QuotaAdmission(PreEnqueuePlugin, PreFilterPlugin, ReservePlugin):
    def __init__(self, client=None, metrics=None, now_fn=None):
        self.client = client
        self.metrics = metrics
        self.now_fn = now_fn or time.monotonic
        # ns -> dim -> charged usage (the authoritative scheduler-side
        # ledger; includes the borrowed portion below)
        self._usage: Dict[str, Dict[str, int]] = {}
        # pod key -> (ns, charge vector): exactly-once charge accounting
        # across Reserve, external-bind observation, and release paths
        self._charged: Dict[str, Tuple[str, Dict[str, int]]] = {}
        self._seeded: Set[str] = set()
        # pods already counted as a "rejected" admission decision — the
        # decisions counter records pod-level outcomes, and _fits_status
        # re-runs on every PreEnqueue wave / PreFilter / release probe
        self._rejected: Set[str] = set()
        # --- cohort borrowing state -------------------------------------
        # ns -> dim -> the portion of _usage charged against cohort
        # headroom rather than the namespace's own hard caps
        self._borrowed: Dict[str, Dict[str, int]] = {}
        # pod key -> (ns, charge vector, loan seq): outstanding loans in
        # grant order; reclaim walks them newest-seq-first
        self._loans: Dict[str, Tuple[str, Dict[str, int], int]] = {}
        # mutable holder (not a bare int) so share_ledger can alias it
        self._loan_seq: Dict[str, int] = {"n": 0}
        # gang key -> charged member count: prices the REMAINING gang
        # aggregate in _fits_status so a PodGroup never half-admits
        self._gang_counts: Dict[str, int] = {}
        self._gang_charged: Dict[str, str] = {}  # pod key -> gang key
        # cohort -> pod key -> effective request: lender demand the
        # reclaim pass must free headroom for (recorded by _fits_status,
        # which runs under the queue lock — eviction happens later, on the
        # housekeeping sweep)
        self._reclaim_demand: Dict[str, Dict[str, Dict[str, int]]] = {}
        self._demand_pods: Dict[str, str] = {}  # pod key -> cohort
        self._last_reclaim: Dict[str, float] = {}
        # cohorts with demand recorded since their last pass: fresh demand
        # bypasses the cooldown (the cooldown paces re-eviction for the
        # SAME unmet demand; the breaker guards genuine storms)
        self._demand_fresh: Set[str] = set()
        self.reclaim_cooldown_s = DEFAULT_RECLAIM_COOLDOWN_S
        # whole-gang borrower eviction, wired by the Scheduler to the
        # drain orchestrator: fn(pods, reason) -> pods evicted
        self.on_evict: Optional[Callable[[List[Pod], str], int]] = None
        # SLO guardrail hook (PR-17 pattern): judged after each executed
        # wave; False = lender-SLO regression, feeds the breaker
        self.reclaim_guard_fn: Optional[Callable[[], bool]] = None
        from ...backend.circuit import CircuitBreaker  # lazy: no cycle

        self.reclaim_breaker = CircuitBreaker(
            failure_threshold=RECLAIM_BREAKER_THRESHOLD,
            reset_timeout_s=RECLAIM_BREAKER_RESET_S,
            now_fn=self.now_fn)
        self.reclaim_suspended = False
        self.reclaims_executed = 0
        # ----------------------------------------------------------------
        # ns -> [SchedulingQuota] index + per-ns (hard, weight, cohort)
        # memo over the cluster quota map: quotas_for sits on the
        # queue-push and DRR rotation hot paths, where an O(all-quotas)
        # scan per call is not acceptable. Invalidated by SchedulingQuota
        # store events (and by quota-map size changes, for event-less
        # clients).
        self._quota_index: Optional[Dict[str, List[SchedulingQuota]]] = None
        self._cohort_index: Dict[str, List[str]] = {}
        self._index_len = -1
        self._derived: Dict[str, Tuple[Optional[Dict[str, int]],
                                       Optional[float], Optional[str]]] = {}
        if client is not None and hasattr(client, "add_event_handler"):
            client.add_event_handler(
                "SchedulingQuota", lambda _e, _o, _n: self.quotas_changed())
        # targeted quota-release queue move, wired by the Scheduler:
        # fn(namespace) -> pods moved
        self.on_release: Optional[Callable[[str], int]] = None

    def name(self) -> str:
        return names.QUOTA_ADMISSION

    def events_to_register(self) -> List[ClusterEvent]:
        # the quota-release move and user edits to SchedulingQuota objects
        # (raising a cap must wake the namespace's gated pods)
        return [ClusterEvent(SCHEDULING_QUOTA, ALL, "SchedulingQuotaChange")]

    # ------------------------------------------------------------- quota view

    def _quota_map(self) -> Dict[str, SchedulingQuota]:
        if self.client is None:
            return {}
        m = getattr(self.client, "scheduling_quotas", None)
        if m is not None:
            return m
        try:
            return self.client.snapshot_map("SchedulingQuota")
        except Exception:  # noqa: BLE001 — clients without the kind: no quota
            return {}

    def quotas_changed(self) -> None:
        """Invalidate the ns index + derived memos (SchedulingQuota event)."""
        self._quota_index = None
        self._derived.clear()

    def _index(self) -> Dict[str, List[SchedulingQuota]]:
        m = self._quota_map()
        if self._quota_index is None or len(m) != self._index_len:
            idx: Dict[str, List[SchedulingQuota]] = {}
            cidx: Dict[str, List[str]] = {}
            for q in m.values():
                idx.setdefault(q.meta.namespace, []).append(q)
            for ns, quotas in idx.items():
                for q in quotas:
                    if q.cohort:
                        members = cidx.setdefault(q.cohort, [])
                        if ns not in members:
                            members.append(ns)
                        break
            self._quota_index = idx
            self._cohort_index = cidx
            self._index_len = len(m)
            self._derived.clear()
        return self._quota_index

    def quotas_for(self, ns: str) -> List[SchedulingQuota]:
        return self._index().get(ns, [])

    def _derived_for(self, ns: str) -> Tuple[Optional[Dict[str, int]],
                                             Optional[float], Optional[str]]:
        """(effective hard caps, fair-share weight, cohort) for a namespace,
        memoized until the quota map changes — weight_for runs on every
        queue push and every DRR rotation visit."""
        self._index()  # revalidate (clears _derived on rebuild)
        d = self._derived.get(ns)
        if d is None:
            quotas = self.quotas_for(ns)
            if not quotas:
                d = (None, None, None)
            else:
                hard: Dict[str, int] = {}
                cohort: Optional[str] = None
                for q in quotas:
                    for dim, cap in q.hard.items():
                        hard[dim] = min(hard[dim], cap) if dim in hard else cap
                    if cohort is None and q.cohort:
                        cohort = q.cohort
                d = (hard, float(max(q.weight for q in quotas)), cohort)
            self._derived[ns] = d
        return d

    def effective_hard(self, ns: str) -> Optional[Dict[str, int]]:
        """Per-dimension caps for a namespace (min across its quota objects;
        every matching quota must admit, exactly like core ResourceQuota).
        None when the namespace has no SchedulingQuota — unlimited."""
        return self._derived_for(ns)[0]

    def weight_for(self, ns: str) -> Optional[float]:
        """Fair-share weight for the queue's DRR layer: max across the
        namespace's quota objects; None = not a tenant (default bucket)."""
        return self._derived_for(ns)[1]

    def cohort_for(self, ns: str) -> Optional[str]:
        """The lending pool this namespace's quota belongs to, or None."""
        return self._derived_for(ns)[2]

    def cohort_members(self, cohort: str) -> List[str]:
        self._index()
        return list(self._cohort_index.get(cohort, []))

    def share_ledger(self, other: "QuotaAdmission") -> None:
        """Alias this instance's ledger state onto ``other``'s. Quota usage
        is cluster-level per-namespace state: in a multi-profile scheduler
        every profile's QuotaAdmission instance must charge and read ONE
        ledger, or charges split across per-profile ledgers and the release
        wave / fair-share weights read one that undercounts usage."""
        self._usage = other._usage
        self._charged = other._charged
        self._seeded = other._seeded
        self._rejected = other._rejected
        self._borrowed = other._borrowed
        self._loans = other._loans
        self._loan_seq = other._loan_seq
        self._gang_counts = other._gang_counts
        self._gang_charged = other._gang_charged
        self._reclaim_demand = other._reclaim_demand
        self._demand_pods = other._demand_pods
        self._last_reclaim = other._last_reclaim
        self._demand_fresh = other._demand_fresh

    # ---------------------------------------------------------------- ledger

    def _ensure_seeded(self, ns: str) -> None:
        """First touch of a namespace: charge every already-bound pod so a
        restarted scheduler resumes with true usage (the ledger analog of
        Coscheduling's bound-count seed). Pods are charged in sorted-key
        order and each charge classifies itself own-quota-first /
        then-cohort, so a takeover reconstructs the outstanding-loan split —
        without it a restarted scheduler would double-count borrowed
        capacity as both used and lendable."""
        if ns in self._seeded:
            return
        self._seeded.add(ns)
        pods = getattr(self.client, "pods", None) if self.client else None
        if pods is None:
            return
        bound = [pod for pod in pods.values()
                 if pod.meta.namespace == ns and pod.spec.node_name]
        for pod in sorted(bound, key=lambda p: p.key()):
            self._charge(pod)

    def usage(self, ns: str) -> Dict[str, int]:
        self._ensure_seeded(ns)
        return dict(self._usage.get(ns, {}))

    def borrowed(self, ns: str) -> Dict[str, int]:
        """The portion of ``usage(ns)`` charged against cohort headroom."""
        self._ensure_seeded(ns)
        return dict(self._borrowed.get(ns, {}))

    def _violated(self, hard: Dict[str, int], used: Dict[str, int],
                  req: Dict[str, int]) -> Optional[str]:
        for dim, cap in hard.items():
            if used.get(dim, 0) + req.get(dim, 0) > cap:
                return dim
        return None

    # ------------------------------------------------------------- cohorts

    def _cohort_state(self, cohort: str) -> Tuple[Dict[str, int],
                                                  Dict[str, int]]:
        """(caps, used) per dimension for a cohort. A dimension's cap is
        the sum of hard caps across the members that declare it, and its
        usage sums the SAME members — an undeclared dimension neither
        contributes capacity nor consumes the pool. Because ``used``
        includes every member's loans, headroom = cap − used lends only
        unused guaranteed quota, never another borrower's loans."""
        caps: Dict[str, int] = {}
        used: Dict[str, int] = {}
        for ns in self.cohort_members(cohort):
            hard = self.effective_hard(ns)
            if hard is None:
                continue
            self._ensure_seeded(ns)
            ns_used = self._usage.get(ns, {})
            for dim, cap in hard.items():
                caps[dim] = caps.get(dim, 0) + cap
                used[dim] = used.get(dim, 0) + ns_used.get(dim, 0)
        return caps, used

    def _cohort_violated(self, cohort: str,
                         req: Dict[str, int]) -> Optional[str]:
        caps, used = self._cohort_state(cohort)
        for dim, cap in caps.items():
            if used.get(dim, 0) + req.get(dim, 0) > cap:
                return dim
        return None

    def cohort_state(self, cohort: str) -> Tuple[Dict[str, int],
                                                 Dict[str, int]]:
        """Public (caps, used) pool view — what /debug/quota and the perf
        harness's zero-oversubscription sampler read."""
        return self._cohort_state(cohort)

    def cohort_headroom(self, cohort: str) -> Dict[str, int]:
        """Per-dimension borrowable capacity left in the pool right now."""
        caps, used = self._cohort_state(cohort)
        return {dim: max(cap - used.get(dim, 0), 0)
                for dim, cap in caps.items()}

    # ---------------------------------------------------------- gang pricing

    def _gang_remaining(self, pod: Pod) -> Tuple[Optional[str], int]:
        """(gang key, uncharged member count) — the multiplier the fits
        check prices so a gang admits atomically: the first member's check
        requires headroom for the whole remaining ``minMember``, and each
        subsequent member's requirement shrinks by the siblings already
        charged. A non-gang pod prices itself (1)."""
        from .coscheduling import pod_group_key

        gkey = pod_group_key(pod)
        if gkey is None or self.client is None:
            return None, 1
        pg = None
        try:
            pg = self.client.get_object("PodGroup", gkey)
        except Exception:  # noqa: BLE001 — clients without the kind
            pg = None
        if pg is None:
            return gkey, 1
        remaining = int(pg.min_member) - self._gang_counts.get(gkey, 0)
        return gkey, max(remaining, 1)

    @staticmethod
    def _scaled(req: Dict[str, int], mult: int) -> Dict[str, int]:
        return req if mult == 1 else {d: v * mult for d, v in req.items()}

    # ----------------------------------------------------------- fits check

    def _fits_status(self, pod: Pod) -> Optional[Status]:
        """None when the pod fits its namespace's quota headroom (or is
        already charged / unquota'd); else the typed QuotaExceeded status.
        Gang members price the remaining gang aggregate; over-own-cap
        tenants fall through to cohort borrowing; a lender blocked only by
        outstanding loans records reclaim demand for the sweep."""
        ns = pod.meta.namespace
        hard = self.effective_hard(ns)
        if hard is None or pod.key() in self._charged:
            return None
        self._ensure_seeded(ns)
        _gkey, mult = self._gang_remaining(pod)
        req = self._scaled(pod_quota_request(pod), mult)
        used = self._usage.get(ns, {})
        cohort = self.cohort_for(ns)
        dim = self._violated(hard, used, req)
        if dim is None:
            if cohort is not None:
                cdim = self._cohort_violated(cohort, req)
                if cdim is not None:
                    # fits its own guarantee, but loans hold the pool: the
                    # lender's demand triggers reclaim-by-preemption
                    self._note_reclaim_demand(cohort, pod, req)
                    return self._reject(pod, ns, cdim, lender=True)
            self._rejected.discard(pod.key())
            self._drop_demand(pod.key())
            return None
        # over its own hard cap: borrow from cohort idle headroom — but
        # never while a lender's reclaim demand is outstanding, or freed
        # capacity would be re-stolen ahead of the lender's retry (the
        # guarantee would heal only at cooldown cadence)
        if (cohort is not None and not self._reclaim_demand.get(cohort)
                and self._cohort_violated(cohort, req) is None):
            self._rejected.discard(pod.key())
            self._drop_demand(pod.key())
            return None
        return self._reject(pod, ns, dim)

    def _reject(self, pod: Pod, ns: str, dim: str,
                lender: bool = False) -> Status:
        # pod-level decision counting: _fits_status re-runs on every
        # PreEnqueue wave, PreFilter and release probe — only the first
        # rejection of an over-quota episode is an admission outcome
        if self.metrics is not None and pod.key() not in self._rejected:
            self._rejected.add(pod.key())
            self.metrics.quota_decisions.inc(ns, "rejected")
        what = ("cohort exhausted by loans" if lender
                else "over quota")
        # Unresolvable: node-capacity preemption cannot raise a namespace
        # quota, so the failure must not fan out a preemption dry-run. The
        # quota-release event (not a node event) wakes the pod.
        return Status.unresolvable(
            f'{ERR_REASON_QUOTA_EXCEEDED}: namespace "{ns}" {what} '
            f'on {dim}')

    # --------------------------------------------------------- charge/release

    def _charge(self, pod: Pod) -> bool:
        """Charge one pod, classifying the charge own-quota-first: only the
        portion that does not fit under the namespace's own hard caps
        becomes a loan against the cohort. Classification is whole-pod
        (a pod is either own-funded or a loan), matching release."""
        key = pod.key()
        if key in self._charged:
            return False
        ns = pod.meta.namespace
        req = pod_quota_request(pod)
        hard = self.effective_hard(ns)
        borrowed = (hard is not None
                    and self.cohort_for(ns) is not None
                    and self._violated(hard, self._usage.get(ns, {}),
                                       req) is not None)
        used = self._usage.setdefault(ns, {})
        for dim, v in req.items():
            used[dim] = used.get(dim, 0) + v
        self._charged[key] = (ns, req)
        from .coscheduling import pod_group_key

        gkey = pod_group_key(pod)
        if gkey is not None:
            self._gang_charged[key] = gkey
            self._gang_counts[gkey] = self._gang_counts.get(gkey, 0) + 1
        if borrowed:
            b = self._borrowed.setdefault(ns, {})
            for dim, v in req.items():
                b[dim] = b.get(dim, 0) + v
            self._loan_seq["n"] += 1
            self._loans[key] = (ns, req, self._loan_seq["n"])
            from ...backend import telemetry

            telemetry.event("borrow_grant", pod=key, namespace=ns,
                            cohort=self.cohort_for(ns) or "")
            if self.metrics is not None:
                self.metrics.quota_decisions.inc(ns, "borrowed")
        self._rejected.discard(key)
        self._drop_demand(key)
        self._sync_metrics(ns)
        return True

    def _release(self, pod_key: str) -> Optional[str]:
        entry = self._charged.pop(pod_key, None)
        if entry is None:
            return None
        ns, req = entry
        used = self._usage.setdefault(ns, {})
        for dim, v in req.items():
            used[dim] = max(used.get(dim, 0) - v, 0)
        gkey = self._gang_charged.pop(pod_key, None)
        if gkey is not None:
            n = self._gang_counts.get(gkey, 0) - 1
            if n > 0:
                self._gang_counts[gkey] = n
            else:
                self._gang_counts.pop(gkey, None)
        loan = self._loans.pop(pod_key, None)
        if loan is not None:
            b = self._borrowed.setdefault(ns, {})
            for dim, v in req.items():
                b[dim] = max(b.get(dim, 0) - v, 0)
        self._sync_metrics(ns)
        return ns

    def _sync_metrics(self, ns: str) -> None:
        if self.metrics is None:
            return
        used = self._usage.get(ns, {})
        borrowed = self._borrowed.get(ns, {})
        for dim in (QUOTA_PODS, QUOTA_CPU, QUOTA_MEMORY, QUOTA_CLAIMS):
            self.metrics.quota_usage.set(ns, dim, value=used.get(dim, 0))
            self.metrics.quota_borrowed.set(ns, dim,
                                            value=borrowed.get(dim, 0))

    # ---------------------------------------------------------------- reclaim

    def _note_reclaim_demand(self, cohort: str, pod: Pod,
                             req: Dict[str, int]) -> None:
        if pod.key() not in self._demand_pods:
            self._demand_fresh.add(cohort)
        self._reclaim_demand.setdefault(cohort, {})[pod.key()] = dict(req)
        self._demand_pods[pod.key()] = cohort

    def _drop_demand(self, pod_key: str) -> None:
        cohort = self._demand_pods.pop(pod_key, None)
        if cohort is not None:
            demands = self._reclaim_demand.get(cohort)
            if demands is not None:
                demands.pop(pod_key, None)
                if not demands:
                    self._reclaim_demand.pop(cohort, None)

    def run_reclaim(self, now: Optional[float] = None) -> int:
        """The reclaim-by-preemption pass, driven from the scheduler's
        housekeeping sweep: for every cohort with recorded lender demand,
        evict borrower pods newest-loan-first (whole gangs — on_evict is
        the drain orchestrator's gang-closure eviction) until the demand
        fits the pool again. Paced by a per-cohort cooldown and gated by
        the SLO breaker; returns pods evicted."""
        if self.on_evict is None or not self._reclaim_demand:
            return 0
        if now is None:
            now = self.now_fn()
        evicted_total = 0
        for cohort in list(self._reclaim_demand):
            live = self._live_demand(cohort)
            if not live:
                continue
            # judge the demands as one AGGREGATE: every recorded lender is
            # entitled (own-fit), so the pool must fit their sum — judging
            # each one-pod demand alone would declare victory after a
            # single freed slot and reclaim at cooldown cadence instead
            agg: Dict[str, int] = {}
            for r in live.values():
                for d, v in r.items():
                    agg[d] = agg.get(d, 0) + v
            if self._cohort_violated(cohort, agg) is None:
                continue
            last = self._last_reclaim.get(cohort)
            if (last is not None and now - last < self.reclaim_cooldown_s
                    and cohort not in self._demand_fresh):
                continue
            if not self.reclaim_breaker.allow():
                if not self.reclaim_suspended:
                    self.reclaim_suspended = True
                    from ...backend import telemetry

                    telemetry.event("reclaim_suspended", cohort=cohort,
                                    breaker=self.reclaim_breaker.state)
                    if self.metrics is not None:
                        self.metrics.quota_reclaims.inc("suspended")
                continue
            if self.reclaim_suspended:
                self.reclaim_suspended = False
            self._last_reclaim[cohort] = now
            self._demand_fresh.discard(cohort)
            n = self._reclaim_cohort(cohort, agg)
            evicted_total += n
            from ...backend import telemetry

            telemetry.event("borrow_reclaim", cohort=cohort, evicted=n,
                            demands=len(live))
            if self.metrics is not None:
                self.metrics.quota_reclaims.inc(
                    "evicted" if n else "noop")
            if n:
                self.reclaims_executed += 1
                # SLO guardrail (PR-17 pattern): a judged regression feeds
                # the breaker; a clean wave heals it — an OPEN breaker only
                # heals through its half-open probe
                if (self.reclaim_guard_fn is not None
                        and not self.reclaim_guard_fn()):
                    self.reclaim_breaker.record_failure()
                elif self.reclaim_breaker.state != "open":
                    self.reclaim_breaker.record_success()
        return evicted_total

    def _live_demand(self, cohort: str) -> Dict[str, Dict[str, int]]:
        """Drop demand entries whose pod is gone, bound, or since charged."""
        demands = self._reclaim_demand.get(cohort, {})
        pods = getattr(self.client, "pods", {}) if self.client else {}
        for key in list(demands):
            pod = pods.get(key)
            if pod is None or pod.spec.node_name or key in self._charged:
                demands.pop(key, None)
                self._demand_pods.pop(key, None)
        if not demands:
            self._reclaim_demand.pop(cohort, None)
        return demands

    def _reclaim_cohort(self, cohort: str, agg: Dict[str, int]) -> int:
        """Evict this cohort's borrower pods newest-loan-first until the
        aggregate lender demand fits. on_evict deletes through the store,
        so each eviction's release lands on this ledger synchronously and
        the loop re-judges against post-eviction headroom."""
        evicted = 0
        loans = sorted(
            ((seq, key, ns) for key, (ns, _r, seq) in self._loans.items()
             if self.cohort_for(ns) == cohort),
            reverse=True)
        pods = getattr(self.client, "pods", {}) if self.client else {}
        for _seq, key, _ns in loans:
            if self._cohort_violated(cohort, agg) is None:
                break
            pod = pods.get(key)
            if pod is None:
                # loan for a pod the store no longer has: reconcile
                ns = self._release(key)
                if ns is not None:
                    self._fire_release(ns)
                continue
            evicted += self.on_evict([pod], "quota_reclaim")
        return evicted

    # --------------------------------------------------------- release waves

    def shadow_admitter(self, ns: str) -> Callable[[Pod], Optional[Status]]:
        """A gate for one quota-release wave: admitted pods charge a SHADOW
        copy of the namespace's usage (and of its cohort's pool), so
        freeing one pod slot re-admits one gated pod instead of the whole
        parked backlog (each would otherwise pass an independent headroom
        check and thrash back)."""
        self._ensure_seeded(ns)
        shadow = dict(self._usage.get(ns, {}))
        hard = self.effective_hard(ns)
        cohort = self.cohort_for(ns)
        if cohort is not None:
            ccaps, cused = self._cohort_state(cohort)
            cshadow = dict(cused)
        else:
            ccaps, cshadow = {}, {}

        def admit(pod: Pod) -> Optional[Status]:
            if hard is None or pod.meta.namespace != ns:
                return self.pre_enqueue_status(pod)
            req = pod_quota_request(pod)
            dim = self._violated(hard, shadow, req)
            cdim = (self._violated(ccaps, cshadow, req)
                    if cohort is not None else None)
            if (dim is not None and cohort is not None
                    and self._reclaim_demand.get(cohort)):
                # outstanding lender demand freezes new loans (mirror of
                # the in-cycle rule): the freed capacity is spoken for
                cdim = cdim or dim
            if dim is not None and (cohort is None or cdim is not None):
                # over its own caps and no borrowable pool headroom either
                return Status.unresolvable(
                    f'{ERR_REASON_QUOTA_EXCEEDED}: namespace "{ns}" over '
                    f'quota on {dim}').with_plugin(self.name())
            if dim is None and cdim is not None:
                # own-fit but the pool is exhausted: the cohort invariant
                # would reject it in-cycle, keep it parked
                return Status.unresolvable(
                    f'{ERR_REASON_QUOTA_EXCEEDED}: namespace "{ns}" cohort '
                    f'exhausted by loans on {cdim}').with_plugin(self.name())
            for d, v in req.items():
                shadow[d] = shadow.get(d, 0) + v
                if cohort is not None:
                    cshadow[d] = cshadow.get(d, 0) + v
            return None

        return admit

    # ------------------------------------------------------------ pre-enqueue

    def pre_enqueue_status(self, pod: Pod) -> Optional[Status]:
        st = self._fits_status(pod)
        return None if st is None else st.with_plugin(self.name())

    def pre_enqueue(self, pod: Pod) -> Status:
        st = self._fits_status(pod)
        return OK if st is None else st

    # ------------------------------------------------------------- pre-filter

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        st = self._fits_status(pod)
        return None, (OK if st is None else st)

    # ---------------------------------------------------------------- reserve

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """The authoritative charge — atomic with the assume on the
        single-threaded loop, so ledger usage never exceeds ``hard`` plus
        granted cohort headroom."""
        ns = pod.meta.namespace
        hard = self.effective_hard(ns)
        if hard is None:
            return OK
        st = self._fits_status(pod)
        if st is not None:
            return st
        self._charge(pod)
        if self.metrics is not None:
            self.metrics.quota_decisions.inc(ns, "admitted")
        return OK

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        ns = self._release(pod.key())
        if ns is not None:
            self._fire_release(ns)

    # ------------------------------------------------------------- lifecycle
    # (driven by the Scheduler's pod event hooks, like Coscheduling's)

    def pod_observed_bound(self, pod: Pod) -> None:
        """A pod bound outside this scheduler's Reserve (external binder,
        peer replica, store replay) still consumes quota."""
        if self.effective_hard(pod.meta.namespace) is None:
            return
        self._ensure_seeded(pod.meta.namespace)
        self._charge(pod)

    def pod_deleted(self, pod: Pod) -> None:
        self._rejected.discard(pod.key())
        self._drop_demand(pod.key())
        ns = self._release(pod.key())
        if ns is not None:
            self._fire_release(ns)

    def _fire_release(self, ns: str) -> None:
        if self.on_release is None:
            return
        if self.quotas_for(ns):
            self.on_release(ns)
        # freed capacity in a cohort member is borrowable pool headroom:
        # wake every OTHER member's gated pods too (the lender whose pod
        # parked on "cohort exhausted" is in a different namespace than
        # the borrower whose eviction freed the pool)
        cohort = self.cohort_for(ns)
        if cohort:
            for member in self.cohort_members(cohort):
                if member != ns and self.quotas_for(member):
                    self.on_release(member)

    # ----------------------------------------------------------- device view

    def device_quota_table(self) -> Dict[str, Tuple[List[int], List[int]]]:
        """ns -> (used, limit) int rows in QUOTA_DIM_ORDER for the device
        over-quota screen (ops/quota.py). ``limit`` is the namespace's own
        hard cap plus its cohort's CURRENT borrowable headroom, so the
        screen admits exactly what the ledger would grant at sync time.
        The rows go stale between syncs: a stale-high limit is harmless
        (commit-time Reserve stays authoritative) and a stale-low one only
        rejects-and-retries — the screen can never oversubscribe."""
        table: Dict[str, Tuple[List[int], List[int]]] = {}
        headroom_memo: Dict[str, Dict[str, int]] = {}
        for ns in list(self._index()):
            hard = self.effective_hard(ns)
            if hard is None:
                continue
            self._ensure_seeded(ns)
            used = self._usage.get(ns, {})
            cohort = self.cohort_for(ns)
            if cohort is not None:
                free = headroom_memo.get(cohort)
                if free is None:
                    free = self.cohort_headroom(cohort)
                    headroom_memo[cohort] = free
            else:
                free = {}
            used_row: List[int] = []
            limit_row: List[int] = []
            for dim in QUOTA_DIM_ORDER:
                used_row.append(min(int(used.get(dim, 0)), _NO_LIMIT))
                if dim in hard:
                    limit_row.append(min(
                        int(hard[dim]) + int(free.get(dim, 0)), _NO_LIMIT))
                else:
                    limit_row.append(_NO_LIMIT)
            table[ns] = (used_row, limit_row)
        return table

    # ----------------------------------------------------------------- debug

    def dump(self) -> dict:
        """/debug/quota body: per-namespace caps, ledger usage, weight,
        borrowing split, plus the per-cohort pool view (guaranteed / used /
        lent, outstanding loans newest-first, reclaim breaker state)."""
        out: dict = {}
        namespaces: dict = {}
        for q in list(self._quota_map().values()):
            ns = q.meta.namespace
            namespaces[ns] = {
                "hard": self.effective_hard(ns) or {},
                "used": self.usage(ns),
                "borrowed": self.borrowed(ns),
                "cohort": self.cohort_for(ns) or "",
                "weight": self.weight_for(ns),
                "charged_pods": sum(1 for _k, (n, _r) in self._charged.items()
                                    if n == ns),
            }
        out = namespaces  # legacy shape: top level is the per-ns map
        cohorts: dict = {}
        self._index()
        for cohort in self._cohort_index:
            caps, used = self._cohort_state(cohort)
            lent = {}
            for ns in self.cohort_members(cohort):
                for dim, v in self._borrowed.get(ns, {}).items():
                    lent[dim] = lent.get(dim, 0) + v
            loans = sorted(
                ((seq, key, ns) for key, (ns, _r, seq) in self._loans.items()
                 if self.cohort_for(ns) == cohort),
                reverse=True)
            cohorts[cohort] = {
                "members": self.cohort_members(cohort),
                "guaranteed": caps,
                "used": used,
                "lent": lent,
                "headroom": {dim: max(cap - used.get(dim, 0), 0)
                             for dim, cap in caps.items()},
                "loans": [{"pod": key, "namespace": ns, "seq": seq}
                          for seq, key, ns in loans],
                "pending_demand": len(self._reclaim_demand.get(cohort, {})),
                "reclaim_breaker": self.reclaim_breaker.dump(),
                "reclaim_suspended": self.reclaim_suspended,
            }
        if cohorts:
            out["_cohorts"] = cohorts
        return out
