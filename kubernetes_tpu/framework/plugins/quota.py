"""QuotaAdmission — scheduler-side multi-tenant admission over
SchedulingQuota (scheduling.x-k8s.io/v1alpha1).

The Kueue/ElasticQuota analog collapsed onto the scheduling framework: a
namespace's SchedulingQuota caps what the scheduler may *admit* (assume +
bind), not what the apiserver may store — an over-quota tenant's pods exist
but park GATED in the unschedulable pool, where they cost no scheduling
cycles and no device batch slots. The plugin is a PreEnqueue/PreFilter pair
plus a Reserve-time charge:

  * PreEnqueue: the queue-admission gate. Every transition toward activeQ
    re-runs it, so a reactivation wave (assigned-pod delete, gang teardown,
    unschedulable-timeout flush) can never flood the active queue with pods
    whose namespace is still over quota (the reactivation-thrash guard).
  * PreFilter: the in-cycle re-check (usage may have grown between enqueue
    and pop — a batched frontend pops hundreds of pods per cycle).
    Over-quota is UnschedulableAndUnresolvable: evicting node-capacity
    victims cannot raise a namespace's quota, so no preemption dry-run
    fans out.
  * Reserve: the authoritative charge, atomically with the assume on the
    single-threaded scheduling loop — usage can never oversubscribe ``hard``
    because the charge IS the admission. Unreserve releases.

Release (unreserve, bound-pod delete) fires a targeted quota-release queue
move for the namespace: only gated/quota-failed pods whose request now fits
(tracked against a shadow ledger, so one freed slot admits one pod, not the
whole parked backlog) re-enter the queue.

The ledger is in-memory and seeded per namespace from the store's bound
pods on first touch, so a restarted scheduler resumes with true usage.

Fair share: the queue's deficit-round-robin layer asks ``weight_for(ns)``
— namespaces with a SchedulingQuota are tenants served in proportion to
``spec.weight``; namespaces without one share the default bucket.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from ...api.types import (
    Pod,
    QUOTA_CLAIMS,
    QUOTA_CPU,
    QUOTA_MEMORY,
    QUOTA_PODS,
    SchedulingQuota,
)
from ...api import resource as resource_api
from ..interface import (
    CycleState,
    OK,
    PreEnqueuePlugin,
    PreFilterPlugin,
    PreFilterResult,
    ReservePlugin,
    Status,
)
from ..types import ALL, ClusterEvent, SCHEDULING_QUOTA
from . import names

ERR_REASON_QUOTA_EXCEEDED = "QuotaExceeded"


def pod_quota_request(pod: Pod) -> Dict[str, int]:
    """The SchedulingQuota dimensions one pod consumes (canonical ints,
    api/resource.py): max(containers)+init+overhead cpu/memory via the
    cached resource_request(), one pod slot, and its resource.k8s.io claim
    count."""
    req = pod.resource_request()
    return {
        QUOTA_PODS: 1,
        QUOTA_CPU: req.get(resource_api.CPU, 0),
        QUOTA_MEMORY: req.get(resource_api.MEMORY, 0),
        QUOTA_CLAIMS: len(pod.spec.resource_claims),
    }


def quota_precheck_status(fwk, pod: Pod) -> Optional[Status]:
    """Host-side stand-in for QuotaAdmission's PreFilter on the batched
    paths (the compiled device program does not model namespace quota):
    returns the non-success Status the pod should fail with before
    dispatch, or None when it may ride the batch."""
    plugin = fwk.plugin(names.QUOTA_ADMISSION)
    if plugin is None:
        return None
    _r, st = plugin.pre_filter(CycleState(), pod)
    return None if st.is_success() else st


class QuotaAdmission(PreEnqueuePlugin, PreFilterPlugin, ReservePlugin):
    def __init__(self, client=None, metrics=None):
        self.client = client
        self.metrics = metrics
        # ns -> dim -> charged usage (the authoritative scheduler-side ledger)
        self._usage: Dict[str, Dict[str, int]] = {}
        # pod key -> (ns, charge vector): exactly-once charge accounting
        # across Reserve, external-bind observation, and release paths
        self._charged: Dict[str, Tuple[str, Dict[str, int]]] = {}
        self._seeded: Set[str] = set()
        # pods already counted as a "rejected" admission decision — the
        # decisions counter records pod-level outcomes, and _fits_status
        # re-runs on every PreEnqueue wave / PreFilter / release probe
        self._rejected: Set[str] = set()
        # ns -> [SchedulingQuota] index + per-ns (hard, weight) memo over
        # the cluster quota map: quotas_for sits on the queue-push and DRR
        # rotation hot paths, where an O(all-quotas) scan per call is not
        # acceptable. Invalidated by SchedulingQuota store events (and by
        # quota-map size changes, for event-less clients).
        self._quota_index: Optional[Dict[str, List[SchedulingQuota]]] = None
        self._index_len = -1
        self._derived: Dict[str, Tuple[Optional[Dict[str, int]],
                                       Optional[float]]] = {}
        if client is not None and hasattr(client, "add_event_handler"):
            client.add_event_handler(
                "SchedulingQuota", lambda _e, _o, _n: self.quotas_changed())
        # targeted quota-release queue move, wired by the Scheduler:
        # fn(namespace) -> pods moved
        self.on_release: Optional[Callable[[str], int]] = None

    def name(self) -> str:
        return names.QUOTA_ADMISSION

    def events_to_register(self) -> List[ClusterEvent]:
        # the quota-release move and user edits to SchedulingQuota objects
        # (raising a cap must wake the namespace's gated pods)
        return [ClusterEvent(SCHEDULING_QUOTA, ALL, "SchedulingQuotaChange")]

    # ------------------------------------------------------------- quota view

    def _quota_map(self) -> Dict[str, SchedulingQuota]:
        if self.client is None:
            return {}
        m = getattr(self.client, "scheduling_quotas", None)
        if m is not None:
            return m
        try:
            return self.client.snapshot_map("SchedulingQuota")
        except Exception:  # noqa: BLE001 — clients without the kind: no quota
            return {}

    def quotas_changed(self) -> None:
        """Invalidate the ns index + derived memos (SchedulingQuota event)."""
        self._quota_index = None
        self._derived.clear()

    def _index(self) -> Dict[str, List[SchedulingQuota]]:
        m = self._quota_map()
        if self._quota_index is None or len(m) != self._index_len:
            idx: Dict[str, List[SchedulingQuota]] = {}
            for q in m.values():
                idx.setdefault(q.meta.namespace, []).append(q)
            self._quota_index = idx
            self._index_len = len(m)
            self._derived.clear()
        return self._quota_index

    def quotas_for(self, ns: str) -> List[SchedulingQuota]:
        return self._index().get(ns, [])

    def _derived_for(self, ns: str) -> Tuple[Optional[Dict[str, int]],
                                             Optional[float]]:
        """(effective hard caps, fair-share weight) for a namespace, memoized
        until the quota map changes — weight_for runs on every queue push and
        every DRR rotation visit."""
        self._index()  # revalidate (clears _derived on rebuild)
        d = self._derived.get(ns)
        if d is None:
            quotas = self.quotas_for(ns)
            if not quotas:
                d = (None, None)
            else:
                hard: Dict[str, int] = {}
                for q in quotas:
                    for dim, cap in q.hard.items():
                        hard[dim] = min(hard[dim], cap) if dim in hard else cap
                d = (hard, float(max(q.weight for q in quotas)))
            self._derived[ns] = d
        return d

    def effective_hard(self, ns: str) -> Optional[Dict[str, int]]:
        """Per-dimension caps for a namespace (min across its quota objects;
        every matching quota must admit, exactly like core ResourceQuota).
        None when the namespace has no SchedulingQuota — unlimited."""
        return self._derived_for(ns)[0]

    def weight_for(self, ns: str) -> Optional[float]:
        """Fair-share weight for the queue's DRR layer: max across the
        namespace's quota objects; None = not a tenant (default bucket)."""
        return self._derived_for(ns)[1]

    def share_ledger(self, other: "QuotaAdmission") -> None:
        """Alias this instance's ledger state onto ``other``'s. Quota usage
        is cluster-level per-namespace state: in a multi-profile scheduler
        every profile's QuotaAdmission instance must charge and read ONE
        ledger, or charges split across per-profile ledgers and the release
        wave / fair-share weights read one that undercounts usage."""
        self._usage = other._usage
        self._charged = other._charged
        self._seeded = other._seeded
        self._rejected = other._rejected

    # ---------------------------------------------------------------- ledger

    def _ensure_seeded(self, ns: str) -> None:
        """First touch of a namespace: charge every already-bound pod so a
        restarted scheduler resumes with true usage (the ledger analog of
        Coscheduling's bound-count seed)."""
        if ns in self._seeded:
            return
        self._seeded.add(ns)
        pods = getattr(self.client, "pods", None) if self.client else None
        if pods is None:
            return
        for pod in list(pods.values()):
            if pod.meta.namespace == ns and pod.spec.node_name:
                self._charge(pod)

    def usage(self, ns: str) -> Dict[str, int]:
        self._ensure_seeded(ns)
        return dict(self._usage.get(ns, {}))

    def _violated(self, hard: Dict[str, int], used: Dict[str, int],
                  req: Dict[str, int]) -> Optional[str]:
        for dim, cap in hard.items():
            if used.get(dim, 0) + req.get(dim, 0) > cap:
                return dim
        return None

    def _fits_status(self, pod: Pod) -> Optional[Status]:
        """None when the pod fits its namespace's quota headroom (or is
        already charged / unquota'd); else the typed QuotaExceeded status."""
        ns = pod.meta.namespace
        hard = self.effective_hard(ns)
        if hard is None or pod.key() in self._charged:
            return None
        self._ensure_seeded(ns)
        dim = self._violated(hard, self._usage.get(ns, {}),
                             pod_quota_request(pod))
        if dim is None:
            # headroom appeared: a later over-quota verdict is a NEW decision
            self._rejected.discard(pod.key())
            return None
        # pod-level decision counting: _fits_status re-runs on every
        # PreEnqueue wave, PreFilter and release probe — only the first
        # rejection of an over-quota episode is an admission outcome
        if self.metrics is not None and pod.key() not in self._rejected:
            self._rejected.add(pod.key())
            self.metrics.quota_decisions.inc(ns, "rejected")
        # Unresolvable: node-capacity preemption cannot raise a namespace
        # quota, so the failure must not fan out a preemption dry-run. The
        # quota-release event (not a node event) wakes the pod.
        return Status.unresolvable(
            f'{ERR_REASON_QUOTA_EXCEEDED}: namespace "{ns}" over quota '
            f'on {dim}')

    def _charge(self, pod: Pod) -> bool:
        key = pod.key()
        if key in self._charged:
            return False
        ns = pod.meta.namespace
        req = pod_quota_request(pod)
        used = self._usage.setdefault(ns, {})
        for dim, v in req.items():
            used[dim] = used.get(dim, 0) + v
        self._charged[key] = (ns, req)
        self._rejected.discard(key)
        self._sync_metrics(ns)
        return True

    def _release(self, pod_key: str) -> Optional[str]:
        entry = self._charged.pop(pod_key, None)
        if entry is None:
            return None
        ns, req = entry
        used = self._usage.setdefault(ns, {})
        for dim, v in req.items():
            used[dim] = max(used.get(dim, 0) - v, 0)
        self._sync_metrics(ns)
        return ns

    def _sync_metrics(self, ns: str) -> None:
        if self.metrics is None:
            return
        used = self._usage.get(ns, {})
        for dim in (QUOTA_PODS, QUOTA_CPU, QUOTA_MEMORY, QUOTA_CLAIMS):
            self.metrics.quota_usage.set(ns, dim, value=used.get(dim, 0))

    def shadow_admitter(self, ns: str) -> Callable[[Pod], Optional[Status]]:
        """A gate for one quota-release wave: admitted pods charge a SHADOW
        copy of the namespace's usage, so freeing one pod slot re-admits one
        gated pod instead of the whole parked backlog (each would otherwise
        pass an independent headroom check and thrash back)."""
        self._ensure_seeded(ns)
        shadow = dict(self._usage.get(ns, {}))
        hard = self.effective_hard(ns)

        def admit(pod: Pod) -> Optional[Status]:
            if hard is None or pod.meta.namespace != ns:
                return self.pre_enqueue_status(pod)
            req = pod_quota_request(pod)
            dim = self._violated(hard, shadow, req)
            if dim is not None:
                return Status.unresolvable(
                    f'{ERR_REASON_QUOTA_EXCEEDED}: namespace "{ns}" over '
                    f'quota on {dim}').with_plugin(self.name())
            for d, v in req.items():
                shadow[d] = shadow.get(d, 0) + v
            return None

        return admit

    # ------------------------------------------------------------ pre-enqueue

    def pre_enqueue_status(self, pod: Pod) -> Optional[Status]:
        st = self._fits_status(pod)
        return None if st is None else st.with_plugin(self.name())

    def pre_enqueue(self, pod: Pod) -> Status:
        st = self._fits_status(pod)
        return OK if st is None else st

    # ------------------------------------------------------------- pre-filter

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        st = self._fits_status(pod)
        return None, (OK if st is None else st)

    # ---------------------------------------------------------------- reserve

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """The authoritative charge — atomic with the assume on the
        single-threaded loop, so ledger usage never exceeds ``hard``."""
        ns = pod.meta.namespace
        hard = self.effective_hard(ns)
        if hard is None:
            return OK
        st = self._fits_status(pod)
        if st is not None:
            return st
        self._charge(pod)
        if self.metrics is not None:
            self.metrics.quota_decisions.inc(ns, "admitted")
        return OK

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        ns = self._release(pod.key())
        if ns is not None:
            self._fire_release(ns)

    # ------------------------------------------------------------- lifecycle
    # (driven by the Scheduler's pod event hooks, like Coscheduling's)

    def pod_observed_bound(self, pod: Pod) -> None:
        """A pod bound outside this scheduler's Reserve (external binder,
        peer replica, store replay) still consumes quota."""
        if self.effective_hard(pod.meta.namespace) is None:
            return
        self._ensure_seeded(pod.meta.namespace)
        self._charge(pod)

    def pod_deleted(self, pod: Pod) -> None:
        self._rejected.discard(pod.key())
        ns = self._release(pod.key())
        if ns is not None:
            self._fire_release(ns)

    def _fire_release(self, ns: str) -> None:
        if self.on_release is not None and self.quotas_for(ns):
            self.on_release(ns)

    # ----------------------------------------------------------------- debug

    def dump(self) -> dict:
        """/debug/quota body: per-namespace caps, ledger usage, weight."""
        out = {}
        for q in list(self._quota_map().values()):
            ns = q.meta.namespace
            out[ns] = {
                "hard": self.effective_hard(ns) or {},
                "used": self.usage(ns),
                "weight": self.weight_for(ns),
                "charged_pods": sum(1 for _k, (n, _r) in self._charged.items()
                                    if n == ns),
            }
        return out
