"""SelectorSpread: spread pods of the same service/controller across nodes
and zones (plugins/selectorspread/selector_spread.go; non-default, legacy —
superseded by PodTopologySpread but kept for capability parity).

Raw Score(node) = number of existing pods on the node matching the selector
deduced from the pod's services + controller owner (selector_spread.go:84).
NormalizeScore inverts per node and, when zone labels exist, blends in a
zone-level inverse count with weight 2/3 (selector_spread.go:55,112-172).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ...api.types import LabelSelector, Pod, get_zone_key
from ..interface import (
    MAX_NODE_SCORE,
    CycleState,
    NodeScore,
    OK,
    PreScorePlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
)
from ..types import NodeInfo
from . import names

ZONE_WEIGHTING = 2.0 / 3.0  # selector_spread.go:55


def default_selector(pod: Pod, store) -> List[LabelSelector]:
    """helper/spread.go DefaultSelector: union of label requirements from
    services selecting the pod plus the pod's controller owner (RC/RS/SS).
    Returned as a list of selectors that must ALL match (requirement AND)."""
    sels: List[LabelSelector] = []
    for svc in store.list_services(pod.meta.namespace):
        if svc.selector and all(
            pod.meta.labels.get(k) == v for k, v in svc.selector.items()
        ):
            sels.append(LabelSelector(match_labels=dict(svc.selector)))
    owner = pod.meta.controller_of()
    if owner is not None:
        key = f"{pod.meta.namespace}/{owner.name}"
        if owner.kind == "ReplicationController":
            rc = store.get_replication_controller(key)
            if rc is not None and rc.selector:
                sels.append(LabelSelector(match_labels=dict(rc.selector)))
        elif owner.kind == "ReplicaSet":
            rs = store.get_replica_set(key)
            if rs is not None and rs.selector is not None:
                sels.append(rs.selector)
        elif owner.kind == "StatefulSet":
            ss = store.get_stateful_set(key)
            if ss is not None and ss.selector is not None:
                sels.append(ss.selector)
    return sels


class SelectorSpread(PreScorePlugin, ScorePlugin, ScoreExtensions):
    """PreScore + Score + NormalizeScore (selector_spread.go:35)."""

    STATE_KEY = "PreScore" + names.SELECTOR_SPREAD

    def __init__(self, store=None, snapshot_fn=None):
        self._store = store
        self._snapshot_fn = snapshot_fn  # () -> List[NodeInfo] (all nodes)

    def name(self) -> str:
        return names.SELECTOR_SPREAD

    @staticmethod
    def _skip(pod: Pod) -> bool:
        # explicit topologySpreadConstraints supersede this plugin
        # (selector_spread.go:76 skipSelectorSpread)
        return bool(pod.spec.topology_spread_constraints)

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        if self._skip(pod):
            return OK
        state.write(self.STATE_KEY, default_selector(pod, self._store))
        return OK

    def score(self, state: CycleState, pod: Pod, node_name: str):
        raise NotImplementedError  # runtime calls score_node with NodeInfo

    def score_node(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        if self._skip(pod):
            return 0, OK
        selectors: List[LabelSelector] = state.read(self.STATE_KEY)
        if not selectors:
            return 0, OK
        count = 0
        for p in node_info.pods:
            if (
                p.meta.namespace == pod.meta.namespace
                and p.meta.deletion_timestamp == 0.0
                and all(s.matches(p.meta.labels) for s in selectors)
            ):
                count += 1
        return count, OK

    def score_extensions(self):
        return self

    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> Status:
        if self._skip(pod):
            return OK
        by_name: Dict[str, NodeInfo] = {
            ni.node.meta.name: ni
            for ni in (self._snapshot_fn() if self._snapshot_fn else [])
            if ni.node is not None
        }
        counts_by_zone: Dict[str, int] = {}
        max_by_node = 0
        zone_of: Dict[str, str] = {}
        for ns in scores:
            max_by_node = max(max_by_node, ns.score)
            ni = by_name.get(ns.name)
            zone = get_zone_key(ni.node) if ni is not None else ""
            zone_of[ns.name] = zone
            if zone:
                counts_by_zone[zone] = counts_by_zone.get(zone, 0) + ns.score
        max_by_zone = max(counts_by_zone.values(), default=0)
        have_zones = bool(counts_by_zone)
        for ns in scores:
            f = float(MAX_NODE_SCORE)
            if max_by_node > 0:
                f = MAX_NODE_SCORE * (max_by_node - ns.score) / float(max_by_node)
            if have_zones:
                zone = zone_of[ns.name]
                if zone:
                    zscore = float(MAX_NODE_SCORE)
                    if max_by_zone > 0:
                        zscore = MAX_NODE_SCORE * (max_by_zone - counts_by_zone[zone]) / float(max_by_zone)
                    f = f * (1.0 - ZONE_WEIGHTING) + ZONE_WEIGHTING * zscore
            ns.score = int(f)
        return OK
