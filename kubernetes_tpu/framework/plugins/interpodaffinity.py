"""InterPodAffinity plugin oracle (interpodaffinity/{filtering,scoring}.go).

PreFilter builds three topology-pair count maps by scanning existing pods
against pre-parsed AffinityTerms (filtering.go:86-135):
  existing_anti:  existing pods' required anti-affinity terms matching the
                  incoming pod, bucketed by the existing pod's node domain;
  affinity:       incoming pod's required affinity terms matching existing pods;
  anti_affinity:  incoming pod's required anti-affinity terms matching existing pods.
Filter is then four boolean checks per node (:308-:368), including the
first-pod-in-cluster special case for self-matching affinity.

Score: preferred (anti-)affinity of the incoming pod against existing pods,
plus symmetric terms of existing pods toward the incoming pod (required
affinity terms weighted by hard_pod_affinity_weight, default 1), bucketed per
topology pair; NormalizeScore maps [min,max] (floored/ceiled at 0) to [0,100].
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

from ...api.types import MATCH_NOTHING, LabelSelector, Pod, PodAffinityTerm
from ..interface import (
    CycleState,
    FilterPlugin,
    NodeScore,
    OK,
    PreFilterExtensions,
    PreFilterPlugin,
    PreFilterResult,
    PreScorePlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
    MAX_NODE_SCORE,
)
from ..types import ADD, DELETE, NODE, POD, UPDATE_NODE_LABEL, ClusterEvent, NodeInfo
from . import names

ERR_EXISTING_ANTI = "node(s) didn't satisfy existing pods anti-affinity rules"
ERR_AFFINITY = "node(s) didn't match pod affinity rules"
ERR_ANTI_AFFINITY = "node(s) didn't match pod anti-affinity rules"

NsLabelsFn = Callable[[str], Dict[str, str]]


@dataclass(frozen=True)
class AffinityTerm:
    """Pre-parsed term (framework/types.go:193 newAffinityTerm)."""

    selector: LabelSelector
    topology_key: str
    namespaces: FrozenSet[str]
    namespace_selector: Optional[LabelSelector]
    weight: int = 0

    @classmethod
    def build(cls, term: PodAffinityTerm, default_ns: str, weight: int = 0) -> "AffinityTerm":
        ns = frozenset(term.namespaces) if term.namespaces else (
            frozenset() if term.namespace_selector is not None else frozenset({default_ns})
        )
        return cls(
            selector=term.label_selector if term.label_selector is not None else MATCH_NOTHING,
            topology_key=term.topology_key,
            namespaces=ns,
            namespace_selector=term.namespace_selector,
            weight=weight,
        )

    def matches(self, pod: Pod, ns_labels_fn: NsLabelsFn) -> bool:
        if pod.meta.namespace in self.namespaces:
            ns_ok = True
        elif self.namespace_selector is not None:
            ns_ok = self.namespace_selector.matches(ns_labels_fn(pod.meta.namespace))
        else:
            ns_ok = False
        return ns_ok and self.selector.matches(pod.meta.labels)


def _parsed_terms(pod: Pod):
    """Parse-once-per-pod term cache (the reference parses at PodInfo build,
    framework/types.go:193; here terms are memoized on the Pod instance)."""
    cached = pod.__dict__.get("_ipa_terms")
    if cached is not None:
        return cached
    a = pod.spec.affinity
    req_aff = [AffinityTerm.build(t, pod.meta.namespace) for t in a.pod_affinity.required] if a and a.pod_affinity else []
    req_anti = [AffinityTerm.build(t, pod.meta.namespace) for t in a.pod_anti_affinity.required] if a and a.pod_anti_affinity else []
    pref_aff = [AffinityTerm.build(w.term, pod.meta.namespace, w.weight) for w in a.pod_affinity.preferred] if a and a.pod_affinity else []
    pref_anti = [AffinityTerm.build(w.term, pod.meta.namespace, w.weight) for w in a.pod_anti_affinity.preferred] if a and a.pod_anti_affinity else []
    cached = (req_aff, req_anti, pref_aff, pref_anti)
    pod.__dict__["_ipa_terms"] = cached
    return cached


def required_affinity_terms(pod: Pod) -> List[AffinityTerm]:
    return _parsed_terms(pod)[0]


def required_anti_affinity_terms(pod: Pod) -> List[AffinityTerm]:
    return _parsed_terms(pod)[1]


def preferred_affinity_terms(pod: Pod) -> List[AffinityTerm]:
    return _parsed_terms(pod)[2]


def preferred_anti_affinity_terms(pod: Pod) -> List[AffinityTerm]:
    return _parsed_terms(pod)[3]


TopoPair = Tuple[str, str]


@dataclass
class _PreFilterState:
    affinity_terms: List[AffinityTerm] = field(default_factory=list)
    anti_affinity_terms: List[AffinityTerm] = field(default_factory=list)
    existing_anti: Dict[TopoPair, int] = field(default_factory=dict)
    affinity: Dict[TopoPair, int] = field(default_factory=dict)
    anti_affinity: Dict[TopoPair, int] = field(default_factory=dict)

    def clone(self) -> "_PreFilterState":
        s = _PreFilterState(list(self.affinity_terms), list(self.anti_affinity_terms))
        s.existing_anti = dict(self.existing_anti)
        s.affinity = dict(self.affinity)
        s.anti_affinity = dict(self.anti_affinity)
        return s


def _bump(m: Dict[TopoPair, int], pair: TopoPair, delta: int) -> None:
    v = m.get(pair, 0) + delta
    if v <= 0:
        m.pop(pair, None)
    else:
        m[pair] = v


class InterPodAffinity(PreFilterPlugin, FilterPlugin, PreScorePlugin, ScorePlugin, ScoreExtensions, PreFilterExtensions):
    PREFILTER_KEY = "PreFilter/InterPodAffinity"
    PRESCORE_KEY = "PreScore/InterPodAffinity"

    def __init__(self, snapshot_fn=None, ns_labels_fn: Optional[NsLabelsFn] = None,
                 hard_pod_affinity_weight: int = 1, ignore_preferred_terms_of_existing_pods: bool = False):
        self.snapshot_fn = snapshot_fn  # () -> List[NodeInfo] (all nodes)
        self.ns_labels_fn: NsLabelsFn = ns_labels_fn or (lambda ns: {})
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self.ignore_preferred = ignore_preferred_terms_of_existing_pods

    def name(self) -> str:
        return names.INTER_POD_AFFINITY

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(POD, ADD | DELETE), ClusterEvent(NODE, ADD | UPDATE_NODE_LABEL)]

    # ------------------------------------------------------------------ filter

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        s = _PreFilterState(
            affinity_terms=required_affinity_terms(pod),
            anti_affinity_terms=required_anti_affinity_terms(pod),
        )
        all_nodes: List[NodeInfo] = self.snapshot_fn() if self.snapshot_fn else []
        need_scan = s.affinity_terms or s.anti_affinity_terms
        for ni in all_nodes:
            node = ni.node
            if node is None:
                continue
            # existing pods' required anti-affinity vs incoming pod — only pods
            # with required anti-affinity matter (snapshot pruned list).
            for ep in ni.pods_with_required_anti_affinity:
                for term in required_anti_affinity_terms(ep):
                    if term.topology_key in node.meta.labels and term.matches(pod, self.ns_labels_fn):
                        _bump(s.existing_anti, (term.topology_key, node.meta.labels[term.topology_key]), 1)
            if not need_scan:
                continue
            for ep in ni.pods:
                for term in s.affinity_terms:
                    if term.topology_key in node.meta.labels and term.matches(ep, self.ns_labels_fn):
                        _bump(s.affinity, (term.topology_key, node.meta.labels[term.topology_key]), 1)
                for term in s.anti_affinity_terms:
                    if term.topology_key in node.meta.labels and term.matches(ep, self.ns_labels_fn):
                        _bump(s.anti_affinity, (term.topology_key, node.meta.labels[term.topology_key]), 1)
        state.write(self.PREFILTER_KEY, s)
        return None, OK

    def pre_filter_extensions(self):
        return self

    def _update_for_pod(self, s: _PreFilterState, incoming: Pod, other: Pod, node, delta: int) -> None:
        for term in required_anti_affinity_terms(other):
            if term.topology_key in node.meta.labels and term.matches(incoming, self.ns_labels_fn):
                _bump(s.existing_anti, (term.topology_key, node.meta.labels[term.topology_key]), delta)
        for term in s.affinity_terms:
            if term.topology_key in node.meta.labels and term.matches(other, self.ns_labels_fn):
                _bump(s.affinity, (term.topology_key, node.meta.labels[term.topology_key]), delta)
        for term in s.anti_affinity_terms:
            if term.topology_key in node.meta.labels and term.matches(other, self.ns_labels_fn):
                _bump(s.anti_affinity, (term.topology_key, node.meta.labels[term.topology_key]), delta)

    def add_pod(self, state: CycleState, pod: Pod, to_add: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState = state.read(self.PREFILTER_KEY)
        if node_info.node is not None:
            self._update_for_pod(s, pod, to_add, node_info.node, 1)
        return OK

    def remove_pod(self, state: CycleState, pod: Pod, to_remove: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState = state.read(self.PREFILTER_KEY)
        if node_info.node is not None:
            self._update_for_pod(s, pod, to_remove, node_info.node, -1)
        return OK

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState = state.read(self.PREFILTER_KEY)
        node = node_info.node
        labels = node.meta.labels

        # check order and codes per filtering.go:377-387:
        # 1. incoming pod's affinity (satisfyPodAffinity + first-pod case) — Unresolvable
        if s.affinity_terms:
            pods_exist = True
            for term in s.affinity_terms:
                tv = labels.get(term.topology_key)
                if tv is None:
                    return Status.unresolvable(ERR_AFFINITY)
                if s.affinity.get((term.topology_key, tv), 0) <= 0:
                    pods_exist = False
            if not pods_exist:
                # allowed only as the first pod in the cluster matching its own affinity
                first_pod_ok = not s.affinity and all(
                    t.matches(pod, self.ns_labels_fn) for t in s.affinity_terms
                )
                if not first_pod_ok:
                    return Status.unresolvable(ERR_AFFINITY)

        # 2. incoming pod's anti-affinity (satisfyPodAntiAffinity) — Unschedulable
        for term in s.anti_affinity_terms:
            tv = labels.get(term.topology_key)
            if tv is not None and s.anti_affinity.get((term.topology_key, tv), 0) > 0:
                return Status.unschedulable(ERR_ANTI_AFFINITY)

        # 3. existing pods' anti-affinity — Unschedulable
        for (tk, tv), cnt in s.existing_anti.items():
            if cnt > 0 and labels.get(tk) == tv:
                return Status.unschedulable(ERR_EXISTING_ANTI)
        return OK

    # ------------------------------------------------------------------ score

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        pref = preferred_affinity_terms(pod)
        pref_anti = preferred_anti_affinity_terms(pod)
        topology_score: Dict[TopoPair, int] = {}
        all_nodes: List[NodeInfo] = self.snapshot_fn() if self.snapshot_fn else []
        scan_all = bool(pref or pref_anti)
        for ni in all_nodes:
            node = ni.node
            if node is None:
                continue
            labels = node.meta.labels
            existing = ni.pods if scan_all else ni.pods_with_affinity
            for ep in existing:
                # incoming pod's preferred terms vs existing pod
                for term in pref:
                    tv = labels.get(term.topology_key)
                    if tv is not None and term.matches(ep, self.ns_labels_fn):
                        _add_score(topology_score, (term.topology_key, tv), term.weight)
                for term in pref_anti:
                    tv = labels.get(term.topology_key)
                    if tv is not None and term.matches(ep, self.ns_labels_fn):
                        _add_score(topology_score, (term.topology_key, tv), -term.weight)
                # symmetric: existing pod's terms vs incoming pod
                if self.hard_pod_affinity_weight > 0:
                    for term in required_affinity_terms(ep):
                        tv = labels.get(term.topology_key)
                        if tv is not None and term.matches(pod, self.ns_labels_fn):
                            _add_score(topology_score, (term.topology_key, tv), self.hard_pod_affinity_weight)
                if not self.ignore_preferred:
                    for term in preferred_affinity_terms(ep):
                        tv = labels.get(term.topology_key)
                        if tv is not None and term.matches(pod, self.ns_labels_fn):
                            _add_score(topology_score, (term.topology_key, tv), term.weight)
                    for term in preferred_anti_affinity_terms(ep):
                        tv = labels.get(term.topology_key)
                        if tv is not None and term.matches(pod, self.ns_labels_fn):
                            _add_score(topology_score, (term.topology_key, tv), -term.weight)
        state.write(self.PRESCORE_KEY, topology_score)
        return OK

    def score_node(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        topology_score: Dict[TopoPair, int] = state.read(self.PRESCORE_KEY)
        labels = node_info.node.meta.labels
        total = 0
        for (tk, tv), w in topology_score.items():
            if labels.get(tk) == tv:
                total += w
        return total, OK

    def score(self, state: CycleState, pod: Pod, node_name: str):
        raise NotImplementedError

    def score_extensions(self):
        return self

    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> Status:
        # scoring.go NormalizeScore: min/max floored/ceiled at 0
        max_count = max([s.score for s in scores] + [0])
        min_count = min([s.score for s in scores] + [0])
        diff = max_count - min_count
        for s in scores:
            s.score = int(MAX_NODE_SCORE * (s.score - min_count) / diff) if diff > 0 else 0
        return OK


def _add_score(m: Dict[TopoPair, int], pair: TopoPair, w: int) -> None:
    m[pair] = m.get(pair, 0) + w
