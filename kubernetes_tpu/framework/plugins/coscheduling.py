"""Coscheduling — gang (all-or-nothing) scheduling over PodGroup.

Analog of the scheduler-plugins Coscheduling plugin (sigs.k8s.io/
scheduler-plugins pkg/coscheduling): pods join a gang via the
``scheduling.x-k8s.io/pod-group`` label; the plugin

  * QueueSort: orders by priority desc, then the gang's first-seen queue
    timestamp, then group key — so members of one gang sort ADJACENTLY and
    drain into the same micro-batch on the TPU path (coscheduling.go Less);
  * PreFilter: fast-fails a member when the cluster holds fewer than
    ``minMember`` total siblings (no point parking resources a gang can
    never complete), and while the group sits in rejection backoff (the
    lastDeniedPG cache — the starvation guard that keeps a hopeless 32-pod
    gang from parking whole-node assumes every cycle);
  * Permit: parks members (WAIT + the group's scheduleTimeoutSeconds) until
    ``minMember`` of them hold a node (waiting + already bound + self), then
    releases the entire gang through the waiting-pods handle;
  * Unreserve: any member's post-Reserve failure rejects every waiting
    sibling — a gang fails wholesale, never in part;
  * PostBind: maintains PodGroup status (scheduled count, phase Running at
    quorum) — the status write fires a PodGroup cluster event that
    reactivates parked siblings.

The batched backends share the same machinery: gang members commit through
``assume_and_bind`` (so Permit parks/releases identically), and the
whole-gang reject on the device path calls ``reject_gang`` here.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ...api.types import (
    POD_GROUP_LABEL,
    POD_GROUP_PENDING,
    POD_GROUP_RUNNING,
    POD_GROUP_SCHEDULING,
    Pod,
    PodGroup,
)
from ..interface import (
    CycleState,
    OK,
    PermitPlugin,
    PostBindPlugin,
    PreFilterPlugin,
    PreFilterResult,
    QueueSortPlugin,
    ReservePlugin,
    Status,
    WAIT,
)
from ..types import ADD, ALL, ClusterEvent, POD, POD_GROUP
from . import names

ERR_REASON_MISSING_GROUP = "pod group not found"
ERR_REASON_TOO_FEW_MEMBERS = "fewer than minMember sibling pods exist"
ERR_REASON_GANG_BACKOFF = "pod group is in rejection backoff"


def pod_group_name(pod: Pod) -> Optional[str]:
    return pod.meta.labels.get(POD_GROUP_LABEL) or None


def pod_group_key(pod: Pod) -> Optional[str]:
    """``namespace/name`` PodGroup key for a gang member, else None."""
    name = pod.meta.labels.get(POD_GROUP_LABEL)
    if not name:
        return None
    return f"{pod.meta.namespace}/{name}"


def gang_precheck_status(fwk, pod: Pod) -> Optional[Status]:
    """Host-side stand-in for Coscheduling's PreFilter on the batched paths
    (the compiled device program does not model gang quorum or rejection
    backoff): returns the non-success Status a gang member should fail with
    before dispatch, or None when the pod may ride the batch."""
    gkey = pod_group_key(pod)
    if gkey is None:
        return None
    plugin = fwk.plugin(names.COSCHEDULING)
    if plugin is None:
        return None
    _r, st = plugin.pre_filter(CycleState(), pod)
    return None if st.is_success() else st


class Coscheduling(QueueSortPlugin, PreFilterPlugin, PermitPlugin,
                   ReservePlugin, PostBindPlugin):
    STATE_KEY = "PreFilter/Coscheduling"

    # plugin-arg defaults (registry): the Permit park timeout when the
    # PodGroup does not name one, and how long a rejected group fast-fails
    # at PreFilter before its members may park resources again
    DEFAULT_PERMIT_TIMEOUT_S = 60.0
    DEFAULT_GANG_BACKOFF_S = 5.0

    def __init__(self, client=None, metrics=None, waiting=None, now_fn=None,
                 permit_timeout_s: float = DEFAULT_PERMIT_TIMEOUT_S,
                 gang_backoff_s: float = DEFAULT_GANG_BACKOFF_S):
        import time

        self.client = client
        self.metrics = metrics
        self.waiting = waiting  # scheduler WaitingPods handle (may be None)
        self.now_fn = now_fn or time.monotonic
        self.permit_timeout_s = permit_timeout_s
        self.gang_backoff_s = gang_backoff_s
        # gang first-seen queue timestamp: members share one sort key so a
        # gang drains contiguously; dropped when the group reaches Running
        self._group_ts: Dict[str, float] = {}
        # gkey -> bound-member count (seeded lazily from the store so a
        # restarted scheduler resumes mid-gang; advanced at PostBind)
        self._bound: Dict[str, int] = {}
        # gkey -> first-member park time (gang wait-duration clock)
        self._first_wait: Dict[str, float] = {}
        # gkey -> denial expiry (lastDeniedPG cache)
        self._denied: Dict[str, float] = {}
        # reentrancy guard: reject_gang cascades through unreserve
        self._rejecting: Set[str] = set()

    def name(self) -> str:
        return names.COSCHEDULING

    def events_to_register(self) -> List[ClusterEvent]:
        # PodGroup churn (creation, the PostBind status writes) and new pod
        # arrivals (a missing sibling appearing) must reactivate parked
        # members
        return [
            ClusterEvent(POD_GROUP, ALL, "PodGroupChange"),
            ClusterEvent(POD, ADD, "PodAdd"),
        ]

    # ------------------------------------------------------------ queue sort

    def sort_key(self, qp) -> Tuple:
        """Heap key: (-priority, gang-or-pod timestamp, group key). Groupless
        pods keep the PrioritySort order exactly (empty third component, so
        equal-(priority, timestamp) pods still fall to the FIFO counter)."""
        pod = qp.pod
        name = pod.meta.labels.get(POD_GROUP_LABEL)
        if not name:
            return (-pod.spec.priority, qp.timestamp, "")
        gkey = f"{pod.meta.namespace}/{name}"
        ts = self._group_ts.setdefault(gkey, qp.timestamp)
        return (-pod.spec.priority, ts, gkey)

    def less(self, a, b) -> bool:
        return self.sort_key(a) < self.sort_key(b)

    # ------------------------------------------------------------- helpers

    def _group(self, gkey: str) -> Optional[PodGroup]:
        if self.client is None:
            return None
        return self.client.get_object("PodGroup", gkey)

    def _members_in_store(self, gkey: str, bound_only: bool = False) -> int:
        pods = getattr(self.client, "pods", None)
        if pods is None:
            return 0
        ns, _, name = gkey.partition("/")
        n = 0
        for p in pods.values():
            if (p.meta.namespace == ns
                    and p.meta.labels.get(POD_GROUP_LABEL) == name
                    and (p.spec.node_name or not bound_only)):
                n += 1
        return n

    def _bound_count(self, gkey: str) -> int:
        n = self._bound.get(gkey)
        if n is None:
            n = self._members_in_store(gkey, bound_only=True)
            self._bound[gkey] = n
        return n

    def _waiting_members(self, gkey: str) -> List[str]:
        if self.waiting is None:
            return []
        return [key for key, pod in self.waiting.iterate()
                if pod_group_key(pod) == gkey]

    def _observe_wait(self, gkey: str, result: str) -> None:
        t0 = self._first_wait.pop(gkey, None)
        if t0 is not None and self.metrics is not None:
            self.metrics.gang_wait_duration.observe(self.now_fn() - t0, result)

    # ------------------------------------------------------------ prefilter

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        gkey = pod_group_key(pod)
        if gkey is None:
            return None, OK
        until = self._denied.get(gkey)
        if until is not None:
            if self.now_fn() < until:
                # starvation guard: a just-rejected gang fast-fails instead
                # of re-parking whole-node assumes under singleton pods.
                # Unresolvable (scheduler-plugins PreFilter semantics):
                # preemption cannot fix a gang, so no dry-run fan-out.
                return None, Status.unresolvable(
                    f'{ERR_REASON_GANG_BACKOFF} "{gkey}"')
            # pop, not del: the commit worker's fallback path and the
            # scheduling thread's gang precheck can both observe the same
            # expiry — the second remover must be a no-op, not a KeyError
            self._denied.pop(gkey, None)
        pg = self._group(gkey)
        if pg is None:
            # the group object has not been created yet: unresolvable — the
            # PodGroup cluster event reactivates the member
            return None, Status.unresolvable(
                f'{ERR_REASON_MISSING_GROUP} "{gkey}"')
        if self._members_in_store(gkey) < pg.min_member:
            # a gang that cannot possibly reach quorum must not park
            # resources at Permit (coscheduling PreFilter's total-pods
            # check); unresolvable — evicting victims cannot create the
            # missing siblings
            return None, Status.unresolvable(
                f'{ERR_REASON_TOO_FEW_MEMBERS} for "{gkey}"')
        return None, OK

    # --------------------------------------------------------------- permit

    def permit(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[Status, float]:
        gkey = pod_group_key(pod)
        if gkey is None:
            return OK, 0.0
        pg = self._group(gkey)
        if pg is None:
            return Status.unschedulable(
                f'{ERR_REASON_MISSING_GROUP} "{gkey}"'), 0.0
        waiting = self._waiting_members(gkey)
        # quorum = parked siblings + already-bound members + this pod
        if len(waiting) + self._bound_count(gkey) + 1 >= pg.min_member:
            self._observe_wait(gkey, "scheduled")
            if self.waiting is not None:
                for key in waiting:
                    self.waiting.allow(key)
            return OK, 0.0
        self._first_wait.setdefault(gkey, self.now_fn())
        self._set_phase(gkey, POD_GROUP_SCHEDULING)
        timeout = float(pg.schedule_timeout_seconds or self.permit_timeout_s)
        return Status(WAIT), timeout

    # -------------------------------------------------------------- reserve

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        return OK  # nothing to hold; unreserve carries the gang semantics

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        """Any member's post-Reserve failure (permit rejection/timeout, bind
        error) takes the whole gang down with it: reject every waiting
        sibling so no partial gang survives."""
        gkey = pod_group_key(pod)
        if gkey is None or gkey in self._rejecting:
            return
        self.reject_gang(gkey, "member_failure", force=False)

    def reject_gang(self, gkey: str, reason: str, force: bool = True) -> int:
        """Reject every waiting member of ``gkey`` (all-or-nothing teardown);
        counts one gang-rejection event and arms the denial backoff. Called
        from Unreserve, the scheduler's permit-timeout sweep, and the
        batched backends' whole-gang reject. Returns members rejected."""
        if gkey in self._rejecting:
            return 0
        self._rejecting.add(gkey)
        try:
            waited = gkey in self._first_wait
            rejected = 0
            if self.waiting is not None:
                for key in self._waiting_members(gkey):
                    if self.waiting.reject(
                            key, f'gang "{gkey}" rejected: {reason}',
                            plugins=(self.name(),)):
                        rejected += 1
            if force or rejected or waited:
                if self.metrics is not None:
                    self.metrics.gangs_rejected.inc(reason)
                self._observe_wait(gkey, "rejected")
                self._denied[gkey] = self.now_fn() + self.gang_backoff_s
                self._set_phase(gkey, POD_GROUP_PENDING)
            return rejected
        finally:
            self._rejecting.discard(gkey)

    # ------------------------------------------------------------ post bind

    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        gkey = pod_group_key(pod)
        if gkey is None:
            return
        self._bump_bound(gkey, 1)
        self._refresh_group_status(gkey)

    def post_bind_batch(self, items) -> None:
        """Commit-plane batched PostBind: a gang whose members bound in one
        batch gets ONE bound-count bump and ONE PodGroup status write
        instead of a store update per member (the per-member writes were a
        per-pod store lock + journal event each on the host.commit path)."""
        per_gang: Dict[str, int] = {}
        for _state, pod, _node in items:
            gkey = pod_group_key(pod)
            if gkey is not None:
                per_gang[gkey] = per_gang.get(gkey, 0) + 1
        for gkey, n in per_gang.items():
            self._bump_bound(gkey, n)
            self._refresh_group_status(gkey)

    def _bump_bound(self, gkey: str, n: int) -> None:
        if gkey in self._bound:
            self._bound[gkey] += n
        else:
            # seed includes these pods: the store already reflects the binds
            self._bound[gkey] = self._members_in_store(gkey, bound_only=True)

    def _refresh_group_status(self, gkey: str) -> None:
        n = self._bound[gkey]
        pg = self._group(gkey)
        if pg is None:
            return
        phase = POD_GROUP_RUNNING if n >= pg.min_member else POD_GROUP_SCHEDULING
        if phase == POD_GROUP_RUNNING:
            self._group_ts.pop(gkey, None)
            self._denied.pop(gkey, None)
        self._update_status(pg, phase=phase, scheduled=n)

    # --------------------------------------------------------- pod lifecycle

    def pod_deleted(self, pod: Pod) -> None:
        """Member deletion bookkeeping (ROADMAP PR4 follow-up: `_bound`
        counts never decremented, so a re-created gang was judged against
        stale quorum). A BOUND member's deletion decrements the gang's
        bound count and refreshes PodGroup status; when the LAST member
        disappears the per-gang plugin state is GC'd wholesale, so a
        future gang reusing the group key starts from a clean slate
        (fresh quorum, fresh queue timestamp, no leftover denial backoff).
        Called from the scheduler's Pod DELETE event hook."""
        gkey = pod_group_key(pod)
        if gkey is None:
            return
        if pod.spec.node_name and gkey in self._bound:
            self._bound[gkey] = max(self._bound[gkey] - 1, 0)
        if self._members_in_store(gkey) == 0:
            self._gc_group(gkey)
            return
        if pod.spec.node_name:
            pg = self._group(gkey)
            if pg is not None:
                n = self._bound_count(gkey)
                phase = (POD_GROUP_RUNNING if n >= pg.min_member
                         else POD_GROUP_SCHEDULING if n else POD_GROUP_PENDING)
                self._update_status(pg, phase=phase, scheduled=n)

    def _gc_group(self, gkey: str) -> None:
        """Drop every per-gang cache for a group with no members left (the
        finished-group GC half of the PodGroup controller follow-up). The
        PodGroup API object survives — it is user-owned — but its status
        resets to Pending/0 so a re-created gang is judged afresh."""
        self._bound.pop(gkey, None)
        self._group_ts.pop(gkey, None)
        self._first_wait.pop(gkey, None)
        self._denied.pop(gkey, None)
        pg = self._group(gkey)
        if pg is not None:
            self._update_status(pg, phase=POD_GROUP_PENDING, scheduled=0)

    def _set_phase(self, gkey: str, phase: str) -> None:
        pg = self._group(gkey)
        if pg is not None and pg.phase != phase:
            self._update_status(pg, phase=phase, scheduled=pg.scheduled)

    def _update_status(self, pg: PodGroup, phase: str, scheduled: int) -> None:
        if self.client is None:
            return
        if pg.phase == phase and pg.scheduled == scheduled:
            return
        from ...apiserver.store import Conflict, NotFound

        try:
            self.client.update_object("PodGroup", dataclasses.replace(
                pg, phase=phase, scheduled=scheduled))
        except (Conflict, NotFound):
            pass  # concurrent writer / group deleted: status is advisory
