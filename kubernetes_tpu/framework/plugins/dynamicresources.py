"""DynamicResources plugin — resource.k8s.io claims gate scheduling.

Oracle implementation of the reference's dynamicresources plugin
(pkg/scheduler/framework/plugins/dynamicresources, the structured-parameters
shape): PreFilter resolves the pod's claims and fails fast when one is
missing; Filter checks the node's published device attributes against the
merged class+claim selectors (api/dra.py — the SAME predicate the TPU
batched claim-feasibility mask computes); Reserve allocates each claim to
the chosen node through the store (rolled back by Unreserve); PostBind
persists the PodSchedulingContext selected-node status.

Allocation is node-level (see api/types.py ResourceClass): claims carry no
per-device inventory, so intra-batch claim contention reduces to the
allocated-node restriction — which is why the batched path can screen claims
with a STATIC per-batch mask and verify exactly at Reserve time.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ...api import dra
from ...api.types import Pod
from ...apiserver.store import Conflict, NotFound
from ..interface import (
    CycleState,
    FilterPlugin,
    OK,
    PostBindPlugin,
    PreFilterPlugin,
    PreFilterResult,
    ReservePlugin,
    Status,
)
from ..types import (
    ADD,
    ALL,
    ClusterEvent,
    NODE,
    NodeInfo,
    RESOURCE_CLAIM,
    RESOURCE_CLASS,
    UPDATE,
)
from . import names

ERR_REASON_MISSING_CLAIM = "waiting for resource claim to be created"
ERR_REASON_CANNOT_ALLOCATE = "cannot allocate all claims"


class _ClaimState:
    """PreFilter → Filter/Reserve state: [(claim key, claim, selectors)]."""

    __slots__ = ("claims", "allocated")

    def __init__(self, claims):
        self.claims = claims
        self.allocated: List[str] = []  # claim keys this pod reserved

    def clone(self) -> "_ClaimState":
        cs = _ClaimState(self.claims)
        cs.allocated = list(self.allocated)
        return cs


class DynamicResources(PreFilterPlugin, FilterPlugin, ReservePlugin, PostBindPlugin):
    STATE_KEY = "PreFilter/DynamicResources"

    def __init__(self, client=None, metrics=None):
        self.client = client
        self.metrics = metrics

    def _count(self, result: str) -> None:
        if self.metrics is not None:
            self.metrics.dra_claim_allocations.inc(result)

    def name(self) -> str:
        return names.DYNAMIC_RESOURCES

    def events_to_register(self) -> List[ClusterEvent]:
        # claim/class churn (the resourceclaim controller materializing a
        # template, a deallocation) and node attribute publication must
        # reactivate pods this plugin failed
        return [
            ClusterEvent(RESOURCE_CLAIM, ALL, "ResourceClaimChange"),
            ClusterEvent(RESOURCE_CLASS, ADD | UPDATE, "ResourceClassChange"),
            ClusterEvent(NODE, ADD | UPDATE, ""),
        ]

    # ----------------------------------------------------------- prefilter

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        refs = dra.claim_refs_for_pod(pod)
        if not refs:
            return None, OK
        claims = []
        for entry_name, claim_key in refs:
            claim = self.client.get_object("ResourceClaim", claim_key)
            if claim is None:
                # the resourceclaim controller has not materialized the
                # template yet (or the claim was deleted): unresolvable — a
                # ResourceClaim event reactivates the pod (dynamicresources
                # PreFilter's "claim not found" path)
                return None, Status.unresolvable(
                    f'{ERR_REASON_MISSING_CLAIM} "{entry_name}"')
            selectors, err = dra.selectors_for_claim(self.client, claim)
            if err:
                return None, Status.unresolvable(err)
            claims.append((claim_key, claim, selectors))
        state.write(self.STATE_KEY, _ClaimState(claims))
        # claims already allocated pin the pod to their node (PreFilter's
        # node-restriction shortcut)
        nodes = None
        for _key, claim, _sels in claims:
            if claim.allocated_node:
                cur = {claim.allocated_node}
                nodes = cur if nodes is None else nodes & cur
        if nodes is not None:
            return PreFilterResult(nodes), OK
        return None, OK

    # -------------------------------------------------------------- filter

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        try:
            s: _ClaimState = state.read(self.STATE_KEY)
        except KeyError:
            return OK
        node = node_info.node
        if node is None:
            return Status.unschedulable(ERR_REASON_CANNOT_ALLOCATE)
        attrs = node.status.device_attributes
        for _key, claim, selectors in s.claims:
            if claim.allocated_node and claim.allocated_node != node.meta.name:
                return Status.unschedulable(ERR_REASON_CANNOT_ALLOCATE)
            for sel in selectors:
                if not sel.matches(attrs):
                    return Status.unschedulable(ERR_REASON_CANNOT_ALLOCATE)
        return OK

    # ------------------------------------------------------------- reserve

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        try:
            s: _ClaimState = state.read(self.STATE_KEY)
        except KeyError:
            return OK
        s.allocated = []
        pod_key = pod.key()
        for claim_key, _claim, _sels in s.claims:
            try:
                self.client.allocate_claim(claim_key, node_name, pod_key)
            except (Conflict, NotFound):
                # raced with another allocation (or the claim vanished):
                # roll back what this pod took and retry the cycle
                self._count("conflict")
                self.unreserve(state, pod, node_name)
                return Status.unschedulable(ERR_REASON_CANNOT_ALLOCATE)
            self._count("allocated")
            s.allocated.append(claim_key)
        return OK

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        try:
            s: _ClaimState = state.read(self.STATE_KEY)
        except KeyError:
            return
        pod_key = pod.key()
        for claim_key in s.allocated:
            self.client.release_claim(claim_key, pod_key)
            self._count("released")
        s.allocated = []

    # ------------------------------------------------------------ postbind

    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        if not pod.spec.resource_claims:
            return
        import dataclasses as _dc

        from ...api.types import ObjectMeta, OwnerReference, PodSchedulingContext

        key = pod.key()
        existing = self.client.get_object("PodSchedulingContext", key)
        try:
            if existing is None:
                # pod-owned: the resourceclaim controller's pod GC (and the
                # ownership graph) reap it with the pod — no leaked contexts
                self.client.create_object("PodSchedulingContext", PodSchedulingContext(
                    meta=ObjectMeta(name=pod.meta.name,
                                    namespace=pod.meta.namespace,
                                    owner_references=(OwnerReference(
                                        kind="Pod", name=pod.meta.name,
                                        controller=True),)),
                    selected_node=node_name))
            elif existing.selected_node != node_name:
                self.client.update_object(
                    "PodSchedulingContext",
                    _dc.replace(existing, selected_node=node_name))
        except Conflict:
            pass  # concurrent writer; the status is already current
