"""Simple per-node plugins: PrioritySort, NodeUnschedulable, NodeName,
TaintToleration, NodePorts.

Oracle (scalar) implementations — semantics cited per plugin; these are the
ground truth the batched tensor kernels (ops/filters.py, ops/scores.py) are
parity-tested against, and the fallback path when the TPU backend is off.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ...api.types import (
    Pod,
    Node,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE,
    Taint,
    Toleration,
)
from ..interface import (
    CycleState,
    FilterPlugin,
    NodeScore,
    OK,
    PreFilterPlugin,
    PreFilterResult,
    PreScorePlugin,
    QueueSortPlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
    default_normalize_score,
    MAX_NODE_SCORE,
)
from ..types import ClusterEvent, NodeInfo, QueuedPodInfo, ports_conflict
from ..types import ADD, NODE, POD, UPDATE, UPDATE_NODE_LABEL, UPDATE_NODE_TAINT, DELETE
from . import names


class PrioritySort(QueueSortPlugin):
    """queuesort/priority_sort.go: pod priority desc, then FIFO timestamp."""

    def name(self) -> str:
        return names.PRIORITY_SORT

    def less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        p1, p2 = a.pod.spec.priority, b.pod.spec.priority
        return p1 > p2 or (p1 == p2 and a.timestamp < b.timestamp)


class NodeUnschedulable(FilterPlugin):
    """nodeunschedulable/node_unschedulable.go: reject spec.unschedulable nodes
    unless the pod tolerates the unschedulable taint."""

    ERR_UNSCHEDULABLE = "node(s) were unschedulable"
    _TAINT = Taint(key="node.kubernetes.io/unschedulable", effect=TAINT_NO_SCHEDULE)

    def name(self) -> str:
        return names.NODE_UNSCHEDULABLE

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(NODE, ADD | UPDATE_NODE_TAINT)]

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        node = node_info.node
        if node is None:
            return Status.unresolvable("node(s) had unknown conditions")
        if node.spec.unschedulable and not any(
            t.tolerates(self._TAINT) for t in pod.spec.tolerations
        ):
            return Status.unresolvable(self.ERR_UNSCHEDULABLE)
        return OK


class NodeName(FilterPlugin):
    """nodename/node_name.go: pod.spec.nodeName must match, if set."""

    ERR_REASON = "node(s) didn't match the requested node name"

    def name(self) -> str:
        return names.NODE_NAME

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if pod.spec.node_name and node_info.node and pod.spec.node_name != node_info.node.meta.name:
            return Status.unresolvable(self.ERR_REASON)
        return OK


def find_matching_untolerated_taint(
    taints, tolerations, effects
) -> Optional[Taint]:
    """v1helper.FindMatchingUntoleratedTaint over the given effects."""
    for t in taints:
        if t.effect in effects and not any(tol.tolerates(t) for tol in tolerations):
            return t
    return None


class TaintToleration(FilterPlugin, PreScorePlugin, ScorePlugin, ScoreExtensions):
    """tainttoleration/taint_toleration.go:
    Filter: every NoSchedule/NoExecute taint must be tolerated.
    Score: count of untolerated PreferNoSchedule taints, normalized reversed."""

    STATE_KEY = "PreScore/TaintToleration"

    def name(self) -> str:
        return names.TAINT_TOLERATION

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(NODE, ADD | UPDATE_NODE_TAINT)]

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        taint = find_matching_untolerated_taint(
            node_info.node.spec.taints if node_info.node else (),
            pod.spec.tolerations,
            (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE),
        )
        if taint is None:
            return OK
        return Status.unresolvable(
            f"node(s) had untolerated taint {{{taint.key}: {taint.value}}}"
        )

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        prefer = tuple(
            t for t in pod.spec.tolerations
            if t.effect in ("", TAINT_PREFER_NO_SCHEDULE)
        )
        state.write(self.STATE_KEY, prefer)
        return OK

    def score(self, state: CycleState, pod: Pod, node_name: str):
        raise NotImplementedError  # runtime calls score_node with NodeInfo

    def score_node(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        tolerations: Tuple[Toleration, ...] = state.read(self.STATE_KEY)
        count = 0
        for t in node_info.node.spec.taints:
            if t.effect == TAINT_PREFER_NO_SCHEDULE and not any(
                tol.tolerates(t) for tol in tolerations
            ):
                count += 1
        return count, OK

    def score_extensions(self):
        return self

    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> Status:
        return default_normalize_score(MAX_NODE_SCORE, True, scores)


class NodePorts(PreFilterPlugin, FilterPlugin):
    """nodeports/node_ports.go: requested hostPorts must not conflict with
    NodeInfo.UsedPorts."""

    STATE_KEY = "PreFilter/NodePorts"
    ERR_REASON = "node(s) didn't have free ports for the requested pod ports"

    def name(self) -> str:
        return names.NODE_PORTS

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(POD, DELETE), ClusterEvent(NODE, ADD)]

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        state.write(self.STATE_KEY, pod.host_ports())
        return None, OK

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        wanted = state.read(self.STATE_KEY)
        if ports_conflict(node_info.used_ports, wanted):
            return Status.unschedulable(self.ERR_REASON)
        return OK
