"""SlicePacking — torus-contiguous placement for slice gangs (oracle side).

The sequential twin of the in-jit slice planner (ops/slice.py plan_slices):
at a slice gang's FIRST member this PreFilter runs the shared greedy oracle
``slice_assign_host`` over the live snapshot and caches one target node per
member ordinal; Filter then pins each member to its planned node, so the
oracle path lands gangs on exactly the windows the device path picks (the
SchedulingSlices parity contract). Inert for pods without the
``ktpu.dev/slice`` marker — the default profiles stay batchable.

Coordinates come from the well-known node labels ONLY (the encoder's
slot-derived synthetic fallback has no oracle analog — slot numbering is a
device-side artifact); unlabeled nodes are simply not sliceable here.

Plan lifetime: targets are reserved (excluded from later plans' feasibility)
until every member ordinal has been handed out — the sequential analog of
the batch planner's taken-cell bitmap. Gang rejection (Coscheduling
reject_gang, permit timeout) clears the plan via ``forget_gang`` so a
retried gang replans against current state.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ...api.types import Pod
from ..interface import (
    CycleState,
    FilterPlugin,
    OK,
    PreFilterPlugin,
    PreFilterResult,
    Status,
)
from ..types import NodeInfo
from . import names
from .coscheduling import pod_group_key
from .noderesources import fits_request


class SlicePacking(PreFilterPlugin, FilterPlugin):
    """Plan-then-pin slice placement over labeled torus coordinates."""

    ERR_NO_SLICE = "no contiguous torus slice for gang"
    ERR_OUTSIDE = "node(s) outside the gang's planned torus slice"
    TARGET_KEY = "PreFilter/SlicePacking/target"

    def __init__(self, snapshot_fn=None, client=None):
        self.snapshot_fn = snapshot_fn
        self.client = client
        # gkey -> {"targets": [node names], "next": ordinal}
        self._plans: Dict[str, dict] = {}
        self._reserved: set = set()  # node names held by active plans

    def name(self) -> str:
        return names.SLICE_PACKING

    # -- PreFilter

    def pre_filter(self, state: CycleState, pod: Pod
                   ) -> Tuple[Optional[PreFilterResult], Status]:
        from ...ops.slice import is_slice_pod

        if not is_slice_pod(pod):
            return None, OK
        gkey = pod_group_key(pod)
        if gkey is None:
            return None, OK
        plan = self._plans.get(gkey)
        if plan is not None and pod.key() in plan["seen"]:
            # the same member is back — the gang's first pass failed
            # somewhere (filter miss, permit teardown): replan from current
            # state instead of re-serving a plan the cluster outgrew
            self.forget_gang(gkey)
            plan = None
        if plan is None:
            plan = self._compute_plan(gkey, pod)
            if plan is None:
                return None, Status.unschedulable(self.ERR_NO_SLICE)
            self._plans[gkey] = plan
            self._reserved.update(plan["targets"])
        target = plan["targets"][plan["next"] % len(plan["targets"])]
        plan["next"] += 1
        plan["seen"].add(pod.key())
        if plan["next"] >= len(plan["targets"]):
            # every ordinal handed out: the members themselves now hold the
            # nodes (assumed/parked capacity), so the reservation dissolves
            self.forget_gang(gkey)
        state.write(self.TARGET_KEY, target)
        return None, OK

    # -- Filter

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        from ...ops.slice import is_slice_pod

        if not is_slice_pod(pod) or pod_group_key(pod) is None:
            return OK
        target = state.read(self.TARGET_KEY)
        if target is None:
            return Status.unschedulable(self.ERR_NO_SLICE)
        node = node_info.node
        if node is None or node.meta.name != target:
            return Status.unschedulable(self.ERR_OUTSIDE)
        return OK

    # -- plan machinery

    def forget_gang(self, gkey: str) -> None:
        """Drop a gang's plan and release its node reservations (called on
        plan exhaustion here and by gang-rejection paths)."""
        plan = self._plans.pop(gkey, None)
        if plan is not None:
            self._reserved.difference_update(plan["targets"])

    def _want(self, gkey: str, pod: Pod) -> int:
        if self.client is not None:
            pg = self.client.get_object("PodGroup", gkey)
            if pg is not None and pg.min_member > 0:
                return int(pg.min_member)
        return 1

    def _compute_plan(self, gkey: str, pod: Pod) -> Optional[dict]:
        from ...ops.slice import (TOPO_SLOT_LABEL, TOPO_SUPERPOD_LABEL,
                                  slice_assign_host)

        node_infos: List[NodeInfo] = (self.snapshot_fn()
                                      if self.snapshot_fn else [])
        coords: List[Tuple[int, int, NodeInfo]] = []
        for ni in node_infos:
            node = ni.node
            if node is None:
                continue
            sp_s = node.meta.labels.get(TOPO_SUPERPOD_LABEL)
            pos_s = node.meta.labels.get(TOPO_SLOT_LABEL)
            if sp_s is None or pos_s is None:
                continue
            try:
                sp, pos = int(sp_s), int(pos_s)
            except (ValueError, OverflowError):
                continue
            if sp >= 0 and pos >= 0:
                coords.append((sp, pos, ni))
        if not coords:
            return None
        # the grid spans exactly the labeled coordinate range; the device
        # grid is capacity-padded beyond it, but padding cells hold no node
        # and never affect window choice or leftover runs
        s_pods = max(c[0] for c in coords) + 1
        ps = max(c[1] for c in coords) + 1
        request = pod.resource_request()
        topo_sp, topo_pos, valid, fits = [], [], [], []
        for sp, pos, ni in coords:
            topo_sp.append(sp)
            topo_pos.append(pos)
            valid.append(True)
            node = ni.node
            fits.append(
                node is not None
                and not node.spec.unschedulable
                and node.meta.name not in self._reserved
                and not fits_request(request, ni))
        targets, ok = slice_assign_host(
            topo_sp, topo_pos, valid, [fits],
            [self._want(gkey, pod)], (s_pods, ps))
        if not ok[0]:
            return None
        return {"targets": [coords[t][2].node.meta.name
                            for t in targets[0]], "next": 0, "seen": set()}
