"""NodeAffinity plugin (nodeaffinity/node_affinity.go).

Filter: pod.spec.nodeSelector (map: all pairs must be node labels) AND
required NodeSelector terms (OR of terms, AND within a term), plus the
per-profile AddedAffinity arg.  PreFilter: if every required term is a
metadata.name matchFields restriction, pre-restrict the candidate set.
Score: sum of weights of matching preferred terms, DefaultNormalizeScore.
"""

from __future__ import annotations

from typing import List, Optional, Set, Tuple

from ...api.types import NodeAffinity as NodeAffinityAPI
from ...api.types import NodeSelector, Pod
from ..interface import (
    CycleState,
    FilterPlugin,
    NodeScore,
    OK,
    PreFilterPlugin,
    PreFilterResult,
    PreScorePlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
    default_normalize_score,
    MAX_NODE_SCORE,
)
from ..types import ADD, NODE, UPDATE_NODE_LABEL, ClusterEvent, NodeInfo
from . import names

ERR_REASON_POD = "node(s) didn't match Pod's node affinity/selector"
ERR_REASON_ENFORCED = "node(s) didn't match scheduler-enforced node affinity"
ERR_REASON_CONFLICT = "node(s) didn't satisfy plugin's node affinity"


def _required_terms(pod: Pod) -> Optional[NodeSelector]:
    a = pod.spec.affinity
    if a and a.node_affinity and a.node_affinity.required:
        return a.node_affinity.required
    return None


def _matches_node_selector_map(pod: Pod, labels) -> bool:
    return all(labels.get(k) == v for k, v in pod.spec.node_selector.items())


class NodeAffinity(PreFilterPlugin, FilterPlugin, PreScorePlugin, ScorePlugin, ScoreExtensions):
    STATE_KEY = "PreFilter/NodeAffinity"
    PRESCORE_KEY = "PreScore/NodeAffinity"

    def __init__(self, added_affinity: Optional[NodeAffinityAPI] = None):
        self.added_affinity = added_affinity  # args.AddedAffinity (per-profile)

    def name(self) -> str:
        return names.NODE_AFFINITY

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(NODE, ADD | UPDATE_NODE_LABEL)]

    # -- PreFilter: metadata.name fast path (node_affinity.go:98-134)

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        required = _required_terms(pod)
        state.write(self.STATE_KEY, required)
        if required is None or not required.terms:
            return None, OK
        node_names: Set[str] = set()
        for term in required.terms:
            if term.match_fields_name is None or term.match_expressions:
                return None, OK  # some term matches by labels → no pre-restriction
            node_names.add(term.match_fields_name)
        if not node_names:
            return None, Status.unresolvable(ERR_REASON_CONFLICT)
        return PreFilterResult(node_names), OK

    # -- Filter

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        node = node_info.node
        if self.added_affinity and self.added_affinity.required:
            if not self.added_affinity.required.matches(node):
                return Status.unresolvable(ERR_REASON_ENFORCED)
        if not _matches_node_selector_map(pod, node.meta.labels):
            return Status.unresolvable(ERR_REASON_POD)
        required = _required_terms(pod)
        if required is not None and not required.matches(node):
            return Status.unresolvable(ERR_REASON_POD)
        return OK

    # -- Score

    def pre_score(self, state: CycleState, pod: Pod, nodes) -> Status:
        preferred = []
        a = pod.spec.affinity
        if a and a.node_affinity:
            preferred.extend(a.node_affinity.preferred)
        if self.added_affinity:
            preferred.extend(self.added_affinity.preferred)
        state.write(self.PRESCORE_KEY, tuple(preferred))
        return OK

    def score_node(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Tuple[int, Status]:
        total = 0
        for term in state.read(self.PRESCORE_KEY):
            if term.weight != 0 and term.preference.matches(node_info.node):
                total += term.weight
        return total, OK

    def score(self, state: CycleState, pod: Pod, node_name: str):
        raise NotImplementedError

    def score_extensions(self):
        return self

    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> Status:
        return default_normalize_score(MAX_NODE_SCORE, False, scores)
