"""DefaultPreemption — the PostFilter plugin
(plugins/defaultpreemption/default_preemption.go).

PostFilter fires after a pod fails all Filters (schedule_one.go:104-122); it
runs the preemption Evaluator and, on success, returns the node the pod is
nominated to (the actual nomination + status write happens in the scheduler's
failure handler, mirroring the reference's NominatingInfo flow).
"""

from __future__ import annotations

import random
from typing import Optional, Tuple

from ..interface import CycleState, PostFilterPlugin, Status
from ..preemption import Evaluator
from . import names


class DefaultPreemption(PostFilterPlugin):
    def __init__(
        self,
        framework=None,
        snapshot_fn=None,
        pdb_lister=None,
        min_candidate_nodes_percentage: int = 10,
        min_candidate_nodes_absolute: int = 100,
        seed: int = 0,
    ):
        # framework is attached lazily (the Framework builds plugins before
        # itself exists) via set_framework in runtime wiring.
        self._fwk = framework
        self._snapshot_fn = snapshot_fn
        self._pdb_lister = pdb_lister or (lambda: [])
        self.min_pct = min_candidate_nodes_percentage
        self.min_abs = min_candidate_nodes_absolute
        self._rng = random.Random(seed)

    def name(self) -> str:
        return names.DEFAULT_PREEMPTION

    def set_framework(self, fwk) -> None:
        self._fwk = fwk

    # CycleState key the TPU batch path uses to hand over device-computed
    # preemption hints: (screen_row np[N] bool, slot_of {name: slot},
    # best_name Optional[str])
    HINTS_KEY = "ktpu.preempt.hints"

    def post_filter(self, state: CycleState, pod, filtered_node_status_map) -> Tuple[Optional[str], Status]:
        # The dry-run filters consume PreFilter CycleState. The sequential
        # path always populated it (schedule_one.go ordering); the TPU batched
        # path skips host-side PreFilter, so backfill it here.
        if not state.prefilter_ran:
            _, st = self._fwk.run_pre_filter_plugins(state, pod)
            if not st.is_success():
                return None, st
        screen_fn = None
        preferred = None
        try:
            screen_row, slot_of, best_name = state.read(self.HINTS_KEY)
        except KeyError:
            pass
        else:
            node_infos_now = self._snapshot_fn() if self._snapshot_fn else []
            if not screen_row.any() and all(
                ni.node is None or ni.node.meta.name in slot_of
                for ni in node_infos_now
            ):
                # the screen proved no node can be freed AND it covers every
                # snapshot node (a node added after the device encode has no
                # slot and must still be dry-run): skip the per-node walk —
                # preemption.go:205's '0 nodes' outcome at O(1)
                return None, Status.unschedulable(
                    "preemption: 0/{} nodes are available".format(
                        len(node_infos_now)))

            def screen_fn(name, _row=screen_row, _slots=slot_of):
                slot = _slots.get(name)
                return True if slot is None else bool(_row[slot])
            # the device ranking ignores PDB-violation minimization
            # (pickOneNode criterion 1): with PDBs present, keep only the
            # screen (exact prescreen semantics) and let the host rank
            if not list(self._pdb_lister()):
                preferred = best_name
        ev = Evaluator(
            plugin_name=self.name(),
            framework=self._fwk,
            pdb_lister=self._pdb_lister,
            state=state,
            min_candidate_nodes_percentage=self.min_pct,
            min_candidate_nodes_absolute=self.min_abs,
            rng=self._rng,
            screen_fn=screen_fn,
            preferred_node=preferred,
        )
        node_infos = self._snapshot_fn() if self._snapshot_fn else []
        return ev.preempt(pod, filtered_node_status_map, node_infos)
