"""Volume plugins: VolumeZone, VolumeRestrictions, NodeVolumeLimits,
VolumeBinding.

Oracle implementations of the reference's volume plugin set
(pkg/scheduler/framework/plugins/{volumezone,volumerestrictions,
nodevolumelimits,volumebinding}); this framework's volume model reduces a
pod's volumes to PVC names (api/types.py PodSpec.volumes), PVs carry topology
as required label matches, and the PV controller is the store's ``bind_pv``.

These stay on the host path permanently (SURVEY.md §7 hard-parts #6:
VolumeBinding is stateful, API-writing, PreBind-heavy — off the hot loop).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ...api.types import (
    BINDING_WAIT_FOR_FIRST_CONSUMER,
    Node,
    PersistentVolumeClaim,
    Pod,
    RWOP,
)
from ..interface import (
    CycleState,
    FilterPlugin,
    OK,
    PreBindPlugin,
    PreFilterPlugin,
    PreFilterResult,
    ReservePlugin,
    Status,
)
from ..types import ClusterEvent, NodeInfo
from ..types import ADD, DELETE, NODE, PV, PVC, STORAGE_CLASS, CSI_NODE, UPDATE
from . import names

ERR_REASON_NOT_BOUND = "pod has unbound immediate PersistentVolumeClaims"
ERR_REASON_PVC_NOT_FOUND = "persistentvolumeclaim not found"
ERR_REASON_CONFLICT = "node(s) had volume node affinity conflict"
ERR_REASON_RWOP = "pod uses a ReadWriteOncePod PVC already in use"
ERR_REASON_LIMIT = "node(s) exceed max volume count"
ERR_REASON_ZONE = "node(s) had no available volume zone"


def _pod_pvcs(pod: Pod, store) -> Tuple[List[PersistentVolumeClaim], Optional[str]]:
    """Resolve the pod's PVC names; (claims, missing-claim-name)."""
    claims = []
    for name in pod.spec.volumes:
        pvc = store.get_pvc(f"{pod.meta.namespace}/{name}")
        if pvc is None:
            return [], name
        claims.append(pvc)
    return claims, None


# ---------------------------------------------------------------------------
# VolumeZone (volumezone/volume_zone.go)

_ZONE_KEYS = (
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)


class VolumeZone(FilterPlugin):
    """Filter: every bound PV's zone/region labels must match the node's
    (volume_zone.go:88 Filter)."""

    def __init__(self, client=None):
        self.client = client

    def name(self) -> str:
        return names.VOLUME_ZONE

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(STORAGE_CLASS, ADD, ""),
            ClusterEvent(NODE, ADD | UPDATE, ""),
            ClusterEvent(PVC, ADD, ""),
            ClusterEvent(PV, ADD | UPDATE, ""),
        ]

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if not pod.spec.volumes:
            return OK
        claims, missing = _pod_pvcs(pod, self.client)
        if missing is not None:
            return Status.unresolvable(ERR_REASON_PVC_NOT_FOUND)
        node = node_info.node
        for pvc in claims:
            if not pvc.bound_pv:
                continue  # unbound handled by VolumeBinding
            pv = self.client.get_pv(pvc.bound_pv)
            if pv is None:
                continue
            for key in _ZONE_KEYS:
                pv_val = pv.meta.labels.get(key)
                if pv_val is None:
                    continue
                # the reference allows __-separated multi-zone label values
                allowed = set(pv_val.split("__"))
                if node.meta.labels.get(key) not in allowed:
                    return Status.unresolvable(ERR_REASON_ZONE)
        return OK


# ---------------------------------------------------------------------------
# VolumeRestrictions (volumerestrictions/volume_restrictions.go)


class VolumeRestrictions(PreFilterPlugin, FilterPlugin):
    """ReadWriteOncePod exclusivity: a RWOP PVC used by any existing pod
    blocks every node hosting that pod (volume_restrictions.go:150-217); the
    legacy GCE-PD/EBS same-volume conflict reduces to 'two pods may not share
    a PVC on one node unless its access mode allows it' in the PVC-name
    volume model."""

    STATE_KEY = "PreFilter/VolumeRestrictions"

    def __init__(self, client=None, snapshot_fn=None):
        self.client = client
        self.snapshot_fn = snapshot_fn

    def name(self) -> str:
        return names.VOLUME_RESTRICTIONS

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(PVC, ADD | DELETE, ""),
            ClusterEvent(NODE, ADD | UPDATE, ""),
        ]

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        """RWOP exclusivity is cluster-wide and decided here: a RWOP claim in
        use by ANY pod rejects at PreFilter with UnschedulableAndUnresolvable
        (volume_restrictions.go:149-152 isReadWriteOncePodAccessModeConflict)."""
        claims, missing = _pod_pvcs(pod, self.client)
        if missing is not None:
            return None, Status.unresolvable(ERR_REASON_PVC_NOT_FOUND)
        rwop = {pvc.meta.key() for pvc in claims if RWOP in pvc.access_modes}
        if rwop and self.snapshot_fn is not None:
            for ni in self.snapshot_fn():
                for key, count in ni.pvc_ref_counts.items():
                    if key in rwop and count > 0:
                        return None, Status.unresolvable(ERR_REASON_RWOP)
        state.write(self.STATE_KEY, rwop)
        return None, OK

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        """Per-node re-check covers pods assumed after PreFilter (the
        preemption dry-run AddPod path)."""
        try:
            rwop = state.read(self.STATE_KEY)
        except KeyError:
            rwop = set()
        if not rwop:
            return OK
        for key, count in node_info.pvc_ref_counts.items():
            if key in rwop and count > 0:
                return Status.unresolvable(ERR_REASON_RWOP)
        return OK


# ---------------------------------------------------------------------------
# NodeVolumeLimits (nodevolumelimits/csi.go)


class NodeVolumeLimits(FilterPlugin):
    """Per-driver attachable-volume count limit from CSINode allocatable:
    existing volumes on the node + the pod's new volumes must fit
    (csi.go:220 Filter)."""

    def __init__(self, client=None):
        self.client = client

    def name(self) -> str:
        return names.NODE_VOLUME_LIMITS

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(CSI_NODE, ADD, ""),
            ClusterEvent(PVC, ADD, ""),
            ClusterEvent(PV, ADD, ""),
        ]

    def _driver_of(self, pvc: PersistentVolumeClaim) -> Optional[str]:
        sc = self.client.get_storage_class(pvc.storage_class)
        return sc.provisioner if sc else None

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if not pod.spec.volumes:
            return OK
        csinode = self.client.get_csinode(node_info.node.meta.name)
        if csinode is None or not csinode.drivers:
            return OK  # no limits known for this node
        claims, missing = _pod_pvcs(pod, self.client)
        if missing is not None:
            return Status.unresolvable(ERR_REASON_PVC_NOT_FOUND)

        new_by_driver: Dict[str, set] = {}
        for pvc in claims:
            d = self._driver_of(pvc)
            if d is not None and d in csinode.drivers:
                new_by_driver.setdefault(d, set()).add(pvc.meta.key())
        if not new_by_driver:
            return OK

        used_by_driver: Dict[str, set] = {}
        for p in node_info.pods:
            for vol in p.spec.volumes:
                pvc = self.client.get_pvc(f"{p.meta.namespace}/{vol}")
                if pvc is None:
                    continue
                d = self._driver_of(pvc)
                if d is not None and d in csinode.drivers:
                    used_by_driver.setdefault(d, set()).add(pvc.meta.key())

        for driver, new_set in new_by_driver.items():
            used = used_by_driver.get(driver, set())
            if len(used | new_set) > csinode.drivers[driver]:
                return Status.unschedulable(ERR_REASON_LIMIT)
        return OK


# ---------------------------------------------------------------------------
# VolumeBinding (volumebinding/volume_binding.go)


class _BindingState:
    """Per-cycle state: claims to bind + per-node chosen PVs
    (volume_binding.go stateData)."""

    def __init__(self, bound, unbound_immediate, delayed):
        self.bound = bound                        # already-bound PVCs
        self.unbound_immediate = unbound_immediate
        self.delayed = delayed                    # WaitForFirstConsumer claims
        self.node_bindings: Dict[str, List[Tuple[str, str]]] = {}  # node -> [(pv, pvc)]

    def clone(self):
        c = _BindingState(self.bound, self.unbound_immediate, self.delayed)
        c.node_bindings = {k: list(v) for k, v in self.node_bindings.items()}
        return c


class VolumeBinding(PreFilterPlugin, FilterPlugin, ReservePlugin, PreBindPlugin):
    """Delayed (WaitForFirstConsumer) PV binding:

    PreFilter partitions the pod's claims (volume_binding.go:168);
    Filter finds matching PVs per node (binder.go FindPodVolumes);
    Reserve assumes the chosen PV⇄PVC pairs (assume_cache.go analog);
    PreBind writes the binds through the API and they take effect
    immediately (the in-process store is its own PV controller).
    Score (behind the VolumeCapacityPriority gate) prefers nodes whose
    matched PVs are utilized most fully (volume_binding.go:296 + scorer.go).
    """

    STATE_KEY = "PreFilter/VolumeBinding"

    def __init__(self, client=None, volume_capacity_priority: bool = None):
        self.client = client
        self._assumed: Dict[str, List[Tuple[str, str]]] = {}  # pod key -> [(pv, pvc)]
        if volume_capacity_priority is None:
            from ...utils.featuregate import DEFAULT_FEATURE_GATE

            volume_capacity_priority = DEFAULT_FEATURE_GATE.enabled("VolumeCapacityPriority")
        self.volume_capacity_priority = volume_capacity_priority

    def name(self) -> str:
        return names.VOLUME_BINDING

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(PV, ADD | UPDATE, ""),
            ClusterEvent(PVC, ADD | UPDATE, ""),
            ClusterEvent(STORAGE_CLASS, ADD, ""),
            ClusterEvent(NODE, ADD | UPDATE, ""),
            ClusterEvent(CSI_NODE, ADD | UPDATE, ""),
        ]

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        claims, missing = _pod_pvcs(pod, self.client)
        if missing is not None:
            return None, Status.unresolvable(f'{ERR_REASON_PVC_NOT_FOUND} "{missing}"')
        bound, unbound_immediate, delayed = [], [], []
        for pvc in claims:
            if pvc.bound_pv:
                bound.append(pvc)
                continue
            sc = self.client.get_storage_class(pvc.storage_class)
            if sc is not None and sc.volume_binding_mode == BINDING_WAIT_FOR_FIRST_CONSUMER:
                delayed.append(pvc)
            else:
                unbound_immediate.append(pvc)
        if unbound_immediate:
            # immediate-mode claims must already be bound (:207)
            return None, Status.unresolvable(ERR_REASON_NOT_BOUND)
        state.write(self.STATE_KEY, _BindingState(bound, unbound_immediate, delayed))
        return None, OK

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        try:
            s: _BindingState = state.read(self.STATE_KEY)
        except KeyError:
            return OK
        node = node_info.node
        # bound claims: PV node affinity must admit this node (:224 Filter)
        for pvc in s.bound:
            pv = self.client.get_pv(pvc.bound_pv)
            if pv is not None and not pv.matches_node(node):
                return Status.unresolvable(ERR_REASON_CONFLICT)
        if not s.delayed:
            return OK
        # delayed claims: greedily match unbound PVs on this node (binder.go
        # findMatchingVolumes — smallest fitting PV first)
        chosen: List[Tuple[str, str]] = []
        taken = set()
        for pvc in s.delayed:
            best = None
            for pv in self.client.list_pvs():
                if pv.bound_pvc or pv.meta.name in taken:
                    continue
                if pv.storage_class != pvc.storage_class:
                    continue
                if pvc.requested_bytes and pv.capacity_bytes < pvc.requested_bytes:
                    continue
                if not pv.matches_node(node):
                    continue
                if best is None or pv.capacity_bytes < best.capacity_bytes:
                    best = pv
            if best is None:
                return Status.unschedulable("node(s) didn't find available persistent volumes to bind")
            taken.add(best.meta.name)
            chosen.append((best.meta.name, pvc.meta.key()))
        s.node_bindings[node.meta.name] = chosen
        return OK

    def score(self, state: CycleState, pod: Pod, node_name: str):
        raise NotImplementedError  # runtime calls score_node with NodeInfo

    def score_node(self, state: CycleState, pod: Pod, node_info: NodeInfo):
        """Line-shaped utilization score over the node's chosen PVs
        (scorer.go buildScorerFunction, default shape: 0%→0 .. 100%→100).
        Feature-gated; 0 when off or no delayed claims (volume_binding.go:296)."""
        if not self.volume_capacity_priority:
            return 0, OK
        try:
            s: _BindingState = state.read(self.STATE_KEY)
        except KeyError:
            return 0, OK
        bindings = s.node_bindings.get(node_info.node.meta.name, [])
        if not bindings:
            return 0, OK
        total = 0.0
        for pv_name, pvc_key in bindings:
            pv = self.client.get_pv(pv_name)
            pvc = self.client.get_pvc(pvc_key)
            if pv is None or pvc is None or pv.capacity_bytes == 0:
                continue
            total += 100.0 * pvc.requested_bytes / pv.capacity_bytes
        return int(total / len(bindings)), OK

    def score_extensions(self):
        return None

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        try:
            s: _BindingState = state.read(self.STATE_KEY)
        except KeyError:
            return OK
        self._assumed[pod.key()] = s.node_bindings.get(node_name, [])
        return OK

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        self._assumed.pop(pod.key(), None)

    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        bindings = self._assumed.pop(pod.key(), [])
        for pv_name, pvc_key in bindings:
            try:
                self.client.bind_pv(pv_name, pvc_key)
            except Exception as e:  # noqa: BLE001 — conflict: another pod took the PV
                return Status.error(f"binding volumes: {e}")
        return OK


# ---------------------------------------------------------------------------
# Non-CSI attach limits: EBSLimits / GCEPDLimits / AzureDiskLimits /
# CinderLimits (nodevolumelimits/non_csi.go)

# per-type defaults (non_csi.go:45-51; pkg/volume/util/attach_limit.go:35,48)
NON_CSI_DEFAULT_LIMITS = {
    "ebs": 39,
    "gce-pd": 16,
    "azure-disk": 16,
    "cinder": 256,
}
KUBE_MAX_PD_VOLS = "KUBE_MAX_PD_VOLS"  # env override (non_csi.go:66)


class NonCSILimits(PreFilterPlugin, FilterPlugin):
    """Count unique in-tree volumes of one cloud type (this framework models
    them as PVs with ``volume_type``) used by the node's existing pods plus
    the incoming pod; reject when over the node's attach limit
    (non_csi.go:210 Filter). Limit precedence: node allocatable
    ``attachable-volumes-<type>`` > $KUBE_MAX_PD_VOLS > per-type default
    (non_csi.go:265-274,379). The incoming pod's typed PV set is resolved
    once at PreFilter; per node, existing volumes come from the NodeInfo's
    pvc_ref_counts index rather than re-walking every pod."""

    def __init__(self, name: str, volume_type: str, client=None):
        self._name = name
        self.volume_type = volume_type
        self.client = client

    def name(self) -> str:
        return self._name

    @property
    def _state_key(self) -> str:
        return "PreFilter" + self._name

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(NODE, ADD, ""),
            ClusterEvent(PVC, ADD, ""),
            ClusterEvent(PV, ADD, ""),
        ]

    def _typed_pv_of_claim(self, pvc: PersistentVolumeClaim) -> Optional[str]:
        pv = self.client.get_pv(pvc.bound_pv) if pvc.bound_pv else None
        if pv is not None and pv.volume_type == self.volume_type:
            return pv.meta.name
        return None

    def _max_volumes(self, node_info: NodeInfo) -> int:
        import os as _os

        alloc_key = f"attachable-volumes-{self.volume_type}"
        from_node = node_info.node.status.allocatable.get(alloc_key)
        if from_node is not None:
            return int(from_node)
        env = _os.environ.get(KUBE_MAX_PD_VOLS, "")
        if env:
            try:
                v = int(env)
                if v > 0:
                    return v
            except ValueError:
                pass
        return NON_CSI_DEFAULT_LIMITS[self.volume_type]

    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        claims, missing = _pod_pvcs(pod, self.client)
        if missing is not None:
            return None, Status.unresolvable(ERR_REASON_PVC_NOT_FOUND)
        new_vols = {
            name for pvc in claims
            if (name := self._typed_pv_of_claim(pvc)) is not None
        }
        state.write(self._state_key, new_vols)
        return None, OK

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        try:
            new_vols: set = state.read(self._state_key)
        except KeyError:
            return Status.error(f"reading {self._state_key!r} from cycleState")
        if not new_vols:
            return OK
        existing = set()
        for pvc_key in node_info.pvc_ref_counts:
            pvc = self.client.get_pvc(pvc_key)
            if pvc is None:
                continue
            name = self._typed_pv_of_claim(pvc)
            if name is not None:
                existing.add(name)
        if len(existing | new_vols) > self._max_volumes(node_info):
            return Status.unschedulable(ERR_REASON_LIMIT)
        return OK


def make_ebs_limits(client=None) -> NonCSILimits:
    return NonCSILimits(names.EBS_LIMITS, "ebs", client)


def make_gce_pd_limits(client=None) -> NonCSILimits:
    return NonCSILimits(names.GCE_PD_LIMITS, "gce-pd", client)


def make_azure_disk_limits(client=None) -> NonCSILimits:
    return NonCSILimits(names.AZURE_DISK_LIMITS, "azure-disk", client)


def make_cinder_limits(client=None) -> NonCSILimits:
    return NonCSILimits(names.CINDER_LIMITS, "cinder", client)
