"""The scheduling-framework plugin contract.

Analog of pkg/scheduler/framework/interface.go: Status codes (:139), the
Plugin base (:305), the per-extension-point interfaces (:315-:492), Framework
(:505) and Handle (:581), PreFilterResult (:627).  This is the stable ABI both
the scalar (oracle) plugins and the TPU batched backend implement.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..api.types import Node, Pod
from .types import ClusterEvent, NodeInfo

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0
MAX_TOTAL_SCORE = (1 << 63) - 1

# ---------------------------------------------------------------------------
# Status (interface.go:139)

SUCCESS = 0
ERROR = 1
UNSCHEDULABLE = 2
UNSCHEDULABLE_AND_UNRESOLVABLE = 3
WAIT = 4
SKIP = 5

_CODE_NAMES = {
    SUCCESS: "Success",
    ERROR: "Error",
    UNSCHEDULABLE: "Unschedulable",
    UNSCHEDULABLE_AND_UNRESOLVABLE: "UnschedulableAndUnresolvable",
    WAIT: "Wait",
    SKIP: "Skip",
}


class Status:
    __slots__ = ("code", "reasons", "plugin")

    def __init__(self, code: int = SUCCESS, reasons: Tuple[str, ...] = (), plugin: str = ""):
        self.code = code
        self.reasons = reasons
        self.plugin = plugin

    @classmethod
    def unschedulable(cls, *reasons: str) -> "Status":
        return cls(UNSCHEDULABLE, reasons)

    @classmethod
    def unresolvable(cls, *reasons: str) -> "Status":
        return cls(UNSCHEDULABLE_AND_UNRESOLVABLE, reasons)

    @classmethod
    def error(cls, *reasons: str) -> "Status":
        return cls(ERROR, reasons)

    def is_success(self) -> bool:
        return self.code == SUCCESS

    def is_unschedulable(self) -> bool:
        return self.code in (UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE)

    def code_name(self) -> str:
        return _CODE_NAMES[self.code]

    def with_plugin(self, name: str) -> "Status":
        self.plugin = name
        return self

    def __repr__(self):
        return f"Status({self.code_name()}, {list(self.reasons)}, plugin={self.plugin!r})"


OK = Status()


# ---------------------------------------------------------------------------
# CycleState (framework/cycle_state.go)


class CycleState:
    """Per-scheduling-cycle scratch: plugin PreFilter/PreScore state keyed by
    plugin-chosen string keys; Clone() for preemption dry-runs."""

    def __init__(self):
        self._data: Dict[str, object] = {}
        self.skip_filter_plugins: Set[str] = set()
        self.skip_score_plugins: Set[str] = set()
        self.record_plugin_metrics = False
        self.prefilter_ran = False  # set by run_pre_filter_plugins

    def read(self, key: str):
        if key not in self._data:
            raise KeyError(key)
        return self._data[key]

    def write(self, key: str, value) -> None:
        self._data[key] = value

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def clone(self) -> "CycleState":
        cs = CycleState()
        for k, v in self._data.items():
            cs._data[k] = v.clone() if hasattr(v, "clone") else v
        cs.skip_filter_plugins = set(self.skip_filter_plugins)
        cs.skip_score_plugins = set(self.skip_score_plugins)
        cs.record_plugin_metrics = self.record_plugin_metrics
        cs.prefilter_ran = self.prefilter_ran
        return cs


@dataclass
class PreFilterResult:
    """interface.go:627: a PreFilter plugin may pre-restrict the node set."""

    node_names: Optional[Set[str]] = None  # None = all nodes

    def all_nodes(self) -> bool:
        return self.node_names is None

    def merge(self, other: "PreFilterResult") -> "PreFilterResult":
        if self.all_nodes():
            return other
        if other.all_nodes():
            return self
        return PreFilterResult(self.node_names & other.node_names)


@dataclass
class NodeScore:
    name: str
    score: int


# ---------------------------------------------------------------------------
# plugin interfaces (interface.go:305-:492)


class Plugin(abc.ABC):
    @abc.abstractmethod
    def name(self) -> str: ...


class QueueSortPlugin(Plugin):
    @abc.abstractmethod
    def less(self, a, b) -> bool:
        """a, b: QueuedPodInfo."""


class EnqueueExtensions(Plugin):
    def events_to_register(self) -> List[ClusterEvent]:
        return []


class PreEnqueuePlugin(Plugin):
    """Gates a pod's entry into the active scheduling queue (interface.go
    PreEnqueuePlugin): a non-success status parks the pod GATED in the
    unschedulable pool — it never occupies a scheduling cycle (or a device
    batch slot) until the gating condition clears and a cluster event
    re-admits it. Runs OUTSIDE the scheduling cycle (no CycleState): queue
    transitions call it, so it must be cheap and side-effect-free."""

    @abc.abstractmethod
    def pre_enqueue(self, pod: Pod) -> Status: ...


class PreFilterPlugin(Plugin):
    @abc.abstractmethod
    def pre_filter(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]: ...

    def pre_filter_extensions(self) -> Optional["PreFilterExtensions"]:
        return None


class PreFilterExtensions(abc.ABC):
    """Incremental CycleState updates for preemption dry-runs (AddPod/RemovePod)."""

    @abc.abstractmethod
    def add_pod(self, state: CycleState, pod: Pod, to_add: Pod, node_info: NodeInfo) -> Status: ...

    @abc.abstractmethod
    def remove_pod(self, state: CycleState, pod: Pod, to_remove: Pod, node_info: NodeInfo) -> Status: ...


class FilterPlugin(Plugin):
    @abc.abstractmethod
    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status: ...


class PostFilterPlugin(Plugin):
    @abc.abstractmethod
    def post_filter(self, state: CycleState, pod: Pod, filtered_node_status_map) -> Tuple[Optional[str], Status]:
        """Returns (nominated_node_name, status)."""


class PreScorePlugin(Plugin):
    @abc.abstractmethod
    def pre_score(self, state: CycleState, pod: Pod, nodes: List[Node]) -> Status: ...


class ScoreExtensions(abc.ABC):
    @abc.abstractmethod
    def normalize_score(self, state: CycleState, pod: Pod, scores: List[NodeScore]) -> Status: ...


class ScorePlugin(Plugin):
    @abc.abstractmethod
    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]: ...

    def score_extensions(self) -> Optional[ScoreExtensions]:
        return None


class ReservePlugin(Plugin):
    @abc.abstractmethod
    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status: ...

    @abc.abstractmethod
    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None: ...


class PermitPlugin(Plugin):
    @abc.abstractmethod
    def permit(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[Status, float]:
        """Returns (status, timeout_seconds); status WAIT parks the pod."""


class PreBindPlugin(Plugin):
    @abc.abstractmethod
    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status: ...


class BindPlugin(Plugin):
    @abc.abstractmethod
    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status: ...


class PostBindPlugin(Plugin):
    @abc.abstractmethod
    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None: ...


EXTENSION_POINTS = (
    "queue_sort", "pre_enqueue", "pre_filter", "filter", "post_filter",
    "pre_score", "score", "reserve", "permit", "pre_bind", "bind", "post_bind",
)


# ---------------------------------------------------------------------------
# Handle: runtime services exposed to plugins (interface.go:581)


class Handle(abc.ABC):
    @abc.abstractmethod
    def snapshot_node_infos(self) -> List[NodeInfo]: ...

    @abc.abstractmethod
    def get_node_info(self, name: str) -> Optional[NodeInfo]: ...

    @abc.abstractmethod
    def client(self): ...

    @abc.abstractmethod
    def parallelizer(self): ...


def default_normalize_score(max_priority: int, reverse: bool, scores: List[NodeScore]) -> Status:
    """helper.DefaultNormalizeScore (plugins/helper/normalize_score.go:30):
    scale raw scores to [0, max_priority]; reverse flips (lower raw = better).
    All-zero max ⇒ everyone gets max_priority when reversed, else 0."""
    max_score = max((s.score for s in scores), default=0)
    if max_score == 0:
        if reverse:
            for s in scores:
                s.score = max_priority
        return OK
    for s in scores:
        v = max_priority * s.score // max_score
        s.score = max_priority - v if reverse else v
    return OK
