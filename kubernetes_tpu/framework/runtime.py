"""Framework runtime: builds a profile's plugins and executes extension
points (pkg/scheduler/framework/runtime/framework.go).

Key behaviors mirrored:
  * run_filter_plugins_with_nominated_pods (:791): filters run twice — first
    with higher-priority nominated pods added to a cloned NodeInfo/CycleState,
    then without — and both passes must succeed.
  * run_score_plugins (:900): raw scores per plugin → NormalizeScore → apply
    plugin weight; node-parallelism in the reference, vectorized-or-sequential
    here (the TPU backend replaces this wholesale on the hot path).
  * Filter short-circuit: plugins run in config order; first non-success
    status wins and is tagged with the plugin name.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..api.types import Pod
from . import interface as fw
from .interface import CycleState, NodeScore, PreFilterResult, Status, OK
from .registry import DEFAULT_PLUGINS, in_tree_registry
from .types import ClusterEvent, Diagnosis, NodeInfo, QueuedPodInfo


class PodNominator:
    """Tracks preemption nominations (framework/interface.go:690;
    nominated pods get re-considered by filters before their victims exit)."""

    def __init__(self):
        self._by_node: Dict[str, List[Pod]] = {}
        self._node_of: Dict[str, str] = {}

    def add_nominated_pod(self, pod: Pod, node_name: str) -> None:
        self.delete_nominated_pod_if_exists(pod)
        if node_name:
            self._by_node.setdefault(node_name, []).append(pod)
            self._node_of[pod.key()] = node_name

    def delete_nominated_pod_if_exists(self, pod: Pod) -> None:
        node = self._node_of.pop(pod.key(), None)
        if node is not None:
            self._by_node[node] = [p for p in self._by_node[node] if p.key() != pod.key()]

    def nominated_pods_for_node(self, node_name: str) -> List[Pod]:
        return self._by_node.get(node_name, [])


# extension point -> the method a plugin must implement to join it (used to
# gate MultiPoint-expanded config entries onto real implementations)
_POINT_METHODS = {
    "queue_sort": "less",
    "pre_filter": "pre_filter",
    "filter": "filter",
    "post_filter": "post_filter",
    "pre_score": "pre_score",
    "score": "score_node",
    "reserve": "reserve",
    "permit": "permit",
    "pre_bind": "pre_bind",
    "bind": "bind",
    "post_bind": "post_bind",
}


class Framework:
    """One profile's plugin set (profile/profile.go maps scheduler-name →
    one of these)."""

    def __init__(
        self,
        handle_ctx: dict,
        plugin_config: Optional[Dict[str, List[Tuple[str, int]]]] = None,
        plugin_args: Optional[Dict[str, dict]] = None,
        registry=None,
        profile_name: str = "default-scheduler",
    ):
        self.profile_name = profile_name
        self.handle_ctx = handle_ctx
        self.nominator: PodNominator = handle_ctx.setdefault("nominator", PodNominator())
        registry = registry or in_tree_registry()
        config = plugin_config or DEFAULT_PLUGINS
        args = plugin_args or {}

        self._instances: Dict[str, object] = {}
        self.points: Dict[str, List[Tuple[object, int]]] = {}
        for point, entries in config.items():
            lst = []
            for name, weight in entries:
                factory = registry.get(name)
                if factory is None:
                    continue  # not-yet-implemented plugin in default config
                if name not in self._instances:
                    self._instances[name] = factory(handle_ctx, args.get(name, {}))
                method = _POINT_METHODS.get(point)
                if method and not hasattr(self._instances[name], method):
                    continue  # MultiPoint-expanded name; plugin doesn't do this point
                lst.append((self._instances[name], weight))
            self.points[point] = lst

        # late-bind plugins that need the framework itself (DefaultPreemption
        # runs filters during its dry-runs)
        for plugin in self._instances.values():
            if hasattr(plugin, "set_framework"):
                plugin.set_framework(self)

    def plugin(self, name: str):
        return self._instances.get(name)

    # --------------------------------------------------------------- events

    def cluster_event_map(self) -> Dict[ClusterEvent, Set[str]]:
        """plugin EventsToRegister → event → interested plugin names
        (fillEventToPluginMap)."""
        out: Dict[ClusterEvent, Set[str]] = {}
        for name, plugin in self._instances.items():
            events = plugin.events_to_register() if hasattr(plugin, "events_to_register") else None
            if not events:
                # plugins that don't opt in are movable by any event
                from .types import WILDCARD_EVENT

                out.setdefault(WILDCARD_EVENT, set()).add(name)
                continue
            for ev in events:
                out.setdefault(ev, set()).add(name)
        return out

    # --------------------------------------------------------------- queue sort

    def queue_sort_key(self):
        qs = self.points.get("queue_sort") or []
        if qs:
            plugin = qs[0][0]
            return lambda qp: (-qp.pod.spec.priority, qp.timestamp)
        return lambda qp: qp.timestamp

    # --------------------------------------------------------------- prefilter

    def run_pre_filter_plugins(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        state.prefilter_ran = True
        result: Optional[PreFilterResult] = None
        for plugin, _w in self.points.get("pre_filter", []):
            r, status = plugin.pre_filter(state, pod)
            if not status.is_success():
                return None, status.with_plugin(plugin.name())
            if r is not None and not r.all_nodes():
                result = r if result is None else result.merge(r)
                if result is not None and not result.node_names:
                    return result, Status.unresolvable(
                        "node(s) didn't satisfy plugin(s) prefilter restriction"
                    ).with_plugin(plugin.name())
        return result, OK

    # --------------------------------------------------------------- filter

    def run_filter_plugins(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        for plugin, _w in self.points.get("filter", []):
            status = plugin.filter(state, pod, node_info)
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    def run_filter_plugins_with_nominated_pods(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        """Two-pass filter (:791): pass 1 with ≥-priority nominated pods
        added; pass 2 without (both must pass, :813 comment)."""
        nominated = [
            p
            for p in self.nominator.nominated_pods_for_node(node_info.node.meta.name if node_info.node else "")
            if p.spec.priority >= pod.spec.priority and p.key() != pod.key()
        ]
        if nominated:
            state2 = state.clone()
            ni2 = node_info.clone()
            for np_ in nominated:
                ni2.add_pod(np_)
                self._run_add_pod_extensions(state2, pod, np_, ni2)
            status = self.run_filter_plugins(state2, pod, ni2)
            if not status.is_success():
                return status
        return self.run_filter_plugins(state, pod, node_info)

    def _run_add_pod_extensions(self, state: CycleState, pod: Pod, added: Pod, ni: NodeInfo) -> None:
        for plugin, _w in self.points.get("pre_filter", []):
            ext = plugin.pre_filter_extensions()
            if ext is not None:
                ext.add_pod(state, pod, added, ni)

    def run_remove_pod_extensions(self, state: CycleState, pod: Pod, removed: Pod, ni: NodeInfo) -> None:
        for plugin, _w in self.points.get("pre_filter", []):
            ext = plugin.pre_filter_extensions()
            if ext is not None:
                ext.remove_pod(state, pod, removed, ni)

    def run_add_pod_extensions(self, state: CycleState, pod: Pod, added: Pod, ni: NodeInfo) -> None:
        self._run_add_pod_extensions(state, pod, added, ni)

    # --------------------------------------------------------------- postfilter

    def run_post_filter_plugins(self, state: CycleState, pod: Pod, status_map) -> Tuple[Optional[str], Status]:
        for plugin, _w in self.points.get("post_filter", []):
            nominated, status = plugin.post_filter(state, pod, status_map)
            if status.is_success() or status.code == fw.ERROR:
                return nominated, status.with_plugin(plugin.name())
        return None, Status.unschedulable("no PostFilter plugin could resolve")

    # --------------------------------------------------------------- score

    def run_pre_score_plugins(self, state: CycleState, pod: Pod, nodes) -> Status:
        for plugin, _w in self.points.get("pre_score", []):
            status = plugin.pre_score(state, pod, nodes)
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    def run_score_plugins(self, state: CycleState, pod: Pod, node_infos: List[NodeInfo]) -> Dict[str, int]:
        """Returns node name → weighted total (:900-:972)."""
        totals = {ni.node.meta.name: 0 for ni in node_infos}
        for plugin, weight in self.points.get("score", []):
            scores = []
            for ni in node_infos:
                raw, status = plugin.score_node(state, pod, ni)
                if not status.is_success():
                    raise RuntimeError(f"score plugin {plugin.name()} failed: {status}")
                scores.append(NodeScore(ni.node.meta.name, raw))
            ext = plugin.score_extensions()
            if ext is not None:
                ext.normalize_score(state, pod, scores)
            for s in scores:
                if s.score > fw.MAX_NODE_SCORE or s.score < fw.MIN_NODE_SCORE:
                    raise RuntimeError(
                        f"plugin {plugin.name()} returned out-of-range score {s.score}"
                    )
                totals[s.name] += s.score * weight
        return totals

    # --------------------------------------------------------------- later points

    def run_reserve_plugins_reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for plugin, _w in self.points.get("reserve", []):
            status = plugin.reserve(state, pod, node_name)
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    def run_reserve_plugins_unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for plugin, _w in reversed(self.points.get("reserve", [])):
            plugin.unreserve(state, pod, node_name)

    def run_permit_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for plugin, _w in self.points.get("permit", []):
            status, _timeout = plugin.permit(state, pod, node_name)
            if not status.is_success() and status.code != fw.WAIT:
                return status.with_plugin(plugin.name())
            if status.code == fw.WAIT:
                return Status(fw.WAIT).with_plugin(plugin.name())
        return OK

    def run_pre_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for plugin, _w in self.points.get("pre_bind", []):
            status = plugin.pre_bind(state, pod, node_name)
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    def run_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for plugin, _w in self.points.get("bind", []):
            status = plugin.bind(state, pod, node_name)
            if status.code != fw.SKIP:
                return status.with_plugin(plugin.name())
        return Status.error("no bind plugin accepted the pod")

    def run_post_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for plugin, _w in self.points.get("post_bind", []):
            plugin.post_bind(state, pod, node_name)
