"""Framework runtime: builds a profile's plugins and executes extension
points (pkg/scheduler/framework/runtime/framework.go).

Key behaviors mirrored:
  * run_filter_plugins_with_nominated_pods (:791): filters run twice — first
    with higher-priority nominated pods added to a cloned NodeInfo/CycleState,
    then without — and both passes must succeed.
  * run_score_plugins (:900): raw scores per plugin → NormalizeScore → apply
    plugin weight; node-parallelism in the reference, vectorized-or-sequential
    here (the TPU backend replaces this wholesale on the hot path).
  * Filter short-circuit: plugins run in config order; first non-success
    status wins and is tagged with the plugin name.
"""

from __future__ import annotations

import contextlib
import functools
from time import perf_counter
from typing import Dict, List, Optional, Set, Tuple

from ..api.types import Pod
from ..utils import tracing
from . import interface as fw
from .interface import CycleState, NodeScore, PreFilterResult, Status, OK
from .registry import DEFAULT_PLUGINS, in_tree_registry
from .types import ClusterEvent, Diagnosis, NodeInfo, QueuedPodInfo


# CycleState key carrying a WAIT permit's timeout to the scheduler, and the
# default park duration when a plugin returns WAIT with no timeout
# (runtime/framework.go maxTimeout is 15min; gangs use far shorter)
PERMIT_TIMEOUT_KEY = "Permit/waitTimeout"
DEFAULT_PERMIT_WAIT_S = 600.0


class PodNominator:
    """Tracks preemption nominations (framework/interface.go:690;
    nominated pods get re-considered by filters before their victims exit)."""

    def __init__(self):
        self._by_node: Dict[str, List[Pod]] = {}
        self._node_of: Dict[str, str] = {}

    def add_nominated_pod(self, pod: Pod, node_name: str) -> None:
        self.delete_nominated_pod_if_exists(pod)
        if node_name:
            self._by_node.setdefault(node_name, []).append(pod)
            self._node_of[pod.key()] = node_name

    def delete_nominated_pod_if_exists(self, pod: Pod) -> None:
        node = self._node_of.pop(pod.key(), None)
        if node is not None:
            self._by_node[node] = [p for p in self._by_node[node] if p.key() != pod.key()]

    def nominated_pods_for_node(self, node_name: str) -> List[Pod]:
        return self._by_node.get(node_name, [])


def _trace_exemplar() -> Optional[dict]:
    """Active trace/span id as an OpenMetrics exemplar for a sampled
    duration observation: a slow plugin_execution_duration p99 bucket then
    links straight to a concrete trace (/debug/spans, KTPU_TRACE_FILE)
    instead of leaving the operator to guess which cycle was slow. One
    global read (None) when tracing is disabled."""
    span = tracing.current()
    if span is None:
        return None
    return {"trace_id": span.trace_id, "span_id": span.span_id}


def _status_str(out) -> str:
    """Extension-point status label from a run_* return value (Status,
    (x, Status) tuple, or anything else = Success)."""
    if isinstance(out, Status):
        return out.code_name()
    if isinstance(out, tuple):
        for x in out:
            if isinstance(x, Status):
                return x.code_name()
    return "Success"


def _instrument_point(point: str):
    """Observe scheduler_framework_extension_point_duration_seconds and open
    a ``framework.<point>`` span around one run_* extension-point executor
    (metrics.go:76 FrameworkExtensionPointDuration; the spans are the
    utiltrace/component-base per-phase attribution of SURVEY §5.1).

    Disabled-tracer cost is one module-global read; no metrics handle on the
    framework (Frameworks built outside a Scheduler) skips timing entirely.
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, state, *args, **kwargs):
            m = self._metrics
            tr = tracing._tracer
            if m is None and tr is None:
                return fn(self, state, *args, **kwargs)
            t0 = perf_counter()
            status = "Error"  # overwritten unless fn raises
            try:
                if tr is not None:
                    with tr.span("framework." + point, profile=self.profile_name):
                        out = fn(self, state, *args, **kwargs)
                else:
                    out = fn(self, state, *args, **kwargs)
                status = _status_str(out)
                return out
            finally:
                if m is not None:
                    m.framework_extension_point_duration.observe(
                        perf_counter() - t0, point, status, self.profile_name)

        return wrapper

    return deco


# extension point -> the method a plugin must implement to join it (used to
# gate MultiPoint-expanded config entries onto real implementations)
_POINT_METHODS = {
    "queue_sort": "less",
    "pre_enqueue": "pre_enqueue",
    "pre_filter": "pre_filter",
    "filter": "filter",
    "post_filter": "post_filter",
    "pre_score": "pre_score",
    "score": "score_node",
    "reserve": "reserve",
    "permit": "permit",
    "pre_bind": "pre_bind",
    "bind": "bind",
    "post_bind": "post_bind",
}


class Framework:
    """One profile's plugin set (profile/profile.go maps scheduler-name →
    one of these)."""

    def __init__(
        self,
        handle_ctx: dict,
        plugin_config: Optional[Dict[str, List[Tuple[str, int]]]] = None,
        plugin_args: Optional[Dict[str, dict]] = None,
        registry=None,
        profile_name: str = "default-scheduler",
    ):
        self.profile_name = profile_name
        self.handle_ctx = handle_ctx
        # SchedulerMetrics handle (the Scheduler always provides one; a
        # Framework built bare skips instrumentation)
        self._metrics = handle_ctx.get("metrics")
        self.nominator: PodNominator = handle_ctx.setdefault("nominator", PodNominator())
        registry = registry or in_tree_registry()
        config = plugin_config or DEFAULT_PLUGINS
        args = plugin_args or {}

        self._instances: Dict[str, object] = {}
        self.points: Dict[str, List[Tuple[object, int]]] = {}
        for point, entries in config.items():
            lst = []
            for name, weight in entries:
                factory = registry.get(name)
                if factory is None:
                    continue  # not-yet-implemented plugin in default config
                if name not in self._instances:
                    self._instances[name] = factory(handle_ctx, args.get(name, {}))
                method = _POINT_METHODS.get(point)
                if method and not hasattr(self._instances[name], method):
                    continue  # MultiPoint-expanded name; plugin doesn't do this point
                lst.append((self._instances[name], weight))
            self.points[point] = lst

        # late-bind plugins that need the framework itself (DefaultPreemption
        # runs filters during its dry-runs)
        for plugin in self._instances.values():
            if hasattr(plugin, "set_framework"):
                plugin.set_framework(self)

    def plugin(self, name: str):
        return self._instances.get(name)

    def _timed(self, state: CycleState, point: str, plugin, call):
        """Run one plugin call with per-plugin span + (sampled) duration
        histogram. Plugin-level metrics follow the reference's sampling
        (metrics.go:91 'sampled'): only cycles whose CycleState carries
        record_plugin_metrics pay the per-plugin observe — extension-point
        totals are always recorded by the _instrument_point wrapper."""
        m = self._metrics if (self._metrics is not None
                              and state.record_plugin_metrics) else None
        tr = tracing._tracer
        if m is None and tr is None:
            return call()
        t0 = perf_counter()
        status = "Error"  # overwritten unless call() raises
        try:
            if tr is not None:
                with tr.span("plugin." + plugin.name(), extension_point=point):
                    out = call()
            else:
                out = call()
            status = _status_str(out)
            return out
        finally:
            if m is not None:
                m.plugin_execution_duration.observe(
                    perf_counter() - t0, plugin.name(), point, status,
                    exemplar=_trace_exemplar())

    # --------------------------------------------------------------- events

    def cluster_event_map(self) -> Dict[ClusterEvent, Set[str]]:
        """plugin EventsToRegister → event → interested plugin names
        (fillEventToPluginMap)."""
        out: Dict[ClusterEvent, Set[str]] = {}
        for name, plugin in self._instances.items():
            events = plugin.events_to_register() if hasattr(plugin, "events_to_register") else None
            if not events:
                # plugins that don't opt in are movable by any event
                from .types import WILDCARD_EVENT

                out.setdefault(WILDCARD_EVENT, set()).add(name)
                continue
            for ev in events:
                out.setdefault(ev, set()).add(name)
        return out

    # --------------------------------------------------------------- queue sort

    def queue_sort_key(self):
        qs = self.points.get("queue_sort") or []
        if qs:
            plugin = qs[0][0]
            # a QueueSort plugin exposing a heap-key extractor (the form
            # SchedulingQueue consumes) drives ordering directly —
            # Coscheduling's gang-adjacent key; plain Less-only plugins get
            # the PrioritySort default
            if hasattr(plugin, "sort_key"):
                return plugin.sort_key
            return lambda qp: (-qp.pod.spec.priority, qp.timestamp)
        return lambda qp: qp.timestamp

    # --------------------------------------------------------------- pre-enqueue

    def run_pre_enqueue_plugins(self, pod: Pod) -> Status:
        """Queue-admission gate (runtime/framework.go RunPreEnqueuePlugins):
        first non-success status wins and the pod parks GATED. Called on
        every queue transition toward activeQ — deliberately outside the
        extension-point instrumentation (no CycleState exists yet and a
        histogram write per queue push would sit on the informer hot path).
        """
        for plugin, _w in self.points.get("pre_enqueue", []):
            status = plugin.pre_enqueue(pod)
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    # --------------------------------------------------------------- prefilter

    @_instrument_point("pre_filter")
    def run_pre_filter_plugins(self, state: CycleState, pod: Pod) -> Tuple[Optional[PreFilterResult], Status]:
        state.prefilter_ran = True
        result: Optional[PreFilterResult] = None
        for plugin, _w in self.points.get("pre_filter", []):
            r, status = self._timed(state, "pre_filter", plugin,
                                    lambda: plugin.pre_filter(state, pod))
            if not status.is_success():
                return None, status.with_plugin(plugin.name())
            if r is not None and not r.all_nodes():
                result = r if result is None else result.merge(r)
                if result is not None and not result.node_names:
                    return result, Status.unresolvable(
                        "node(s) didn't satisfy plugin(s) prefilter restriction"
                    ).with_plugin(plugin.name())
        return result, OK

    # --------------------------------------------------------------- filter

    def run_filter_plugins(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        # Filter is the per-NODE hot loop, so it is instrumented differently
        # from the per-pod points: the "filter" EXTENSION-POINT histogram is
        # observed once per attempt by the scheduler (the reference observes
        # Filter at findNodesThatFitPod level, not per node), per-plugin
        # timing is inlined (no closures) and only on sampled cycles, and
        # spans only when the tracer is live. Unsampled cycles with tracing
        # off pay one branch — anything per-plugin here was a measured ~2x
        # oracle-path slowdown at 13 plugins × hundreds of nodes per pod.
        tr = tracing._tracer
        if tr is not None:
            with tr.span("framework.filter", profile=self.profile_name):
                return self._filter_loop_timed(state, pod, node_info)
        if self._metrics is not None and state.record_plugin_metrics:
            return self._filter_loop_recorded(state, pod, node_info)
        for plugin, _w in self.points.get("filter", []):
            status = plugin.filter(state, pod, node_info)
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    def _filter_loop_timed(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        """Tracer-on filter loop: per-plugin spans (+ sampled metrics) via
        _timed — debug mode, where span fidelity beats raw speed."""
        for plugin, _w in self.points.get("filter", []):
            status = self._timed(state, "filter", plugin,
                                 lambda: plugin.filter(state, pod, node_info))
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    def _filter_loop_recorded(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        """Sampled-cycle filter loop: inline per-plugin duration observe
        (a raising plugin still gets its sample, with status Error)."""
        m = self._metrics
        for plugin, _w in self.points.get("filter", []):
            t0 = perf_counter()
            label = "Error"
            try:
                status = plugin.filter(state, pod, node_info)
                label = status.code_name()
            finally:
                m.plugin_execution_duration.observe(
                    perf_counter() - t0, plugin.name(), "filter", label,
                    exemplar=_trace_exemplar())
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    def run_filter_plugins_with_nominated_pods(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        """Two-pass filter (:791): pass 1 with ≥-priority nominated pods
        added; pass 2 without (both must pass, :813 comment)."""
        nominated = [
            p
            for p in self.nominator.nominated_pods_for_node(node_info.node.meta.name if node_info.node else "")
            if p.spec.priority >= pod.spec.priority and p.key() != pod.key()
        ]
        if nominated:
            state2 = state.clone()
            ni2 = node_info.clone()
            for np_ in nominated:
                ni2.add_pod(np_)
                self._run_add_pod_extensions(state2, pod, np_, ni2)
            status = self.run_filter_plugins(state2, pod, ni2)
            if not status.is_success():
                return status
        return self.run_filter_plugins(state, pod, node_info)

    def _run_add_pod_extensions(self, state: CycleState, pod: Pod, added: Pod, ni: NodeInfo) -> None:
        for plugin, _w in self.points.get("pre_filter", []):
            ext = plugin.pre_filter_extensions()
            if ext is not None:
                ext.add_pod(state, pod, added, ni)

    def run_remove_pod_extensions(self, state: CycleState, pod: Pod, removed: Pod, ni: NodeInfo) -> None:
        for plugin, _w in self.points.get("pre_filter", []):
            ext = plugin.pre_filter_extensions()
            if ext is not None:
                ext.remove_pod(state, pod, removed, ni)

    def run_add_pod_extensions(self, state: CycleState, pod: Pod, added: Pod, ni: NodeInfo) -> None:
        self._run_add_pod_extensions(state, pod, added, ni)

    # --------------------------------------------------------------- postfilter

    @_instrument_point("post_filter")
    def run_post_filter_plugins(self, state: CycleState, pod: Pod, status_map) -> Tuple[Optional[str], Status]:
        for plugin, _w in self.points.get("post_filter", []):
            nominated, status = self._timed(
                state, "post_filter", plugin,
                lambda: plugin.post_filter(state, pod, status_map))
            if status.is_success() or status.code == fw.ERROR:
                return nominated, status.with_plugin(plugin.name())
        return None, Status.unschedulable("no PostFilter plugin could resolve")

    # --------------------------------------------------------------- score

    @_instrument_point("pre_score")
    def run_pre_score_plugins(self, state: CycleState, pod: Pod, nodes) -> Status:
        for plugin, _w in self.points.get("pre_score", []):
            status = self._timed(state, "pre_score", plugin,
                                 lambda: plugin.pre_score(state, pod, nodes))
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    @_instrument_point("score")
    def run_score_plugins(self, state: CycleState, pod: Pod, node_infos: List[NodeInfo]) -> Dict[str, int]:
        """Returns node name → weighted total (:900-:972)."""
        totals = {ni.node.meta.name: 0 for ni in node_infos}
        for plugin, weight in self.points.get("score", []):
            def _score_one(plugin=plugin):
                scores = []
                for ni in node_infos:
                    raw, status = plugin.score_node(state, pod, ni)
                    if not status.is_success():
                        raise RuntimeError(f"score plugin {plugin.name()} failed: {status}")
                    scores.append(NodeScore(ni.node.meta.name, raw))
                ext = plugin.score_extensions()
                if ext is not None:
                    ext.normalize_score(state, pod, scores)
                return scores

            scores = self._timed(state, "score", plugin, _score_one)
            for s in scores:
                if s.score > fw.MAX_NODE_SCORE or s.score < fw.MIN_NODE_SCORE:
                    raise RuntimeError(
                        f"plugin {plugin.name()} returned out-of-range score {s.score}"
                    )
                totals[s.name] += s.score * weight
        return totals

    # --------------------------------------------------------------- later points

    @_instrument_point("reserve")
    def run_reserve_plugins_reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for plugin, _w in self.points.get("reserve", []):
            status = self._timed(state, "reserve", plugin,
                                 lambda: plugin.reserve(state, pod, node_name))
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    @_instrument_point("unreserve")
    def run_reserve_plugins_unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for plugin, _w in reversed(self.points.get("reserve", [])):
            self._timed(state, "unreserve", plugin,
                        lambda: plugin.unreserve(state, pod, node_name))

    @_instrument_point("permit")
    def run_permit_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for plugin, _w in self.points.get("permit", []):
            status, timeout = self._timed(
                state, "permit", plugin,
                lambda: plugin.permit(state, pod, node_name))
            if not status.is_success() and status.code != fw.WAIT:
                return status.with_plugin(plugin.name())
            if status.code == fw.WAIT:
                # the plugin's wait timeout rides the CycleState so the
                # scheduler can park the pod with a real deadline
                # (waiting_pods_map.go's per-pod timer)
                state.write(PERMIT_TIMEOUT_KEY,
                            float(timeout) if timeout else DEFAULT_PERMIT_WAIT_S)
                return Status(fw.WAIT).with_plugin(plugin.name())
        return OK

    @_instrument_point("pre_bind")
    def run_pre_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for plugin, _w in self.points.get("pre_bind", []):
            status = self._timed(state, "pre_bind", plugin,
                                 lambda: plugin.pre_bind(state, pod, node_name))
            if not status.is_success():
                return status.with_plugin(plugin.name())
        return OK

    @_instrument_point("bind")
    def run_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for plugin, _w in self.points.get("bind", []):
            status = self._timed(state, "bind", plugin,
                                 lambda: plugin.bind(state, pod, node_name))
            if status.code != fw.SKIP:
                return status.with_plugin(plugin.name())
        return Status.error("no bind plugin accepted the pod")

    @_instrument_point("post_bind")
    def run_post_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for plugin, _w in self.points.get("post_bind", []):
            self._timed(state, "post_bind", plugin,
                        lambda: plugin.post_bind(state, pod, node_name))

    # ----------------------------------------------------- batched bind tail
    # (the commit data plane's coalesced instrumentation: one extension-point
    # observation and one span cover a whole committed batch instead of one
    # per pod — at 5k nodes the per-pod wrapper overhead alone was a
    # measured multi-ms slice of host.commit. Per-plugin SAMPLED metrics are
    # deliberately not recorded on the batched executors; the per-pod paths
    # keep them.)

    def _observe_plugin_sample(self, state, point, plugin, call):
        """One SAMPLED per-plugin observation inside a batched executor:
        items whose CycleState carries record_plugin_metrics (attempt-1 /
        1-in-20, the per-pod sampling rule) still feed
        plugin_execution_duration — the batch path batches the
        extension-point totals, not the sampled per-plugin contract."""
        if self._metrics is None or not state.record_plugin_metrics:
            return call()
        t0 = perf_counter()
        status = "Error"
        try:
            out = call()
            status = _status_str(out)
            return out
        finally:
            self._metrics.plugin_execution_duration.observe(
                perf_counter() - t0, plugin.name(), point, status,
                exemplar=_trace_exemplar())

    def _run_point_batch(self, point: str, items, call) -> list:
        """Run ``call(plugin, state, pod, node_name) -> Status`` for every
        plugin at ``point`` over every (state, pod, node_name) item, with
        ONE framework_extension_point_duration observation and one span for
        the whole batch (sampled items keep their per-plugin duration
        observations). Returns per-item Status (first failure per item
        wins; remaining plugins skip that item, matching the per-pod
        short-circuit)."""
        statuses = [OK] * len(items)
        plugins = self.points.get(point, [])
        if not plugins or not items:
            return statuses
        m = self._metrics
        tr = tracing._tracer
        t0 = perf_counter()
        worst = "Success"
        try:
            span = (tr.span("framework." + point, profile=self.profile_name,
                            batch=len(items))
                    if tr is not None else contextlib.nullcontext())
            with span:
                # item-outer: pod i runs its whole plugin chain before pod
                # i+1 starts — byte-for-byte the per-pod executor's order
                for i, (state, pod, node_name) in enumerate(items):
                    for plugin, _w in plugins:
                        st = self._observe_plugin_sample(
                            state, point, plugin,
                            lambda p=plugin, s=state, pd=pod, n=node_name:
                            call(p, s, pd, n))
                        if st is not None and not st.is_success():
                            statuses[i] = st.with_plugin(plugin.name())
                            worst = st.code_name()
                            break
            return statuses
        except Exception:
            worst = "Error"
            raise
        finally:
            if m is not None:
                m.framework_extension_point_duration.observe(
                    perf_counter() - t0, point, worst, self.profile_name)

    def run_reserve_plugins_reserve_batch(self, items) -> list:
        """Batched Reserve over (state, pod, node_name) items; per-item
        short-circuit semantics identical to run_reserve_plugins_reserve.
        A failed item's ALREADY-RUN reserve plugins are unreserved by the
        caller via run_reserve_plugins_unreserve (whole-point unreserve is
        the per-pod contract too — Unreserve must tolerate a reserve that
        never ran, and every in-tree plugin does)."""
        return self._run_point_batch(
            "reserve", items,
            lambda plugin, state, pod, node: plugin.reserve(state, pod, node))

    def run_permit_plugins_batch(self, items, on_wait=None) -> list:
        """Batched Permit: per-item semantics of run_permit_plugins (first
        WAIT wins and stamps PERMIT_TIMEOUT_KEY on the item's CycleState;
        first failure wins) with one instrumentation record per batch.
        ``on_wait(i, status)`` fires the moment item i votes WAIT — BEFORE
        the next item's permit runs. Gang quorum depends on this: member
        i+1's Coscheduling permit counts member i among the parked holders,
        exactly as the per-pod cycle interleaves park and permit."""
        statuses = [OK] * len(items)
        plugins = self.points.get("permit", [])
        if not plugins or not items:
            return statuses
        m = self._metrics
        tr = tracing._tracer
        t0 = perf_counter()
        worst = "Success"
        try:
            span = (tr.span("framework.permit", profile=self.profile_name,
                            batch=len(items))
                    if tr is not None else contextlib.nullcontext())
            with span:
                for i, (state, pod, node_name) in enumerate(items):
                    for plugin, _w in plugins:
                        status, timeout = self._observe_plugin_sample(
                            state, "permit", plugin,
                            lambda p=plugin, s=state, pd=pod, n=node_name:
                            p.permit(s, pd, n))
                        if not status.is_success() and status.code != fw.WAIT:
                            statuses[i] = status.with_plugin(plugin.name())
                            worst = status.code_name()
                            break
                        if status.code == fw.WAIT:
                            state.write(PERMIT_TIMEOUT_KEY,
                                        float(timeout) if timeout
                                        else DEFAULT_PERMIT_WAIT_S)
                            statuses[i] = Status(fw.WAIT).with_plugin(
                                plugin.name())
                            if on_wait is not None:
                                on_wait(i, statuses[i])
                            break
            return statuses
        except Exception:
            worst = "Error"
            raise
        finally:
            if m is not None:
                m.framework_extension_point_duration.observe(
                    perf_counter() - t0, "permit", worst, self.profile_name)

    def run_pre_bind_plugins_batch(self, items) -> list:
        return self._run_point_batch(
            "pre_bind", items,
            lambda plugin, state, pod, node: plugin.pre_bind(state, pod, node))

    def run_post_bind_plugins_batch(self, items) -> None:
        """Batched PostBind: plugins exposing ``post_bind_batch`` get the
        whole batch in one call (Coscheduling updates each touched gang's
        status ONCE per commit instead of once per member); the rest run
        per item."""
        plugins = self.points.get("post_bind", [])
        if not plugins or not items:
            return
        m = self._metrics
        tr = tracing._tracer
        t0 = perf_counter()
        try:
            span = (tr.span("framework.post_bind", profile=self.profile_name,
                            batch=len(items))
                    if tr is not None else contextlib.nullcontext())
            with span:
                sampled = (m is not None
                           and any(state.record_plugin_metrics
                                   for state, _p, _n in items))
                for plugin, _w in plugins:
                    batch_fn = getattr(plugin, "post_bind_batch", None)
                    if batch_fn is not None:
                        tp0 = perf_counter()
                        batch_fn(items)
                        if sampled:
                            # batch-granular plugin sample: the whole-batch
                            # call IS this plugin's unit of work here
                            m.plugin_execution_duration.observe(
                                perf_counter() - tp0, plugin.name(),
                                "post_bind", "Success",
                                exemplar=_trace_exemplar())
                    else:
                        for state, pod, node_name in items:
                            self._observe_plugin_sample(
                                state, "post_bind", plugin,
                                lambda p=plugin, s=state, pd=pod,
                                n=node_name: p.post_bind(s, pd, n))
        finally:
            if m is not None:
                m.framework_extension_point_duration.observe(
                    perf_counter() - t0, "post_bind", "Success",
                    self.profile_name)
