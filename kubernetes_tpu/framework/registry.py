"""Plugin registry and the default plugin configuration.

Analog of plugins/registry.go:46 (name → factory) and
apis/config/v1beta3/default_plugins.go:32-51 (default enabled set + weights).
Factories receive a ``handle``-like context dict so plugins can grab the
snapshot lister, client, and per-plugin args.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from .plugins import names
from .plugins.basic import NodeName, NodePorts, NodeUnschedulable, PrioritySort, TaintToleration
from .plugins.coscheduling import Coscheduling
from .plugins.defaultbinder import DefaultBinder
from .plugins.defaultpreemption import DefaultPreemption
from .plugins.dynamicresources import DynamicResources
from .plugins.quota import QuotaAdmission
from .plugins.imagelocality import ImageLocality
from .plugins.interpodaffinity import InterPodAffinity
from .plugins.nodeaffinity import NodeAffinity
from .plugins.noderesources import BalancedAllocation, Fit
from .plugins.podtopologyspread import PodTopologySpread
from .plugins.selectorspread import SelectorSpread
from .plugins.slicepacking import SlicePacking
from .plugins.volume import (
    NodeVolumeLimits,
    VolumeBinding,
    VolumeRestrictions,
    VolumeZone,
    make_azure_disk_limits,
    make_cinder_limits,
    make_ebs_limits,
    make_gce_pd_limits,
)

Factory = Callable[[dict, dict], object]  # (handle_ctx, args) -> Plugin


def in_tree_registry() -> Dict[str, Factory]:
    return {
        names.PRIORITY_SORT: lambda h, a: PrioritySort(),
        names.NODE_UNSCHEDULABLE: lambda h, a: NodeUnschedulable(),
        names.NODE_NAME: lambda h, a: NodeName(),
        names.TAINT_TOLERATION: lambda h, a: TaintToleration(),
        names.NODE_PORTS: lambda h, a: NodePorts(),
        names.NODE_AFFINITY: lambda h, a: NodeAffinity(added_affinity=a.get("added_affinity")),
        names.NODE_RESOURCES_FIT: lambda h, a: Fit(
            strategy=a.get("strategy", "LeastAllocated"),
            resources=tuple(a.get("resources", (("cpu", 1), ("memory", 1)))),
            shape=tuple(a.get("shape", ())),
        ),
        names.NODE_RESOURCES_BALANCED_ALLOCATION: lambda h, a: BalancedAllocation(
            resources=tuple(a.get("resources", (("cpu", 1), ("memory", 1)))),
        ),
        names.IMAGE_LOCALITY: lambda h, a: ImageLocality(snapshot_fn=h.get("snapshot_fn")),
        names.POD_TOPOLOGY_SPREAD: lambda h, a: PodTopologySpread(
            snapshot_fn=h.get("snapshot_fn"),
            default_constraints=tuple(a.get("default_constraints", ())),
            system_defaulted=a.get("system_defaulted", False),
        ),
        names.INTER_POD_AFFINITY: lambda h, a: InterPodAffinity(
            snapshot_fn=h.get("snapshot_fn"),
            ns_labels_fn=h.get("ns_labels_fn"),
            hard_pod_affinity_weight=a.get("hard_pod_affinity_weight", 1),
        ),
        names.DEFAULT_BINDER: lambda h, a: DefaultBinder(client=h.get("client")),
        names.VOLUME_ZONE: lambda h, a: VolumeZone(client=h.get("client")),
        names.VOLUME_RESTRICTIONS: lambda h, a: VolumeRestrictions(
            client=h.get("client"), snapshot_fn=h.get("snapshot_fn")
        ),
        names.NODE_VOLUME_LIMITS: lambda h, a: NodeVolumeLimits(client=h.get("client")),
        names.EBS_LIMITS: lambda h, a: make_ebs_limits(client=h.get("client")),
        names.GCE_PD_LIMITS: lambda h, a: make_gce_pd_limits(client=h.get("client")),
        names.AZURE_DISK_LIMITS: lambda h, a: make_azure_disk_limits(client=h.get("client")),
        names.CINDER_LIMITS: lambda h, a: make_cinder_limits(client=h.get("client")),
        names.SELECTOR_SPREAD: lambda h, a: SelectorSpread(
            store=h.get("client"), snapshot_fn=h.get("snapshot_fn")
        ),
        names.VOLUME_BINDING: lambda h, a: VolumeBinding(client=h.get("client")),
        names.DYNAMIC_RESOURCES: lambda h, a: DynamicResources(
            client=h.get("client"), metrics=h.get("metrics")),
        names.QUOTA_ADMISSION: lambda h, a: QuotaAdmission(
            client=h.get("client"), metrics=h.get("metrics"),
            now_fn=h.get("now_fn")),
        names.SLICE_PACKING: lambda h, a: SlicePacking(
            snapshot_fn=h.get("snapshot_fn"), client=h.get("client")),
        names.COSCHEDULING: lambda h, a: Coscheduling(
            client=h.get("client"), metrics=h.get("metrics"),
            waiting=h.get("waiting_pods"), now_fn=h.get("now_fn"),
            permit_timeout_s=a.get(
                "permit_timeout_s", Coscheduling.DEFAULT_PERMIT_TIMEOUT_S),
            gang_backoff_s=a.get(
                "gang_backoff_s", Coscheduling.DEFAULT_GANG_BACKOFF_S)),
        names.DEFAULT_PREEMPTION: lambda h, a: DefaultPreemption(
            snapshot_fn=h.get("snapshot_fn"),
            pdb_lister=(h["client"].list_pdbs if h.get("client") is not None and hasattr(h["client"], "list_pdbs") else None),
            min_candidate_nodes_percentage=a.get("min_candidate_nodes_percentage", 10),
            min_candidate_nodes_absolute=a.get("min_candidate_nodes_absolute", 100),
            seed=a.get("seed", 0),
        ),
    }


# (plugin name, weight) per extension point — default_plugins.go:32-51.
DEFAULT_PLUGINS: Dict[str, List[Tuple[str, int]]] = {
    # Coscheduling owns QueueSort (gang members sort adjacently); for
    # groupless pods its key degrades EXACTLY to PrioritySort's
    # (-priority, queue timestamp) order
    "queue_sort": [(names.COSCHEDULING, 0)],
    # queue-admission gate: over-quota pods park GATED without spending a
    # scheduling cycle (upstream PreEnqueue semantics; SchedulingQueue runs
    # the point on every transition toward activeQ)
    "pre_enqueue": [(names.QUOTA_ADMISSION, 0)],
    "pre_filter": [
        # first: quota then gang quorum — the two cheapest fast-fails, both
        # namespace-level (no per-node work behind them)
        (names.QUOTA_ADMISSION, 0),
        (names.COSCHEDULING, 0),
        (names.NODE_AFFINITY, 0),
        (names.NODE_PORTS, 0),
        (names.NODE_RESOURCES_FIT, 0),
        (names.VOLUME_RESTRICTIONS, 0),
        (names.POD_TOPOLOGY_SPREAD, 0),
        (names.INTER_POD_AFFINITY, 0),
        (names.VOLUME_BINDING, 0),
        (names.DYNAMIC_RESOURCES, 0),
        # slice-topology plan (inert without the ktpu.dev/slice marker):
        # runs LAST so the plan sees every cheaper fast-fail first
        (names.SLICE_PACKING, 0),
    ],
    "filter": [
        (names.NODE_UNSCHEDULABLE, 0),
        (names.NODE_NAME, 0),
        (names.TAINT_TOLERATION, 0),
        (names.NODE_AFFINITY, 0),
        (names.NODE_PORTS, 0),
        (names.NODE_RESOURCES_FIT, 0),
        (names.VOLUME_RESTRICTIONS, 0),
        (names.NODE_VOLUME_LIMITS, 0),
        (names.VOLUME_BINDING, 0),
        (names.VOLUME_ZONE, 0),
        (names.POD_TOPOLOGY_SPREAD, 0),
        (names.INTER_POD_AFFINITY, 0),
        (names.DYNAMIC_RESOURCES, 0),
        # torus pin for slice-gang members (ops/slice.py plan; id 11 in the
        # batch path's first-fail attribution)
        (names.SLICE_PACKING, 0),
    ],
    "post_filter": [(names.DEFAULT_PREEMPTION, 0)],
    "pre_score": [
        (names.TAINT_TOLERATION, 0),
        (names.NODE_AFFINITY, 0),
        (names.POD_TOPOLOGY_SPREAD, 0),
        (names.INTER_POD_AFFINITY, 0),
        (names.IMAGE_LOCALITY, 0),
    ],
    "score": [
        (names.NODE_RESOURCES_BALANCED_ALLOCATION, 1),
        (names.IMAGE_LOCALITY, 1),
        (names.INTER_POD_AFFINITY, 2),
        (names.NODE_RESOURCES_FIT, 1),
        (names.NODE_AFFINITY, 2),
        (names.POD_TOPOLOGY_SPREAD, 2),
        (names.TAINT_TOLERATION, 3),
    ],
    # QuotaAdmission first: the charge is the cheapest reserve step and its
    # rejection must precede volume/claim reservations (its Unreserve runs
    # last in the reverse teardown, releasing the charge after them)
    "reserve": [(names.QUOTA_ADMISSION, 0), (names.VOLUME_BINDING, 0),
                (names.DYNAMIC_RESOURCES, 0), (names.COSCHEDULING, 0)],
    "permit": [(names.COSCHEDULING, 0)],
    "pre_bind": [(names.VOLUME_BINDING, 0)],
    "bind": [(names.DEFAULT_BINDER, 0)],
    "post_bind": [(names.DYNAMIC_RESOURCES, 0), (names.COSCHEDULING, 0)],
}
