from . import interface, types  # noqa: F401
from .interface import (  # noqa: F401
    CycleState,
    Handle,
    Status,
    OK,
    SUCCESS,
    ERROR,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    WAIT,
    SKIP,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
    NodeScore,
    PreFilterResult,
)
from .types import NodeInfo, Resource, QueuedPodInfo, ClusterEvent, Diagnosis, FitError  # noqa: F401
