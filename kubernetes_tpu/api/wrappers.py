"""Fluent object builders for tests and workload generators.

Analog of pkg/scheduler/testing/wrappers.go:190 (PodWrapper) and :633
(NodeWrapper) — the reference's unit/integration/perf tests all construct
objects through these, and ours do too.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from .types import (
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PreferredSchedulingTerm,
    Requirement,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
    DO_NOT_SCHEDULE,
)


class PodWrapper:
    def __init__(self, name: str = "pod", namespace: str = "default"):
        self.pod = Pod(meta=ObjectMeta(name=name, namespace=namespace, uid=f"{namespace}/{name}"))
        self.pod.spec.containers.append(Container(name="c0", image="registry/pause:3.7"))

    def obj(self) -> Pod:
        return self.pod

    def uid(self, uid: str) -> "PodWrapper":
        self.pod.meta.uid = uid
        return self

    def label(self, k: str, v: str) -> "PodWrapper":
        self.pod.meta.labels[k] = v
        return self

    def labels(self, labels: Dict[str, str]) -> "PodWrapper":
        self.pod.meta.labels.update(labels)
        return self

    def req(self, requests: Dict[str, object]) -> "PodWrapper":
        """Set resource requests on the main container (PodWrapper.Req)."""
        self.pod.spec.containers[0].requests = dict(requests)
        return self

    def init_req(self, requests: Dict[str, object]) -> "PodWrapper":
        self.pod.spec.init_containers.append(Container(name=f"init{len(self.pod.spec.init_containers)}", requests=dict(requests)))
        return self

    def overhead(self, overhead: Dict[str, object]) -> "PodWrapper":
        self.pod.spec.overhead = dict(overhead)
        return self

    def container(self, image: str, requests: Optional[Dict[str, object]] = None) -> "PodWrapper":
        self.pod.spec.containers.append(
            Container(name=f"c{len(self.pod.spec.containers)}", image=image, requests=dict(requests or {}))
        )
        return self

    def node(self, name: str) -> "PodWrapper":
        self.pod.spec.node_name = name
        return self

    def pvc(self, claim_name: str) -> "PodWrapper":
        """Add a PVC-backed volume (testing/wrappers.go PVC)."""
        self.pod.spec.volumes = self.pod.spec.volumes + (claim_name,)
        return self

    def resource_claim(self, name: str, claim_name: str = "",
                       template_name: str = "") -> "PodWrapper":
        """Add a pod.spec.resourceClaims entry (resource.k8s.io DRA):
        either a direct claim reference or a template reference the
        resourceclaim controller materializes as ``<pod>-<name>``."""
        from .types import PodResourceClaim

        self.pod.spec.resource_claims = self.pod.spec.resource_claims + (
            PodResourceClaim(name=name, claim_name=claim_name,
                             template_name=template_name),
        )
        return self

    def pod_group(self, name: str) -> "PodWrapper":
        """Join a gang: set the scheduling.x-k8s.io pod-group label the
        Coscheduling plugin keys on (the PodGroup object itself is created
        separately in the pod's namespace)."""
        from .types import POD_GROUP_LABEL

        self.pod.meta.labels[POD_GROUP_LABEL] = name
        return self

    def owner(self, kind: str, name: str) -> "PodWrapper":
        """Set the controller ownerReference (metav1.GetControllerOf)."""
        from .types import OwnerReference

        self.pod.meta.owner_references = self.pod.meta.owner_references + (
            OwnerReference(kind=kind, name=name, controller=True),
        )
        return self

    def priority(self, p: int) -> "PodWrapper":
        self.pod.spec.priority = p
        return self

    def scheduler_name(self, name: str) -> "PodWrapper":
        self.pod.spec.scheduler_name = name
        return self

    def node_selector(self, sel: Dict[str, str]) -> "PodWrapper":
        self.pod.spec.node_selector = dict(sel)
        return self

    def toleration(self, key: str = "", operator: str = "Equal", value: str = "", effect: str = "") -> "PodWrapper":
        self.pod.spec.tolerations = self.pod.spec.tolerations + (
            Toleration(key=key, operator=operator, value=value, effect=effect),
        )
        return self

    def host_port(self, port: int, protocol: str = "TCP", host_ip: str = "") -> "PodWrapper":
        c = self.pod.spec.containers[0]
        c.ports = c.ports + (ContainerPort(host_port=port, container_port=port, protocol=protocol, host_ip=host_ip),)
        return self

    def node_affinity_in(self, key: str, values: Sequence[str]) -> "PodWrapper":
        """Required node affinity: key In values (PodWrapper.NodeAffinityIn)."""
        term = NodeSelectorTerm(match_expressions=(Requirement(key, "In", tuple(values)),))
        return self._add_required_node_term(term)

    def node_affinity_not_in(self, key: str, values: Sequence[str]) -> "PodWrapper":
        term = NodeSelectorTerm(match_expressions=(Requirement(key, "NotIn", tuple(values)),))
        return self._add_required_node_term(term)

    def _add_required_node_term(self, term: NodeSelectorTerm) -> "PodWrapper":
        aff = self.pod.spec.affinity or Affinity()
        na = aff.node_affinity or NodeAffinity()
        req = na.required or NodeSelector()
        na.required = NodeSelector(terms=req.terms + (term,))
        aff.node_affinity = na
        self.pod.spec.affinity = aff
        return self

    def preferred_node_affinity(self, weight: int, key: str, values: Sequence[str]) -> "PodWrapper":
        aff = self.pod.spec.affinity or Affinity()
        na = aff.node_affinity or NodeAffinity()
        na.preferred = na.preferred + (
            PreferredSchedulingTerm(
                weight=weight,
                preference=NodeSelectorTerm(match_expressions=(Requirement(key, "In", tuple(values)),)),
            ),
        )
        aff.node_affinity = na
        self.pod.spec.affinity = aff
        return self

    def pod_affinity(self, topology_key: str, selector: LabelSelector, anti: bool = False) -> "PodWrapper":
        """Required pod (anti-)affinity term (PodWrapper.PodAffinity/PodAntiAffinity)."""
        aff = self.pod.spec.affinity or Affinity()
        term = PodAffinityTerm(label_selector=selector, topology_key=topology_key)
        if anti:
            pa = aff.pod_anti_affinity or PodAntiAffinity()
            pa.required = pa.required + (term,)
            aff.pod_anti_affinity = pa
        else:
            pa = aff.pod_affinity or PodAffinity()
            pa.required = pa.required + (term,)
            aff.pod_affinity = pa
        self.pod.spec.affinity = aff
        return self

    def preferred_pod_affinity(self, weight: int, topology_key: str, selector: LabelSelector, anti: bool = False) -> "PodWrapper":
        aff = self.pod.spec.affinity or Affinity()
        wterm = WeightedPodAffinityTerm(weight=weight, term=PodAffinityTerm(label_selector=selector, topology_key=topology_key))
        if anti:
            pa = aff.pod_anti_affinity or PodAntiAffinity()
            pa.preferred = pa.preferred + (wterm,)
            aff.pod_anti_affinity = pa
        else:
            pa = aff.pod_affinity or PodAffinity()
            pa.preferred = pa.preferred + (wterm,)
            aff.pod_affinity = pa
        self.pod.spec.affinity = aff
        return self

    def spread_constraint(
        self,
        max_skew: int,
        topology_key: str,
        when_unsatisfiable: str = DO_NOT_SCHEDULE,
        selector: Optional[LabelSelector] = None,
        min_domains: Optional[int] = None,
    ) -> "PodWrapper":
        self.pod.spec.topology_spread_constraints = self.pod.spec.topology_spread_constraints + (
            TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=topology_key,
                when_unsatisfiable=when_unsatisfiable,
                label_selector=selector,
                min_domains=min_domains,
            ),
        )
        return self


class NodeWrapper:
    def __init__(self, name: str = "node"):
        self.node_ = Node(meta=ObjectMeta(name=name, namespace="", uid=f"node/{name}"))
        self.label("kubernetes.io/hostname", name)

    def obj(self) -> Node:
        return self.node_

    def label(self, k: str, v: str) -> "NodeWrapper":
        self.node_.meta.labels[k] = v
        return self

    def capacity(self, resources: Dict[str, object]) -> "NodeWrapper":
        """Sets capacity AND allocatable (NodeWrapper.Capacity semantics)."""
        self.node_.status.capacity = dict(resources)
        self.node_.status.allocatable = dict(resources)
        return self

    def allocatable(self, resources: Dict[str, object]) -> "NodeWrapper":
        self.node_.status.allocatable = dict(resources)
        return self

    def taint(self, key: str, value: str = "", effect: str = "NoSchedule") -> "NodeWrapper":
        self.node_.spec.taints = self.node_.spec.taints + (Taint(key=key, value=value, effect=effect),)
        return self

    def unschedulable(self, v: bool = True) -> "NodeWrapper":
        self.node_.spec.unschedulable = v
        return self

    def image(self, name: str, size_bytes: int) -> "NodeWrapper":
        self.node_.status.images = self.node_.status.images + (
            ContainerImage(names=(name,), size_bytes=size_bytes),
        )
        return self

    def device_attrs(self, attrs: Dict[str, object]) -> "NodeWrapper":
        """Publish a device slice (NodeStatus.device_attributes): the
        attribute map resource.k8s.io selectors match against."""
        self.node_.status.device_attributes.update(attrs)
        return self


def make_pod(name: str = "pod", namespace: str = "default") -> PodWrapper:
    return PodWrapper(name, namespace)


def make_node(name: str = "node") -> NodeWrapper:
    return NodeWrapper(name)
