"""Generic JSON wire codec for the API dataclasses.

The reference serializes API objects through the runtime.Scheme + codecs
stack (apimachinery pkg/runtime/serializer/); here every API type is a plain
typed dataclass, so one reflection codec covers the whole surface: dataclass
fields round-trip by name, tuples/lists/dicts/Optionals recurse by their
type hints. Field names stay snake_case (this framework's own wire format —
not the reference's camelCase JSON; the seam is versioned by ``apiVersion``
in the envelope, see backend/service.py).
"""

from __future__ import annotations

import dataclasses
import typing
from functools import lru_cache
from typing import Any, Dict, get_args, get_origin, get_type_hints


def to_wire(obj: Any) -> Any:
    """Dataclass → JSON-compatible structure (recursive)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if v is None:
                continue  # omitempty
            out[f.name] = to_wire(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    raise TypeError(f"not wire-encodable: {type(obj).__name__}")


@lru_cache(maxsize=None)
def _hints(cls) -> Dict[str, Any]:
    return get_type_hints(cls)


def _from_hint(hint: Any, v: Any) -> Any:
    if v is None:
        return None
    origin = get_origin(hint)
    if origin is typing.Union:  # Optional[T] and unions: first matching arm
        args = [a for a in get_args(hint) if a is not type(None)]
        return _from_hint(args[0], v) if args else v
    if origin in (list, typing.List):
        (item,) = get_args(hint) or (Any,)
        return [_from_hint(item, x) for x in v]
    if origin in (tuple, typing.Tuple):
        args = get_args(hint)
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_from_hint(args[0], x) for x in v)
        if args:
            return tuple(_from_hint(a, x) for a, x in zip(args, v))
        return tuple(v)
    if origin in (dict, typing.Dict):
        kt, vt = get_args(hint) or (Any, Any)
        return {_from_hint(kt, k): _from_hint(vt, x) for k, x in v.items()}
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        return from_wire(hint, v)
    if hint is Any or hint is object:
        return v
    if isinstance(hint, type) and isinstance(v, hint):
        return v
    if isinstance(hint, type):
        return hint(v)  # int/float/str/bool coercion
    return v


def from_wire(cls, data: Dict[str, Any]):
    """JSON structure → dataclass of type ``cls`` (recursive, hint-driven).
    Unknown fields are ignored (forward compatibility)."""
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _from_hint(hints.get(f.name, Any), data[f.name])
    return cls(**kwargs)
