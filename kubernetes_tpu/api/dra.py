"""Dynamic Resource Allocation structured parameters — the ONE selector
model shared by the scalar DynamicResources plugin and the TPU batched
claim-feasibility mask (backend/batch.py claim_feasibility_mask), so
oracle↔kernel parity is exact by construction (the api/resource.py pattern).

A selector map is ``attribute key -> expression``:

    {"tpu.dev/cores": ">=4", "tpu.dev/gen": "v5", "tpu.dev/pcie": "!=1"}

Expressions are ``[op]operand`` with op one of ``== != >= > <= <`` (bare
operand means equality); integer operands parse to ints, anything else is a
string. Node attribute values (NodeStatus.device_attributes) are ints or
strings. Matching semantics (identical on host and device):

  * an absent attribute never matches, under ANY operator;
  * ==/!= require the same value type (int vs string) — a type mismatch is
    a non-match, not an error;
  * ordering operators match only int attribute against int operand.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Tuple

# selector op codes — also the device encoding (backend/batch.py); -1 pads
OP_EQ = 0
OP_NE = 1
OP_GE = 2
OP_GT = 3
OP_LE = 4
OP_LT = 5

_OP_TOKENS = (
    (">=", OP_GE), ("<=", OP_LE), ("==", OP_EQ), ("!=", OP_NE),
    (">", OP_GT), ("<", OP_LT),
)

# attribute value kinds — the device encoding's type tag (0 = absent)
KIND_ABSENT = 0
KIND_INT = 1
KIND_STR = 2

_INT32_MIN, _INT32_MAX = -(2 ** 31), 2 ** 31 - 1


def attr_kind_val(value) -> Tuple[int, object]:
    """Canonical (kind, value) for one published attribute: ints clamp to
    int32 (the device cell width), strings pass through, anything else is
    treated as absent (bools included — ambiguous between the two domains)."""
    if isinstance(value, bool) or value is None:
        return KIND_ABSENT, 0
    if isinstance(value, int):
        return KIND_INT, min(max(value, _INT32_MIN), _INT32_MAX)
    if isinstance(value, str):
        return KIND_STR, value
    return KIND_ABSENT, 0


@dataclasses.dataclass(frozen=True)
class DeviceSelector:
    """One parsed attribute requirement: ``key op operand`` with the operand
    already typed (operand_kind KIND_INT/KIND_STR)."""

    key: str
    op: int = OP_EQ
    operand_kind: int = KIND_INT
    operand: object = 0

    def matches(self, attrs: Mapping[str, object]) -> bool:
        kind, val = attr_kind_val(attrs.get(self.key)) if attrs else (KIND_ABSENT, 0)
        if kind == KIND_ABSENT:
            return False
        if self.op == OP_EQ:
            return kind == self.operand_kind and val == self.operand
        if self.op == OP_NE:
            return kind == self.operand_kind and val != self.operand
        if kind != KIND_INT or self.operand_kind != KIND_INT:
            return False
        if self.op == OP_GE:
            return val >= self.operand
        if self.op == OP_GT:
            return val > self.operand
        if self.op == OP_LE:
            return val <= self.operand
        return val < self.operand  # OP_LT


def _typed_operand(tok: str) -> Tuple[int, object]:
    try:
        return KIND_INT, min(max(int(tok, 10), _INT32_MIN), _INT32_MAX)
    except ValueError:
        return KIND_STR, tok


def parse_selector(key: str, expr) -> DeviceSelector:
    """One map entry -> DeviceSelector. Non-string expressions (YAML ints)
    mean equality on that value."""
    if not isinstance(expr, str):
        kind, val = attr_kind_val(expr)
        if kind == KIND_ABSENT:
            kind, val = KIND_STR, str(expr)
        return DeviceSelector(key, OP_EQ, kind, val)
    s = expr.strip()
    for tok, op in _OP_TOKENS:
        if s.startswith(tok):
            kind, val = _typed_operand(s[len(tok):].strip())
            return DeviceSelector(key, op, kind, val)
    kind, val = _typed_operand(s)
    return DeviceSelector(key, OP_EQ, kind, val)


def parse_selectors(selectors: Mapping[str, object]) -> List[DeviceSelector]:
    return [parse_selector(k, v) for k, v in sorted((selectors or {}).items())]


# ---------------------------------------------------------------------------
# pod -> claims resolution (shared by plugin, controller, batched builder)


def effective_claim_name(pod_name: str, prc) -> str:
    """The ResourceClaim object name a PodResourceClaim resolves to:
    claim_name when direct, else the controller-generated ``<pod>-<entry>``."""
    return prc.claim_name if prc.claim_name else f"{pod_name}-{prc.name}"


def claim_refs_for_pod(pod) -> List[Tuple[str, str]]:
    """[(entry name, claim object key)] for every pod.spec.resourceClaims
    entry."""
    return [
        (prc.name, f"{pod.meta.namespace}/{effective_claim_name(pod.meta.name, prc)}")
        for prc in pod.spec.resource_claims
    ]


def selectors_for_claim(store, claim) -> Tuple[List[DeviceSelector], Optional[str]]:
    """Merged class + claim selectors (claim entries override the class's on
    the same key, resourceclaim/structured semantics); (selectors, error).
    A missing ResourceClass is an error — the claim cannot be evaluated."""
    merged: Dict[str, object] = {}
    if claim.resource_class_name:
        rc = store.get_object("ResourceClass", claim.resource_class_name)
        if rc is None:
            return [], f'resourceclass "{claim.resource_class_name}" not found'
        merged.update(rc.selectors or {})
    merged.update(claim.selectors or {})
    return parse_selectors(merged), None
