"""core/v1 (+ apps/batch/policy/storage/scheduling/autoscaling) external
wire conversions: the REFERENCE's camelCase JSON manifest shapes ⇄ this
framework's internal dataclasses.

This is the L1 conversion layer (staging/src/k8s.io/api shapes to internal
types, apimachinery conversion functions): a standard Kubernetes manifest —
`spec.containers[].resources.requests`, `affinity.nodeAffinity.required...`,
`topologySpreadConstraints`, `tolerations` — decodes to the internal Pod,
and internal objects encode back to manifest-shaped dicts. register() wires
every kind into a Scheme (api/scheme.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

from . import types as t
from .scheme import GroupVersionKind, Scheme

# --------------------------------------------------------------------- meta


def meta_from(md: dict) -> t.ObjectMeta:
    refs = tuple(
        t.OwnerReference(kind=r.get("kind", ""), name=r.get("name", ""),
                         controller=bool(r.get("controller", False)),
                         block_owner_deletion=bool(r.get("blockOwnerDeletion", False)))
        for r in (md.get("ownerReferences") or ()))
    return t.ObjectMeta(
        name=md.get("name", ""),
        namespace=md.get("namespace", "default"),
        uid=str(md.get("uid", "")),
        labels=dict(md.get("labels") or {}),
        annotations=dict(md.get("annotations") or {}),
        owner_references=refs,
        finalizers=tuple(md.get("finalizers") or ()),
    )


def meta_to(m: t.ObjectMeta) -> dict:
    md: dict = {"name": m.name, "namespace": m.namespace}
    if m.uid:
        md["uid"] = m.uid
    if m.labels:
        md["labels"] = dict(m.labels)
    if m.annotations:
        md["annotations"] = dict(m.annotations)
    if m.resource_version:
        md["resourceVersion"] = str(m.resource_version)
    if m.owner_references:
        md["ownerReferences"] = [
            {"kind": r.kind, "name": r.name, "controller": r.controller,
             "blockOwnerDeletion": r.block_owner_deletion}
            for r in m.owner_references]
    if m.finalizers:
        md["finalizers"] = list(m.finalizers)
    if m.deletion_timestamp:
        md["deletionTimestamp"] = m.deletion_timestamp
    return md


# ---------------------------------------------------------------- selectors


def label_selector_from(sel: Optional[dict]) -> Optional[t.LabelSelector]:
    if sel is None:
        return None
    return t.LabelSelector(
        match_labels=dict(sel.get("matchLabels") or {}),
        match_expressions=tuple(
            t.Requirement(key=e.get("key", ""), operator=e.get("operator", "In"),
                          values=tuple(e.get("values") or ()))
            for e in (sel.get("matchExpressions") or ())),
    )


def label_selector_to(sel: Optional[t.LabelSelector]) -> Optional[dict]:
    if sel is None:
        return None
    out: dict = {}
    if sel.match_labels:
        out["matchLabels"] = dict(sel.match_labels)
    if sel.match_expressions:
        out["matchExpressions"] = [
            {"key": r.key, "operator": r.operator, "values": list(r.values)}
            for r in sel.match_expressions]
    return out


def _nst_from(term: dict) -> t.NodeSelectorTerm:
    fields_name = None
    for f in term.get("matchFields") or ():
        if f.get("key") == "metadata.name" and f.get("operator") == "In":
            vals = f.get("values") or ()
            fields_name = vals[0] if vals else None
    return t.NodeSelectorTerm(
        match_expressions=tuple(
            t.Requirement(key=e.get("key", ""), operator=e.get("operator", "In"),
                          values=tuple(e.get("values") or ()))
            for e in (term.get("matchExpressions") or ())),
        match_fields_name=fields_name,
    )


def _nst_to(term: t.NodeSelectorTerm) -> dict:
    out: dict = {}
    if term.match_expressions:
        out["matchExpressions"] = [
            {"key": r.key, "operator": r.operator, "values": list(r.values)}
            for r in term.match_expressions]
    if term.match_fields_name is not None:
        out["matchFields"] = [{"key": "metadata.name", "operator": "In",
                               "values": [term.match_fields_name]}]
    return out


def _pat_from(term: dict) -> t.PodAffinityTerm:
    return t.PodAffinityTerm(
        label_selector=label_selector_from(term.get("labelSelector")),
        topology_key=term.get("topologyKey", ""),
        namespaces=tuple(term.get("namespaces") or ()),
        namespace_selector=label_selector_from(term.get("namespaceSelector")),
    )


def _pat_to(term: t.PodAffinityTerm) -> dict:
    out: dict = {"topologyKey": term.topology_key}
    if term.label_selector is not None:
        out["labelSelector"] = label_selector_to(term.label_selector)
    if term.namespaces:
        out["namespaces"] = list(term.namespaces)
    if term.namespace_selector is not None:
        out["namespaceSelector"] = label_selector_to(term.namespace_selector)
    return out


def affinity_from(aff: Optional[dict]) -> Optional[t.Affinity]:
    if not aff:
        return None
    na = pa = paa = None
    if aff.get("nodeAffinity"):
        n = aff["nodeAffinity"]
        req = n.get("requiredDuringSchedulingIgnoredDuringExecution")
        na = t.NodeAffinity(
            required=t.NodeSelector(terms=tuple(
                _nst_from(term) for term in (req.get("nodeSelectorTerms") or ())))
            if req else None,
            preferred=tuple(
                t.PreferredSchedulingTerm(weight=int(p.get("weight", 1)),
                                          preference=_nst_from(p.get("preference") or {}))
                for p in (n.get("preferredDuringSchedulingIgnoredDuringExecution") or ())),
        )
    for src_key, anti in (("podAffinity", False), ("podAntiAffinity", True)):
        if not aff.get(src_key):
            continue
        p = aff[src_key]
        required = tuple(_pat_from(term) for term in
                         (p.get("requiredDuringSchedulingIgnoredDuringExecution") or ()))
        preferred = tuple(
            t.WeightedPodAffinityTerm(weight=int(w.get("weight", 1)),
                                      term=_pat_from(w.get("podAffinityTerm") or {}))
            for w in (p.get("preferredDuringSchedulingIgnoredDuringExecution") or ()))
        if anti:
            paa = t.PodAntiAffinity(required=required, preferred=preferred)
        else:
            pa = t.PodAffinity(required=required, preferred=preferred)
    if na is None and pa is None and paa is None:
        return None
    return t.Affinity(node_affinity=na, pod_affinity=pa, pod_anti_affinity=paa)


def affinity_to(aff: Optional[t.Affinity]) -> Optional[dict]:
    if aff is None:
        return None
    out: dict = {}
    if aff.node_affinity is not None:
        n: dict = {}
        if aff.node_affinity.required is not None:
            n["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [
                    _nst_to(term) for term in aff.node_affinity.required.terms]}
        if aff.node_affinity.preferred:
            n["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": p.weight, "preference": _nst_to(p.preference)}
                for p in aff.node_affinity.preferred]
        out["nodeAffinity"] = n
    for attr, key in (("pod_affinity", "podAffinity"),
                      ("pod_anti_affinity", "podAntiAffinity")):
        pa = getattr(aff, attr)
        if pa is None:
            continue
        p: dict = {}
        if pa.required:
            p["requiredDuringSchedulingIgnoredDuringExecution"] = [
                _pat_to(term) for term in pa.required]
        if pa.preferred:
            p["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": w.weight, "podAffinityTerm": _pat_to(w.term)}
                for w in pa.preferred]
        out[key] = p
    return out


# ---------------------------------------------------------------------- pod


def _security_context_from(sc: Optional[dict]) -> Optional[t.SecurityContext]:
    if not sc:
        return None
    caps = sc.get("capabilities") or {}
    return t.SecurityContext(
        privileged=sc.get("privileged"),
        allow_privilege_escalation=sc.get("allowPrivilegeEscalation"),
        run_as_non_root=sc.get("runAsNonRoot"),
        run_as_user=sc.get("runAsUser"),
        capabilities_add=tuple(caps.get("add") or ()),
        capabilities_drop=tuple(caps.get("drop") or ()),
    )


def _security_context_to(sc: Optional[t.SecurityContext]) -> Optional[dict]:
    if sc is None:
        return None
    out: dict = {}
    for attr, key in (("privileged", "privileged"),
                      ("allow_privilege_escalation", "allowPrivilegeEscalation"),
                      ("run_as_non_root", "runAsNonRoot"),
                      ("run_as_user", "runAsUser")):
        v = getattr(sc, attr)
        if v is not None:
            out[key] = v
    if sc.capabilities_add or sc.capabilities_drop:
        out["capabilities"] = {}
        if sc.capabilities_add:
            out["capabilities"]["add"] = list(sc.capabilities_add)
        if sc.capabilities_drop:
            out["capabilities"]["drop"] = list(sc.capabilities_drop)
    return out


def _container_from(c: dict) -> t.Container:
    res = c.get("resources") or {}
    return t.Container(
        name=c.get("name", ""),
        image=c.get("image", ""),
        requests=dict(res.get("requests") or {}),
        limits=dict(res.get("limits") or {}),
        ports=tuple(
            t.ContainerPort(host_port=int(p.get("hostPort", 0)),
                            container_port=int(p.get("containerPort", 0)),
                            protocol=p.get("protocol", t.PROTO_TCP),
                            host_ip=p.get("hostIP", ""))
            for p in (c.get("ports") or ())),
        security_context=_security_context_from(c.get("securityContext")),
    )


def _container_to(c: t.Container) -> dict:
    out: dict = {"name": c.name, "image": c.image}
    res: dict = {}
    if c.requests:
        res["requests"] = {k: str(v) for k, v in c.requests.items()}
    if c.limits:
        res["limits"] = {k: str(v) for k, v in c.limits.items()}
    if res:
        out["resources"] = res
    if c.ports:
        out["ports"] = [
            {k: v for k, v in (("hostPort", p.host_port),
                               ("containerPort", p.container_port),
                               ("protocol", p.protocol), ("hostIP", p.host_ip)) if v}
            for p in c.ports]
    sc = _security_context_to(c.security_context)
    if sc:
        out["securityContext"] = sc
    return out


def pod_from(doc: dict) -> t.Pod:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    volumes = []
    ephemeral = []
    for v in spec.get("volumes") or ():
        pvc = v.get("persistentVolumeClaim")
        if pvc and pvc.get("claimName"):
            volumes.append(pvc["claimName"])
        elif v.get("ephemeral") is not None:
            ephemeral.append(v.get("name", ""))
    tolerations = tuple(
        t.Toleration(key=x.get("key", ""), operator=x.get("operator", "Equal"),
                     value=x.get("value", ""), effect=x.get("effect", ""),
                     toleration_seconds=x.get("tolerationSeconds"))
        for x in (spec.get("tolerations") or ()))
    spreads = tuple(
        t.TopologySpreadConstraint(
            max_skew=int(c.get("maxSkew", 1)),
            topology_key=c.get("topologyKey", ""),
            when_unsatisfiable=c.get("whenUnsatisfiable", t.DO_NOT_SCHEDULE),
            label_selector=label_selector_from(c.get("labelSelector")),
            min_domains=c.get("minDomains"))
        for c in (spec.get("topologySpreadConstraints") or ()))
    resource_claims = tuple(
        t.PodResourceClaim(
            name=rc.get("name", ""),
            claim_name=(rc.get("source") or {}).get("resourceClaimName", ""),
            template_name=(rc.get("source") or {}).get(
                "resourceClaimTemplateName", ""))
        for rc in (spec.get("resourceClaims") or ()))
    pod_spec = t.PodSpec(
        containers=[_container_from(c) for c in (spec.get("containers") or ())],
        init_containers=[_container_from(c) for c in (spec.get("initContainers") or ())],
        node_name=spec.get("nodeName", ""),
        node_selector=dict(spec.get("nodeSelector") or {}),
        affinity=affinity_from(spec.get("affinity")),
        tolerations=tolerations,
        topology_spread_constraints=spreads,
        priority=int(spec.get("priority") or 0),
        priority_class_name=spec.get("priorityClassName", ""),
        preemption_policy=spec.get("preemptionPolicy") or "PreemptLowerPriority",
        scheduler_name=spec.get("schedulerName") or "default-scheduler",
        overhead=dict(spec.get("overhead") or {}),
        volumes=tuple(volumes),
        ephemeral_claims=tuple(ephemeral),
        resource_claims=resource_claims,
        service_account_name=spec.get("serviceAccountName", ""),
        host_network=bool(spec.get("hostNetwork", False)),
        host_pid=bool(spec.get("hostPID", False)),
        host_ipc=bool(spec.get("hostIPC", False)),
        security_context=_security_context_from(spec.get("securityContext")),
    )
    return t.Pod(
        meta=meta_from(doc.get("metadata") or {}),
        spec=pod_spec,
        status=t.PodStatus(
            phase=status.get("phase", "Pending"),
            nominated_node_name=status.get("nominatedNodeName", ""),
        ),
    )


def pod_to(pod: t.Pod) -> dict:
    spec: dict = {}
    if pod.spec.containers:
        spec["containers"] = [_container_to(c) for c in pod.spec.containers]
    if pod.spec.init_containers:
        spec["initContainers"] = [_container_to(c) for c in pod.spec.init_containers]
    if pod.spec.node_name:
        spec["nodeName"] = pod.spec.node_name
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(pod.spec.node_selector)
    aff = affinity_to(pod.spec.affinity)
    if aff:
        spec["affinity"] = aff
    if pod.spec.tolerations:
        spec["tolerations"] = [
            {k: v for k, v in (("key", x.key), ("operator", x.operator),
                               ("value", x.value), ("effect", x.effect),
                               ("tolerationSeconds", x.toleration_seconds))
             if v not in ("", None)}
            for x in pod.spec.tolerations]
    if pod.spec.topology_spread_constraints:
        spec["topologySpreadConstraints"] = [
            {k: v for k, v in (
                ("maxSkew", c.max_skew), ("topologyKey", c.topology_key),
                ("whenUnsatisfiable", c.when_unsatisfiable),
                ("labelSelector", label_selector_to(c.label_selector)),
                ("minDomains", c.min_domains)) if v is not None}
            for c in pod.spec.topology_spread_constraints]
    if pod.spec.priority:
        spec["priority"] = pod.spec.priority
    if pod.spec.priority_class_name:
        spec["priorityClassName"] = pod.spec.priority_class_name
    if pod.spec.preemption_policy != "PreemptLowerPriority":
        spec["preemptionPolicy"] = pod.spec.preemption_policy
    if pod.spec.scheduler_name != "default-scheduler":
        spec["schedulerName"] = pod.spec.scheduler_name
    if pod.spec.overhead:
        spec["overhead"] = {k: str(v) for k, v in pod.spec.overhead.items()}
    vols = [{"name": name, "persistentVolumeClaim": {"claimName": name}}
            for name in pod.spec.volumes]
    vols += [{"name": name, "ephemeral": {}} for name in pod.spec.ephemeral_claims]
    if vols:
        spec["volumes"] = vols
    if pod.spec.resource_claims:
        spec["resourceClaims"] = [
            {"name": rc.name,
             "source": ({"resourceClaimName": rc.claim_name} if rc.claim_name
                        else {"resourceClaimTemplateName": rc.template_name})}
            for rc in pod.spec.resource_claims]
    if pod.spec.service_account_name:
        spec["serviceAccountName"] = pod.spec.service_account_name
    for attr, key in (("host_network", "hostNetwork"), ("host_pid", "hostPID"),
                      ("host_ipc", "hostIPC")):
        if getattr(pod.spec, attr):
            spec[key] = True
    sc = _security_context_to(pod.spec.security_context)
    if sc:
        spec["securityContext"] = sc
    status: dict = {"phase": pod.status.phase}
    if pod.status.nominated_node_name:
        status["nominatedNodeName"] = pod.status.nominated_node_name
    return {"metadata": meta_to(pod.meta), "spec": spec, "status": status}


# --------------------------------------------------------------------- node


def node_from(doc: dict) -> t.Node:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    ready = True
    for cond in status.get("conditions") or ():
        if cond.get("type") == "Ready":
            ready = cond.get("status") == "True"
    return t.Node(
        meta=meta_from(doc.get("metadata") or {}),
        spec=t.NodeSpec(
            unschedulable=bool(spec.get("unschedulable", False)),
            taints=tuple(
                t.Taint(key=x.get("key", ""), value=x.get("value", ""),
                        effect=x.get("effect", t.TAINT_NO_SCHEDULE))
                for x in (spec.get("taints") or ())),
            pod_cidr=spec.get("podCIDR", ""),
        ),
        status=t.NodeStatus(
            capacity=dict(status.get("capacity") or {}),
            allocatable=dict(status.get("allocatable")
                             or status.get("capacity") or {}),
            images=tuple(
                t.ContainerImage(names=tuple(i.get("names") or ()),
                                 size_bytes=int(i.get("sizeBytes", 0)))
                for i in (status.get("images") or ())),
            ready=ready,
            device_attributes=dict(status.get("deviceAttributes") or {}),
        ),
    )


def node_to(node: t.Node) -> dict:
    spec: dict = {}
    if node.spec.unschedulable:
        spec["unschedulable"] = True
    if node.spec.taints:
        spec["taints"] = [
            {k: v for k, v in (("key", x.key), ("value", x.value),
                               ("effect", x.effect)) if v}
            for x in node.spec.taints]
    if node.spec.pod_cidr:
        spec["podCIDR"] = node.spec.pod_cidr
    status: dict = {
        "capacity": {k: str(v) for k, v in node.status.capacity.items()},
        "allocatable": {k: str(v) for k, v in node.status.allocatable.items()},
        "conditions": [{"type": "Ready",
                        "status": "True" if node.status.ready else "False"}],
    }
    if node.status.images:
        status["images"] = [{"names": list(i.names), "sizeBytes": i.size_bytes}
                            for i in node.status.images]
    if node.status.device_attributes:
        status["deviceAttributes"] = dict(node.status.device_attributes)
    return {"metadata": meta_to(node.meta), "spec": spec, "status": status}


# ------------------------------------------------------------- other kinds


def namespace_from(doc: dict) -> t.Namespace:
    return t.Namespace(meta=meta_from(doc.get("metadata") or {}))


def namespace_to(ns: t.Namespace) -> dict:
    return {"metadata": meta_to(ns.meta)}


def priority_class_from(doc: dict) -> t.PriorityClass:
    return t.PriorityClass(meta=meta_from(doc.get("metadata") or {}),
                           value=int(doc.get("value", 0)))


def priority_class_to(pc: t.PriorityClass) -> dict:
    return {"metadata": meta_to(pc.meta), "value": pc.value}


def pdb_from(doc: dict) -> t.PodDisruptionBudget:
    spec = doc.get("spec") or {}
    return t.PodDisruptionBudget(
        meta=meta_from(doc.get("metadata") or {}),
        selector=label_selector_from(spec.get("selector")),
        min_available=spec.get("minAvailable"),
        max_unavailable=spec.get("maxUnavailable"),
    )


def pdb_to(pdb: t.PodDisruptionBudget) -> dict:
    spec: dict = {}
    if pdb.selector is not None:
        spec["selector"] = label_selector_to(pdb.selector)
    if pdb.min_available is not None:
        spec["minAvailable"] = pdb.min_available
    if pdb.max_unavailable is not None:
        spec["maxUnavailable"] = pdb.max_unavailable
    return {"metadata": meta_to(pdb.meta), "spec": spec,
            "status": {"disruptionsAllowed": pdb.disruptions_allowed,
                       "currentHealthy": pdb.current_healthy,
                       "desiredHealthy": pdb.desired_healthy,
                       "expectedPods": pdb.expected_pods}}


def service_from(doc: dict) -> t.Service:
    spec = doc.get("spec") or {}
    def _int_port(v) -> int:
        # named (string) targetPorts are resolved against container ports in
        # the reference; this model is int-only — degrade to 0, don't crash
        try:
            return int(v or 0)
        except (TypeError, ValueError):
            return 0

    ports = tuple(
        t.ServicePort(
            name=p.get("name", ""), protocol=p.get("protocol", "TCP"),
            port=_int_port(p.get("port")),
            target_port=_int_port(p.get("targetPort", p.get("port", 0))),
            node_port=_int_port(p.get("nodePort")),
        )
        for p in spec.get("ports") or ()
    )
    affinity_cfg = ((spec.get("sessionAffinityConfig") or {}).get("clientIP")
                    or {})
    cluster_ip = spec.get("clusterIP", "")
    return t.Service(
        meta=meta_from(doc.get("metadata") or {}),
        selector=dict(spec.get("selector") or {}),
        external_ips=tuple(spec.get("externalIPs") or ()),
        type=spec.get("type", "ClusterIP"),
        headless=cluster_ip == "None",
        cluster_ip="" if cluster_ip == "None" else cluster_ip,
        ports=ports,
        session_affinity=spec.get("sessionAffinity", "None"),
        session_affinity_timeout_s=int(affinity_cfg.get("timeoutSeconds", 10800)),
    )


def service_to(svc: t.Service) -> dict:
    spec: dict = {}
    if svc.selector:
        spec["selector"] = dict(svc.selector)
    if svc.external_ips:
        spec["externalIPs"] = list(svc.external_ips)
    if svc.type != "ClusterIP":
        spec["type"] = svc.type
    if svc.headless:
        spec["clusterIP"] = "None"  # explicit headless marker round-trips
    elif svc.cluster_ip:
        spec["clusterIP"] = svc.cluster_ip
    if svc.ports:
        spec["ports"] = [
            {k: v for k, v in (
                ("name", p.name), ("protocol", p.protocol), ("port", p.port),
                ("targetPort", p.target_port), ("nodePort", p.node_port),
            ) if v not in ("", 0) or k == "port"}
            for p in svc.ports
        ]
    if svc.session_affinity != "None":
        spec["sessionAffinity"] = svc.session_affinity
        spec["sessionAffinityConfig"] = {
            "clientIP": {"timeoutSeconds": svc.session_affinity_timeout_s}}
    return {"metadata": meta_to(svc.meta), "spec": spec}


def storage_class_from(doc: dict) -> t.StorageClass:
    return t.StorageClass(
        meta=meta_from(doc.get("metadata") or {}),
        provisioner=doc.get("provisioner", ""),
        volume_binding_mode=doc.get("volumeBindingMode", t.BINDING_IMMEDIATE),
        allow_volume_expansion=bool(doc.get("allowVolumeExpansion", False)),
    )


def storage_class_to(sc: t.StorageClass) -> dict:
    out = {"metadata": meta_to(sc.meta), "provisioner": sc.provisioner,
           "volumeBindingMode": sc.volume_binding_mode}
    if sc.allow_volume_expansion:
        out["allowVolumeExpansion"] = True
    return out


def pvc_from(doc: dict) -> t.PersistentVolumeClaim:
    spec = doc.get("spec") or {}
    req = ((spec.get("resources") or {}).get("requests") or {}).get("storage", 0)
    from . import resource as resource_api

    return t.PersistentVolumeClaim(
        meta=meta_from(doc.get("metadata") or {}),
        storage_class=spec.get("storageClassName", ""),
        access_modes=tuple(spec.get("accessModes") or ()),
        requested_bytes=int(resource_api.parse_quantity(req)) if req else 0,
    )


def pvc_to(pvc: t.PersistentVolumeClaim) -> dict:
    spec: dict = {}
    if pvc.storage_class:
        spec["storageClassName"] = pvc.storage_class
    if pvc.access_modes:
        spec["accessModes"] = list(pvc.access_modes)
    if pvc.requested_bytes:
        spec["resources"] = {"requests": {"storage": str(pvc.requested_bytes)}}
    out = {"metadata": meta_to(pvc.meta), "spec": spec}
    if pvc.bound_pv:
        out["spec"]["volumeName"] = pvc.bound_pv
    return out


def _pod_template_from(tpl: Optional[dict], namespace: str) -> Optional[t.Pod]:
    if not tpl:
        return None
    doc = {"metadata": dict(tpl.get("metadata") or {}), "spec": tpl.get("spec") or {}}
    doc["metadata"].setdefault("name", "template")
    doc["metadata"].setdefault("namespace", namespace)
    return pod_from(doc)


def _pod_template_to(tpl: Optional[t.Pod]) -> Optional[dict]:
    if tpl is None:
        return None
    d = pod_to(tpl)
    return {"metadata": {k: v for k, v in d["metadata"].items()
                         if k in ("labels", "annotations")},
            "spec": d["spec"]}


def _int_or_percent(v, total: int, default: int, round_up: bool) -> int:
    """metav1 IntOrString resolution (intstr.GetScaledValueFromIntOrPercent):
    "25%" scales against ``total`` (surge rounds up, unavailable down)."""
    if v is None:
        return default
    if isinstance(v, str) and v.endswith("%"):
        import math

        frac = int(v[:-1]) * total / 100.0
        return math.ceil(frac) if round_up else math.floor(frac)
    return int(v)


def deployment_from(doc: dict) -> t.Deployment:
    spec = doc.get("spec") or {}
    strategy = spec.get("strategy") or {}
    rolling = strategy.get("rollingUpdate") or {}
    meta = meta_from(doc.get("metadata") or {})
    r = spec.get("replicas")
    replicas = 1 if r is None else int(r)  # explicit 0 = scale-to-zero
    return t.Deployment(
        meta=meta,
        selector=label_selector_from(spec.get("selector")),
        replicas=replicas,
        template=_pod_template_from(spec.get("template"), meta.namespace),
        strategy=strategy.get("type", "RollingUpdate"),
        max_surge=_int_or_percent(rolling.get("maxSurge"), replicas, 1, True),
        max_unavailable=_int_or_percent(rolling.get("maxUnavailable"),
                                        replicas, 1, False),
    )


def deployment_to(d: t.Deployment) -> dict:
    spec: dict = {"replicas": d.replicas}
    if d.selector is not None:
        spec["selector"] = label_selector_to(d.selector)
    tpl = _pod_template_to(d.template)
    if tpl:
        spec["template"] = tpl
    spec["strategy"] = {"type": d.strategy}
    if d.strategy == "RollingUpdate":
        spec["strategy"]["rollingUpdate"] = {"maxSurge": d.max_surge,
                                             "maxUnavailable": d.max_unavailable}
    return {"metadata": meta_to(d.meta), "spec": spec}


def job_from(doc: dict) -> t.Job:
    spec = doc.get("spec") or {}
    meta = meta_from(doc.get("metadata") or {})
    return t.Job(
        meta=meta,
        completions=int(spec.get("completions", 1)),
        parallelism=int(spec.get("parallelism", 1)),
        template=_pod_template_from(spec.get("template"), meta.namespace),
        backoff_limit=int(spec.get("backoffLimit", 6)),
        active_deadline_seconds=spec.get("activeDeadlineSeconds"),
        ttl_seconds_after_finished=spec.get("ttlSecondsAfterFinished"),
    )


def job_to(j: t.Job) -> dict:
    spec: dict = {"completions": j.completions, "parallelism": j.parallelism,
                  "backoffLimit": j.backoff_limit}
    if j.active_deadline_seconds is not None:
        spec["activeDeadlineSeconds"] = j.active_deadline_seconds
    if j.ttl_seconds_after_finished is not None:
        spec["ttlSecondsAfterFinished"] = j.ttl_seconds_after_finished
    tpl = _pod_template_to(j.template)
    if tpl:
        spec["template"] = tpl
    status = {"succeeded": j.succeeded, "failed": j.failed}
    if j.condition:
        status["conditions"] = [{"type": j.condition, "status": "True",
                                 "reason": j.failed_reason}]
    return {"metadata": meta_to(j.meta), "spec": spec, "status": status}


def hpa_from(doc: dict) -> t.HorizontalPodAutoscaler:
    spec = doc.get("spec") or {}
    ref = spec.get("scaleTargetRef") or {}
    target_util = 80
    for m in spec.get("metrics") or ():
        res = m.get("resource") or {}
        if res.get("name") == "cpu":
            target_util = int((res.get("target") or {}).get("averageUtilization", 80))
    return t.HorizontalPodAutoscaler(
        meta=meta_from(doc.get("metadata") or {}),
        target_kind=ref.get("kind", "Deployment"),
        target_name=ref.get("name", ""),
        min_replicas=int(spec.get("minReplicas", 1)),
        max_replicas=int(spec.get("maxReplicas", 10)),
        target_cpu_utilization=target_util,
    )


def hpa_to(h: t.HorizontalPodAutoscaler) -> dict:
    return {"metadata": meta_to(h.meta),
            "spec": {"scaleTargetRef": {"kind": h.target_kind, "name": h.target_name},
                     "minReplicas": h.min_replicas, "maxReplicas": h.max_replicas,
                     "metrics": [{"type": "Resource", "resource": {
                         "name": "cpu", "target": {
                             "type": "Utilization",
                             "averageUtilization": h.target_cpu_utilization}}}]},
            "status": {"currentReplicas": h.current_replicas,
                       "desiredReplicas": h.desired_replicas}}


# ----------------------------------------------------------------- register


def _default_pod(pod: t.Pod) -> None:
    """core/v1 pod defaulting (defaults.go): container resource limits
    default requests; toleration operator; protocol handled at decode."""
    for c in list(pod.spec.containers) + list(pod.spec.init_containers):
        for r, q in c.limits.items():
            c.requests.setdefault(r, q)


def api_service_from(doc: dict) -> t.APIService:
    spec = doc.get("spec") or {}
    svc_ref = spec.get("service") or {}
    endpoint = doc.get("service_endpoint", "")
    if not endpoint and svc_ref:
        # apiregistration's ServiceReference (ns/name/port) reduced to a
        # host:port the plain-HTTP proxy can dial
        endpoint = f"{svc_ref.get('name', '')}:{svc_ref.get('port', 443)}"
    return t.APIService(
        meta=meta_from(doc.get("metadata") or {}),
        group=spec.get("group", doc.get("group", "")),
        version=spec.get("version", doc.get("version", "v1")),
        service_endpoint=endpoint,
        insecure_skip_tls_verify=bool(
            spec.get("insecureSkipTLSVerify",
                     doc.get("insecure_skip_tls_verify", True))),
        group_priority_minimum=int(
            spec.get("groupPriorityMinimum",
                     doc.get("group_priority_minimum", 1000))),
        version_priority=int(
            spec.get("versionPriority", doc.get("version_priority", 15))),
    )


def api_service_to(svc: t.APIService) -> dict:
    return {"metadata": meta_to(svc.meta),
            "spec": {"group": svc.group, "version": svc.version,
                     "insecureSkipTLSVerify": svc.insecure_skip_tls_verify,
                     "groupPriorityMinimum": svc.group_priority_minimum,
                     "versionPriority": svc.version_priority},
            "service_endpoint": svc.service_endpoint}


# ------------------------------------------------- resource.k8s.io/v1alpha2


def resource_class_from(doc: dict) -> t.ResourceClass:
    return t.ResourceClass(
        meta=meta_from(doc.get("metadata") or {}),
        driver_name=doc.get("driverName", ""),
        selectors=dict(doc.get("selectors") or {}))


def resource_class_to(rc: t.ResourceClass) -> dict:
    out: dict = {"metadata": meta_to(rc.meta)}
    if rc.driver_name:
        out["driverName"] = rc.driver_name
    if rc.selectors:
        out["selectors"] = dict(rc.selectors)
    return out


def resource_claim_from(doc: dict) -> t.ResourceClaim:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    return t.ResourceClaim(
        meta=meta_from(doc.get("metadata") or {}),
        resource_class_name=spec.get("resourceClassName", ""),
        selectors=dict(spec.get("selectors") or {}),
        allocated_node=(status.get("allocation") or {}).get("nodeName", ""),
        reserved_for=tuple(status.get("reservedFor") or ()))


def resource_claim_to(claim: t.ResourceClaim) -> dict:
    spec: dict = {}
    if claim.resource_class_name:
        spec["resourceClassName"] = claim.resource_class_name
    if claim.selectors:
        spec["selectors"] = dict(claim.selectors)
    status: dict = {}
    if claim.allocated_node:
        status["allocation"] = {"nodeName": claim.allocated_node}
    if claim.reserved_for:
        status["reservedFor"] = list(claim.reserved_for)
    out: dict = {"metadata": meta_to(claim.meta), "spec": spec}
    if status:
        out["status"] = status
    return out


def resource_claim_template_from(doc: dict) -> t.ResourceClaimTemplate:
    spec = doc.get("spec") or {}
    return t.ResourceClaimTemplate(
        meta=meta_from(doc.get("metadata") or {}),
        resource_class_name=spec.get("resourceClassName", ""),
        selectors=dict(spec.get("selectors") or {}))


def resource_claim_template_to(tmpl: t.ResourceClaimTemplate) -> dict:
    spec: dict = {}
    if tmpl.resource_class_name:
        spec["resourceClassName"] = tmpl.resource_class_name
    if tmpl.selectors:
        spec["selectors"] = dict(tmpl.selectors)
    return {"metadata": meta_to(tmpl.meta), "spec": spec}


def pod_scheduling_context_from(doc: dict) -> t.PodSchedulingContext:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    return t.PodSchedulingContext(
        meta=meta_from(doc.get("metadata") or {}),
        selected_node=spec.get("selectedNode", status.get("selectedNode", "")),
        potential_nodes=tuple(spec.get("potentialNodes") or ()))


def pod_scheduling_context_to(ctx: t.PodSchedulingContext) -> dict:
    spec: dict = {}
    if ctx.selected_node:
        spec["selectedNode"] = ctx.selected_node
    if ctx.potential_nodes:
        spec["potentialNodes"] = list(ctx.potential_nodes)
    return {"metadata": meta_to(ctx.meta), "spec": spec}


# ------------------------------------------- scheduling.x-k8s.io/v1alpha1


def pod_group_from(doc: dict) -> t.PodGroup:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    return t.PodGroup(
        meta=meta_from(doc.get("metadata") or {}),
        min_member=int(spec.get("minMember", 1)),
        schedule_timeout_seconds=int(spec.get("scheduleTimeoutSeconds", 0)),
        phase=status.get("phase", t.POD_GROUP_PENDING),
        scheduled=int(status.get("scheduled", 0)))


def pod_group_to(pg: t.PodGroup) -> dict:
    spec: dict = {"minMember": pg.min_member}
    if pg.schedule_timeout_seconds:
        spec["scheduleTimeoutSeconds"] = pg.schedule_timeout_seconds
    status: dict = {}
    if pg.phase and pg.phase != t.POD_GROUP_PENDING:
        status["phase"] = pg.phase
    if pg.scheduled:
        status["scheduled"] = pg.scheduled
    out: dict = {"metadata": meta_to(pg.meta), "spec": spec}
    if status:
        out["status"] = status
    return out


def scheduling_quota_from(doc: dict) -> t.SchedulingQuota:
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    return t.SchedulingQuota(
        meta=meta_from(doc.get("metadata") or {}),
        hard={k: int(v) for k, v in (spec.get("hard") or {}).items()},
        weight=int(spec.get("weight", 1)),
        cohort=str(spec.get("cohort", "") or ""),
        used={k: int(v) for k, v in (status.get("used") or {}).items()})


def scheduling_quota_to(sq: t.SchedulingQuota) -> dict:
    spec: dict = {"weight": sq.weight}
    if sq.hard:
        spec["hard"] = dict(sq.hard)
    if sq.cohort:
        spec["cohort"] = sq.cohort
    out: dict = {"metadata": meta_to(sq.meta), "spec": spec}
    if sq.used:
        out["status"] = {"used": dict(sq.used)}
    return out


def register(scheme: Scheme) -> None:
    """Register every modeled external version (AddToScheme analog)."""
    core = [
        ("Pod", t.Pod, pod_from, pod_to),
        ("Node", t.Node, node_from, node_to),
        ("Namespace", t.Namespace, namespace_from, namespace_to),
        ("Service", t.Service, service_from, service_to),
        ("PersistentVolumeClaim", t.PersistentVolumeClaim, pvc_from, pvc_to),
    ]
    for kind, typ, dec, enc in core:
        scheme.add_known_type(GroupVersionKind("", "v1", kind), typ, dec, enc)
    scheme.add_known_type(
        GroupVersionKind("scheduling.k8s.io", "v1", "PriorityClass"),
        t.PriorityClass, priority_class_from, priority_class_to)
    scheme.add_known_type(
        GroupVersionKind("policy", "v1", "PodDisruptionBudget"),
        t.PodDisruptionBudget, pdb_from, pdb_to)
    scheme.add_known_type(
        GroupVersionKind("storage.k8s.io", "v1", "StorageClass"),
        t.StorageClass, storage_class_from, storage_class_to)
    scheme.add_known_type(
        GroupVersionKind("apps", "v1", "Deployment"),
        t.Deployment, deployment_from, deployment_to)
    scheme.add_known_type(
        GroupVersionKind("batch", "v1", "Job"), t.Job, job_from, job_to)
    scheme.add_known_type(
        GroupVersionKind("autoscaling", "v2", "HorizontalPodAutoscaler"),
        t.HorizontalPodAutoscaler, hpa_from, hpa_to)
    scheme.add_known_type(
        GroupVersionKind("apiregistration.k8s.io", "v1", "APIService"),
        t.APIService, api_service_from, api_service_to)
    for kind, typ, dec, enc in (
        ("ResourceClass", t.ResourceClass,
         resource_class_from, resource_class_to),
        ("ResourceClaim", t.ResourceClaim,
         resource_claim_from, resource_claim_to),
        ("ResourceClaimTemplate", t.ResourceClaimTemplate,
         resource_claim_template_from, resource_claim_template_to),
        ("PodSchedulingContext", t.PodSchedulingContext,
         pod_scheduling_context_from, pod_scheduling_context_to),
    ):
        scheme.add_known_type(
            GroupVersionKind("resource.k8s.io", "v1alpha2", kind),
            typ, dec, enc)
    scheme.add_known_type(
        GroupVersionKind("scheduling.x-k8s.io", "v1alpha1", "PodGroup"),
        t.PodGroup, pod_group_from, pod_group_to)
    scheme.add_known_type(
        GroupVersionKind("scheduling.x-k8s.io", "v1alpha1", "SchedulingQuota"),
        t.SchedulingQuota, scheduling_quota_from, scheduling_quota_to)
    scheme.add_defaulter(t.Pod, _default_pod)
