"""Scheduling-relevant API object model.

A deliberately small, typed mirror of the parts of k8s.io/api/core/v1 (plus
scheduling/v1 priority and policy/v1 PDB) that the scheduler consumes:
Pod spec (resources, affinity, tolerations, topology-spread, priority, ports),
Node (allocatable, taints, labels, images), and label/node selectors.

These are plain dataclasses — the "wire format" of this framework is Python
objects (and, on the hot path, the dense tensors produced by ops/encode.py).
Reference anchors are cited per type.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import resource as resource_api

# ---------------------------------------------------------------------------
# meta


@dataclass(frozen=True)
class OwnerReference:
    """metav1.OwnerReference (kind + name + controller flag); drives both
    SelectorSpread's owner lookup (helper/spread.go DefaultSelector) and the
    garbage collector's ownership graph."""

    kind: str = ""
    name: str = ""
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0
    creation_timestamp: float = 0.0  # set by the store on create (metav1)
    deletion_timestamp: float = 0.0  # >0 ⇒ terminating (metav1 DeletionTimestamp)
    owner_references: Tuple["OwnerReference", ...] = ()
    # metav1 Finalizers: a delete with finalizers present only marks the
    # object terminating; removal happens when the last finalizer is cleared
    # (the pvc/pv-protection controllers' mechanism)
    finalizers: Tuple[str, ...] = ()

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def controller_of(self) -> Optional["OwnerReference"]:
        """metav1.GetControllerOf: the single ownerReference with controller=true."""
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None


# ---------------------------------------------------------------------------
# selectors (apimachinery pkg/labels + core/v1 node selectors)

# LabelSelector / NodeSelectorRequirement operators
IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"


@dataclass
class Requirement:
    """One match expression. Semantics of labels.Requirement.Matches
    (apimachinery pkg/labels/selector.go): an absent key matches NotIn and
    DoesNotExist; Gt/Lt parse the label value as an integer."""

    key: str
    operator: str
    values: Tuple[str, ...] = ()

    def matches(self, labels: Dict[str, str]) -> bool:
        has = self.key in labels
        if self.operator == IN:
            return has and labels[self.key] in self.values
        if self.operator == NOT_IN:
            return not has or labels[self.key] not in self.values
        if self.operator == EXISTS:
            return has
        if self.operator == DOES_NOT_EXIST:
            return not has
        if self.operator in (GT, LT):
            if not has:
                return False
            try:
                lhs = int(labels[self.key])
                rhs = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lhs > rhs if self.operator == GT else lhs < rhs
        raise ValueError(f"unknown operator {self.operator!r}")


@dataclass
class LabelSelector:
    """metav1.LabelSelector: matchLabels AND matchExpressions (all must hold).
    An empty selector matches everything; a None selector matches nothing
    (v1helper.LabelSelectorAsSelector convention) — plugins model that with the
    shared MATCH_NOTHING sentinel below (labels.Nothing() analog)."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: Tuple[Requirement, ...] = ()
    match_nothing: bool = False  # labels.Nothing(): unforgeable never-match

    def matches(self, labels: Dict[str, str]) -> bool:
        if self.match_nothing:
            return False
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        return all(r.matches(labels) for r in self.match_expressions)

    def signature(self) -> Tuple:
        """Hashable identity used by the incremental selector-count index
        (backend/sigindex.py)."""
        return (
            tuple(sorted(self.match_labels.items())),
            tuple((r.key, r.operator, tuple(r.values)) for r in self.match_expressions),
            self.match_nothing,
        )


MATCH_NOTHING = LabelSelector(match_nothing=True)


@dataclass
class NodeSelectorTerm:
    """core/v1.NodeSelectorTerm: AND of matchExpressions (+ matchFields, of
    which only metadata.name is legal — modeled via ``match_fields_name``)."""

    match_expressions: Tuple[Requirement, ...] = ()
    match_fields_name: Optional[str] = None  # compiled 'metadata.name' In [x]

    def matches(self, node: "Node") -> bool:
        if self.match_fields_name is not None and node.meta.name != self.match_fields_name:
            return False
        if not self.match_expressions and self.match_fields_name is None:
            return False  # empty term matches nothing (nodeaffinity.go semantics)
        return all(r.matches(node.meta.labels) for r in self.match_expressions)


@dataclass
class NodeSelector:
    """core/v1.NodeSelector: OR of terms."""

    terms: Tuple[NodeSelectorTerm, ...] = ()

    def matches(self, node: "Node") -> bool:
        return any(t.matches(node) for t in self.terms)


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: Tuple[PreferredSchedulingTerm, ...] = ()


@dataclass
class PodAffinityTerm:
    """core/v1.PodAffinityTerm. ``namespaces`` empty + selector None ⇒ the
    incoming pod's own namespace (defaulting done at AffinityTerm build time,
    framework/types.go:193 newAffinityTerm)."""

    label_selector: Optional[LabelSelector] = None
    topology_key: str = ""
    namespaces: Tuple[str, ...] = ()
    namespace_selector: Optional[LabelSelector] = None


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required: Tuple[PodAffinityTerm, ...] = ()
    preferred: Tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass
class PodAntiAffinity:
    required: Tuple[PodAffinityTerm, ...] = ()
    preferred: Tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


# ---------------------------------------------------------------------------
# taints / tolerations

TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

TOLERATION_OP_EQUAL = "Equal"
TOLERATION_OP_EXISTS = "Exists"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = TAINT_NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    """core/v1.Toleration.ToleratesTaint semantics
    (component-helpers scheduling/corev1 helpers): empty effect matches all
    effects; empty key with Exists matches all taints."""

    key: str = ""
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""
    # None = tolerate forever; N = the NoExecute taint manager evicts after
    # N seconds (core/v1 Toleration.TolerationSeconds)
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator in ("", TOLERATION_OP_EQUAL):
            return self.value == taint.value
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        return False


# ---------------------------------------------------------------------------
# topology spread

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = DO_NOT_SCHEDULE
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None


# ---------------------------------------------------------------------------
# pod

PROTO_TCP = "TCP"
PROTO_UDP = "UDP"
PROTO_SCTP = "SCTP"


@dataclass(frozen=True)
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = PROTO_TCP
    host_ip: str = ""


@dataclass
class SecurityContext:
    """core/v1 SecurityContext, reduced to the fields Pod Security admission
    levels check (policy/pkg/api + pod-security-admission checks)."""

    privileged: Optional[bool] = None
    allow_privilege_escalation: Optional[bool] = None
    run_as_non_root: Optional[bool] = None
    run_as_user: Optional[int] = None
    capabilities_add: Tuple[str, ...] = ()
    capabilities_drop: Tuple[str, ...] = ()


@dataclass
class Container:
    name: str = ""
    image: str = ""
    requests: Dict[str, object] = field(default_factory=dict)  # resource -> quantity
    limits: Dict[str, object] = field(default_factory=dict)
    ports: Tuple[ContainerPort, ...] = ()
    security_context: Optional[SecurityContext] = None
    image_pull_policy: str = ""  # "" = kubelet default (IfNotPresent)


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: Tuple[Toleration, ...] = ()
    topology_spread_constraints: Tuple[TopologySpreadConstraint, ...] = ()
    priority: int = 0
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"  # or "Never" (core/v1 PreemptionPolicy)
    scheduler_name: str = "default-scheduler"
    overhead: Dict[str, object] = field(default_factory=dict)
    volumes: Tuple[str, ...] = ()  # PVC names (volume subsystem modeled by claim name)
    # generic ephemeral volume names: the ephemeral-volume controller creates
    # a PVC "<pod>-<name>" per entry, owned by the pod
    ephemeral_claims: Tuple[str, ...] = ()
    # secret/configMap volume sources by object name (core/v1 Volume
    # SecretVolumeSource/ConfigMapVolumeSource). These need no binding and
    # never gate scheduling (the SchedulingSecrets perf row measures exactly
    # that); the kubelet mounts them and the node authorizer limits kubelet
    # reads to objects referenced by pods bound to that node.
    secret_volumes: Tuple[str, ...] = ()
    config_map_volumes: Tuple[str, ...] = ()
    # resource.k8s.io claims consumed by this pod (core/v1
    # PodSpec.ResourceClaims); the DynamicResources plugin gates scheduling
    # on them and the resourceclaim controller materializes template entries
    resource_claims: Tuple["PodResourceClaim", ...] = ()
    service_account_name: str = ""
    host_network: bool = False
    host_pid: bool = False
    host_ipc: bool = False
    security_context: Optional[SecurityContext] = None  # pod-level defaults
    runtime_class_name: str = ""  # node.k8s.io RuntimeClass (overhead source)


@dataclass(frozen=True)
class PodResourceClaim:
    """core/v1 PodResourceClaim (pod.spec.resourceClaims[]): names one
    resource.k8s.io claim the pod consumes. Exactly one source is set:
    ``claim_name`` references an existing ResourceClaim directly;
    ``template_name`` names a ResourceClaimTemplate the resourceclaim
    controller materializes as ``<pod>-<name>`` (the generic-ephemeral-volume
    naming scheme, reused)."""

    name: str = ""
    claim_name: str = ""
    template_name: str = ""


@dataclass
class PodStatus:
    phase: str = "Pending"
    nominated_node_name: str = ""
    start_time: float = 0.0
    reason: str = ""   # machine-readable phase reason, e.g. "Evicted"
    message: str = ""  # human-readable detail


@dataclass
class Pod:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def key(self) -> str:
        return self.meta.key()

    def resource_request(self) -> Dict[str, int]:
        """computePodResourceRequest (noderesources/fit.go:159): canonical-int
        per-resource request = max(sum(containers), max(initContainers)) + overhead.
        Cached on the instance (specs are treated as immutable once created);
        clones share the cache via __dict__ copy. Callers must not mutate the
        returned dict."""
        cached = self.__dict__.get("_req_cache")
        if cached is not None:
            return cached
        total: Dict[str, int] = {}
        for c in self.spec.containers:
            for r, q in c.requests.items():
                total[r] = total.get(r, 0) + resource_api.canonical(r, q)
        for c in self.spec.init_containers:
            for r, q in c.requests.items():
                v = resource_api.canonical(r, q)
                if v > total.get(r, 0):
                    total[r] = v
        for r, q in self.spec.overhead.items():
            total[r] = total.get(r, 0) + resource_api.canonical(r, q)
        self.__dict__["_req_cache"] = total
        return total

    def invalidate_request_cache(self) -> None:
        """Drop the cached resource_request(). Must be called by anything
        that mutates container requests/limits after creation (LimitRanger
        defaulting, mutating-webhook patches) — clones share the cache, so a
        stale entry would silently feed the scheduler and quota accounting
        (ADVICE r3)."""
        self.__dict__.pop("_req_cache", None)

    def host_ports(self) -> Tuple[ContainerPort, ...]:
        return tuple(
            p for c in self.spec.containers for p in c.ports if p.host_port > 0
        )

    def clone(self) -> "Pod":
        """Copy with independent meta/spec/status; container/affinity objects
        are shared (treated as immutable once created — assume/bind only ever
        rewrites spec.node_name and status fields). Hand-rolled __dict__
        copies: this runs twice per scheduled pod (assume + bind) and
        dataclasses.replace() re-runs __init__ each call — ~6× slower."""
        new = object.__new__(Pod)
        new.__dict__.update(self.__dict__)
        meta = object.__new__(ObjectMeta)
        meta.__dict__.update(self.meta.__dict__)
        meta.labels = dict(self.meta.labels)
        spec = object.__new__(PodSpec)
        spec.__dict__.update(self.spec.__dict__)
        status = object.__new__(PodStatus)
        status.__dict__.update(self.status.__dict__)
        new.meta, new.spec, new.status = meta, spec, status
        return new


# ---------------------------------------------------------------------------
# node


@dataclass(frozen=True)
class ContainerImage:
    names: Tuple[str, ...] = ()
    size_bytes: int = 0


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: Tuple[Taint, ...] = ()
    pod_cidr: str = ""  # allocated by the nodeipam controller


@dataclass
class NodeStatus:
    capacity: Dict[str, object] = field(default_factory=dict)
    allocatable: Dict[str, object] = field(default_factory=dict)
    images: Tuple[ContainerImage, ...] = ()
    ready: bool = True
    # pressure conditions (core/v1 NodeConditionType MemoryPressure/
    # DiskPressure/PIDPressure), set by the kubelet eviction manager; the
    # nodelifecycle controller mirrors them as NoSchedule taints
    memory_pressure: bool = False
    disk_pressure: bool = False
    pid_pressure: bool = False
    # node-published device slice (resource.k8s.io structured parameters):
    # the per-node attribute map a DRA driver's kubelet plugin publishes
    # (the NodeResourceSlice object collapsed onto NodeStatus, like
    # allocatable). Values are ints or strings; selectors in
    # ResourceClass/ResourceClaim match against these (api/dra.py).
    device_attributes: Dict[str, object] = field(default_factory=dict)


@dataclass
class Node:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    def name(self) -> str:
        return self.meta.name

    def allocatable_canonical(self) -> Dict[str, int]:
        return {
            r: resource_api.canonical(r, q) for r, q in self.status.allocatable.items()
        }


# zone identity (component-helpers/node/topology/helpers.go GetZoneKey)
LABEL_TOPOLOGY_ZONE = "topology.kubernetes.io/zone"
LABEL_TOPOLOGY_REGION = "topology.kubernetes.io/region"
LABEL_FAILURE_DOMAIN_BETA_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_FAILURE_DOMAIN_BETA_REGION = "failure-domain.beta.kubernetes.io/region"
LABEL_HOSTNAME = "kubernetes.io/hostname"


def get_zone_key(node: "Node") -> str:
    """Unique per failure-zone id from node labels; '' when zoneless. Beta
    labels take precedence; region and zone are joined with a NUL separator
    (GetZoneKey, component-helpers/node/topology/helpers.go:30)."""
    labels = node.meta.labels
    zone = labels.get(LABEL_FAILURE_DOMAIN_BETA_ZONE, labels.get(LABEL_TOPOLOGY_ZONE, ""))
    region = labels.get(LABEL_FAILURE_DOMAIN_BETA_REGION, labels.get(LABEL_TOPOLOGY_REGION, ""))
    if not zone and not region:
        return ""
    return f"{region}:\x00:{zone}"


# ---------------------------------------------------------------------------
# misc cluster objects the scheduler reads


@dataclass
class CustomResourceDefinition:
    """apiextensions.k8s.io/v1 CustomResourceDefinition, reduced to the
    registration surface the dynamic-kind store path consumes
    (staging/src/k8s.io/apiextensions-apiserver/pkg/apis/apiextensions/v1):
    group + names + served version + scope. The schema/conversion machinery
    is out of scope — custom objects carry free-form spec dicts."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)  # name = plural.group
    group: str = ""
    version: str = "v1"
    kind: str = ""
    plural: str = ""
    namespaced: bool = True


@dataclass
class CustomResource:
    """A dynamic-kind object: typed meta + free-form spec/status payloads
    (the unstructured.Unstructured analog)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    api_version: str = ""
    kind: str = ""
    spec: Dict[str, object] = field(default_factory=dict)
    status: Dict[str, object] = field(default_factory=dict)


@dataclass
class APIService:
    """apiregistration.k8s.io/v1 APIService, reduced to the aggregation
    surface (kube-aggregator apis/apiregistration/v1/types.go): which
    group/version is served and where to proxy it. Local services (no
    endpoint) mean "served by this apiserver" — the built-in groups."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)  # name = version.group
    group: str = ""
    version: str = "v1"
    # backend endpoint ("host:port" or full URL); "" = local (built-in)
    service_endpoint: str = ""
    insecure_skip_tls_verify: bool = True
    group_priority_minimum: int = 1000
    version_priority: int = 15


@dataclass
class Namespace:
    meta: ObjectMeta = field(default_factory=ObjectMeta)


# ---------------------------------------------------------------------------
# workload objects (core/v1 Service + ReplicationController, apps/v1
# ReplicaSet + StatefulSet + Deployment + DaemonSet, batch/v1 Job) — consumed
# by SelectorSpread's owner-selector lookup and the controller-manager loops.


@dataclass
class ServicePort:
    """core/v1 ServicePort (types.go ServicePort): one exposed port of a
    Service; node_port is populated for NodePort/LoadBalancer services."""

    name: str = ""
    protocol: str = "TCP"
    port: int = 0          # the ClusterIP-facing port
    target_port: int = 0   # backend pod port (int form only)
    node_port: int = 0     # 0 = not a NodePort


@dataclass
class Service:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)  # spec.selector (map form)
    external_ips: Tuple[str, ...] = ()  # spec.externalIPs (DenyServiceExternalIPs)
    # kube-proxy surface (pkg/proxy/iptables + ipvs proxiers)
    type: str = "ClusterIP"            # ClusterIP | NodePort | LoadBalancer
    cluster_ip: str = ""               # virtual IP ("" = none allocated)
    headless: bool = False             # wire form clusterIP: "None"
    ports: Tuple[ServicePort, ...] = ()
    session_affinity: str = "None"     # None | ClientIP
    session_affinity_timeout_s: int = 10800  # ClientIPConfig.TimeoutSeconds default


@dataclass
class ReplicationController:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)  # spec.selector (map form)
    replicas: int = 1
    template: Optional["Pod"] = None


@dataclass
class ReplicaSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    replicas: int = 1
    template: Optional["Pod"] = None


@dataclass
class StatefulSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    replicas: int = 1
    template: Optional["Pod"] = None


@dataclass
class Deployment:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    replicas: int = 1
    template: Optional["Pod"] = None
    # rollout strategy (apps/v1 DeploymentStrategy): RollingUpdate honors the
    # surge/unavailable windows; Recreate tears the old RS down first
    strategy: str = "RollingUpdate"
    max_surge: int = 1
    max_unavailable: int = 1


@dataclass
class DaemonSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    template: Optional["Pod"] = None


@dataclass
class Job:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    completions: int = 1
    parallelism: int = 1
    template: Optional["Pod"] = None
    succeeded: int = 0
    # failure policy (job_controller.go syncJob): stop retrying after
    # backoffLimit pod failures; kill the job past activeDeadlineSeconds
    backoff_limit: int = 6
    active_deadline_seconds: Optional[int] = None
    failed: int = 0
    # "" | "Complete" | "Failed" (+ failure reason in failed_reason)
    condition: str = ""
    failed_reason: str = ""
    start_time: float = 0.0
    completion_time: float = 0.0  # set when condition turns terminal
    # ttl-after-finished controller: delete this long after completion
    ttl_seconds_after_finished: Optional[int] = None


@dataclass
class CronJob:
    """batch/v1 CronJob: spawns Jobs on a 5-field cron schedule
    (pkg/controller/cronjob)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    schedule: str = "* * * * *"
    template: Optional["Pod"] = None  # the spawned Job's pod template
    completions: int = 1
    parallelism: int = 1
    suspend: bool = False
    last_schedule_minute: int = -1  # epoch-minute of the last firing


@dataclass
class VolumeAttachment:
    """storage/v1 VolumeAttachment: a PV attached to a node, maintained by
    the attach/detach controller (pkg/controller/volume/attachdetach)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    pv_name: str = ""
    node_name: str = ""
    attached: bool = True


@dataclass(frozen=True)
class EndpointAddress:
    pod_key: str = ""
    node_name: str = ""


@dataclass
class Endpoints:
    """core/v1 Endpoints — ready pod addresses backing a Service, maintained
    by the endpoints controller and consumed by kube-proxy."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    addresses: Tuple[EndpointAddress, ...] = ()


@dataclass
class EndpointSlice:
    """discovery.k8s.io/v1 EndpointSlice — the scalable sharded form of
    Endpoints (≤ max-endpoints addresses per slice), maintained by the
    endpointslice controller."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    service: str = ""  # owning Service key
    addresses: Tuple[EndpointAddress, ...] = ()


@dataclass
class ResourceQuota:
    """core/v1 ResourceQuota: per-namespace hard caps on aggregate resource
    requests + object counts; enforced by the admission chain, usage kept by
    the quota controller. Canonical-int units (api/resource.py)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    hard: Dict[str, int] = field(default_factory=dict)   # "pods", "requests.cpu" (milli), "requests.memory" (KiB)
    used: Dict[str, int] = field(default_factory=dict)


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease — the leader-election lock object
    (tools/leaderelection/resourcelock LeaseLock)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0


@dataclass
class PriorityClass:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0


@dataclass
class PodDisruptionBudget:
    """policy/v1 PDB: spec (minAvailable/maxUnavailable, int or "N%") and the
    status the disruption controller maintains (disruption.go updatePdbStatus),
    consumed by preemption (preemption.go:397 criteria)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    # spec — None means unset; exactly one of the two is normally set
    min_available: Optional[object] = None    # int or "N%"
    max_unavailable: Optional[object] = None  # int or "N%"
    # status
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0


@dataclass
class LimitRangeItem:
    """core/v1 LimitRangeItem (the Container type is what admission
    applies; plugin/pkg/admission/limitranger)."""

    type: str = "Container"
    default: Dict[str, object] = field(default_factory=dict)          # limits
    default_request: Dict[str, object] = field(default_factory=dict)  # requests
    max: Dict[str, object] = field(default_factory=dict)
    min: Dict[str, object] = field(default_factory=dict)


@dataclass
class LimitRange:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    limits: Tuple[LimitRangeItem, ...] = ()


# volume binding modes (storage/v1 StorageClass.VolumeBindingMode)
BINDING_IMMEDIATE = "Immediate"
BINDING_WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"

# access modes
RWO = "ReadWriteOnce"
RWX = "ReadWriteMany"
ROX = "ReadOnlyMany"
RWOP = "ReadWriteOncePod"


@dataclass
class PersistentVolumeClaim:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class: str = ""
    bound_pv: str = ""
    access_modes: Tuple[str, ...] = ()
    requested_bytes: int = 0


@dataclass
class PersistentVolume:
    """storage PV: capacity + node affinity via topology labels (the
    reference keeps zone/region in PV labels; volumezone/volume_zone.go)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    capacity_bytes: int = 0
    storage_class: str = ""
    bound_pvc: str = ""  # claimRef as namespace/name
    access_modes: Tuple[str, ...] = ()
    # in-tree volume source kind for the non-CSI attach-limit filters
    # (nodevolumelimits/non_csi.go): "ebs" | "gce-pd" | "azure-disk" | "cinder" | ""
    volume_type: str = ""
    # nodeAffinity reduced to required label matches (topology terms)
    node_affinity: Dict[str, Tuple[str, ...]] = field(default_factory=dict)

    def matches_node(self, node: "Node") -> bool:
        for key, allowed in self.node_affinity.items():
            if node.meta.labels.get(key) not in allowed:
                return False
        return True


@dataclass
class StorageClass:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = BINDING_IMMEDIATE
    allow_volume_expansion: bool = False  # PVC resize gate (pvcresize admission)


# the default-class marker the DefaultStorageClass admission plugin reads
# (plugin/pkg/admission/storage/storageclass/setdefault)
ANNOTATION_DEFAULT_STORAGE_CLASS = "storageclass.kubernetes.io/is-default-class"


@dataclass
class ServiceAccount:
    """core/v1 ServiceAccount (the identity object the serviceaccount
    admission plugin defaults onto pods and the serviceaccount controller
    maintains per namespace)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    automount_service_account_token: bool = True


@dataclass
class ConfigMap:
    """core/v1 ConfigMap (the root-ca-cert-publisher controller's target)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class Secret:
    """core/v1 Secret (staging/src/k8s.io/api/core/v1/types.go Secret):
    the SchedulingSecrets perf workload mounts these, the serviceaccount
    controller mints token secrets, and NodeRestriction gates kubelet reads
    to secrets referenced by pods bound to that node."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    type: str = "Opaque"
    data: Dict[str, str] = field(default_factory=dict)  # values base64 by convention


# bootstrap token secret type (cluster-bootstrap/token/api: the kubeadm
# join-token family the bootstrapsigner/tokencleaner controllers manage)
SECRET_TYPE_BOOTSTRAP_TOKEN = "bootstrap.kubernetes.io/token"


@dataclass
class Event:
    """core/v1 Event (the user-visible record kubectl get events shows):
    involved object + reason/note with series counting. The in-process
    EventRecorder (utils/events.py) persists these through the store when
    wired with one (events/event_broadcaster.go writes through the Events
    API the same way)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: str = ""  # "ns/name" of the object the event is about
    reason: str = ""
    message: str = ""
    type: str = "Normal"       # Normal | Warning
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    reporting_controller: str = ""


@dataclass
class RuntimeClass:
    """node.k8s.io/v1 RuntimeClass: handler selection + pod overhead; the
    RuntimeClass admission plugin defaults spec.overhead from it."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    handler: str = ""
    overhead: Dict[str, object] = field(default_factory=dict)  # resource -> quantity
    # scheduling constraints merged onto pods using this class
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: Tuple[Toleration, ...] = ()


@dataclass
class IngressClass:
    """networking.k8s.io/v1 IngressClass; the is-default-class annotation
    marks the cluster default (DefaultIngressClass admission)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    controller: str = ""


ANNOTATION_DEFAULT_INGRESS_CLASS = "ingressclass.kubernetes.io/is-default-class"


@dataclass
class IngressRule:
    host: str = ""
    service_name: str = ""  # backend service (reduced single-backend form)
    service_port: int = 0


@dataclass
class Ingress:
    """networking.k8s.io/v1 Ingress, reduced to class selection + host→
    service rules (the DefaultIngressClass admission surface)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    ingress_class_name: str = ""
    rules: Tuple[IngressRule, ...] = ()


@dataclass
class CertificateSigningRequest:
    """certificates.k8s.io/v1 CSR, reduced to the control-flow surface the
    csrapproving/csrsigning/csrcleaner controllers drive (the x509/crypto
    layer is environment — what matters for parity is the approve → sign →
    clean lifecycle over the API)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    signer_name: str = ""            # e.g. kubernetes.io/kube-apiserver-client-kubelet
    username: str = ""               # requesting identity
    groups: Tuple[str, ...] = ()
    usages: Tuple[str, ...] = ()     # "client auth" | "server auth" | ...
    request: str = ""                # the CSR blob (opaque here)
    # status
    approved: bool = False
    denied: bool = False
    approval_reason: str = ""
    certificate: str = ""            # issued by the signing controller
    issued_at: float = 0.0


@dataclass
class HorizontalPodAutoscaler:
    """autoscaling/v2-shaped HPA, reduced to a cpu-utilization target over a
    scale-target workload (pkg/controller/podautoscaler). The metrics-API
    seam is ``ClusterStore.pod_metrics`` (pod key → milli-cpu usage)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    target_kind: str = "Deployment"   # scaleTargetRef
    target_name: str = ""
    min_replicas: int = 1
    max_replicas: int = 10
    # target average utilization: usage / per-pod cpu request, in percent
    target_cpu_utilization: int = 80
    # status
    current_replicas: int = 0
    desired_replicas: int = 0
    last_scale_time: float = 0.0


@dataclass
class CSINode:
    """storage/v1 CSINode: per-driver attachable volume limits
    (nodevolumelimits/csi.go reads CSINode.Spec.Drivers[].Allocatable)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    drivers: Dict[str, int] = field(default_factory=dict)  # driver name -> max volumes


@dataclass
class Binding:
    """pods/{name}/binding subresource payload
    (pkg/registry/core/pod/storage/storage.go:146 BindingREST)."""

    pod_key: str = ""
    node_name: str = ""


# ---------------------------------------------------------------------------
# resource.k8s.io (Dynamic Resource Allocation, structured parameters)
#
# The DRA surface reduced to typed attribute selectors instead of opaque
# driver blobs: a selector map is ``attribute key -> expression`` (e.g.
# {"tpu.dev/cores": ">=4", "tpu.dev/gen": "v5"}; api/dra.py parses and
# evaluates them against NodeStatus.device_attributes). Allocation is
# node-level: a claim allocates to one node and any number of pods on that
# node may reserve it (per-device inventory is out of scope — attributes
# describe the node's device class, not individual devices).


@dataclass
class ResourceClass:
    """resource.k8s.io ResourceClass (cluster-scoped): driver identity plus
    the class-level structured-parameter selectors every claim of this class
    inherits."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    driver_name: str = ""
    selectors: Dict[str, object] = field(default_factory=dict)


@dataclass
class ResourceClaim:
    """resource.k8s.io ResourceClaim (namespaced): a request for devices
    matching the class + claim selectors, plus the allocation status the
    scheduler's DynamicResources plugin maintains (Reserve writes
    ``allocated_node``; pods consuming the claim appear in
    ``reserved_for``)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    resource_class_name: str = ""
    selectors: Dict[str, object] = field(default_factory=dict)
    # status
    allocated_node: str = ""            # "" = unallocated
    reserved_for: Tuple[str, ...] = ()  # pod keys consuming the claim


@dataclass
class ResourceClaimTemplate:
    """resource.k8s.io ResourceClaimTemplate (namespaced): the spec the
    resourceclaim controller stamps out as a pod-owned ResourceClaim for
    every pod.spec.resourceClaims entry that references it."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    resource_class_name: str = ""
    selectors: Dict[str, object] = field(default_factory=dict)


@dataclass
class PodSchedulingContext:
    """resource.k8s.io PodSchedulingContext (namespaced; name = pod name):
    the scheduler⇄driver negotiation object — here the scheduler's PostBind
    persists the selected node (the driver side is in-process, so
    potential_nodes stays informational)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selected_node: str = ""
    potential_nodes: Tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# scheduling.x-k8s.io (gang scheduling / coscheduling)

# the pod label naming the PodGroup a pod belongs to (scheduler-plugins'
# pod-group.scheduling.sigs.k8s.io label, shortened to this repo's group)
POD_GROUP_LABEL = "scheduling.x-k8s.io/pod-group"

# PodGroup status phases (scheduler-plugins apis/scheduling/v1alpha1)
POD_GROUP_PENDING = "Pending"
POD_GROUP_SCHEDULING = "Scheduling"
POD_GROUP_RUNNING = "Running"


# canonical SchedulingQuota dimension names (the subset of the core
# ResourceQuota evaluator's dimensions the scheduler admits on, plus the
# resource.k8s.io claim count)
QUOTA_PODS = "pods"
QUOTA_CPU = "requests.cpu"        # milli-cpu (api/resource.py canonical)
QUOTA_MEMORY = "requests.memory"  # KiB
QUOTA_CLAIMS = "claims"           # pod.spec.resourceClaims entries

# the fixed dimension order every [*, Q] quota tensor row uses — one source
# of truth shared by the ledger's device-table export (framework/plugins/
# quota.py) and the device-side over-quota screen (ops/quota.py)
QUOTA_DIM_ORDER = (QUOTA_PODS, QUOTA_CPU, QUOTA_MEMORY, QUOTA_CLAIMS)


@dataclass
class SchedulingQuota:
    """scheduling.x-k8s.io SchedulingQuota (namespaced): the scheduler-side
    multi-tenant admission contract — per-namespace hard caps the quota
    admission gate (framework/plugins/quota.py) enforces BEFORE a pod may
    occupy a device batch slot, plus the fair-share ``weight`` the
    scheduling queue's deficit-round-robin dequeuer serves the namespace
    with. Distinct from core/v1 ResourceQuota (apiserver admission on pod
    CREATE): this kind admits on *scheduling* — usage counts scheduled
    (assumed + bound) pods, so an over-quota tenant's pods exist but park
    in the unschedulable queue until capacity frees.

    ``hard`` keys are the QUOTA_* dimension names in canonical ints; absent
    keys are unlimited. ``used`` is advisory status (the authoritative
    ledger lives in the QuotaAdmission plugin and is rebuilt from the store
    on restart).

    ``cohort`` (Kueue's direction) names a lending pool: namespaces whose
    quotas share a cohort may borrow each other's UNUSED guaranteed
    headroom past their own ``hard`` caps. Borrowed charges are
    reclaimable — a lender's own pod arriving while the cohort is
    exhausted preempts borrower pods to take its guarantee back. Empty =
    no cohort (hard caps only, the pre-borrowing behavior)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    hard: Dict[str, int] = field(default_factory=dict)
    weight: int = 1  # fair-share weight (>= 0; 0 = background tenant)
    cohort: str = ""  # lending pool name ("" = not in any cohort)
    # status
    used: Dict[str, int] = field(default_factory=dict)


@dataclass
class PodGroup:
    """scheduling.x-k8s.io PodGroup (namespaced): the gang contract for
    all-or-nothing placement. Pods join via the POD_GROUP_LABEL label; the
    Coscheduling plugin parks members at Permit until ``min_member`` of them
    hold a node, then releases the whole gang — or rejects it wholesale when
    ``schedule_timeout_seconds`` passes first (a 32-pod training job with 31
    pods bound is pure waste; multi-host TPU jobs need all or nothing)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    min_member: int = 1
    # 0 = the Coscheduling plugin's default permit timeout applies
    schedule_timeout_seconds: int = 0
    # status (maintained by the Coscheduling plugin's PostBind/Unreserve)
    phase: str = POD_GROUP_PENDING
    scheduled: int = 0  # members currently bound
