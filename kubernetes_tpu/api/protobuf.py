"""Protobuf serialization for API objects (VERDICT r3 missing #7).

The reference negotiates ``application/vnd.kubernetes.protobuf`` alongside
JSON on every REST endpoint (runtime/serializer/protobuf/protobuf.go with
the ``k8s\\x00`` magic prefix over a runtime.Unknown envelope). This
framework's API types are reflection-encoded dataclasses, so the binary
form is one struct-shaped schema (native/ktpu_api.proto KValue) carrying
exactly the field tree the JSON codec produces — real protobuf wire bytes
(varints, length-delimited fields), generically schema'd rather than
per-type generated; the envelope keeps the magic prefix + kind metadata so
the negotiation surface matches.

Messages compile on demand with protoc into native/build (the
grpc_service.py pattern).
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Any, List, Tuple

from . import types as api_types
from .codec import from_wire, to_wire

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_PROTO_DIR = os.path.join(_REPO_ROOT, "native")
_PROTO = os.path.join(_PROTO_DIR, "ktpu_api.proto")
_BUILD_DIR = os.path.join(_PROTO_DIR, "build")
_PB2 = os.path.join(_BUILD_DIR, "ktpu_api_pb2.py")

# runtime/serializer/protobuf/protobuf.go:43 — the 4-byte envelope prefix
MAGIC = b"k8s\x00"
CONTENT_TYPE = "application/vnd.kubernetes.protobuf"

_pb2 = None
_pb2_lock = threading.Lock()


def pb2_available() -> bool:
    """True when pb2() will succeed (the apiserver codec is not vendored
    the way native/ktpu_device_pb2.py is — tests skip with a reason
    instead of erroring when the on-demand build cannot happen)."""
    from ..utils.protoc import build_available

    return build_available(_pb2, _PB2, _PROTO)


def pb2():
    global _pb2
    if _pb2 is not None:
        return _pb2
    with _pb2_lock:
        if _pb2 is not None:
            return _pb2
        if (not os.path.exists(_PB2)
                or os.path.getmtime(_PB2) < os.path.getmtime(_PROTO)):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            subprocess.run(
                ["protoc", f"--python_out={_BUILD_DIR}", "-I", _PROTO_DIR, _PROTO],
                check=True, capture_output=True, timeout=60)
        import importlib.util

        spec = importlib.util.spec_from_file_location("ktpu_api_pb2", _PB2)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _pb2 = mod
        return _pb2


# ----------------------------------------------------- wire tree <-> KValue


def _to_kvalue(v: Any):
    p = pb2()
    kv = p.KValue()
    if isinstance(v, bool):          # bool BEFORE int: bool is an int subtype
        kv.b = v
    elif isinstance(v, int):
        kv.i = v
    elif isinstance(v, float):
        kv.d = v
    elif isinstance(v, str):
        kv.s = v
    elif isinstance(v, (list, tuple)):
        kv.list.SetInParent()  # an EMPTY list must still set the oneof arm
        kv.list.items.extend(_to_kvalue(x) for x in v)
    elif isinstance(v, dict):
        kv.map.SetInParent()  # likewise for the empty map
        for k, x in v.items():
            kv.map.fields[str(k)].CopyFrom(_to_kvalue(x))
    elif v is None:
        kv.raw = b""
    else:
        raise TypeError(f"not protobuf-encodable: {type(v).__name__}")
    return kv


def _from_kvalue(kv) -> Any:
    which = kv.WhichOneof("kind")
    if which == "s":
        return kv.s
    if which == "i":
        return int(kv.i)
    if which == "d":
        return kv.d
    if which == "b":
        return kv.b
    if which == "list":
        return [_from_kvalue(x) for x in kv.list.items]
    if which == "map":
        return {k: _from_kvalue(x) for k, x in kv.map.fields.items()}
    return None  # raw/None


# ------------------------------------------------------------ object codecs


def encode_object(kind: str, obj, api_version: str = "v1") -> bytes:
    """Typed object → magic-prefixed protobuf bytes."""
    p = pb2()
    ko = p.KObject(kind=kind, api_version=api_version)
    ko.value.CopyFrom(_to_kvalue(to_wire(obj)))
    return MAGIC + ko.SerializeToString()


def decode_object(data: bytes, expected_kind: str = ""):
    """Magic-prefixed protobuf bytes → typed object (kind from envelope)."""
    if not data.startswith(MAGIC):
        raise ValueError("missing protobuf magic prefix")
    p = pb2()
    ko = p.KObject.FromString(data[len(MAGIC):])
    kind = ko.kind or expected_kind
    cls = getattr(api_types, kind, None)
    if cls is None:
        raise TypeError(f"unknown kind {kind!r}")
    return kind, from_wire(cls, _from_kvalue(ko.value))


def encode_list(kind: str, objs: List[Any], resource_version: int = 0) -> bytes:
    p = pb2()
    kl = p.KObjectList(kind=kind, resource_version=resource_version)
    for obj in objs:
        ko = kl.items.add()
        ko.kind = kind
        ko.value.CopyFrom(_to_kvalue(to_wire(obj)))
    return MAGIC + kl.SerializeToString()


def decode_list(data: bytes) -> Tuple[str, List[Any], int]:
    if not data.startswith(MAGIC):
        raise ValueError("missing protobuf magic prefix")
    p = pb2()
    kl = p.KObjectList.FromString(data[len(MAGIC):])
    cls = getattr(api_types, kl.kind, None)
    if cls is None:
        raise TypeError(f"unknown kind {kl.kind!r}")
    return kl.kind, [from_wire(cls, _from_kvalue(ko.value)) for ko in kl.items], \
        int(kl.resource_version)
