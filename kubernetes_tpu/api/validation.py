"""API field validation (pkg/apis/core/validation/validation.go).

The reference validates every object in the registry strategy after
admission defaulting (6,868 lines of field checks); this repo decoded bad
manifests silently (VERDICT r3 missing #5). This module is the distilled
corpus: the checks that change behavior — name/label syntax, container
shape, resource request/limit consistency, enum domains, numeric ranges,
immutability on update — wired into the store's write path right after the
admission chain (the strategy.Validate position).

Each validator mirrors its reference function and returns a list of
``field.Path: message`` strings; writers raise ``ValidationError`` (the
apiserver front maps it to 422 Invalid, like api machinery's
errors.NewInvalid).
"""

from __future__ import annotations

import re
from typing import List, Optional

from . import resource as resource_api
from .types import QUOTA_CLAIMS, QUOTA_CPU, QUOTA_MEMORY, QUOTA_PODS

# util/validation/validation.go IsDNS1123Subdomain / IsDNS1123Label /
# IsQualifiedName / IsValidLabelValue
_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_DNS1123_SUBDOMAIN = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")
_QUALIFIED_NAME_PART = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")
_LABEL_VALUE = re.compile(r"^([A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?)?$")

MAX_DNS1123_SUBDOMAIN = 253
MAX_DNS1123_LABEL = 63
MAX_LABEL_VALUE = 63

VALID_RESTART_POLICIES = {"Always", "OnFailure", "Never", ""}
VALID_TAINT_EFFECTS = {"NoSchedule", "PreferNoSchedule", "NoExecute"}
VALID_TOLERATION_OPERATORS = {"Exists", "Equal", ""}
VALID_WHEN_UNSATISFIABLE = {"DoNotSchedule", "ScheduleAnyway"}
VALID_PREEMPTION_POLICIES = {"PreemptLowerPriority", "Never", ""}
# the user-priority ceiling (validation.go ValidatePriorityClass; values
# above 1e9 are reserved for system classes)
HIGHEST_USER_PRIORITY = 1_000_000_000


class ValidationError(Exception):
    """errors.NewInvalid analog: carries the per-field error list."""

    def __init__(self, kind: str, name: str, errors: List[str]):
        self.kind = kind
        self.name = name
        self.errors = errors
        super().__init__(
            f"{kind} {name!r} is invalid: " + "; ".join(errors[:8]))


def is_dns1123_subdomain(value: str) -> bool:
    return (0 < len(value) <= MAX_DNS1123_SUBDOMAIN
            and _DNS1123_SUBDOMAIN.match(value) is not None)


def is_dns1123_label(value: str) -> bool:
    return (0 < len(value) <= MAX_DNS1123_LABEL
            and _DNS1123_LABEL.match(value) is not None)


def is_qualified_name(value: str) -> List[str]:
    """IsQualifiedName: [prefix/]name; prefix a DNS subdomain, name ≤63."""
    errs = []
    parts = value.split("/")
    if len(parts) == 1:
        name = parts[0]
    elif len(parts) == 2:
        prefix, name = parts
        if not prefix:
            errs.append("prefix part must be non-empty")
        elif not is_dns1123_subdomain(prefix):
            errs.append(f"prefix part {prefix!r} must be a DNS subdomain")
    else:
        return [f"a qualified name {value!r} must have at most one '/'"]
    if not name:
        errs.append("name part must be non-empty")
    elif len(name) > MAX_DNS1123_LABEL or not _QUALIFIED_NAME_PART.match(name):
        errs.append(f"name part {name!r} must consist of alphanumerics, "
                    "'-', '_' or '.', ≤63 chars, alphanumeric-bounded")
    return errs


def validate_labels(labels, path: str) -> List[str]:
    """unversioned validation ValidateLabels."""
    errs = []
    for k, v in (labels or {}).items():
        errs += [f"{path}.{k}: {m}" for m in is_qualified_name(str(k))]
        sv = str(v)
        if len(sv) > MAX_LABEL_VALUE or not _LABEL_VALUE.match(sv):
            errs.append(f"{path}.{k}: label value {sv!r} must be ≤63 chars "
                        "of alphanumerics, '-', '_' or '.'")
    return errs


def validate_object_meta(meta, requires_namespace: bool, path="metadata") -> List[str]:
    """ValidateObjectMeta (validation.go:356): name syntax, namespace
    syntax/presence, label syntax."""
    errs = []
    if not meta.name:
        errs.append(f"{path}.name: name is required")
    elif not is_dns1123_subdomain(meta.name):
        errs.append(f"{path}.name: {meta.name!r} must be a lowercase RFC-1123 "
                    "subdomain (a-z0-9, '-', '.')")
    ns = getattr(meta, "namespace", "")
    if requires_namespace:
        if not ns:
            errs.append(f"{path}.namespace: namespace is required")
        elif not is_dns1123_label(ns):
            errs.append(f"{path}.namespace: {ns!r} must be a lowercase "
                        "RFC-1123 label")
    errs += validate_labels(getattr(meta, "labels", None), f"{path}.labels")
    return errs


# ------------------------------------------------------------------- pods


def _validate_resource_amounts(requests, limits, path) -> List[str]:
    """validateContainerResourceRequirements: parseable, non-negative,
    request ≤ limit per resource."""
    errs = []
    parsed = {}
    for field_name, amounts in (("requests", requests), ("limits", limits)):
        for res, q in (amounts or {}).items():
            try:
                v = resource_api.canonical(res, q)
            except Exception:  # noqa: BLE001 — unparseable quantity
                errs.append(f"{path}.{field_name}.{res}: quantity {q!r} is invalid")
                continue
            if v < 0:
                errs.append(f"{path}.{field_name}.{res}: must be ≥ 0")
            parsed[(field_name, res)] = v
    for res, _q in (limits or {}).items():
        req = parsed.get(("requests", res))
        lim = parsed.get(("limits", res))
        if req is not None and lim is not None and req > lim:
            errs.append(f"{path}.requests.{res}: must be ≤ the {res} limit")
    return errs


def _validate_containers(containers, path, init=False) -> List[str]:
    """validateContainers (validation.go:3013): non-empty (main set), unique
    DNS-label names, image set, port ranges, resource consistency."""
    errs = []
    if not containers and not init:
        return [f"{path}: must contain at least one container"]
    seen = set()
    for i, c in enumerate(containers or ()):
        p = f"{path}[{i}]"
        if not c.name:
            errs.append(f"{p}.name: name is required")
        elif not is_dns1123_label(c.name):
            errs.append(f"{p}.name: {c.name!r} must be a lowercase RFC-1123 label")
        elif c.name in seen:
            errs.append(f"{p}.name: duplicate container name {c.name!r}")
        seen.add(c.name)
        for j, port in enumerate(getattr(c, "ports", ()) or ()):
            for attr in ("container_port", "host_port"):
                v = getattr(port, attr, 0)
                if v and not (0 < v <= 65535):
                    errs.append(f"{p}.ports[{j}].{attr}: {v} must be in 1-65535")
        errs += _validate_resource_amounts(
            getattr(c, "requests", None), getattr(c, "limits", None),
            f"{p}.resources")
    return errs


def _validate_tolerations(tolerations, path) -> List[str]:
    """validateTolerations: operator/effect domains; Exists forbids value;
    empty key requires Exists."""
    errs = []
    for i, t in enumerate(tolerations or ()):
        p = f"{path}[{i}]"
        if t.operator not in VALID_TOLERATION_OPERATORS:
            errs.append(f"{p}.operator: {t.operator!r} must be Exists or Equal")
        if t.effect and t.effect not in VALID_TAINT_EFFECTS:
            errs.append(f"{p}.effect: {t.effect!r} must be one of "
                        f"{sorted(VALID_TAINT_EFFECTS)}")
        if t.operator == "Exists" and t.value:
            errs.append(f"{p}.value: must be empty when operator is Exists")
        if not t.key and t.operator not in ("Exists", ""):
            errs.append(f"{p}.operator: must be Exists when key is empty")
    return errs


def _validate_spread_constraints(constraints, path) -> List[str]:
    """validateTopologySpreadConstraints: maxSkew ≥ 1, topologyKey set,
    whenUnsatisfiable domain, no duplicate {key, whenUnsatisfiable}."""
    errs = []
    seen = set()
    for i, c in enumerate(constraints or ()):
        p = f"{path}[{i}]"
        if c.max_skew < 1:
            errs.append(f"{p}.maxSkew: {c.max_skew} must be ≥ 1")
        if not c.topology_key:
            errs.append(f"{p}.topologyKey: topologyKey is required")
        if c.when_unsatisfiable not in VALID_WHEN_UNSATISFIABLE:
            errs.append(f"{p}.whenUnsatisfiable: {c.when_unsatisfiable!r} "
                        "must be DoNotSchedule or ScheduleAnyway")
        dup = (c.topology_key, c.when_unsatisfiable)
        if dup in seen:
            errs.append(f"{p}.topologyKey: duplicate constraint "
                        f"{{{c.topology_key}, {c.when_unsatisfiable}}}")
        seen.add(dup)
        # validateMinDomains: ≥ 1, and only with DoNotSchedule
        md = getattr(c, "min_domains", None)
        if md is not None:
            if md < 1:
                errs.append(f"{p}.minDomains: {md} must be greater than 0")
            if c.when_unsatisfiable != "DoNotSchedule":
                errs.append(f"{p}.minDomains: can only be specified when "
                            "whenUnsatisfiable is DoNotSchedule")
        errs += _validate_label_selector(getattr(c, "label_selector", None),
                                        f"{p}.labelSelector")
    return errs


_SELECTOR_SET_OPS = {"In", "NotIn"}
_SELECTOR_EXIST_OPS = {"Exists", "DoesNotExist"}
_SELECTOR_NUM_OPS = {"Gt", "Lt"}


def _validate_requirement(req, path, node: bool) -> List[str]:
    """ValidateLabelSelectorRequirement / ValidateNodeSelectorRequirement:
    operator domain; In/NotIn need ≥1 value; Exists/DoesNotExist forbid
    values; node-only Gt/Lt need exactly one integer value."""
    errs = [f"{path}.key: {m}" for m in is_qualified_name(req.key)] if req.key \
        else [f"{path}.key: key is required"]
    op = req.operator
    allowed = _SELECTOR_SET_OPS | _SELECTOR_EXIST_OPS | (
        _SELECTOR_NUM_OPS if node else set())
    if op not in allowed:
        errs.append(f"{path}.operator: {op!r} is not a valid operator")
        return errs
    if op in _SELECTOR_SET_OPS and not req.values:
        errs.append(f"{path}.values: must be specified when operator is {op}")
    if op in _SELECTOR_EXIST_OPS and req.values:
        errs.append(f"{path}.values: may not be specified when operator is {op}")
    if op in _SELECTOR_NUM_OPS:
        if len(req.values) != 1:
            errs.append(f"{path}.values: must have a single element for {op}")
        else:
            try:
                int(req.values[0])
            except ValueError:
                errs.append(f"{path}.values[0]: {req.values[0]!r} must be an integer")
    return errs


def _validate_label_selector(sel, path) -> List[str]:
    """ValidateLabelSelector (metav1 validation)."""
    if sel is None:
        return []
    errs = validate_labels(sel.match_labels, f"{path}.matchLabels")
    for i, req in enumerate(sel.match_expressions or ()):
        errs += _validate_requirement(req, f"{path}.matchExpressions[{i}]",
                                      node=False)
    return errs


def _validate_pod_affinity_term(term, path) -> List[str]:
    """validatePodAffinityTerm (validation.go:3280): topologyKey required,
    selector shapes valid, namespace names valid."""
    errs = []
    if not term.topology_key:
        errs.append(f"{path}.topologyKey: can not be empty")
    errs += _validate_label_selector(term.label_selector, f"{path}.labelSelector")
    errs += _validate_label_selector(term.namespace_selector,
                                     f"{path}.namespaceSelector")
    for i, ns in enumerate(term.namespaces or ()):
        if not is_dns1123_label(ns):
            errs.append(f"{path}.namespaces[{i}]: {ns!r} must be a DNS label")
    return errs


def _validate_affinity(affinity, path) -> List[str]:
    """validateAffinity (validation.go:3236): node selector terms' expression
    shape, pod (anti-)affinity term shape, preferred weights in 1-100."""
    errs = []
    if affinity is None:
        return errs
    na = affinity.node_affinity
    if na is not None:
        base = f"{path}.nodeAffinity"
        if na.required is not None:
            for ti, term in enumerate(na.required.terms or ()):
                tp = f"{base}.required.nodeSelectorTerms[{ti}]"
                for ei, req in enumerate(term.match_expressions or ()):
                    errs += _validate_requirement(
                        req, f"{tp}.matchExpressions[{ei}]", node=True)
        for pi, pref in enumerate(na.preferred or ()):
            pp = f"{base}.preferred[{pi}]"
            if not (1 <= pref.weight <= 100):
                errs.append(f"{pp}.weight: {pref.weight} must be in the range 1-100")
            for ei, req in enumerate(pref.preference.match_expressions or ()):
                errs += _validate_requirement(
                    req, f"{pp}.preference.matchExpressions[{ei}]", node=True)
    for attr, key in (("pod_affinity", "podAffinity"),
                      ("pod_anti_affinity", "podAntiAffinity")):
        pa = getattr(affinity, attr)
        if pa is None:
            continue
        base = f"{path}.{key}"
        for ti, term in enumerate(pa.required or ()):
            errs += _validate_pod_affinity_term(term, f"{base}.required[{ti}]")
        for ti, wt in enumerate(pa.preferred or ()):
            tp = f"{base}.preferred[{ti}]"
            if not (1 <= wt.weight <= 100):
                errs.append(f"{tp}.weight: {wt.weight} must be in the range 1-100")
            errs += _validate_pod_affinity_term(wt.term, f"{tp}.podAffinityTerm")
    return errs


def validate_pod(pod) -> List[str]:
    """ValidatePod / ValidatePodSpec (validation.go:3488)."""
    errs = validate_object_meta(pod.meta, requires_namespace=True)
    spec = pod.spec
    errs += _validate_containers(spec.containers, "spec.containers")
    errs += _validate_containers(spec.init_containers,
                                 "spec.initContainers", init=True)
    # init container names must not collide with main containers
    main = {c.name for c in spec.containers}
    for i, c in enumerate(spec.init_containers or ()):
        if c.name in main:
            errs.append(f"spec.initContainers[{i}].name: duplicates a "
                        f"container name {c.name!r}")
    # AccumulateUniqueHostPorts (validation.go:3003): a (hostIP, protocol,
    # hostPort) triple may appear at most once across the pod's containers
    seen_hp = set()
    for ci, c in enumerate(spec.containers or ()):
        for pi, port in enumerate(getattr(c, "ports", ()) or ()):
            hp = getattr(port, "host_port", 0)
            if not hp:
                continue
            key = (getattr(port, "host_ip", ""), getattr(port, "protocol", "TCP"), hp)
            if key in seen_hp:
                errs.append(f"spec.containers[{ci}].ports[{pi}].hostPort: "
                            f"duplicate host port {key}")
            seen_hp.add(key)
    errs += _validate_tolerations(spec.tolerations, "spec.tolerations")
    errs += _validate_spread_constraints(
        spec.topology_spread_constraints, "spec.topologySpreadConstraints")
    errs += _validate_affinity(spec.affinity, "spec.affinity")
    errs += validate_labels(spec.node_selector, "spec.nodeSelector")
    if spec.preemption_policy not in VALID_PREEMPTION_POLICIES:
        errs.append(f"spec.preemptionPolicy: {spec.preemption_policy!r} must "
                    "be PreemptLowerPriority or Never")
    if spec.priority_class_name and not is_dns1123_subdomain(spec.priority_class_name):
        errs.append("spec.priorityClassName: must be a DNS subdomain")
    return errs


def validate_pod_update(old, new) -> List[str]:
    """ValidatePodUpdate (validation.go:4262): spec is immutable except
    node_name (binding), tolerations additions, and container images —
    the reference allows image updates and toleration appends only."""
    errs = []
    if old.spec.node_name and new.spec.node_name != old.spec.node_name:
        errs.append("spec.nodeName: may not be changed once set (pods/binding"
                    " is the only writer)")
    for attr, label in (
        ("node_selector", "spec.nodeSelector"),
        ("priority", "spec.priority"),
        ("scheduler_name", "spec.schedulerName"),
        ("host_network", "spec.hostNetwork"),
    ):
        if getattr(new.spec, attr) != getattr(old.spec, attr):
            errs.append(f"{label}: field is immutable")
    if len(new.spec.containers or ()) != len(old.spec.containers or ()):
        errs.append("spec.containers: may not add or remove containers")
    return errs


# ------------------------------------------------------------ other kinds


def validate_node(node) -> List[str]:
    """ValidateNode (validation.go:5022): meta + taint domains + capacity."""
    errs = validate_object_meta(node.meta, requires_namespace=False)
    seen_taints = set()
    for i, t in enumerate(node.spec.taints or ()):
        p = f"spec.taints[{i}]"
        if not t.key:
            errs.append(f"{p}.key: key is required")
        else:
            errs += [f"{p}.key: {m}" for m in is_qualified_name(t.key)]
        if t.effect not in VALID_TAINT_EFFECTS:
            errs.append(f"{p}.effect: {t.effect!r} must be one of "
                        f"{sorted(VALID_TAINT_EFFECTS)}")
        if t.value and _LABEL_VALUE.match(t.value) is None:
            errs.append(f"{p}.value: {t.value!r} is not a valid taint value")
        # validateNodeTaints: duplicate (key, effect) pairs rejected
        pair = (t.key, t.effect)
        if pair in seen_taints:
            errs.append(f"{p}: duplicate taint {pair}")
        seen_taints.add(pair)
    for res, q in (node.status.capacity or {}).items():
        try:
            if resource_api.canonical(res, q) < 0:
                errs.append(f"status.capacity.{res}: must be ≥ 0")
        except Exception:  # noqa: BLE001
            errs.append(f"status.capacity.{res}: quantity {q!r} is invalid")
    return errs


def validate_service(svc) -> List[str]:
    """ValidateService (validation.go:4497): port ranges + selector labels."""
    errs = validate_object_meta(svc.meta, requires_namespace=True)
    for i, port in enumerate(getattr(svc, "ports", ()) or ()):
        v = getattr(port, "port", 0)
        if not (0 < v <= 65535):
            errs.append(f"spec.ports[{i}].port: {v} must be in 1-65535")
    errs += validate_labels(getattr(svc, "selector", None), "spec.selector")
    return errs


def validate_priority_class(pc) -> List[str]:
    """ValidatePriorityClass: user values below the system ceiling."""
    errs = validate_object_meta(pc.meta, requires_namespace=False)
    if getattr(pc, "value", 0) > HIGHEST_USER_PRIORITY \
            and not pc.meta.name.startswith("system-"):
        errs.append(f"value: must be ≤ {HIGHEST_USER_PRIORITY}")
    return errs


def validate_namespace(ns) -> List[str]:
    errs = []
    if not ns.meta.name:
        errs.append("metadata.name: name is required")
    elif not is_dns1123_label(ns.meta.name):
        errs.append(f"metadata.name: {ns.meta.name!r} must be a lowercase "
                    "RFC-1123 label")
    errs += validate_labels(ns.meta.labels, "metadata.labels")
    return errs


_CLUSTER_SCOPED_META_ONLY = (
    "PersistentVolume", "StorageClass", "CSINode", "ClusterRole",
    "ClusterRoleBinding", "ResourceClass",
)
_NAMESPACED_META_ONLY = (
    "PersistentVolumeClaim", "ConfigMap", "Secret", "ServiceAccount",
    "ReplicaSet", "ReplicationController", "StatefulSet", "Deployment",
    "DaemonSet", "Job", "CronJob", "Endpoints", "EndpointSlice", "Lease",
    "PodDisruptionBudget", "ResourceQuota", "LimitRange",
    "HorizontalPodAutoscaler", "ResourceClaim", "ResourceClaimTemplate",
    "PodSchedulingContext",
)


def validate_pod_group(pg) -> list:
    errs = validate_object_meta(pg.meta, requires_namespace=True)
    if pg.min_member < 1:
        errs.append("spec.minMember: must be >= 1")
    if pg.schedule_timeout_seconds < 0:
        errs.append("spec.scheduleTimeoutSeconds: must be >= 0")
    return errs


def validate_scheduling_quota(sq) -> list:
    errs = validate_object_meta(sq.meta, requires_namespace=True)
    if sq.weight < 0:
        errs.append("spec.weight: must be >= 0")
    if sq.cohort and not is_dns1123_label(sq.cohort):
        errs.append(f"spec.cohort: {sq.cohort!r} must be a lowercase "
                    "RFC-1123 label")
    for dim, v in sq.hard.items():
        if dim not in _QUOTA_DIMENSIONS:
            errs.append(f"spec.hard[{dim}]: unknown quota dimension "
                        f"(expected one of {sorted(_QUOTA_DIMENSIONS)})")
        elif not isinstance(v, int) or v < 0:
            errs.append(f"spec.hard[{dim}]: must be a non-negative integer")
    return errs


# one source of truth with the ledger's dimension keys (api/types.py /
# framework/plugins/quota.py) — a dimension added there validates here
_QUOTA_DIMENSIONS = frozenset(
    (QUOTA_PODS, QUOTA_CPU, QUOTA_MEMORY, QUOTA_CLAIMS))


def validate(kind: str, obj) -> None:
    """Strategy.Validate dispatch; raises ValidationError on failure."""
    if kind == "PodGroup":
        errs = validate_pod_group(obj)
        if errs:
            raise ValidationError(kind, obj.meta.name, errs)
        return
    if kind == "SchedulingQuota":
        errs = validate_scheduling_quota(obj)
        if errs:
            raise ValidationError(kind, obj.meta.name, errs)
        return
    if kind == "Pod":
        errs = validate_pod(obj)
    elif kind == "Node":
        errs = validate_node(obj)
    elif kind == "Service":
        errs = validate_service(obj)
    elif kind == "PriorityClass":
        errs = validate_priority_class(obj)
    elif kind == "Namespace":
        errs = validate_namespace(obj)
    elif kind in _CLUSTER_SCOPED_META_ONLY:
        errs = validate_object_meta(obj.meta, requires_namespace=False)
    elif kind in _NAMESPACED_META_ONLY:
        errs = validate_object_meta(obj.meta, requires_namespace=True)
    else:
        return  # webhook configs etc.: meta-free or internal kinds
    if errs:
        raise ValidationError(kind, getattr(obj.meta, "name", ""), errs)


def validate_update(kind: str, old, new) -> None:
    validate(kind, new)
    if kind == "Pod" and old is not None:
        errs = validate_pod_update(old, new)
        if errs:
            raise ValidationError(kind, new.meta.name, errs)
