"""Resource quantities and their canonical integer encodings.

Mirrors the semantics of apimachinery's ``resource.Quantity``
(staging/src/k8s.io/apimachinery/pkg/api/resource) for the subset the scheduler
uses: parsing decimal/binary-SI strings, milli-value extraction for CPU, and
integer byte values for memory-like resources.

Canonical device units
----------------------
The TPU backend stores resources as int32 tensors.  To stay exact within int32
range each resource class gets a canonical unit, defined HERE and used by both
the scalar oracle plugins and the tensor encoder (so oracle↔kernel parity is
exact by construction):

  cpu                 -> millicores      (reference: Resource.MilliCPU, framework/types.go:414)
  memory              -> KiB, ceil       (reference keeps bytes in int64; int32 KiB is exact to 2 TiB)
  ephemeral-storage   -> MiB, ceil
  hugepages-*         -> MiB, ceil
  pods                -> count
  extended resources  -> integer value (counts; e.g. example.com/foo)
"""

from __future__ import annotations

import math
import re
from fractions import Fraction

from ..native import canonical_native as _canonical_native

# Resource names (subset of k8s.io/api/core/v1 const names).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"
HUGEPAGES_PREFIX = "hugepages-"

_BINARY_SUFFIXES = {
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}
_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9), "u": Fraction(1, 10**6), "m": Fraction(1, 10**3),
    "": Fraction(1), "k": Fraction(10**3), "M": Fraction(10**6),
    "G": Fraction(10**9), "T": Fraction(10**12), "P": Fraction(10**15), "E": Fraction(10**18),
}

_QUANTITY_RE = re.compile(r"^([+-]?[0-9.]+)([A-Za-z]{0,2})$")


def parse_quantity(value) -> Fraction:
    """Parse a quantity (string like '100m', '1Gi', '2', or a number) to a Fraction."""
    if isinstance(value, Fraction):
        return value
    if isinstance(value, (int, float)):
        return Fraction(value).limit_denominator(10**9)
    s = str(value).strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity {value!r}")
    num, suffix = m.groups()
    # Fraction parses decimal strings exactly ("2.0000000001" included) —
    # never limit_denominator here or this path and the native C++ parser
    # would disagree on >9-fractional-digit quantities
    base = Fraction(num)
    if suffix in _BINARY_SUFFIXES:
        return base * _BINARY_SUFFIXES[suffix]
    if suffix in _DECIMAL_SUFFIXES:
        return base * _DECIMAL_SUFFIXES[suffix]
    raise ValueError(f"invalid quantity suffix {suffix!r} in {value!r}")


def milli_value(value) -> int:
    """Quantity -> integer milli-units, rounding up (Quantity.MilliValue semantics)."""
    return math.ceil(parse_quantity(value) * 1000)


def int_value(value) -> int:
    """Quantity -> integer units, rounding up (Quantity.Value semantics)."""
    return math.ceil(parse_quantity(value))


def _native_cls(resource: str) -> int:
    if resource == CPU:
        return 1  # CLS_MILLI
    if resource == MEMORY:
        return 2  # CLS_KIB
    if resource == EPHEMERAL_STORAGE or resource.startswith(HUGEPAGES_PREFIX):
        return 3  # CLS_MIB
    return 0  # CLS_COUNT


_canonical_memo: dict = {}


def canonical(resource: str, value) -> int:
    """Canonical int for the device tensors AND the scalar oracle. See module
    doc. String quantities go through the native C++ parser when built
    (native/ktpu_quantity.cpp, same exact semantics); anything else — or a
    native miss — takes the Fraction path. String results are memoized:
    workloads reuse a handful of quantity strings ("500m", "2Gi") across
    thousands of pods and this sits on the add_pod/encode hot path."""
    if isinstance(value, str):
        key = (resource, value)
        r = _canonical_memo.get(key)
        if r is not None:
            return r
        r = _canonical_native(value, _native_cls(resource))
        if r is None:
            r = _canonical_py(resource, value)
        if len(_canonical_memo) < 1 << 20:
            _canonical_memo[key] = r
        return r
    return _canonical_py(resource, value)


def _canonical_py(resource: str, value) -> int:
    if resource == CPU:
        return milli_value(value)
    if resource == MEMORY:
        return math.ceil(parse_quantity(value) / 2**10)
    if resource == EPHEMERAL_STORAGE or resource.startswith(HUGEPAGES_PREFIX):
        return math.ceil(parse_quantity(value) / 2**20)
    # pods / extended resources: plain integer counts
    return int_value(value)


def is_extended(resource: str) -> bool:
    """Extended resources are domain-prefixed names (v1helper.IsExtendedResourceName)."""
    return "/" in resource and not resource.startswith("kubernetes.io/")


# Default requests applied by the *scoring* path only, mirroring
# util.GetNonzeroRequests (pkg/scheduler/util/pod_resources.go): pods with no
# request still "cost" a nominal amount so spreading scores stay meaningful.
DEFAULT_MILLI_CPU_REQUEST = 100          # 0.1 core
DEFAULT_MEMORY_REQUEST_KIB = 200 * 1024  # 200 MiB in KiB
