"""runtime.Scheme analog: the versioned-conversion + defaulting + codec
registry (apimachinery pkg/runtime/scheme.go:46, serializer/).

The reference keeps one INTERNAL (hub) type per kind and converts each
EXTERNAL (versioned, wire-shaped) representation to/from it through
registered conversion functions, applying registered defaulters on decode.
Here the internal types are this framework's dataclasses (api/types.py) and
external versions are JSON-shaped dicts (e.g. core/v1 camelCase manifests —
api/corev1.py registers those). The codec path:

    decode: bytes/dict --(convert_from)--> internal obj --(defaulters)--> obj
    encode: internal obj --(convert_to)--> dict with apiVersion/kind --> bytes

Unknown apiVersion/kind raise SchemeError (the NotRegisteredErr analog).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Tuple


class SchemeError(Exception):
    """Unregistered group/version/kind or failed conversion."""


@dataclasses.dataclass(frozen=True)
class GroupVersionKind:
    group: str
    version: str
    kind: str

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version

    @staticmethod
    def from_api_version(api_version: str, kind: str) -> "GroupVersionKind":
        if "/" in api_version:
            g, v = api_version.split("/", 1)
        else:
            g, v = "", api_version
        return GroupVersionKind(g, v, kind)


class Scheme:
    def __init__(self):
        # gvk -> (internal type, from_external, to_external)
        self._kinds: Dict[GroupVersionKind, Tuple[type, Callable, Callable]] = {}
        self._defaulters: Dict[type, List[Callable]] = {}
        # internal type -> preferred gvk for encoding
        self._preferred: Dict[type, GroupVersionKind] = {}

    # ------------------------------------------------------------ registry

    def add_known_type(self, gvk: GroupVersionKind, internal_type: type,
                       from_external: Callable[[dict], object],
                       to_external: Callable[[object], dict],
                       preferred: bool = True) -> None:
        """Register one external version of a kind with its conversions
        (AddKnownTypes + AddConversionFunc collapsed: external versions here
        are wire dicts, not Go structs)."""
        self._kinds[gvk] = (internal_type, from_external, to_external)
        if preferred or internal_type not in self._preferred:
            self._preferred[internal_type] = gvk

    def add_defaulter(self, internal_type: type, fn: Callable[[object], None]) -> None:
        """Registered defaulters run on every decode (AddTypeDefaultingFunc)."""
        self._defaulters.setdefault(internal_type, []).append(fn)

    def recognizes(self, gvk: GroupVersionKind) -> bool:
        return gvk in self._kinds

    def registered_kinds(self) -> List[GroupVersionKind]:
        return list(self._kinds)

    # --------------------------------------------------------------- codec

    def default(self, obj: object) -> object:
        for t in type(obj).__mro__:
            for fn in self._defaulters.get(t, ()):
                fn(obj)
        return obj

    def decode(self, data) -> object:
        """Wire (bytes/str/dict with apiVersion+kind) → defaulted internal
        object (the UniversalDecoder path: external → internal → default)."""
        if isinstance(data, (bytes, str)):
            data = json.loads(data)
        if not isinstance(data, dict):
            raise SchemeError(f"cannot decode {type(data).__name__}")
        api_version = data.get("apiVersion", "")
        kind = data.get("kind", "")
        if not kind:
            raise SchemeError("missing kind")
        gvk = GroupVersionKind.from_api_version(api_version, kind)
        reg = self._kinds.get(gvk)
        if reg is None:
            raise SchemeError(f"no kind registered for {gvk}")
        _t, from_external, _to = reg
        obj = from_external(data)
        return self.default(obj)

    def encode(self, obj: object,
               gvk: Optional[GroupVersionKind] = None) -> dict:
        """Internal object → wire dict with apiVersion/kind (versioned
        encode; the preferred external version unless one is named)."""
        if gvk is None:
            gvk = self._preferred.get(type(obj))
            if gvk is None:
                raise SchemeError(f"no version registered for {type(obj).__name__}")
        reg = self._kinds.get(gvk)
        if reg is None:
            raise SchemeError(f"no kind registered for {gvk}")
        internal_type, _from, to_external = reg
        if not isinstance(obj, internal_type):
            raise SchemeError(
                f"{gvk} encodes {internal_type.__name__}, got {type(obj).__name__}")
        out = {"apiVersion": gvk.api_version, "kind": gvk.kind}
        out.update(to_external(obj))
        return out

    def encode_json(self, obj: object,
                    gvk: Optional[GroupVersionKind] = None) -> bytes:
        return json.dumps(self.encode(obj, gvk)).encode()


_scheme: Optional[Scheme] = None


def default_scheme() -> Scheme:
    """The process-global scheme with every in-tree version registered
    (the legacyscheme.Scheme analog)."""
    global _scheme
    if _scheme is None:
        _scheme = Scheme()
        from . import corev1

        corev1.register(_scheme)
    return _scheme
