"""kubectl over HTTP: a ClusterStore-shaped adapter speaking to the REST
apiserver front (apiserver/http.py), so the CLI drives a remote control
plane exactly like the reference kubectl drives kube-apiserver.

    kubectl(RemoteStore("http://127.0.0.1:6443"), ["get", "pods"])
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..api import types as api_types
from ..api.codec import from_wire, to_wire
from ..apiserver.http import RESOURCES
from ..apiserver.store import ClusterStore, Conflict, NotFound

# kind -> (group path, plural)
_PATHS = {kind: (group, plural) for (group, plural), kind in RESOURCES.items()}
# the one scoping truth (silent drift here would mis-route URLs)
_CLUSTER_SCOPED = ClusterStore.CLUSTER_SCOPED_KINDS


class RemoteStore:
    """The subset of the ClusterStore surface kubectl/cli.py touches,
    served over the wire."""

    CLUSTER_SCOPED_KINDS = _CLUSTER_SCOPED

    def __init__(self, server: str):
        self.server = server.rstrip("/")

    # ------------------------------------------------------------- transport

    def _url(self, kind: str, namespace: Optional[str], name: Optional[str]) -> str:
        group, plural = _PATHS[kind]
        parts = [self.server, group]
        if namespace is not None and kind not in _CLUSTER_SCOPED:
            parts += ["namespaces", namespace]
        parts.append(plural)
        if name is not None:
            parts.append(name)
        return "/".join(parts)

    def _req(self, method: str, url: str, body: Optional[dict] = None) -> Tuple[int, dict]:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method,
                                     headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def _split(self, kind: str, key: str) -> Tuple[Optional[str], str]:
        if kind in _CLUSTER_SCOPED or "/" not in key:
            return None, key
        ns, name = key.split("/", 1)
        return ns, name

    def _raise(self, code: int, out: dict) -> None:
        msg = out.get("message", "")
        if code == 404:
            raise NotFound(msg)
        if code == 409:
            raise Conflict(msg)
        raise RuntimeError(f"apiserver {code}: {msg}")

    # ------------------------------------------------------------- verbs

    def list_objects(self, kind: str) -> Tuple[List[object], int]:
        code, out = self._req("GET", self._url(kind, None, None))
        if code != 200:
            self._raise(code, out)
        cls = getattr(api_types, kind)
        objs = [from_wire(cls, item) for item in out["items"]]
        return objs, int(out["metadata"]["resourceVersion"])

    def get_object(self, kind: str, key: str):
        ns, name = self._split(kind, key)
        code, out = self._req("GET", self._url(kind, ns or "default", name))
        if code == 404:
            return None
        if code != 200:
            self._raise(code, out)
        return from_wire(getattr(api_types, kind), out)

    def get_pod(self, key: str):
        return self.get_object("Pod", key)

    def get_node(self, name: str):
        return self.get_object("Node", name)

    def snapshot_map(self, kind: str) -> Dict[str, object]:
        objs, _rv = self.list_objects(kind)
        return {self._key_of(kind, o): o for o in objs}

    class _NodeView:
        """Dict-like node accessor: point lookups are single GETs (cordon /
        delete checks must not LIST-and-decode a 50k-node cluster)."""

        def __init__(self, rs: "RemoteStore"):
            self._rs = rs

        def get(self, name: str, default=None):
            obj = self._rs.get_object("Node", name)
            return obj if obj is not None else default

        def __contains__(self, name: str) -> bool:
            return self.get(name) is not None

        def __getitem__(self, name: str):
            obj = self.get(name)
            if obj is None:
                raise KeyError(name)
            return obj

        def values(self):
            return self._rs.list_objects("Node")[0]

        def __iter__(self):
            return iter(n.meta.name for n in self.values())

        def __len__(self):
            return len(self.values())

    @property
    def nodes(self) -> "_NodeView":
        return RemoteStore._NodeView(self)

    def _key_of(self, kind: str, obj) -> str:
        return obj.meta.name if kind in _CLUSTER_SCOPED else obj.meta.key()

    def create_object(self, kind: str, obj) -> None:
        ns = None if kind in _CLUSTER_SCOPED else obj.meta.namespace
        code, out = self._req("POST", self._url(kind, ns, None), to_wire(obj))
        if code not in (200, 201):
            self._raise(code, out)

    create_pod = lambda self, obj: self.create_object("Pod", obj)  # noqa: E731
    create_node = lambda self, obj: self.create_object("Node", obj)  # noqa: E731

    def update_object(self, kind: str, obj) -> None:
        ns, name = self._split(kind, self._key_of(kind, obj))
        code, out = self._req("PUT", self._url(kind, ns or "default", name), to_wire(obj))
        if code != 200:
            self._raise(code, out)

    update_pod = lambda self, obj: self.update_object("Pod", obj)  # noqa: E731
    update_node = lambda self, obj: self.update_object("Node", obj)  # noqa: E731

    def delete_object(self, kind: str, key: str) -> None:
        ns, name = self._split(kind, key)
        code, out = self._req("DELETE", self._url(kind, ns or "default", name))
        if code not in (200, 404):
            self._raise(code, out)

    delete_pod = lambda self, key: self.delete_object("Pod", key)  # noqa: E731
    delete_node = lambda self, name: self.delete_object("Node", name)  # noqa: E731
