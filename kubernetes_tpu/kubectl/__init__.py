"""CLI (L5): the kubectl command surface over the in-process store
(staging/src/k8s.io/kubectl/pkg/cmd/cmd.go:95 command tree).
"""

from .cli import kubectl

__all__ = ["kubectl"]
