"""kubectl over the ClusterStore (kubectl/pkg/cmd/cmd.go:95,250).

Verbs: get, describe, create -f, apply -f, delete, scale, cordon/uncordon,
taint. Input documents are YAML with the familiar shapes; the translator in
``objects.py`` maps them onto this framework's API dataclasses.

``kubectl(store, argv)`` returns the rendered output string — the CLI main
wraps it with argv/stdout, tests call it directly.
"""

from __future__ import annotations

import shlex
from typing import List, Optional

from ..apiserver.store import ClusterStore, NotFound
from . import objects

GETTABLE = {
    "pods": "Pod", "pod": "Pod", "po": "Pod",
    "nodes": "Node", "node": "Node", "no": "Node",
    "services": "Service", "service": "Service", "svc": "Service",
    "deployments": "Deployment", "deployment": "Deployment", "deploy": "Deployment",
    "replicasets": "ReplicaSet", "replicaset": "ReplicaSet", "rs": "ReplicaSet",
    "statefulsets": "StatefulSet", "statefulset": "StatefulSet", "sts": "StatefulSet",
    "daemonsets": "DaemonSet", "daemonset": "DaemonSet", "ds": "DaemonSet",
    "jobs": "Job", "job": "Job",
    "namespaces": "Namespace", "namespace": "Namespace", "ns": "Namespace",
    "endpoints": "Endpoints", "ep": "Endpoints",
    "persistentvolumes": "PersistentVolume", "pv": "PersistentVolume",
    "persistentvolumeclaims": "PersistentVolumeClaim", "pvc": "PersistentVolumeClaim",
    "storageclasses": "StorageClass", "sc": "StorageClass",
    "leases": "Lease", "lease": "Lease",
    "priorityclasses": "PriorityClass", "pc": "PriorityClass",
    "horizontalpodautoscalers": "HorizontalPodAutoscaler", "hpa": "HorizontalPodAutoscaler",
    "configmaps": "ConfigMap", "configmap": "ConfigMap", "cm": "ConfigMap",
    "secrets": "Secret", "secret": "Secret",
    "certificatesigningrequests": "CertificateSigningRequest",
    "csr": "CertificateSigningRequest",
    "runtimeclasses": "RuntimeClass", "runtimeclass": "RuntimeClass",
    "ingresses": "Ingress", "ingress": "Ingress", "ing": "Ingress",
    "ingressclasses": "IngressClass", "ingressclass": "IngressClass",
    "events": "Event", "event": "Event", "ev": "Event",
    "serviceaccounts": "ServiceAccount", "serviceaccount": "ServiceAccount",
    "sa": "ServiceAccount",
    "poddisruptionbudgets": "PodDisruptionBudget", "pdb": "PodDisruptionBudget",
    "cronjobs": "CronJob", "cronjob": "CronJob", "cj": "CronJob",
    "clusterroles": "ClusterRole", "clusterrolebindings": "ClusterRoleBinding",
    "resourceclasses": "ResourceClass", "resourceclass": "ResourceClass",
    "resourceclaims": "ResourceClaim", "resourceclaim": "ResourceClaim",
    "resourceclaimtemplates": "ResourceClaimTemplate",
    "resourceclaimtemplate": "ResourceClaimTemplate",
    "podschedulingcontexts": "PodSchedulingContext",
    "podschedulingcontext": "PodSchedulingContext",
    "podgroups": "PodGroup", "podgroup": "PodGroup", "pg": "PodGroup",
}


def kubectl(store: ClusterStore, argv) -> str:
    if isinstance(argv, str):
        argv = shlex.split(argv)
    if not argv:
        return _usage()
    verb, *rest = argv
    handlers = {
        "get": _get,
        "describe": _describe,
        "create": _create_or_apply,
        "apply": _create_or_apply,
        "delete": _delete,
        "scale": _scale,
        "cordon": _cordon,
        "uncordon": _uncordon,
        "taint": _taint,
        "label": _label,
        "drain": _drain,
        "top": _top,
        "auth": _auth,
        "rollout": _rollout,
    }
    h = handlers.get(verb)
    if h is None:
        return _usage()
    return h(store, rest, verb=verb)


def _usage() -> str:
    return ("usage: kubectl get|describe|create|apply|delete|scale|"
            "cordon|uncordon|taint|label|drain|top|auth|rollout ...")


def _namespace(args: List[str]) -> str:
    for i, a in enumerate(args):
        if a in ("-n", "--namespace") and i + 1 < len(args):
            return args[i + 1]
        if a.startswith("--namespace=") or a.startswith("-n="):
            return a.split("=", 1)[1]
    return "default"


def _positional(args: List[str]) -> List[str]:
    out = []
    skip = False
    for a in args:
        if skip:
            skip = False
            continue
        if a in ("-n", "--namespace", "-f", "--filename", "--replicas",
                 "-o", "--output", "--as"):
            skip = True
            continue
        if a.startswith("-"):
            continue
        out.append(a)
    return out


def _get(store: ClusterStore, args: List[str], verb="get") -> str:
    pos = _positional(args)
    if not pos:
        return "error: resource type required"
    kind = GETTABLE.get(pos[0])
    if kind is None:
        return f"error: unknown resource type {pos[0]!r}"
    ns = _namespace(args)
    objs, _rv = store.list_objects(kind)
    if kind not in ClusterStore.CLUSTER_SCOPED_KINDS:
        objs = [o for o in objs if o.meta.namespace == ns]
    if len(pos) > 1:
        objs = [o for o in objs if o.meta.name == pos[1]]
        if not objs:
            return f'Error from server (NotFound): {pos[0]} "{pos[1]}" not found'
    output = _flag_value(args, "-o", "--output")
    if output in ("yaml", "json"):
        # versioned encode through the scheme (kubectl get -o yaml parity);
        # kinds without a registered external version use the reflection
        # codec with an explicit kind marker
        import json as _json

        import yaml as _yaml

        from ..api.codec import to_wire
        from ..api.scheme import SchemeError, default_scheme

        scheme = default_scheme()
        docs = []
        for o in sorted(objs, key=lambda o: o.meta.name):
            try:
                docs.append(scheme.encode(o))
            except SchemeError:
                docs.append(dict(to_wire(o), kind=kind))
        if not docs:
            return "No resources found."
        if output == "json":
            payload = docs[0] if len(docs) == 1 else {"kind": "List", "items": docs}
            return _json.dumps(payload, indent=2)
        return _yaml.safe_dump_all(docs, sort_keys=False).rstrip()
    rows = [objects.columns_for(kind, o, store) for o in sorted(objs, key=lambda o: o.meta.name)]
    header = objects.header_for(kind)
    return _tabulate([header] + rows)


def _flag_value(args: List[str], *names) -> Optional[str]:
    for i, a in enumerate(args):
        if a in names and i + 1 < len(args):
            return args[i + 1]
        for n in names:
            if a.startswith(n + "="):
                return a.split("=", 1)[1]
    return None


def _tabulate(rows: List[List[str]]) -> str:
    if len(rows) == 1:
        return "No resources found."
    widths = [max(len(str(r[i])) for r in rows) for i in range(len(rows[0]))]
    return "\n".join(
        "   ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip()
        for r in rows
    )


def _describe(store: ClusterStore, args: List[str], verb="describe") -> str:
    pos = _positional(args)
    if len(pos) < 2:
        return "error: describe needs TYPE NAME"
    kind = GETTABLE.get(pos[0])
    if kind is None:
        return f"error: unknown resource type {pos[0]!r}"
    ns = _namespace(args)
    key = pos[1] if kind in ClusterStore.CLUSTER_SCOPED_KINDS else f"{ns}/{pos[1]}"
    obj = store.get_pod(key) if kind == "Pod" else store.get_object(kind, key)
    if obj is None:
        return f'Error from server (NotFound): {pos[0]} "{pos[1]}" not found'
    return objects.describe(kind, obj, store)


def _create_or_apply(store: ClusterStore, args: List[str], verb="create") -> str:
    filename: Optional[str] = None
    for i, a in enumerate(args):
        if a in ("-f", "--filename") and i + 1 < len(args):
            filename = args[i + 1]
    if filename is None:
        return f"error: {verb} requires -f FILENAME"
    import yaml

    with open(filename) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    out = []
    for doc in docs:
        kind, obj = objects.from_manifest(doc)
        key = store._key_of(kind, obj)
        existing = store.get_pod(key) if kind == "Pod" else store.get_object(kind, key)
        exists = existing is not None
        if exists and verb == "apply":
            obj.meta.resource_version = 0
            if kind == "Pod":
                # server-side-apply-ish: the manifest does not own scheduling
                # state — keep the binding and phase unless it pins a node
                if not obj.spec.node_name:
                    obj.spec.node_name = existing.spec.node_name
                    obj.status = existing.clone().status
                store.update_pod(obj)
            else:
                store.update_object(kind, obj)
            out.append(f"{kind.lower()}/{obj.meta.name} configured")
        elif exists:
            out.append(f'Error from server (AlreadyExists): {kind.lower()} "{obj.meta.name}" already exists')
        else:
            if kind == "Pod":
                store.create_pod(obj)
            elif kind == "Node":
                store.create_node(obj)
            else:
                store.create_object(kind, obj)
            out.append(f"{kind.lower()}/{obj.meta.name} created")
    return "\n".join(out)


def _delete(store: ClusterStore, args: List[str], verb="delete") -> str:
    pos = _positional(args)
    if len(pos) < 2:
        return "error: delete needs TYPE NAME"
    kind = GETTABLE.get(pos[0])
    if kind is None:
        return f"error: unknown resource type {pos[0]!r}"
    ns = _namespace(args)
    key = pos[1] if kind in ClusterStore.CLUSTER_SCOPED_KINDS else f"{ns}/{pos[1]}"
    if kind == "Pod":
        if store.get_pod(key) is None:
            return f'Error from server (NotFound): pods "{pos[1]}" not found'
        store.delete_pod(key)
    elif kind == "Node":
        if key not in store.nodes:
            return f'Error from server (NotFound): nodes "{pos[1]}" not found'
        store.delete_node(key)
    else:
        if store.get_object(kind, key) is None:
            return f'Error from server (NotFound): {pos[0]} "{pos[1]}" not found'
        store.delete_object(kind, key)
    return f'{kind.lower()} "{pos[1]}" deleted'


def _scale(store: ClusterStore, args: List[str], verb="scale") -> str:
    import dataclasses

    replicas: Optional[int] = None
    for i, a in enumerate(args):
        if a == "--replicas" and i + 1 < len(args):
            replicas = int(args[i + 1])
        elif a.startswith("--replicas="):
            replicas = int(a.split("=", 1)[1])
    pos = _positional(args)
    if replicas is None or len(pos) < 2:
        return "error: scale needs TYPE NAME --replicas=N"
    kind = GETTABLE.get(pos[0])
    if kind not in ("Deployment", "ReplicaSet", "StatefulSet"):
        return f"error: cannot scale {pos[0]}"
    key = f"{_namespace(args)}/{pos[1]}"
    obj = store.get_object(kind, key)
    if obj is None:
        return f'Error from server (NotFound): {pos[0]} "{pos[1]}" not found'
    new = dataclasses.replace(obj, replicas=replicas)
    new.meta = dataclasses.replace(obj.meta)
    store.update_object(kind, new)
    return f"{kind.lower()}/{pos[1]} scaled"


def _set_unschedulable(store: ClusterStore, args: List[str], value: bool, verb: str) -> str:
    import dataclasses

    pos = _positional(args)
    if not pos:
        return f"error: {verb} needs NODE"
    node = store.nodes.get(pos[0])
    if node is None:
        return f'Error from server (NotFound): nodes "{pos[0]}" not found'
    new = dataclasses.replace(node)
    new.meta = dataclasses.replace(node.meta)
    new.spec = dataclasses.replace(node.spec, unschedulable=value)
    store.update_node(new)
    word = "cordoned" if value else "uncordoned"
    return f"node/{pos[0]} {word}"


def _cordon(store, args, verb="cordon"):
    return _set_unschedulable(store, args, True, verb)


def _uncordon(store, args, verb="uncordon"):
    return _set_unschedulable(store, args, False, verb)


def _taint(store, args, verb="taint"):
    """kubectl taint nodes NODE key=value:Effect | key:Effect- (remove)."""
    import dataclasses

    from ..api.types import Taint

    pos = _positional(args)
    if len(pos) < 3 or pos[0] not in ("node", "nodes"):
        return "error: taint nodes NODE KEY=VAL:EFFECT[-]"
    node = store.nodes.get(pos[1])
    if node is None:
        return f'Error from server (NotFound): nodes "{pos[1]}" not found'
    spec = pos[2]
    remove = spec.endswith("-")
    spec = spec.rstrip("-")
    kv, _, effect = spec.partition(":")
    key, _, value = kv.partition("=")
    taints = [t for t in node.spec.taints if t.key != key]
    if not remove:
        if not effect:
            return "error: taint effect required (NoSchedule|PreferNoSchedule|NoExecute)"
        taints.append(Taint(key=key, value=value, effect=effect))
    new = dataclasses.replace(node)
    new.meta = dataclasses.replace(node.meta)
    new.spec = dataclasses.replace(node.spec, taints=tuple(taints))
    store.update_node(new)
    return f"node/{pos[1]} {'untainted' if remove else 'tainted'}"


def _label(store, args, verb="label"):
    """kubectl label TYPE NAME key=value | key- (remove)."""
    import dataclasses

    pos = _positional(args)
    if len(pos) < 3:
        return "error: label TYPE NAME KEY=VAL[-]"
    kind = GETTABLE.get(pos[0]) or GETTABLE.get(pos[0] + "s")
    if kind is None:
        return f"error: unknown resource type {pos[0]!r}"
    ns = _namespace(args)
    key_ = pos[1] if kind in ClusterStore.CLUSTER_SCOPED_KINDS else f"{ns}/{pos[1]}"
    obj = store.get_pod(key_) if kind == "Pod" else store.get_object(kind, key_)
    if obj is None:
        return f'Error from server (NotFound): {pos[0]} "{pos[1]}" not found'
    labels = dict(obj.meta.labels)
    for spec in pos[2:]:
        if spec.endswith("-"):
            labels.pop(spec[:-1], None)
        else:
            k, _, v = spec.partition("=")
            labels[k] = v
    new = dataclasses.replace(obj)
    new.meta = dataclasses.replace(obj.meta, labels=labels)
    if kind == "Pod":
        store.update_pod(new)
    elif kind == "Node":
        store.update_node(new)
    else:
        store.update_object(kind, new)
    return f"{pos[0]}/{pos[1]} labeled"


def _drain(store, args, verb="drain"):
    """kubectl drain NODE: cordon + evict every pod bound to it (the
    capability-level drain: no grace periods; PDBs are the disruption
    controller's concern)."""
    pos = _positional(args)
    if not pos:
        return "error: drain needs NODE"
    out = _cordon(store, [pos[0]])
    if out.startswith("Error"):
        return out
    evicted = []
    for pod in list(store.snapshot_map("Pod").values()):
        if pod.spec.node_name == pos[0]:
            store.delete_pod(pod.meta.key())
            evicted.append(pod.meta.name)
    return f"node/{pos[0]} drained ({len(evicted)} pods evicted)"


def _top(store, args, verb="top"):
    """kubectl top pods|nodes: usage from the metrics seam
    (store.pod_metrics; the metrics-server stand-in)."""
    pos = _positional(args)
    if not pos or pos[0] not in ("pods", "pod", "po", "nodes", "node", "no"):
        return "error: top needs pods|nodes"
    ns = _namespace(args)
    if pos[0] in ("pods", "pod", "po"):
        rows = [["NAME", "CPU(cores)"]]
        for key, milli in sorted(store.pod_metrics.items()):
            pod = store.get_pod(key)
            if pod is None or pod.meta.namespace != ns:
                continue
            rows.append([pod.meta.name, f"{milli}m"])
        return _tabulate(rows)
    # nodes: aggregate bound pods' usage per node
    per_node = {}
    for key, milli in store.pod_metrics.items():
        pod = store.get_pod(key)
        if pod is not None and pod.spec.node_name:
            per_node[pod.spec.node_name] = per_node.get(pod.spec.node_name, 0) + milli
    rows = [["NAME", "CPU(cores)", "CPU%"]]
    for name in sorted(store.nodes):
        node = store.nodes[name]
        used = per_node.get(name, 0)
        cap = node.allocatable_canonical().get("cpu", 0)
        pct = f"{100 * used // cap}%" if cap else "<unknown>"
        rows.append([name, f"{used}m", pct])
    return _tabulate(rows)


def _auth(store, args, verb="auth"):
    """kubectl auth can-i VERB RESOURCE [--as USER]: answers from the
    store's RBAC authorizer (apiserver/auth.py)."""
    pos = _positional(args)
    if len(pos) < 3 or pos[0] != "can-i":
        return "error: auth can-i VERB RESOURCE"
    as_user = _flag_value(args, "--as")
    if as_user:
        user, groups = as_user, ()
    else:
        user = store.request_user()
        groups = store.request_groups() or (
            ("system:masters",) if user == "system:admin" else ())
    kind = GETTABLE.get(pos[2], pos[2])
    authorizer = store.authorizer
    if authorizer is None:
        return "yes (no authorizer configured)"
    check = getattr(authorizer, "allowed_for", None)
    if check is not None:
        ok = check(user, groups, pos[1], kind)
    else:
        ok = authorizer.allowed(user, pos[1], kind)
    return "yes" if ok else "no"


def _rollout(store, args, verb="rollout"):
    """kubectl rollout status|history deployment NAME (the revision-tracked
    ReplicaSets the deployment controller maintains)."""
    pos = _positional(args)
    if len(pos) < 3 or pos[0] not in ("status", "history"):
        return "error: rollout status|history deployment NAME"
    if GETTABLE.get(pos[1]) != "Deployment":
        return "error: rollout supports deployments"
    ns = _namespace(args)
    dep = store.get_object("Deployment", f"{ns}/{pos[2]}")
    if dep is None:
        return f'Error from server (NotFound): deployment "{pos[2]}" not found'
    revisions = []
    for rs in store.snapshot_map("ReplicaSet").values():
        ref = rs.meta.controller_of()
        if (rs.meta.namespace == ns and ref is not None
                and ref.kind == "Deployment" and ref.name == pos[2]):
            rev = rs.meta.annotations.get("deployment.kubernetes.io/revision", "?")
            revisions.append((rev, rs))
    revisions.sort(key=lambda t: int(t[0]) if str(t[0]).isdigit() else -1)
    if pos[0] == "history":
        rows = [["REVISION", "REPLICASET", "REPLICAS"]]
        for rev, rs in revisions:
            rows.append([str(rev), rs.meta.name, str(rs.replicas)])
        return _tabulate(rows)
    # status: ready when the NEWEST revision's live pods cover spec.replicas
    # (a mid-rollout deployment with old-revision pods is still waiting)
    newest = revisions[-1][1].meta.name if revisions else None
    ready = 0
    for p in store.snapshot_map("Pod").values():
        if p.meta.namespace != ns or p.status.phase not in ("Pending", "Running"):
            continue
        ref = p.meta.controller_of()
        if (ref is not None and ref.kind == "ReplicaSet" and ref.name == newest
                and p.spec.node_name):
            ready += 1
    if ready >= dep.replicas:
        return f'deployment "{pos[2]}" successfully rolled out'
    return (f'Waiting for deployment "{pos[2]}" rollout to finish: '
            f'{ready} of {dep.replicas} updated replicas are available...')
