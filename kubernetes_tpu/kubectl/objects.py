"""Manifest translation + printers for kubectl (the scheme/codec +
cli-runtime printers role).

``from_manifest`` accepts the familiar YAML shapes (apiVersion/kind/metadata/
spec) and produces this framework's dataclasses; printers render the standard
get columns and describe blocks.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..api.resource import parse_quantity
from ..api.types import (
    DaemonSet,
    Deployment,
    Job,
    LabelSelector,
    Requirement,
    Namespace,
    Node,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PriorityClass,
    ReplicaSet,
    Service,
    StatefulSet,
    StorageClass,
    Toleration,
)
from ..api.wrappers import make_node, make_pod


def _meta(doc: dict) -> ObjectMeta:
    md = doc.get("metadata", {}) or {}
    return ObjectMeta(
        name=md.get("name", ""),
        namespace=md.get("namespace", "default"),
        labels=dict(md.get("labels", {}) or {}),
        annotations=dict(md.get("annotations", {}) or {}),
    )


def _pod_from_spec(name: str, namespace: str, md: dict, spec: dict) -> Pod:
    pw = make_pod(name, namespace)
    for k, v in (md.get("labels") or {}).items():
        pw.label(k, v)
    for c in spec.get("containers", []) or []:
        requests = ((c.get("resources") or {}).get("requests")) or {}
        pw.container(c.get("image", ""), requests=requests or None)
    if spec.get("nodeName"):
        pw.node(spec["nodeName"])
    if spec.get("priority") is not None:
        pw.priority(int(spec["priority"]))
    if spec.get("schedulerName"):
        pw.scheduler_name(spec["schedulerName"])
    if spec.get("nodeSelector"):
        pw.node_selector(dict(spec["nodeSelector"]))
    pod = pw.obj()
    pod.meta.annotations = dict(md.get("annotations", {}) or {})
    tolerations = []
    for t in spec.get("tolerations", []) or []:
        tolerations.append(Toleration(
            key=t.get("key", ""), operator=t.get("operator", "Equal"),
            value=t.get("value", ""), effect=t.get("effect", ""),
        ))
    if tolerations:
        pod.spec.tolerations = tuple(tolerations)
    return pod


def _selector(doc: dict) -> LabelSelector:
    sel = doc.get("selector") or {}
    if "matchLabels" in sel or "matchExpressions" in sel:
        exprs = tuple(
            Requirement(
                key=e.get("key", ""),
                operator=e.get("operator", "In"),
                values=tuple(e.get("values", []) or ()),
            )
            for e in (sel.get("matchExpressions") or [])
        )
        return LabelSelector(
            match_labels=dict(sel.get("matchLabels", {}) or {}),
            match_expressions=exprs,
        )
    return LabelSelector(match_labels=dict(sel))


def _template(doc: dict, meta: ObjectMeta) -> Pod:
    tpl = doc.get("template", {}) or {}
    return _pod_from_spec(
        "template", meta.namespace, tpl.get("metadata", {}) or {}, tpl.get("spec", {}) or {}
    )


def from_manifest(doc: dict) -> Tuple[str, object]:
    kind = doc.get("kind", "")
    meta = _meta(doc)
    spec = doc.get("spec", {}) or {}
    if kind == "Pod":
        # full-fidelity core/v1 decode (affinity, spread, security context,
        # ephemeral volumes) through the scheme (api/scheme.py)
        from ..api import corev1
        from ..api.scheme import default_scheme

        return kind, default_scheme().default(corev1.pod_from(doc))
    if kind == "Node":
        nw = make_node(meta.name)
        for k, v in meta.labels.items():
            nw.label(k, v)
        cap = (doc.get("status", {}) or {}).get("capacity") or spec.get("capacity") or {}
        if cap:
            nw.capacity(dict(cap))
        if spec.get("unschedulable"):
            nw.unschedulable()
        for t in spec.get("taints", []) or []:
            nw.taint(t.get("key", ""), t.get("value", ""), t.get("effect", "NoSchedule"))
        return kind, nw.obj()
    if kind == "Service":
        return kind, Service(meta=meta, selector=dict(spec.get("selector", {}) or {}))
    if kind == "Deployment":
        strategy = spec.get("strategy", {}) or {}
        rolling = strategy.get("rollingUpdate", {}) or {}
        return kind, Deployment(meta=meta, selector=_selector(spec),
                                replicas=int(spec.get("replicas", 1)),
                                template=_template(spec, meta),
                                strategy=strategy.get("type", "RollingUpdate"),
                                max_surge=int(rolling.get("maxSurge", 1)),
                                max_unavailable=int(rolling.get("maxUnavailable", 1)))
    if kind == "ReplicaSet":
        return kind, ReplicaSet(meta=meta, selector=_selector(spec),
                                replicas=int(spec.get("replicas", 1)),
                                template=_template(spec, meta))
    if kind == "StatefulSet":
        return kind, StatefulSet(meta=meta, selector=_selector(spec),
                                 replicas=int(spec.get("replicas", 1)),
                                 template=_template(spec, meta))
    if kind == "DaemonSet":
        return kind, DaemonSet(meta=meta, selector=_selector(spec),
                               template=_template(spec, meta))
    if kind == "Job":
        return kind, Job(meta=meta, completions=int(spec.get("completions", 1)),
                         parallelism=int(spec.get("parallelism", 1)),
                         template=_template(spec, meta))
    if kind == "Namespace":
        return kind, Namespace(meta=meta)
    if kind == "PriorityClass":
        return kind, PriorityClass(meta=meta, value=int(doc.get("value", 0)))
    if kind == "StorageClass":
        return kind, StorageClass(
            meta=meta, provisioner=doc.get("provisioner", ""),
            volume_binding_mode=doc.get("volumeBindingMode", "Immediate"))
    if kind == "PersistentVolume":
        cap = (spec.get("capacity") or {}).get("storage", 0)
        return kind, PersistentVolume(
            meta=meta, capacity_bytes=int(parse_quantity(cap)),
            storage_class=spec.get("storageClassName", ""))
    if kind == "PersistentVolumeClaim":
        req = (((spec.get("resources") or {}).get("requests")) or {}).get("storage", 0)
        return kind, PersistentVolumeClaim(
            meta=meta, storage_class=spec.get("storageClassName", ""),
            requested_bytes=int(parse_quantity(req)))
    raise ValueError(f"unsupported manifest kind {kind!r}")


# ---------------------------------------------------------------------------
# printers

HEADERS: Dict[str, List[str]] = {
    "Pod": ["NAME", "STATUS", "NODE"],
    "Node": ["NAME", "STATUS", "TAINTS"],
    "Service": ["NAME", "SELECTOR"],
    "Deployment": ["NAME", "REPLICAS"],
    "ReplicaSet": ["NAME", "REPLICAS"],
    "StatefulSet": ["NAME", "REPLICAS"],
    "DaemonSet": ["NAME"],
    "Job": ["NAME", "COMPLETIONS"],
    "Namespace": ["NAME", "STATUS"],
    "Endpoints": ["NAME", "ENDPOINTS"],
    "PersistentVolume": ["NAME", "CLAIM", "STORAGECLASS"],
    "PersistentVolumeClaim": ["NAME", "VOLUME", "STORAGECLASS"],
    "StorageClass": ["NAME", "BINDINGMODE"],
    "Lease": ["NAME", "HOLDER"],
    "PriorityClass": ["NAME", "VALUE"],
}


def header_for(kind: str) -> List[str]:
    if kind == "Event":
        return ["LAST SEEN", "TYPE", "REASON", "OBJECT", "MESSAGE"]
    return HEADERS.get(kind, ["NAME"])


def columns_for(kind: str, obj, store) -> List[str]:
    if kind == "Event":
        import time as _t

        age = max(0, int(_t.time() - (obj.last_timestamp or 0)))
        last = f"{age}s" if obj.last_timestamp else "<unknown>"
        msg = obj.message if obj.count <= 1 else f"{obj.message} (x{obj.count})"
        return [last, obj.type, obj.reason, obj.involved_object, msg]
    if kind == "Pod":
        return [obj.meta.name, obj.status.phase, obj.spec.node_name or "<none>"]
    if kind == "Node":
        status = "Ready" if obj.status.ready else "NotReady"
        if obj.spec.unschedulable:
            status += ",SchedulingDisabled"
        taints = ",".join(f"{t.key}:{t.effect}" for t in obj.spec.taints) or "<none>"
        return [obj.meta.name, status, taints]
    if kind == "Service":
        sel = ",".join(f"{k}={v}" for k, v in sorted(obj.selector.items())) or "<none>"
        return [obj.meta.name, sel]
    if kind in ("Deployment", "ReplicaSet", "StatefulSet"):
        return [obj.meta.name, str(obj.replicas)]
    if kind == "Job":
        return [obj.meta.name, f"{obj.succeeded}/{obj.completions}"]
    if kind == "Namespace":
        return [obj.meta.name, "Terminating" if obj.meta.deletion_timestamp else "Active"]
    if kind == "Endpoints":
        return [obj.meta.name, ",".join(a.pod_key for a in obj.addresses) or "<none>"]
    if kind == "PersistentVolume":
        return [obj.meta.name, obj.bound_pvc or "<none>", obj.storage_class]
    if kind == "PersistentVolumeClaim":
        return [obj.meta.name, obj.bound_pv or "<none>", obj.storage_class]
    if kind == "StorageClass":
        return [obj.meta.name, obj.volume_binding_mode]
    if kind == "Lease":
        return [obj.meta.name, obj.holder_identity]
    if kind == "PriorityClass":
        return [obj.meta.name, str(obj.value)]
    return [obj.meta.name]


def describe(kind: str, obj, store) -> str:
    lines = [f"Name:         {obj.meta.name}"]
    if kind not in ("Node", "Namespace", "PersistentVolume", "StorageClass", "PriorityClass"):
        lines.append(f"Namespace:    {obj.meta.namespace}")
    if obj.meta.labels:
        lines.append("Labels:       " + ",".join(f"{k}={v}" for k, v in sorted(obj.meta.labels.items())))
    if kind == "Pod":
        lines.append(f"Status:       {obj.status.phase}")
        lines.append(f"Node:         {obj.spec.node_name or '<none>'}")
        if obj.status.nominated_node_name:
            lines.append(f"NominatedNodeName: {obj.status.nominated_node_name}")
        req = obj.spec.requests if hasattr(obj.spec, "requests") else {}
        if req:
            lines.append(f"Requests:     {req}")
    elif kind == "Node":
        lines.append(f"Unschedulable: {obj.spec.unschedulable}")
        lines.append(f"Ready:        {obj.status.ready}")
        for t in obj.spec.taints:
            lines.append(f"Taint:        {t.key}={t.value}:{t.effect}")
        lines.append(f"Capacity:     {obj.status.capacity}")
        pods = [p for p in store.snapshot_map("Pod").values()
                if p.spec.node_name == obj.meta.name]
        lines.append(f"Pods:         {len(pods)}")
    elif kind in ("Deployment", "ReplicaSet", "StatefulSet"):
        lines.append(f"Replicas:     {obj.replicas}")
    elif kind == "Job":
        lines.append(f"Completions:  {obj.succeeded}/{obj.completions}")
    return "\n".join(lines)
