"""Zone tree: zone → node names, with round-robin zone interleaving
(internal/cache/node_tree.go:32 nodeTree).

The snapshot's flat node list is materialized in this order so that
scheduling (and its sampled early-exit window) naturally spreads pods
across zones rather than filling one zone's nodes first.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

from ..api.types import Node, get_zone_key


class NodeTree:
    """Maintains per-zone node-name lists (node_tree.go:32); ``list()``
    yields names zone-round-robin (node_tree.go updateNodesInTreeOrder)."""

    def __init__(self):
        self._zones: Dict[str, List[str]] = {}
        self._members: set = set()  # O(1) membership; lists keep zone order
        self.num_nodes = 0

    def add_node(self, node: Node) -> None:
        if node.meta.name in self._members:
            return
        zone = get_zone_key(node)
        self._zones.setdefault(zone, []).append(node.meta.name)
        self._members.add(node.meta.name)
        self.num_nodes += 1

    def remove_node(self, node: Node) -> None:
        if node.meta.name not in self._members:
            return
        zone = get_zone_key(node)
        names = self._zones.get(zone)
        if names is None or node.meta.name not in names:
            return
        names.remove(node.meta.name)
        self._members.discard(node.meta.name)
        if not names:
            del self._zones[zone]
        self.num_nodes -= 1

    def update_node(self, old: Node, new: Node) -> None:
        if get_zone_key(old) == get_zone_key(new):
            return
        self.remove_node(old)
        self.add_node(new)

    def list(self) -> List[str]:
        """All node names, one per zone per round (node_tree.go list order)."""
        out: List[str] = []
        lists = list(self._zones.values())
        i = 0
        while len(out) < self.num_nodes:
            for names in lists:
                if i < len(names):
                    out.append(names[i])
            i += 1
        return out


def zone_interleaved(node_infos: Iterable) -> List:
    """Order NodeInfos zone-round-robin — used by Snapshot.refresh_lists
    (same visit order as nodeTree.list(), via a throwaway tree)."""
    by_name = {}
    tree = NodeTree()
    for ni in node_infos:
        by_name[ni.node.meta.name] = ni
        tree.add_node(ni.node)
    return [by_name[name] for name in tree.list()]
