"""Cache debugger: drift comparer + dumper, trigger on SIGUSR2
(internal/cache/debugger/{debugger,comparer,dumper}.go).

The comparer diffs the scheduler's cache and queue against the store's truth
(CompareNodes/ComparePods, comparer.go); the dumper renders the cache and
waiting pods to the log (dumper.go). Both run on demand or on SIGUSR2
(debugger.go:67 ListenForSignal).
"""

from __future__ import annotations

import logging
import signal
from typing import List, Tuple

logger = logging.getLogger(__name__)


class CacheComparer:
    """cache/queue vs apiserver-truth drift detector (comparer.go:34)."""

    def __init__(self, store, cache, queue):
        self.store = store
        self.cache = cache
        self.queue = queue

    def compare_nodes(self) -> Tuple[List[str], List[str]]:
        """(missed, redundant): nodes in truth but not cache, and vice versa
        (comparer.go CompareNodes)."""
        actual = {n for n in self.store.nodes}
        cached = {n for n, ni in self.cache.nodes.items() if ni.node is not None}
        return sorted(actual - cached), sorted(cached - actual)

    def compare_pods(self) -> Tuple[List[str], List[str]]:
        """(missed, redundant) over scheduled pods in cache + pending pods in
        queue vs the store's pods (comparer.go ComparePods)."""
        actual = set(self.store.pods.keys())
        cached = set()
        for ni in self.cache.nodes.values():
            for p in ni.pods:
                cached.add(p.meta.key())
        queued = {qp.pod.meta.key() for qp in self.queue.pending_pod_infos()}
        known = cached | queued
        return sorted(actual - known), sorted(cached - actual)

    def compare(self) -> bool:
        """Log discrepancies; True when in sync (debugger.go Comparer.Compare)."""
        missed_n, redundant_n = self.compare_nodes()
        missed_p, redundant_p = self.compare_pods()
        ok = not (missed_n or redundant_n or missed_p or redundant_p)
        if not ok:
            logger.warning(
                "cache mismatch: nodes missed=%s redundant=%s; pods missed=%s redundant=%s",
                missed_n, redundant_n, missed_p, redundant_p,
            )
        else:
            logger.info("cache comparison: in sync")
        return ok


class CacheDumper:
    """Render cache + queue state for debugging (dumper.go:37 DumpAll)."""

    def __init__(self, cache, queue):
        self.cache = cache
        self.queue = queue

    def dump_all(self) -> str:
        lines = ["Dump of cached NodeInfo"]
        for name, ni in sorted(self.cache.nodes.items()):
            lines.append(
                f"Node: {name}, deleted: {ni.node is None}, pods: {len(ni.pods)}, "
                f"requested: cpu={ni.requested.milli_cpu}m mem={ni.requested.memory}, "
                f"allocatable: cpu={ni.allocatable.milli_cpu}m mem={ni.allocatable.memory}"
            )
        lines.append("Dump of scheduling queue")
        for qp in self.queue.pending_pod_infos():
            lines.append(
                f"Pod: {qp.pod.meta.key()}, attempts: {qp.attempts}, "
                f"unschedulable plugins: {sorted(qp.unschedulable_plugins)}"
            )
        text = "\n".join(lines)
        logger.info("%s", text)
        return text


class CacheDebugger:
    """Comparer + dumper behind one signal hook (debugger.go:35)."""

    def __init__(self, store, cache, queue):
        self.comparer = CacheComparer(store, cache, queue)
        self.dumper = CacheDumper(cache, queue)

    def listen_for_signal(self, signum: int = signal.SIGUSR2) -> None:
        """Install the SIGUSR2 handler (debugger.go:67); main thread only."""

        def _handle(_sig, _frame):
            self.comparer.compare()
            self.dumper.dump_all()

        signal.signal(signum, _handle)
