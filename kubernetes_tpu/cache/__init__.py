from .cache import Cache  # noqa: F401
from .snapshot import Snapshot  # noqa: F401
