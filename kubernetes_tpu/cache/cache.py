"""Scheduler cache: assume/confirm/expire pod state machine + incremental
snapshot (internal/cache/cache.go).

State machine (interface.go:32-56 diagram):

    AssumePod → (FinishBinding → expire-after-TTL | AddPod confirms |
                 ForgetPod removes)

Assumed pods are counted in their node's NodeInfo immediately so the next
cycle sees them (the optimistic-commit that lets scheduling run ahead of
binding, schedule_one.go:734 assume).  ``cleanup(now)`` sweeps expired
assumptions (cache.go:731 run/cleanupAssumedPods — here called by the
scheduler loop instead of a background goroutine).

Snapshot updates are O(changed nodes): every NodeInfo mutation bumps its
monotonic generation; ``update_snapshot`` re-clones only nodes whose
generation is newer than the snapshot's (cache.go:198 UpdateSnapshot).  The
same generation stream drives the TPU backend's delta uploads.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api.types import Node, Pod
from ..framework.types import NodeInfo, next_generation
from ..testing import locktrace
from .snapshot import Snapshot

DEFAULT_ASSUME_TTL = 30.0  # durationToExpireAssumedPod (scheduler.go:311)


@dataclass
class _PodState:
    pod: Pod
    assumed: bool = False
    binding_finished: bool = False
    deadline: Optional[float] = None


class Cache:
    def __init__(self, ttl: float = DEFAULT_ASSUME_TTL, now_fn=time.monotonic):
        self._lock = locktrace.make_rlock("Cache")
        self.ttl = ttl
        self.now_fn = now_fn
        self.nodes: Dict[str, NodeInfo] = {}
        self.pod_states: Dict[str, _PodState] = {}  # pod key -> state
        self._assumed: set = set()                  # keys with assumed=True
        # dirty-tracking so update_snapshot is O(changes), like the reference's
        # generation-ordered node list (cache.go headNode)
        self._dirty: set = set()
        self._removed: set = set()
        self._sync_generation = 0
        # priority histogram over pods assigned to nodes: lets the batched
        # preemption path prove "no evictable victim exists anywhere" in
        # O(1) instead of dry-running candidates (preemption.go:319's
        # eligibility is per-pod; this is the cluster-level shortcut)
        self._prio_counts: Dict[int, int] = {}
        # entries with a REAL Node object (ghost NodeInfos excluded):
        # node_count() sits on the per-batch hot path and a full scan of
        # self.nodes was a measured ~5ms/call at 5k nodes
        self._real_nodes = 0

    # ------------------------------------------------------------- pods

    # locked cores — ONE implementation each, shared by the per-pod verbs
    # and apply_batch so the two paths can never drift

    def _assume_locked(self, pod: Pod, node_name: str) -> None:
        key = pod.key()
        if key in self.pod_states:
            raise KeyError(f"pod {key} already in cache")
        pod.spec.node_name = node_name
        self._add_pod_to_node(pod, node_name)
        self.pod_states[key] = _PodState(pod=pod, assumed=True)
        self._assumed.add(key)

    def _finish_locked(self, pod: Pod) -> None:
        ps = self.pod_states.get(pod.key())
        if ps and ps.assumed:
            ps.binding_finished = True
            ps.deadline = self.now_fn() + self.ttl

    def _forget_locked(self, pod: Pod) -> None:
        ps = self.pod_states.pop(pod.key(), None)
        self._assumed.discard(pod.key())
        if ps is not None:
            self._remove_pod_from_node(ps.pod, ps.pod.spec.node_name)

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        """Optimistically commit ``pod`` to ``node_name``. Takes ownership of
        the passed object (callers pass a clone; its spec.node_name is set
        here so Reserve/Permit/Bind plugins see the assignment, matching the
        reference's assumedPod)."""
        with self._lock:
            self._assume_locked(pod, node_name)

    def finish_binding(self, pod: Pod) -> None:
        with self._lock:
            self._finish_locked(pod)

    def forget_pod(self, pod: Pod) -> None:
        """Binding failed: roll the assumption back (cache.go:416)."""
        with self._lock:
            self._forget_locked(pod)

    def add_pod(self, pod: Pod) -> None:
        """Informer confirmation of a bound pod (cache.go:497)."""
        key = pod.key()
        with self._lock:
            ps = self.pod_states.get(key)
            if ps is not None and ps.assumed:
                if ps.pod.spec.node_name != pod.spec.node_name:
                    # scheduled elsewhere than assumed: relocate
                    self._remove_pod_from_node(ps.pod, ps.pod.spec.node_name)
                    self._add_pod_to_node(pod, pod.spec.node_name)
                self.pod_states[key] = _PodState(pod=pod)
                self._assumed.discard(key)
                return
            if ps is not None:
                return  # duplicate add
            self._add_pod_to_node(pod, pod.spec.node_name)
            self.pod_states[key] = _PodState(pod=pod)

    def update_pod(self, old: Pod, new: Pod) -> None:
        with self._lock:
            ps = self.pod_states.get(old.key())
            if ps is None:
                self.add_pod(new)
                return
            self._remove_pod_from_node(ps.pod, ps.pod.spec.node_name)
            self._add_pod_to_node(new, new.spec.node_name)
            self.pod_states[old.key()] = _PodState(pod=new)

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            ps = self.pod_states.pop(pod.key(), None)
            self._assumed.discard(pod.key())
            if ps is not None:
                self._remove_pod_from_node(ps.pod, ps.pod.spec.node_name)

    def apply_batch(self, ops) -> List[Optional[Exception]]:
        """Batched pod-state transitions — the cache half of the commit data
        plane: one lock round trip applies a whole scheduler batch's worth
        of assume/finish/forget transitions (per-pod calls were 2+ lock
        acquisitions per committed pod on the measured host.commit
        bottleneck). ``ops`` is a sequence of tuples:

            ("assume", pod, node_name)  — assume_pod semantics
            ("finish", pod)             — finish_binding semantics
            ("forget", pod)             — forget_pod semantics

        Each op applies independently; a failing op (assume of an already-
        cached key) records its exception and later ops still apply. Returns
        per-op None-or-exception in input order — callers decide per pod,
        exactly as with the per-pod calls."""
        out: List[Optional[Exception]] = [None] * len(ops)
        with self._lock:
            for i, op in enumerate(ops):
                verb = op[0]
                if verb == "assume":
                    try:
                        self._assume_locked(op[1], op[2])
                    except KeyError as err:
                        out[i] = err
                elif verb == "finish":
                    self._finish_locked(op[1])
                elif verb == "forget":
                    self._forget_locked(op[1])
                else:
                    out[i] = ValueError(f"unknown cache batch op {verb!r}")
        return out

    def is_assumed(self, pod_key: str) -> bool:
        with self._lock:
            ps = self.pod_states.get(pod_key)
            return bool(ps and ps.assumed)

    def cleanup(self, now: Optional[float] = None) -> List[Pod]:
        """Expire assumed-but-never-confirmed pods; returns the expired pods
        (cleanupAssumedPods, cache.go:735)."""
        now = self.now_fn() if now is None else now
        expired = []
        with self._lock:
            for key in list(self._assumed):
                ps = self.pod_states.get(key)
                if ps and ps.binding_finished and ps.deadline is not None and now > ps.deadline:
                    expired.append(ps.pod)
                    self.pod_states.pop(key)
                    self._assumed.discard(key)
                    self._remove_pod_from_node(ps.pod, ps.pod.spec.node_name)
        return expired

    # ------------------------------------------------------------- nodes

    def add_node(self, node: Node) -> None:
        with self._lock:
            ni = self.nodes.get(node.meta.name)
            if ni is None:
                ni = NodeInfo()
                self.nodes[node.meta.name] = ni
            if ni.node is None:
                self._real_nodes += 1
            ni.set_node(node)
            self._dirty.add(node.meta.name)
            self._removed.discard(node.meta.name)

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def remove_node(self, node_name: str) -> None:
        with self._lock:
            ni = self.nodes.get(node_name)
            if ni is None:
                return
            # keep the entry while pods remain (reference keeps ghost nodes
            # for pods not yet deleted), else drop
            if ni.node is not None:
                self._real_nodes -= 1
            ni.node = None
            ni.generation = next_generation()
            self._dirty.add(node_name)
            if not ni.pods:
                del self.nodes[node_name]
                self._dirty.discard(node_name)
                self._removed.add(node_name)

    def _node_info(self, node_name: str) -> NodeInfo:  # ktpu: locked
        ni = self.nodes.get(node_name)
        if ni is None:
            ni = NodeInfo()  # pod arrived before its node: ghost entry
            self.nodes[node_name] = ni
        return ni

    def _add_pod_to_node(self, pod: Pod, node_name: str) -> None:  # ktpu: locked
        if node_name:
            self._node_info(node_name).add_pod(pod)
            self._dirty.add(node_name)
            self._removed.discard(node_name)
            prio = pod.spec.priority
            self._prio_counts[prio] = self._prio_counts.get(prio, 0) + 1

    def _remove_pod_from_node(self, pod: Pod, node_name: str) -> None:  # ktpu: locked
        ni = self.nodes.get(node_name)
        if ni is not None:
            ni.remove_pod(pod)
            prio = pod.spec.priority
            left = self._prio_counts.get(prio, 0) - 1
            if left > 0:
                self._prio_counts[prio] = left
            else:
                self._prio_counts.pop(prio, None)
            self._dirty.add(node_name)
            if ni.node is None and not ni.pods:
                self.nodes.pop(node_name, None)
                self._dirty.discard(node_name)
                self._removed.add(node_name)

    # ------------------------------------------------------------- snapshot

    def update_snapshot(self, snapshot: Snapshot) -> Snapshot:
        """Incremental: re-clone only NodeInfos dirtied since the snapshot's
        generation; O(changes) not O(nodes) (cache.go:198's generation-ordered
        list, realized as a dirty set). A snapshot older than the dirty-set
        horizon (e.g. a brand-new Snapshot) gets a full resync."""
        with self._lock:
            max_gen = snapshot.generation
            changed = False
            full = snapshot.generation < self._horizon()
            # structural = node-set membership or a zone changed → the
            # snapshot's cached interleave order must be rebuilt; pod-only
            # churn (the batch commit path) keeps it (snapshot.py refresh_lists)
            structural = full
            batch_changed = set()
            names = self.nodes.keys() if full else (self._dirty | self._removed)
            for name in names:
                ni = self.nodes.get(name)
                if ni is None:
                    if name in snapshot.node_info_map:
                        del snapshot.node_info_map[name]
                        snapshot.changed_names.add(name)
                        batch_changed.add(name)
                        changed = True
                        structural = True
                    continue
                if ni.generation > snapshot.generation:
                    if not structural and snapshot.order_affected_by(name, ni.node):
                        structural = True
                    prev = snapshot.node_info_map.get(name)
                    if prev is None or prev.node is not ni.node:
                        snapshot.node_object_version += 1
                    snapshot.node_info_map[name] = ni.clone()
                    snapshot.changed_names.add(name)
                    batch_changed.add(name)
                    max_gen = max(max_gen, ni.generation)
                    changed = True
            if full:
                stale = [n for n in snapshot.node_info_map if n not in self.nodes]
                for n in stale:
                    del snapshot.node_info_map[n]
                    snapshot.changed_names.add(n)
                    batch_changed.add(n)
                    changed = True
            self._dirty.clear()
            self._removed.clear()
            self._sync_generation = max_gen
            if changed:
                snapshot.refresh_lists(structural=structural,
                                       changed_names=batch_changed)
            snapshot.generation = max_gen
        return snapshot

    def _horizon(self) -> int:  # ktpu: locked
        """Oldest snapshot generation the dirty set can serve incrementally."""
        return self._sync_generation

    def dirty_nodes(self, since_generation: int) -> List[str]:
        """Node names whose generation advanced past ``since_generation`` —
        the TPU backend's delta-upload worklist."""
        with self._lock:
            return [n for n, ni in self.nodes.items() if ni.generation > since_generation]

    def min_pod_priority(self) -> Optional[int]:
        """Lowest priority among pods currently assigned to nodes; None when
        no pod is assigned. A pending pod with priority <= this value cannot
        have preemption victims anywhere."""
        with self._lock:
            return min(self._prio_counts) if self._prio_counts else None

    def node_count(self) -> int:
        with self._lock:
            return self._real_nodes

    def has_real_node(self, node_name: str) -> bool:
        """True iff the cache holds a LIVE Node object under this name
        (ghost entries kept for not-yet-deleted pods don't count) — the
        commit-time existence probe for placements decided while the node
        was being removed."""
        with self._lock:
            ni = self.nodes.get(node_name)
            return ni is not None and ni.node is not None

    def missing_real_nodes(self, names) -> set:
        """Subset of ``names`` with no LIVE Node object — the batched form
        of has_real_node (one lock acquisition for a whole commit's worth
        of winner probes; the commit plane is the measured bottleneck)."""
        with self._lock:
            out = set()
            for name in names:
                ni = self.nodes.get(name)
                if ni is None or ni.node is None:
                    out.add(name)
            return out

    def stats(self) -> Tuple[int, int, int]:
        """(nodes, pods, assumed_pods) — the scheduler_cache_size gauge feed
        and the /debug/cache counts (cache.go:96 Dump's totals)."""
        with self._lock:
            return self._real_nodes, len(self.pod_states), len(self._assumed)
