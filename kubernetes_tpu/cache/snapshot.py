"""Per-cycle immutable snapshot (internal/cache/snapshot.go:29).

Holds cloned NodeInfos keyed by name plus the flat list and the pruned
secondary lists the affinity plugins iterate (have_pods_with_affinity,
have_pods_with_required_anti_affinity, used PVC set).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..framework.types import NodeInfo


class Snapshot:
    def __init__(self):
        self.node_info_map: Dict[str, NodeInfo] = {}
        self.node_info_list: List[NodeInfo] = []
        self.have_pods_with_affinity_list: List[NodeInfo] = []
        self.have_pods_with_required_anti_affinity_list: List[NodeInfo] = []
        self.used_pvc_set: Set[str] = set()
        self.generation: int = 0
        # zone-interleave order cache: the interleaved ORDER depends only on
        # (name, zone) membership, not on pod contents — pod-only churn (the
        # per-batch commit path) reuses it instead of rebuilding a throwaway
        # NodeTree over every node (was 50ms+/batch at 5k nodes)
        self._order: List[str] = []
        self._zone_of: Dict[str, str] = {}

    def get(self, name: str) -> Optional[NodeInfo]:
        return self.node_info_map.get(name)

    def list(self) -> List[NodeInfo]:
        return self.node_info_list

    def refresh_lists(self, structural: bool = True) -> None:
        """Rebuild the flat + pruned lists from node_info_map. The flat list
        is zone-round-robin ordered (nodeTree order, node_tree.go:32) so the
        sampled scheduling window spreads across zones.

        ``structural=False`` is the caller's promise that no node was added,
        removed, or re-zoned since the last refresh (only pod contents
        changed) — the cached interleave order is reused and only the list
        pointers + pruned lists are rebuilt (O(N) dict lookups, not an O(N)
        tree rebuild with per-node zone-label extraction)."""
        from ..api.types import get_zone_key

        if structural or not self._order:
            from .node_tree import zone_interleaved

            self.node_info_list = zone_interleaved(
                ni for ni in self.node_info_map.values() if ni.node is not None
            )
            self._order = [ni.node.meta.name for ni in self.node_info_list]
            self._zone_of = {
                ni.node.meta.name: get_zone_key(ni.node) for ni in self.node_info_list
            }
        else:
            m = self.node_info_map
            self.node_info_list = [m[name] for name in self._order]
        self.have_pods_with_affinity_list = [ni for ni in self.node_info_list if ni.pods_with_affinity]
        self.have_pods_with_required_anti_affinity_list = [
            ni for ni in self.node_info_list if ni.pods_with_required_anti_affinity
        ]
        self.used_pvc_set = {k for ni in self.node_info_list for k in ni.pvc_ref_counts}
