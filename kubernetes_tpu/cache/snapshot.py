"""Per-cycle immutable snapshot (internal/cache/snapshot.go:29).

Holds cloned NodeInfos keyed by name plus the flat list and the pruned
secondary lists (have_pods_with_affinity, have_pods_with_required_anti_affinity,
used PVC set). The pruned lists are computed LAZILY: the batched commit path
refreshes the snapshot once per batch and never reads them, so eager rebuilds
were pure O(N) overhead per batch; a property rebuilds them on first access
after a refresh (the oracle path's per-cycle access pattern is unchanged).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..framework.types import NodeInfo


class Snapshot:
    def __init__(self):
        self.node_info_map: Dict[str, NodeInfo] = {}
        self.node_info_list: List[NodeInfo] = []
        self.generation: int = 0
        # zone-interleave order cache: the interleaved ORDER depends only on
        # (name, zone) membership, not on pod contents — pod-only churn (the
        # per-batch commit path) reuses it instead of rebuilding a throwaway
        # NodeTree over every node (was 50ms+/batch at 5k nodes)
        self._order: List[str] = []
        self._pos: Dict[str, int] = {}
        self._zone_of: Dict[str, str] = {}
        self._pruned_stale = True
        self._affinity_list: List[NodeInfo] = []
        self._anti_affinity_list: List[NodeInfo] = []
        self._used_pvc: Set[str] = set()
        # device-sync bookkeeping (backend/device_state.py): names whose
        # NodeInfo was re-cloned/deleted since the device last consumed them,
        # and a version that bumps on any membership/zone change — lets
        # reconcile/has_dirty probe O(changes) instead of O(nodes)
        self.changed_names: Set[str] = set()
        self.structure_version: int = 0
        # bumps whenever a re-cloned NodeInfo carries a DIFFERENT Node object
        # (labels/taints/allocatable may have changed) — consumers caching
        # label-derived indexes (ops/volume_mask.py) key on it
        self.node_object_version: int = 0

    def get(self, name: str) -> Optional[NodeInfo]:
        return self.node_info_map.get(name)

    def order_affected_by(self, name: str, node) -> bool:
        """Would replacing ``name``'s NodeInfo (whose .node is ``node``)
        change the cached interleave order? True for new names, removals
        (node None), and zone changes — the one place the order invariant
        lives (cache.update_snapshot consults this instead of re-deriving
        the membership/zone rule)."""
        from ..api.types import get_zone_key

        prev_zone = self._zone_of.get(name)
        return (node is None or prev_zone is None
                or get_zone_key(node) != prev_zone)

    def list(self) -> List[NodeInfo]:
        return self.node_info_list

    # ---- pruned lists (snapshot.go:49-58), rebuilt on demand ----------------

    def _rebuild_pruned(self) -> None:
        self._affinity_list = [ni for ni in self.node_info_list if ni.pods_with_affinity]
        self._anti_affinity_list = [
            ni for ni in self.node_info_list if ni.pods_with_required_anti_affinity
        ]
        self._used_pvc = {k for ni in self.node_info_list for k in ni.pvc_ref_counts}
        self._pruned_stale = False

    @property
    def have_pods_with_affinity_list(self) -> List[NodeInfo]:
        if self._pruned_stale:
            self._rebuild_pruned()
        return self._affinity_list

    @property
    def have_pods_with_required_anti_affinity_list(self) -> List[NodeInfo]:
        if self._pruned_stale:
            self._rebuild_pruned()
        return self._anti_affinity_list

    @property
    def used_pvc_set(self) -> Set[str]:
        if self._pruned_stale:
            self._rebuild_pruned()
        return self._used_pvc

    def refresh_lists(self, structural: bool = True,
                      changed_names: Optional[Set[str]] = None) -> None:
        """Rebuild the flat list from node_info_map. The flat list is
        zone-round-robin ordered (nodeTree order, node_tree.go:32) so the
        sampled scheduling window spreads across zones.

        ``structural=False`` is the caller's promise that no node was added,
        removed, or re-zoned since the last refresh (only pod contents
        changed): the cached interleave order is kept, and with
        ``changed_names`` the refresh patches only those positions —
        O(changes), not O(nodes)."""
        from ..api.types import get_zone_key

        if structural or not self._order:
            self.structure_version += 1
            from .node_tree import zone_interleaved

            self.node_info_list = zone_interleaved(
                ni for ni in self.node_info_map.values() if ni.node is not None
            )
            self._order = [ni.node.meta.name for ni in self.node_info_list]
            self._pos = {name: i for i, name in enumerate(self._order)}
            self._zone_of = {
                ni.node.meta.name: get_zone_key(ni.node) for ni in self.node_info_list
            }
        elif changed_names is not None:
            lst, m, pos = self.node_info_list, self.node_info_map, self._pos
            for name in changed_names:
                i = pos.get(name)
                if i is not None:
                    lst[i] = m[name]
        else:
            m = self.node_info_map
            self.node_info_list = [m[name] for name in self._order]
        self._pruned_stale = True
