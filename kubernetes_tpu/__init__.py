"""kubernetes_tpu — a TPU-native scheduling framework with the capabilities of
the Kubernetes kube-scheduler (reference: pohly/kubernetes @ v1.25-dev).

Architecture (see SURVEY.md §7):
  - ``api/``        lightweight typed API objects (Pod, Node, ...) mirroring the
                    scheduling-relevant surface of staging/src/k8s.io/api.
  - ``framework/``  the scheduling-framework contract: 13 extension points,
                    Status codes, CycleState, plugin registry/runtime — the
                    analog of pkg/scheduler/framework.
  - ``cache/``      assume/confirm/expire scheduler cache with generation-based
                    incremental snapshots (pkg/scheduler/internal/cache).
  - ``queue/``      activeQ/backoffQ/unschedulable priority queue with
                    cluster-event gating (pkg/scheduler/internal/queue).
  - ``ops/``        the TPU compute path: dense tensor schemas, the host-side
                    selector/taint/port compiler, and batched JAX filter/score
                    kernels (vmap over the node axis).
  - ``backend/``    device-resident cluster state with generation-keyed delta
                    uploads, and the batched scheduling step (lax.scan
                    sequential-commit over a pod micro-batch).
  - ``parallel/``   node-axis sharding over a jax.sharding.Mesh.
  - ``scheduler/``  the Scheduler object and scheduleOne / batched loops.
  - ``perf/``       scheduler_perf-equivalent YAML workload harness.
"""

__version__ = "0.1.0"
