"""cmd/kube-apiserver (app/server.go:90 NewAPIServerCommand, :157 Run):
the standalone launchable API server binary.

    python -m kubernetes_tpu.cmd.apiserver --port 6443 \
        --wal /var/lib/ktpu/store.wal \
        --token-auth-file tokens.csv --authorization-mode Node,RBAC

Assembles the same pieces the embedded form uses (serve_api over a
ClusterStore with the admission chain), adds the binary-level concerns:
durable storage (WAL restore + attach), authn from the reference's static
token file format (token,user,uid[,"group1,group2"] per line),
the Node/RBAC authorizer chain, and healthz/readyz on the same mux via the
store-backed handler. SIGTERM drains and snapshots."""

from __future__ import annotations

import argparse
import csv
import signal
import sys
import threading


def build_auth(args, store):
    from ..apiserver.auth import (
        AuthConfig,
        Authenticator,
        FlowController,
        NodeAuthorizer,
        RBACAuthorizer,
        UserInfo,
    )

    tokens = {}
    if args.token_auth_file:
        # the reference static token file (--token-auth-file,
        # staging/src/k8s.io/apiserver/pkg/authentication/token/tokenfile):
        # token,user,uid[,"group1,group2"] per line — token FIRST
        with open(args.token_auth_file) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = next(csv.reader([line]))
                if len(parts) < 3:
                    raise SystemExit(
                        f"{args.token_auth_file}:{lineno}: token file lines "
                        "are token,user,uid[,\"group1,group2\"]")
                token, user = parts[0].strip(), parts[1].strip()
                groups = tuple(g.strip() for g in parts[3].split(",")
                               if g.strip()) if len(parts) > 3 else ()
                tokens[token] = UserInfo(user, groups)
    authenticator = Authenticator(tokens=tokens) if tokens else None
    modes = [m.strip() for m in (args.authorization_mode or "").split(",") if m.strip()]
    unknown = [m for m in modes if m not in ("Node", "RBAC")]
    if unknown:
        # fail startup like the reference binary — a typo'd mode silently
        # ignored would leave the server wide open behind an authz banner
        raise SystemExit(
            f"--authorization-mode: unknown mode(s) {unknown}; supported: Node, RBAC")
    authorizer = None
    if "RBAC" in modes:
        authorizer = RBACAuthorizer(store)
    if "Node" in modes:
        authorizer = NodeAuthorizer(store, delegate=authorizer)
    flow = FlowController() if args.enable_priority_and_fairness else None
    if authenticator is None and authorizer is None and flow is None:
        return None
    return AuthConfig(authenticator=authenticator, authorizer=authorizer,
                      flow=flow)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kube-apiserver")
    parser.add_argument("--port", type=int, default=6443)
    parser.add_argument("--wal", default="",
                        help="durable store path (restore + append; empty = memory-only)")
    parser.add_argument("--token-auth-file", default="")
    parser.add_argument("--authorization-mode", default="",
                        help='comma list: "Node", "RBAC" (empty = open)')
    parser.add_argument("--enable-priority-and-fairness", action="store_true")
    parser.add_argument("--snapshot-on-exit",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="compact the WAL into a snapshot on SIGTERM "
                             "(--no-snapshot-on-exit for fast shutdown)")
    args = parser.parse_args(argv)

    from ..apiserver.http import serve_api, shutdown_api
    from ..apiserver.store import ClusterStore
    from ..apiserver.wal import restore

    if args.wal:
        store = restore(args.wal)  # also re-attaches a compacted WAL
        print(f"restored {sum(len(store._kind_map(k)) for k in store.KINDS)} "
              f"objects from {args.wal}", file=sys.stderr)
    else:
        store = ClusterStore()

    auth = build_auth(args, store)
    server, port = serve_api(store, port=args.port, auth=auth)
    print(f"kube-apiserver listening on 127.0.0.1:{port} "
          f"(authz={args.authorization_mode or 'open'}, "
          f"wal={'on' if args.wal else 'off'})", file=sys.stderr)

    stop = threading.Event()

    def _term(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        if args.wal and args.snapshot_on_exit and store._wal is not None:
            store._wal.snapshot(store)
        shutdown_api(server)
    return 0


if __name__ == "__main__":
    sys.exit(main())
