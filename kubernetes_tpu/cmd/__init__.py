"""Component binaries (the cmd/* layer): scheduler and controller-manager
entry points with the component-base serving surface (healthz/readyz/configz/
metrics mux, leader election, feature gates).
"""
