"""Scheduler command (cmd/kube-scheduler/app/server.go).

``Setup`` decodes KubeSchedulerConfiguration (v1beta2/v1beta3 YAML),
builds the scheduler over a store, and wires the component-base serving
surface: /healthz, /readyz, /configz, /metrics on one mux
(server.go:146 Run installs the same endpoints), plus leader election
(server.go:205-225) gating the scheduling loop.

``main()`` is the binary: `python -m kubernetes_tpu.cmd.server --config f.yaml
[--simulate nodes=N,pods=P]` — simulate mode stands in for a cluster the way
kubemark hollow nodes do.
"""

from __future__ import annotations

import argparse
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..apiserver.store import ClusterStore
from ..client.informer import SharedInformerFactory
from ..client.leaderelection import LeaderElectionConfig, LeaderElector
from ..config.factory import scheduler_from_config
from ..config.types import KubeSchedulerConfiguration, load_config
from ..metrics.registry import Registry
from ..utils.featuregate import DEFAULT_FEATURE_GATE


# default per-list entry cap for /debug dumps (override per request with
# ?limit=N): a 5k-node queue/cache dump serialized whole is megabytes of
# JSON from the serving thread — bounded by default, explicit to go deeper
DEFAULT_DEBUG_LIMIT = 1000


def _cap(items, limit):
    """(first ``limit`` entries, original length if truncated else None) —
    the one cap-plus-marker primitive every /debug handler shares, so a
    capped list is never indistinguishable from a genuinely short one."""
    items = list(items)
    if limit is not None and 0 <= limit < len(items):
        return items[:limit], len(items)
    return items, None


def _accepts_limit(fn) -> bool:
    """Whether a debug handler takes the ``limit`` kwarg (checked by
    signature, never by catching TypeError around the CALL — a genuine
    TypeError from inside the handler must not re-execute it uncapped)."""
    import inspect

    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
    return ("limit" in params
            or any(p.kind == p.VAR_KEYWORD for p in params.values()))


class ComponentServer:
    """healthz/readyz/configz/metrics/debug mux shared by the component
    binaries (component-base: healthz.InstallHandler + configz +
    legacyregistry + the /debug introspection family)."""

    def __init__(self, configz: dict, registry: Optional[Registry] = None,
                 ready_fn=None, port: int = 0, debug: Optional[dict] = None):
        self.configz = configz
        self.registry = registry
        self.ready_fn = ready_fn or (lambda: True)
        # /debug/<name> → zero-arg callable returning a JSON-serializable
        # body (build_debug_handlers wires the scheduler's set)
        self.debug = debug or {}
        # signature introspection is constant per handler — once here, not
        # per request on the serving thread
        self._debug_accepts_limit = {n: _accepts_limit(f)
                                     for n, f in self.debug.items()}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    self._respond(200, "ok", "text/plain")
                elif path == "/readyz":
                    ok = outer.ready_fn()
                    self._respond(200 if ok else 500, "ok" if ok else "not ready", "text/plain")
                elif path == "/configz":
                    self._respond(200, json.dumps(outer.configz), "application/json")
                elif path == "/metrics":
                    # content negotiation: an OpenMetrics scraper (Accept:
                    # application/openmetrics-text) gets exemplars on the
                    # histogram buckets; everyone else gets 0.0.4 text,
                    # byte-identical to before (exemplars are illegal there)
                    om = "openmetrics-text" in (self.headers.get("Accept") or "")
                    text = (outer.registry.expose(openmetrics=om)
                            if outer.registry else "")
                    ctype = ("application/openmetrics-text; version=1.0.0; "
                             "charset=utf-8" if om
                             else "text/plain; version=0.0.4")
                    self._respond(200, text, ctype)
                elif path == "/debug" or path == "/debug/":
                    self._respond(200, json.dumps(
                        {"endpoints": sorted("/debug/" + n for n in outer.debug)}),
                        "application/json")
                elif path.startswith("/debug/"):
                    name = path[len("/debug/"):]
                    fn = outer.debug.get(name)
                    if fn is None:
                        self._respond(404, "not found", "text/plain")
                        return
                    # ?limit=N caps unbounded dumps (queue/cache/spans/...)
                    # at N entries per list; the default keeps a 5k-node
                    # dump bounded instead of serializing the whole world
                    import urllib.parse as _up

                    limit = DEFAULT_DEBUG_LIMIT
                    try:
                        q = _up.parse_qs(query)
                        if "limit" in q:
                            limit = max(0, int(q["limit"][0]))
                    except (ValueError, IndexError):
                        pass
                    try:
                        out = (fn(limit=limit)
                               if outer._debug_accepts_limit.get(name)
                               else fn())
                        body = json.dumps(out, default=str)
                    except Exception as exc:  # noqa: BLE001 — debug must not kill serving
                        self._respond(500, json.dumps(
                            {"error": f"{type(exc).__name__}: {exc}"}),
                            "application/json")
                        return
                    self._respond(200, body, "application/json")
                else:
                    self._respond(404, "not found", "text/plain")

            def _respond(self, code, body, ctype):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def _device_occupancy(device) -> dict:
    """Per-axis tensor occupancy for /debug/devicestate: used vs capacity
    per RESOURCE axis (summed over valid mirrored rows — the question an
    operator actually asks: how full is the fleet per resource?) plus how
    much of each static vocab/axis the encoder has consumed. Reads only the
    host-side mirror — no device round-trip from the serving thread."""
    from ..ops import schema

    mirror = device._mirror
    valid = mirror["valid"].reshape(-1).astype(bool)
    used = mirror["requested"][valid].sum(axis=0)
    cap = mirror["allocatable"][valid].sum(axis=0)
    enc = device.encoder
    fixed = (("cpu", schema.COL_CPU), ("memory", schema.COL_MEM),
             ("ephemeral-storage", schema.COL_EPH), ("pods", schema.COL_PODS))
    resources = {name: {"used": int(used[col]), "capacity": int(cap[col])}
                 for name, col in fixed}
    for rid in range(1, len(enc.scalar_vocab)):
        col = schema.N_FIXED_COLS + rid - 1
        if col < used.shape[0]:
            resources[str(enc.scalar_vocab.item(rid))] = {
                "used": int(used[col]), "capacity": int(cap[col])}
    caps = device.caps
    axes = {
        "nodes": {"used": int(valid.sum()), "capacity": caps.nodes},
        "resources": {"used": schema.N_FIXED_COLS + len(enc.scalar_vocab) - 1,
                      "capacity": caps.resources},
        "labelKeys": {"used": len(enc.key_vocab) - 1,
                      "capacity": caps.label_keys},
        "ports": {"used": len(enc.port_vocab) - 1,
                  "capacity": caps.port_words * 32},
        # live(), not len-1: image ids free when the last reporting node
        # leaves (elastic churn), so the raw table length counts holes
        "images": {"used": enc.image_vocab.live(), "capacity": caps.images},
        "prioClasses": {"used": len(enc.prio_vocab),
                        "capacity": caps.prio_classes},
        "sigs": {"used": device.sig_table.n_sigs, "capacity": caps.sigs},
        "attrKeys": {"used": len(device.attr_slots),
                     "capacity": device._attr_cols},
    }
    return {"resources": resources, "axes": axes}


def _slice_rows(device):
    """(fragmentation rows, per-cell free map, grid) off the host mirror —
    the shared read for /debug/slices and the devicestate topology block.
    No device round-trip: slice-free means zero pods on the host."""
    from ..ops.schema import COL_PODS
    from ..ops.slice import fragmentation_host

    mirror = device._mirror
    valid = mirror["valid"].reshape(-1).astype(bool)
    sp_arr = mirror["topo_sp"].reshape(-1)
    pos_arr = mirror["topo_pos"].reshape(-1)
    free = valid & (mirror["requested"][:, COL_PODS] == 0)
    grid = (device.caps.superpods, device.caps.sp_slots)
    rows = fragmentation_host(sp_arr, pos_arr, valid, free, grid)
    cell_free = {}
    for idx in range(len(sp_arr)):
        sp, pos = int(sp_arr[idx]), int(pos_arr[idx])
        if valid[idx] and 0 <= sp < grid[0] and 0 <= pos < grid[1]:
            cell_free[(sp, pos)] = bool(free[idx])
    return rows, cell_free, grid


def _topology_block(device, limit=None) -> dict:
    """Per-node torus coords + per-superpod free/used chip counts for
    /debug/devicestate (``?limit=`` caps the node list)."""
    from ..ops import schema

    mirror = device._mirror
    nodes = []
    for name, slot in sorted(device.encoder.node_slots.items()):
        sp = int(mirror["topo_sp"][slot])
        pos = int(mirror["topo_pos"][slot])
        if sp >= 0 and pos >= 0:
            nodes.append({"node": name, "superpod": sp, "slot": pos})
    capped, orig = _cap(nodes, limit)
    rows, _cells, grid = _slice_rows(device)
    out = {
        "chipsPerNode": schema.CHIPS_PER_NODE,
        "grid": {"superpods": grid[0], "slots": grid[1]},
        "nodes": capped,
        "superpods": [{"sp": r["sp"],
                       "freeChips": r["free"] * schema.CHIPS_PER_NODE,
                       "usedChips": r["used"] * schema.CHIPS_PER_NODE}
                      for r in rows],
    }
    if orig is not None:
        out["nodesTruncated"] = orig
    return out


def build_debug_handlers(sched) -> dict:
    """The /debug endpoint family over a live scheduler (SURVEY §5.2's
    SIGUSR2 comparer/dumper, but always-on and JSON over the serving mux):

      /debug/queue        active/backoff/unschedulable dump
      /debug/cache        comparer drift report + node/pod/assumed counts
      /debug/devicestate  DeviceState capacities, sig-table occupancy,
                          batch-sizer model, torus topology block
                          (TPU/batched schedulers only)
      /debug/slices       torus occupancy map: per-superpod cell strings
                          plus free/used/largest-run/fragmentation rows
                          (the slice-packing operator view)
      /debug/spans        tail of the in-memory span exporter
      /debug/circuit      device-service circuit breaker state, resync and
                          degradation counters (WireScheduler only)
      /debug/fabric       device-side HA fabric: active replica, per-
                          endpoint health/breaker/epoch, failover journal
                          (WireScheduler with >1 device endpoint)
      /debug/sessions     HA session table: this replica's identity plus the
                          device service's per-client lease age, deltaSeq,
                          and in-flight hold counts (WireScheduler only)
      /debug/flightrecorder  device-runtime flight recorder: compile/retrace
                          ledger, HBM/transfer counters, and the bounded
                          event ring (backend/telemetry.py; enabled=False
                          when the telemetry layer is off)
      /debug/dispatch     dispatch profiler: per-(program, bucket) device-
                          time stats with cost-ledger flops/bytes (achieved
                          FLOP/s where both exist) and the per-dispatch
                          dwell/exec/fetch record ring (backend/telemetry.py
                          DispatchLedger; enabled=False when telemetry off)
      /debug/locktrace    lock-order graph, acquisition counts, blocking
                          events from testing/locktrace.py (enabled only
                          under KTPU_LOCKTRACE=1)
      /debug/quota        per-namespace SchedulingQuota caps, the ledger's
                          live usage, fair-share weight, charged pod count,
                          plus the per-cohort borrowing pool: guaranteed/
                          lent/headroom, outstanding loans (newest first),
                          pending reclaim demand, reclaim breaker state
      /debug/ledger       pod-lifetime latency ledger: live/closed entry
                          counts, eviction count, per-pod segment
                          accumulators (metrics/latency_ledger.py;
                          enabled=False when the ledger is off)
      /debug/timeline     unified Chrome trace-event JSON (Perfetto /
                          chrome://tracing loadable): span tail + flight-
                          recorder events + ledger pod segments + the
                          dispatch profiler's device track on one
                          wall-clock axis, batchId/pod-UID correlated
      /debug/rebalance    continuous-rebalancing state: trigger band +
                          current packing score, wave budget, SLO breaker,
                          recent migration waves, pending uncordons
                          (enabled=False without an attached Rebalancer)

    Every handler takes an entry cap (``?limit=N`` on the mux, default
    DEFAULT_DEBUG_LIMIT) so a 5k-node dump stays bounded.
    """
    from ..cache.debugger import CacheComparer
    from ..utils import tracing

    def _capped_lists(out, limit, keys):
        """Cap ``out[key]`` lists in place, recording original lengths
        under out["truncated"][key] (counts stay exact, truncation is
        always visible)."""
        for key in keys:
            entries, orig = _cap(out.get(key) or [], limit)
            out[key] = entries
            if orig is not None:
                out.setdefault("truncated", {})[key] = orig
        return out

    def queue_dump(limit=None):
        return _capped_lists(sched.queue.dump(), limit,
                             ("active", "backoff", "unschedulable", "gated"))

    def quota_dump(limit=None):
        plugin = sched._quota_plugin()
        if plugin is None:
            return {"enabled": False}
        out = plugin.dump()
        # cohort pool view rides the same dump under a reserved key so the
        # per-namespace table stays flat
        cohorts = out.pop("_cohorts", {})
        capped, orig = _cap(sorted(out.items()), limit)
        result = {"enabled": True, "namespaces": dict(capped)}
        if orig is not None:
            result["namespacesTruncated"] = orig
        ccapped, corig = _cap(sorted(cohorts.items()), limit)
        result["cohorts"] = {}
        for name, entry in ccapped:
            loans, lorig = _cap(entry.get("loans") or [], limit)
            entry = dict(entry, loans=loans)
            if lorig is not None:
                entry["loansTruncated"] = lorig
            result["cohorts"][name] = entry
        if corig is not None:
            result["cohortsTruncated"] = corig
        return result

    def cache_dump(limit=None):
        comparer = CacheComparer(sched.store, sched.cache, sched.queue)
        missed_n, redundant_n = comparer.compare_nodes()
        missed_p, redundant_p = comparer.compare_pods()
        nodes, pods, assumed = sched.cache.stats()
        return _capped_lists({
            "nodes": nodes, "pods": pods, "assumedPods": assumed,
            "inSync": not (missed_n or redundant_n or missed_p or redundant_p),
            "missedNodes": missed_n, "redundantNodes": redundant_n,
            "missedPods": missed_p, "redundantPods": redundant_p,
        }, limit, ("missedNodes", "redundantNodes", "missedPods",
                   "redundantPods"))

    def device_dump(limit=None):
        import dataclasses

        device = getattr(sched, "device", None)
        if device is None:
            return {"enabled": False}
        occupancy = _device_occupancy(device)
        capped, orig = _cap(occupancy["resources"].items(), limit)
        if orig is not None:
            occupancy["resources"] = dict(capped)
            occupancy["resourcesTruncated"] = orig
        out = {
            "enabled": True,
            "caps": dataclasses.asdict(device.caps),
            "sigTable": {"nSigs": device.sig_table.n_sigs,
                         "nTerms": device.sig_table.n_terms},
            "topoEnabled": bool(device.topo_enabled),
            "nodesMirrored": len(device.encoder.node_slots),
            "batchCounter": getattr(sched, "batch_counter", 0),
            "pipelinedBatches": getattr(sched, "pipelined_batches", 0),
            "fallbackScheduled": getattr(sched, "fallback_scheduled", 0),
            "batchScheduled": getattr(sched, "batch_scheduled", 0),
            "uploadBytes": device.upload_bytes,
            "occupancy": occupancy,
        }
        sizer = getattr(sched, "sizer", None)
        if sizer is not None:
            out["batchSizer"] = {
                "a": sizer._a, "b": sizer._b, "updates": sizer.updates,
                "deadlineS": sizer.deadline_s, "target": sizer.target(),
                "maxBatch": sizer.max_batch,
            }
        out["topology"] = _topology_block(device, limit)
        return out

    def slices_dump(limit=None):
        """Torus occupancy map: one row per mapped superpod — a cell string
        ('.' free host, '#' used host, '-' no host at that slot) plus the
        free/used/largest-run/fragmentation accounting behind the
        scheduler_slice_fragmentation gauge."""
        device = getattr(sched, "device", None)
        if device is None:
            return {"enabled": False}
        rows, cell_free, grid = _slice_rows(device)
        superpods = []
        for r in rows:
            s = r["sp"]
            cells = "".join(
                "-" if (s, b) not in cell_free
                else ("." if cell_free[(s, b)] else "#")
                for b in range(grid[1]))
            superpods.append({**r, "map": cells})
        capped, orig = _cap(superpods, limit)
        out = {"enabled": True,
               "grid": {"superpods": grid[0], "slots": grid[1]},
               "superpods": capped}
        if orig is not None:
            out["superpodsTruncated"] = orig
        return out

    def spans_dump(limit=None):
        return [s.to_otlp() for s in tracing.tail(
            256 if limit is None or limit < 0 else limit)]

    def circuit_dump(limit=None):
        if not hasattr(sched, "debug_circuit"):
            return {"enabled": False}
        return sched.debug_circuit()

    def fabric_dump(limit=None):
        if not hasattr(sched, "debug_fabric"):
            return {"enabled": False}
        out = sched.debug_fabric()
        if not out.get("enabled"):
            return out
        return _capped_lists(out, limit, ("replicas", "log"))

    def sessions_dump(limit=None):
        if not hasattr(sched, "debug_sessions"):
            return {"enabled": False}
        out = sched.debug_sessions()
        svc = out.get("service")
        if isinstance(svc, dict) and isinstance(svc.get("sessions"), list):
            svc["sessions"], orig = _cap(svc["sessions"], limit)
            if orig is not None:
                svc["sessionsTruncated"] = orig
        return out

    def flightrecorder_dump(limit=None):
        from ..backend import telemetry

        t = telemetry.get()
        if t is None:
            return {"enabled": False}
        return t.dump(limit)

    def dispatch_dump(limit=None):
        from ..backend import telemetry

        t = telemetry.get()
        if t is None:
            return {"enabled": False}
        return t.dispatch_ledger.dump(limit)

    def locktrace_dump(limit=None):
        from ..testing import locktrace

        if not locktrace.enabled():
            return {"enabled": False}
        out = locktrace.tracer().report()
        out["enabled"] = True
        out["cycles"] = locktrace.tracer().cycles()
        return _capped_lists(out, limit,
                             ("blockingViolations", "blockingAllowed"))

    def ledger_dump(limit=None):
        from ..metrics import latency_ledger

        led = latency_ledger.get()
        if led is None:
            return {"enabled": False}
        return led.dump(limit)

    def rebalance_dump(limit=None):
        rb = getattr(sched, "rebalancer", None)
        if rb is None:
            return {"enabled": False}
        return rb.debug_dump(limit)

    def timeline_dump(limit=None):
        """One Chrome trace-event JSON body unifying the span tail, the
        flight-recorder ring, and the latency ledger's pod segments —
        `curl :PORT/debug/timeline > t.json` then load in Perfetto."""
        from ..backend import telemetry
        from ..metrics import latency_ledger

        cap = 256 if limit is None or limit < 0 else limit
        t = telemetry.get()
        flight = t.flight.dump(cap) if t is not None else []
        dispatch = (t.dispatch_ledger.dump(cap)["records"]
                    if t is not None else [])
        return latency_ledger.chrome_trace(
            spans=tracing.tail(cap), flight=flight, dispatch=dispatch,
            ledger=latency_ledger.get(), limit=cap)

    return {"queue": queue_dump, "cache": cache_dump,
            "devicestate": device_dump, "slices": slices_dump,
            "spans": spans_dump,
            "circuit": circuit_dump, "sessions": sessions_dump,
            "fabric": fabric_dump,
            "flightrecorder": flightrecorder_dump, "quota": quota_dump,
            "dispatch": dispatch_dump,
            "locktrace": locktrace_dump, "ledger": ledger_dump,
            "timeline": timeline_dump, "rebalance": rebalance_dump}


def setup(store: ClusterStore, cfg: Optional[KubeSchedulerConfiguration] = None,
          raw: Optional[dict] = None, feature_gates: str = "",
          use_informers: bool = True, tpu: bool = False,
          device_endpoints=None, **kwargs):
    """server.go:300 Setup: config + registries → a runnable scheduler.

    ``device_endpoints`` (list or comma-separated string) points the
    scheduler at remote DeviceService bindings over the wire; more than
    one enables the device-side HA fabric (backend/fabric.py)."""
    from ..backend import telemetry
    from ..utils.tracing import maybe_enable_from_env

    maybe_enable_from_env()  # KTPU_TRACE_FILE: OTLP-shaped span export (§5.1)
    if feature_gates:
        DEFAULT_FEATURE_GATE.set_from_string(feature_gates)
    factory = SharedInformerFactory(store) if use_informers else None
    if device_endpoints:
        from ..backend.service import WireScheduler

        kwargs.setdefault("scheduler_cls", WireScheduler)
        kwargs.setdefault("endpoint", device_endpoints)
    elif tpu and DEFAULT_FEATURE_GATE.enabled("TPUBatchedScheduling"):
        from ..backend.tpu_scheduler import TPUScheduler

        kwargs.setdefault("scheduler_cls", TPUScheduler)
    sched = scheduler_from_config(
        store, cfg=cfg, raw=raw, informer_factory=factory, **kwargs
    )
    # KTPU_TELEMETRY=1: device-runtime observability (compile ledger, HBM/
    # transfer gauges, flight recorder) feeding THIS scheduler's registry —
    # off by default, one-global-read disabled cost
    telemetry.maybe_enable_from_env(sched.smetrics)
    # KTPU_LEDGER=1: pod-lifetime latency ledger (per-segment e2e
    # attribution + tenant SLO histograms + /debug/timeline) — same
    # off-by-default, one-global-read contract; the quota tenant index
    # bounds the {namespace} label set
    from ..metrics import latency_ledger

    latency_ledger.maybe_enable_from_env(sched.smetrics,
                                         tenant_fn=sched._ns_fair_weight)
    return sched


class SchedulerApp:
    """The running binary: serving mux + leader-elected scheduling loop."""

    def __init__(self, store: ClusterStore, raw_config: Optional[dict] = None,
                 identity: str = "kube-scheduler-0", port: int = 0,
                 feature_gates: str = "", tpu: bool = False,
                 device_endpoints=None, wire_pipeline_depth=None):
        self.cfg = load_config(raw_config)
        self.store = store
        extra = ({"wire_pipeline_depth": wire_pipeline_depth}
                 if device_endpoints and wire_pipeline_depth is not None
                 else {})
        self.sched = setup(store, cfg=self.cfg, feature_gates=feature_gates,
                           tpu=tpu, device_endpoints=device_endpoints,
                           **extra)
        self.elector = LeaderElector(
            store,
            LeaderElectionConfig(
                lock_name="kube-scheduler", identity=identity,
                lease_duration=self.cfg.leader_elect_lease_duration,
                renew_deadline=self.cfg.leader_elect_renew_deadline,
                retry_period=self.cfg.leader_elect_retry_period,
            ),
        ) if self.cfg.leader_elect else None
        self.server = ComponentServer(
            configz={"kubescheduler.config.k8s.io": _configz_view(self.cfg)},
            registry=getattr(self.sched.smetrics, "registry", None),
            ready_fn=lambda: True,
            port=port,
            debug=build_debug_handlers(self.sched),
        )
        self._stop = threading.Event()

    def tick(self) -> int:
        """One leader-gated scheduling round; returns cycles run."""
        if self.elector is not None and not self.elector.run_once():
            return 0
        return self.sched.run_until_settled()

    def run(self, tick_interval: float = 0.05) -> threading.Thread:
        self.server.start()

        def _loop():
            while not self._stop.is_set():
                self.tick()
                self._stop.wait(tick_interval)

        t = threading.Thread(target=_loop, name="kube-scheduler", daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        self.server.stop()


def _configz_view(cfg: KubeSchedulerConfiguration) -> dict:
    return {
        "apiVersion": cfg.api_version,
        "parallelism": cfg.parallelism,
        "percentageOfNodesToScore": cfg.percentage_of_nodes_to_score,
        "podInitialBackoffSeconds": cfg.pod_initial_backoff_seconds,
        "podMaxBackoffSeconds": cfg.pod_max_backoff_seconds,
        "leaderElection": {"leaderElect": cfg.leader_elect},
        "profiles": [p.scheduler_name for p in cfg.profiles],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kube-scheduler")
    parser.add_argument("--config", help="KubeSchedulerConfiguration YAML path")
    parser.add_argument("--port", type=int, default=10259)
    parser.add_argument("--feature-gates", default="")
    parser.add_argument("--leader-elect", default=None, choices=["true", "false"])
    parser.add_argument("--simulate", default="",
                        help="nodes=N,pods=P: run against a synthetic cluster")
    parser.add_argument("--device-endpoints", default="",
                        help="comma-separated device-service endpoints "
                             "(http://host:port); more than one enables "
                             "the device-side HA fabric")
    parser.add_argument("--serve-devices", type=int, default=0,
                        help="serve N in-process DeviceService bindings and "
                             "point the scheduler at all of them — the "
                             "single-binary fabric demo topology")
    parser.add_argument("--wire-pipeline-depth", type=int, default=None,
                        help="wire batches kept in flight on the pipelined "
                             "transport (default: KTPU_WIRE_PIPELINE_DEPTH "
                             "or 3; 0 = strictly request/response)")
    args = parser.parse_args(argv)

    raw = None
    if args.config:
        import yaml

        with open(args.config) as f:
            raw = yaml.safe_load(f)
    if args.leader_elect is not None:
        raw = dict(raw or {})
        raw.setdefault("leaderElection", {})["leaderElect"] = args.leader_elect == "true"

    store = ClusterStore()
    endpoints = [e.strip() for e in args.device_endpoints.split(",")
                 if e.strip()]
    device_servers = []
    if args.serve_devices:
        from ..backend.service import DeviceService, serve

        for _ in range(args.serve_devices):
            server, dev_port = serve(DeviceService())
            device_servers.append(server)
            endpoints.append(f"http://127.0.0.1:{dev_port}")
        print(f"device fabric: serving {len(device_servers)} DeviceService "
              f"bindings: {', '.join(endpoints[-len(device_servers):])}")
    app = SchedulerApp(store, raw_config=raw, port=args.port,
                       feature_gates=args.feature_gates,
                       device_endpoints=endpoints or None,
                       wire_pipeline_depth=args.wire_pipeline_depth)
    if args.simulate:
        from ..api.wrappers import make_node, make_pod

        params = dict(kv.split("=") for kv in args.simulate.split(","))
        for i in range(int(params.get("nodes", 100))):
            store.create_node(make_node(f"node-{i}").capacity(
                {"cpu": "8", "memory": "32Gi", "pods": 110}).obj())
        for i in range(int(params.get("pods", 200))):
            store.create_pod(make_pod(f"pod-{i}").req({"cpu": "100m", "memory": "256Mi"}).obj())
    thread = app.run()
    print(f"kube-scheduler serving on 127.0.0.1:{app.server.port} "
          f"(healthz/readyz/configz/metrics); leaderElect={app.cfg.leader_elect}")
    try:
        while thread.is_alive():
            time.sleep(1)
            if args.simulate:
                bound = sum(1 for p in store.pods.values() if p.spec.node_name)
                if bound == len(store.pods):
                    print(f"simulation complete: {bound} pods bound")
                    break
    except KeyboardInterrupt:
        pass
    app.stop()
    for server in device_servers:
        server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
