"""Shared informers (client-go tools/cache/shared_informer.go:302).

SharedIndexInformer = Reflector → DeltaFIFO → thread-safe indexed store →
handler fan-out. New handlers added after sync receive synthetic Adds for
every cached object (shared_informer.go:397 AddEventHandler). The
SharedInformerFactory dedups informers per kind (informers/factory.go).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from .delta_fifo import ADDED, DELETED, REPLACED, SYNC, UPDATED, Delta, DeltaFIFO
from .reflector import Reflector

# handler callbacks: (event, old, new); event one of "add"/"update"/"delete"
EventHandler = Callable[[str, Optional[object], Optional[object]], None]

Indexer = Callable[[object], List[str]]


class ThreadSafeStore:
    """Indexed object cache (tools/cache/thread_safe_store.go)."""

    def __init__(self, indexers: Optional[Dict[str, Indexer]] = None):
        self._lock = threading.RLock()
        self._items: Dict[str, object] = {}
        self._indexers: Dict[str, Indexer] = dict(indexers or {})
        self._indices: Dict[str, Dict[str, set]] = {name: {} for name in self._indexers}

    def _update_index(self, key: str, old, new) -> None:
        for name, fn in self._indexers.items():
            index = self._indices[name]
            if old is not None:
                for v in fn(old):
                    s = index.get(v)
                    if s is not None:
                        s.discard(key)
                        if not s:
                            del index[v]
            if new is not None:
                for v in fn(new):
                    index.setdefault(v, set()).add(key)

    def add(self, key: str, obj) -> None:
        with self._lock:
            old = self._items.get(key)
            self._items[key] = obj
            self._update_index(key, old, obj)

    def delete(self, key: str) -> None:
        with self._lock:
            old = self._items.pop(key, None)
            if old is not None:
                self._update_index(key, old, None)

    def get(self, key: str):
        with self._lock:
            return self._items.get(key)

    def list(self) -> List[object]:
        with self._lock:
            return list(self._items.values())

    def list_keys(self) -> List[str]:
        with self._lock:
            return list(self._items.keys())

    def by_index(self, index_name: str, value: str) -> List[object]:
        with self._lock:
            keys = self._indices.get(index_name, {}).get(value, set())
            return [self._items[k] for k in keys if k in self._items]


class SharedIndexInformer:
    def __init__(self, store, kind: str, key_fn: Callable[[object], str],
                 indexers: Optional[Dict[str, Indexer]] = None):
        self.kind = kind
        self._key_fn = key_fn
        self.indexer = ThreadSafeStore(indexers)
        self.fifo = DeltaFIFO(key_fn, known_objects=self.indexer.list_keys)
        self.reflector = Reflector(store, kind, self.fifo)
        self._handlers: List[EventHandler] = []
        self._lock = threading.RLock()
        self._started = False

    # -- wiring

    def add_event_handler(self, handler: EventHandler) -> None:
        """Fan-out registration; replays synthetic adds for cached objects
        when registered after sync (shared_informer.go:397)."""
        with self._lock:
            self._handlers.append(handler)
            for obj in self.indexer.list():
                handler("add", None, obj)

    def start(self) -> None:
        # check-and-set under the lock: two consumers starting the shared
        # informer concurrently must not double-list (locks pass finding)
        with self._lock:
            if self._started:
                return
            self._started = True
        self.reflector.list_and_establish_watch()
        self.pump()

    def pump(self, max_items: int = 100000) -> int:
        """Drain reflector watch events + FIFO into the indexer/handlers.
        The synchronous analog of the informer's processLoop; cheap when idle."""
        self.reflector.step()
        n = 0
        while n < max_items:
            deltas = self.fifo.pop()
            if deltas is None:
                break
            n += 1
            self._handle_deltas(deltas)
        return n

    def _handle_deltas(self, deltas: List[Delta]) -> None:
        for d in deltas:
            if isinstance(d.object, str):  # tombstone key only
                key = d.object
                obj = self.indexer.get(key)
            else:
                key = self._key_fn(d.object)
                obj = d.object
            old = self.indexer.get(key)
            if d.type in (ADDED, UPDATED, REPLACED, SYNC):
                self.indexer.add(key, obj)
                event = "update" if old is not None else "add"
                self._fan_out(event, old, obj)
            elif d.type == DELETED:
                self.indexer.delete(key)
                if old is not None:
                    self._fan_out("delete", old, None)

    def _fan_out(self, event: str, old, new) -> None:
        with self._lock:
            handlers = list(self._handlers)
        for h in handlers:
            h(event, old, new)

    def has_synced(self) -> bool:
        with self._lock:  # pairs with start()'s check-and-set
            started = self._started
        return started and self.fifo.has_synced()

    # -- lister surface

    def get(self, key: str):
        return self.indexer.get(key)

    def list(self) -> List[object]:
        return self.indexer.list()


class SharedInformerFactory:
    """One informer per kind, shared by all consumers
    (informers/factory.go NewSharedInformerFactory)."""

    KEY_FNS: Dict[str, Callable[[object], str]] = {}

    def __init__(self, store):
        self.store = store
        self._informers: Dict[str, SharedIndexInformer] = {}
        self._lock = threading.RLock()

    def informer_for(self, kind: str, indexers: Optional[Dict[str, Indexer]] = None) -> SharedIndexInformer:
        # keying must agree with the store's CRUD: cluster-scoped kinds by
        # bare name, namespaced by ns/name (one shared set, the store's)
        from ..apiserver.store import ClusterStore

        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                key_fn = self.KEY_FNS.get(
                    kind,
                    (lambda o: o.meta.name)
                    if kind in ClusterStore.CLUSTER_SCOPED_KINDS
                    else (lambda o: o.meta.key()),
                )
                inf = SharedIndexInformer(self.store, kind, key_fn, indexers)
                self._informers[kind] = inf
            return inf

    def start(self) -> None:
        """Start all registered informers (factory.Start)."""
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.start()

    def pump(self) -> int:
        """Drive all informers one synchronous round; returns events handled."""
        with self._lock:
            informers = list(self._informers.values())
        return sum(inf.pump() for inf in informers)

    def wait_for_cache_sync(self) -> bool:
        self.start()
        self.pump()
        with self._lock:  # registration may race the sync check
            informers = list(self._informers.values())
        return all(inf.has_synced() for inf in informers)
