"""Client runtime (the L3 layer, staging/src/k8s.io/client-go):
reflector + DeltaFIFO + shared informers, workqueue, leader election.

Every component in this framework consumes cluster state through this layer
(the reference's informer bus, SURVEY.md §2.5) rather than touching the
store's maps directly: a Reflector LISTs then WATCHes one kind, feeds a
DeltaFIFO, and a SharedIndexInformer pops deltas into an indexed local cache
while fanning out to event handlers.
"""

from .delta_fifo import Delta, DeltaFIFO
from .informer import SharedIndexInformer, SharedInformerFactory
from .leaderelection import LeaderElector
from .reflector import Reflector
from .workqueue import RateLimitingQueue, parallelize_until

__all__ = [
    "Delta",
    "DeltaFIFO",
    "LeaderElector",
    "RateLimitingQueue",
    "Reflector",
    "SharedIndexInformer",
    "SharedInformerFactory",
    "parallelize_until",
]
