"""REST list+watch client (client-go rest.Request + the Reflector's remote
half — VERDICT r3 §2.5 partial: "informers run in-process against the
store, not over REST").

``APIClient`` is store-shaped for the read path: ``list_objects(kind)`` and
``watch(kind, since)`` against the HTTP apiserver (apiserver/http.py), so
Reflector / SharedInformerFactory / controllers run UNCHANGED over a real
network boundary — the reference's client-go topology:

    factory = SharedInformerFactory(APIClient("http://127.0.0.1:6443"))

The watch is the chunked JSON-lines stream with resourceVersion resume; a
410 surfaces as ``Expired`` so the reflector relists (reflector.go:254's
relist-on-expiry), and transport drops surface as ``Expired`` too — a
relist is the safe recovery either way.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from collections import deque
from typing import Deque, Optional, Tuple

from ..api.codec import from_wire
from ..api import types as api_types
from ..apiserver.http import RESOURCES
from ..apiserver.store import Expired, WatchEvent

# kind -> (group path, plural) from the server's routing table
_PATH_OF = {kind: (group, plural) for (group, plural), kind in RESOURCES.items()}


def _decode(kind: str, wire: dict):
    cls = getattr(api_types, kind, None)
    if cls is None:
        raise TypeError(f"unknown kind {kind!r}")
    return from_wire(cls, wire)


class RESTWatch:
    """watch.Interface over the chunked JSON-lines stream: a reader thread
    feeds a queue; ``next(timeout)`` pops. Store-Watch-shaped so the
    Reflector consumes it unchanged."""

    def __init__(self, url: str, kind: str):
        self.kind = kind
        self._events: Deque[WatchEvent] = deque()
        self._cond = threading.Condition()
        self.stopped = False
        self._error: Optional[Exception] = None
        self._resp = urllib.request.urlopen(url, timeout=300)
        self._thread = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"restwatch-{kind}")
        self._thread.start()

    def _read_loop(self) -> None:
        try:
            # polling read: stop() tears the blocking readline via close(),
            # so a stale read here costs one extra loop at most
            while not self.stopped:  # ktpu: unguarded-ok(polling flag; stop() closes the socket to interrupt the blocking readline)
                line = self._resp.readline()
                if not line:
                    break  # server closed the stream
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue
                ev = WatchEvent(
                    seq=int(doc.get("resourceVersion", 0)),
                    type=doc["type"],
                    object=_decode(self.kind, doc["object"]),
                )
                with self._cond:
                    self._events.append(ev)
                    self._cond.notify_all()
        except Exception as exc:  # noqa: BLE001 — transport death → Expired
            self._error = exc  # ktpu: unguarded-ok(published before the cond-guarded stopped flip in finally; readers check stopped first)
        finally:
            with self._cond:
                self.stopped = True
                self._cond.notify_all()

    def next(self, timeout: float = 0.0) -> Optional[WatchEvent]:
        with self._cond:
            if not self._events and not self.stopped and timeout:
                self._cond.wait(timeout)
            if self._events:
                return self._events.popleft()
            if self.stopped:
                # a dead stream must not read as "no events": the reflector
                # needs to relist (reference: watch error → relist)
                raise Expired(f"watch stream for {self.kind} ended"
                              + (f": {self._error}" if self._error else ""))
            return None

    def stop(self) -> None:
        # flip under the cond + notify: a consumer parked in next()'s wait
        # must wake NOW, not when the reader thread notices the closed
        # socket (found by the locks pass: the unguarded write was only
        # eventually published through the reader's finally block)
        with self._cond:
            self.stopped = True
            self._cond.notify_all()
        try:
            self._resp.close()
        except OSError:
            pass


class APIClient:
    """Store-shaped REST read client (list_objects/watch) + typed writes
    where controllers need them later. One instance per server."""

    def __init__(self, server: str):
        self.server = server.rstrip("/")

    def _collection_url(self, kind: str) -> str:
        group, plural = _PATH_OF[kind]
        return f"{self.server}/{group}/{plural}"

    # ------------------------------------------------------------- read path

    def list_objects(self, kind: str) -> Tuple[list, int]:
        with urllib.request.urlopen(self._collection_url(kind), timeout=30) as r:
            doc = json.loads(r.read())
        rv = int(doc.get("metadata", {}).get("resourceVersion", 0))
        return [_decode(kind, item) for item in doc.get("items", ())], rv

    def watch(self, kind: str, since: int) -> RESTWatch:
        url = f"{self._collection_url(kind)}?watch=1&resourceVersion={since}"
        try:
            return RESTWatch(url, kind)
        except urllib.error.HTTPError as e:
            if e.code == 410:
                raise Expired(f"resourceVersion {since} expired") from e
            raise
