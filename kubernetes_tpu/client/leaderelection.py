"""Leader election on a Lease lock (client-go tools/leaderelection/
leaderelection.go:177 LeaderElector).

Active-passive HA: candidates race to create/update one Lease object via the
store's optimistic-concurrency update; the holder renews every
retry_period, others take over when renew_time + lease_duration passes
(leaderelection.go tryAcquireOrRenew). Crash-only: a dead leader's lease
simply expires.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..api.types import Lease, ObjectMeta
from ..apiserver.store import Conflict, NotFound

logger = logging.getLogger(__name__)


@dataclass
class LeaderElectionConfig:
    lock_name: str = "kube-scheduler"
    lock_namespace: str = "kube-system"
    identity: str = "scheduler-0"
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0


class LeaderElector:
    def __init__(self, store, config: LeaderElectionConfig,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 now_fn=time.monotonic):
        self.store = store
        self.config = config
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.now_fn = now_fn
        self._leading = False
        self._last_renew = 0.0

    @property
    def _key(self) -> str:
        return f"{self.config.lock_namespace}/{self.config.lock_name}"

    def is_leader(self) -> bool:
        return self._leading

    def _expired(self, lease: Lease) -> bool:
        return self.now_fn() > lease.renew_time + lease.lease_duration_seconds

    def try_acquire_or_renew(self) -> bool:
        """One acquire/renew attempt (leaderelection.go:322
        tryAcquireOrRenew); returns True while holding the lock."""
        cfg = self.config
        now = self.now_fn()
        lease = self.store.get_lease(self._key)
        if lease is None:
            new = Lease(
                meta=ObjectMeta(name=cfg.lock_name, namespace=cfg.lock_namespace),
                holder_identity=cfg.identity,
                lease_duration_seconds=cfg.lease_duration,
                acquire_time=now,
                renew_time=now,
            )
            try:
                self.store.create_lease(new)
            except Conflict:
                return self._set_leading(False)
            self._last_renew = now
            return self._set_leading(True)

        if lease.holder_identity != cfg.identity and not self._expired(lease):
            return self._set_leading(False)

        # we hold it, or it expired: take/renew via guarded update
        import dataclasses as _dc

        transitions = lease.lease_transitions + (
            0 if lease.holder_identity == cfg.identity else 1
        )
        new = _dc.replace(
            lease,
            holder_identity=cfg.identity,
            # the acquirer's OWN duration, not the previous holder's
            # (leaderelection.go writes LeaseDurationSeconds from config)
            lease_duration_seconds=cfg.lease_duration,
            acquire_time=lease.acquire_time if lease.holder_identity == cfg.identity else now,
            renew_time=now,
            lease_transitions=transitions,
        )
        new.meta = _dc.replace(lease.meta)
        try:
            self.store.update_lease(new, expect_rv=lease.meta.resource_version)
        except (Conflict, NotFound):
            # renew failed; give up leadership only past the renew deadline
            # (leaderelection.go:275 renewLoop's RenewDeadline timeout)
            if self._leading and now - self._last_renew < cfg.renew_deadline:
                return True
            return self._set_leading(False)
        self._last_renew = now
        return self._set_leading(True)

    def _set_leading(self, leading: bool) -> bool:
        if leading and not self._leading:
            logger.info("leaderelection: %s became leader", self.config.identity)
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            logger.warning("leaderelection: %s lost leadership", self.config.identity)
            if self.on_stopped_leading:
                self.on_stopped_leading()
        self._leading = leading
        return leading

    def run_once(self) -> bool:
        """One election tick; call every retry_period (LeaderElector.Run's
        wait.JitterUntil body, unrolled for the pump-driven runtime)."""
        return self.try_acquire_or_renew()
