"""DeltaFIFO (client-go tools/cache/delta_fifo.go:97).

A producer/consumer queue keyed by object key where each entry accumulates
the ordered list of deltas (Added/Updated/Deleted/Replaced/Sync) seen since
the consumer last popped that key. Replace() implements the relist
reconciliation: it emits Replaced for every listed object and synthesizes
Deleted for known objects missing from the list (delta_fifo.go:515 Replace).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

ADDED = "Added"
UPDATED = "Updated"
DELETED = "Deleted"
REPLACED = "Replaced"
SYNC = "Sync"


@dataclass(frozen=True)
class Delta:
    type: str
    object: object


class DeltaFIFO:
    def __init__(self, key_fn: Callable[[object], str], known_objects: Optional[Callable[[], List[str]]] = None):
        """key_fn: object → cache key. known_objects: () → keys the consumer's
        store currently holds (for Replace's deleted-object detection)."""
        self._key_fn = key_fn
        self._known = known_objects
        self._lock = threading.Condition()
        self._items: Dict[str, List[Delta]] = {}
        self._queue: List[str] = []
        self.populated = False
        self.initial_population_count = 0

    def _key_of(self, obj) -> str:
        return self._key_fn(obj)

    def _queue_action(self, action: str, obj) -> None:  # ktpu: locked
        key = self._key_of(obj)
        deltas = self._items.get(key)
        if deltas is None:
            self._items[key] = [Delta(action, obj)]
            self._queue.append(key)
        else:
            deltas.append(Delta(action, obj))
            self._dedup(key)
        self._lock.notify_all()

    def _dedup(self, key: str) -> None:  # ktpu: locked
        """Collapse two consecutive Deleted deltas (delta_fifo.go dedupDeltas)."""
        deltas = self._items[key]
        if len(deltas) >= 2 and deltas[-1].type == DELETED and deltas[-2].type == DELETED:
            self._items[key] = deltas[:-2] + [deltas[-1]]

    def add(self, obj) -> None:
        with self._lock:
            self.populated = True
            self._queue_action(ADDED, obj)

    def update(self, obj) -> None:
        with self._lock:
            self.populated = True
            self._queue_action(UPDATED, obj)

    def delete(self, obj) -> None:
        with self._lock:
            self.populated = True
            self._queue_action(DELETED, obj)

    def replace(self, objects: List[object]) -> None:
        """Relist reconciliation (delta_fifo.go:515): Replaced for each listed
        object; synthesized Deleted for known-but-absent objects."""
        with self._lock:
            keys = set()
            for obj in objects:
                keys.add(self._key_of(obj))
                self._queue_action(REPLACED, obj)
            # Union of the consumer store's keys AND keys with queued un-popped
            # deltas: a key whose Added is still queued but which is absent
            # from the relist would otherwise never get a tombstone, leaving a
            # deleted object in the informer cache until the next relist
            # (client-go's Replace scans queued items for exactly this case).
            known = set(self._known()) if self._known is not None else set()
            known.update(self._items.keys())
            for key in known:
                if key not in keys:
                    # deleted while we were disconnected; tombstone carries
                    # the last known object if any
                    deltas = self._items.get(key)
                    last = deltas[-1].object if deltas else None
                    if last is None and self._known is not None:
                        last = key  # DeletedFinalStateUnknown analog: key only
                    if deltas is None:
                        self._items[key] = [Delta(DELETED, last)]
                        self._queue.append(key)
                    else:
                        deltas.append(Delta(DELETED, last))
                        self._dedup(key)
            if not self.populated:
                self.populated = True
                self.initial_population_count = len(self._queue)
            self._lock.notify_all()

    def pop(self, timeout: float = 0.0) -> Optional[List[Delta]]:
        """Pop the oldest key's accumulated deltas; None when empty after
        timeout (the reference blocks; callers here pump)."""
        with self._lock:
            if not self._queue and timeout > 0:
                self._lock.wait(timeout)
            if not self._queue:
                return None
            key = self._queue.pop(0)
            deltas = self._items.pop(key)
            if self.initial_population_count > 0:
                self.initial_population_count -= 1
            return deltas

    def has_synced(self) -> bool:
        """True once the initial Replace has been fully popped
        (delta_fifo.go HasSynced)."""
        with self._lock:
            return self.populated and self.initial_population_count == 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)
