"""Reflector (client-go tools/cache/reflector.go:49).

ListAndWatch one kind from the store into a DeltaFIFO: LIST at a
resourceVersion, Replace() the FIFO, then stream WATCH events; on a watch
expiry (410 Gone) relist from scratch (reflector.go:254,440).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..apiserver.store import ADDED, DELETED, Expired, MODIFIED, Watch
from .delta_fifo import DeltaFIFO

logger = logging.getLogger(__name__)


class Reflector:
    def __init__(self, store, kind: str, fifo: DeltaFIFO):
        self.store = store
        self.kind = kind
        self.fifo = fifo
        self.last_sync_rv = 0
        self._watch: Optional[Watch] = None
        self._stop = threading.Event()

    # -- the ListAndWatch pieces, callable stepwise (tests/pump) or via run()

    def list_and_establish_watch(self) -> None:
        """LIST → fifo.Replace → open WATCH at the list rv (reflector.go:254)."""
        if self._watch is not None:
            self._watch.stop()
            self._watch = None
        objects, rv = self.store.list_objects(self.kind)
        self.fifo.replace(objects)
        self.last_sync_rv = rv
        self._watch = self.store.watch(self.kind, since=rv)

    def step(self, timeout: float = 0.0) -> int:
        """Drain available watch events into the FIFO; returns count.
        Re-lists transparently on journal expiry (the 410 path)."""
        if self._watch is None:
            self.list_and_establish_watch()
        assert self._watch is not None
        n = 0
        while True:
            ev = self._watch.next(timeout=timeout if n == 0 else 0.0)
            if ev is None:
                return n
            n += 1
            self.last_sync_rv = ev.seq
            if ev.type == ADDED:
                self.fifo.add(ev.object)
            elif ev.type == MODIFIED:
                self.fifo.update(ev.object)
            elif ev.type == DELETED:
                self.fifo.delete(ev.object)

    def relist(self) -> None:
        """Forced relist (watch error / Expired): reconcile via Replace."""
        try:
            self.list_and_establish_watch()
        except Expired:
            logger.warning("reflector %s: relist raced with compaction; retrying", self.kind)
            self.list_and_establish_watch()

    def run(self, poll_interval: float = 0.05) -> threading.Thread:
        """Background ListAndWatch loop (Reflector.Run)."""

        def _loop():
            while not self._stop.is_set():
                try:
                    self.step(timeout=poll_interval)
                except Expired:
                    self.relist()

        t = threading.Thread(target=_loop, name=f"reflector-{self.kind}", daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        if self._watch is not None:
            self._watch.stop()
