"""Workqueue (client-go util/workqueue): dedup FIFO with per-item
exponential-backoff rate limiting, plus the chunked parallel-for that backs
the scheduler's Parallelizer (workqueue.ParallelizeUntil).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Dict, List, Optional


class RateLimitingQueue:
    """Dedup work queue + per-item exponential backoff
    (workqueue/{queue,delaying_queue,rate_limiting_queue}.go). Items being
    processed that are re-added are marked dirty and requeued on done()
    (queue.go's dirty/processing sets)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0,
                 now_fn=time.monotonic):
        self._lock = threading.Condition()
        self._queue: List[object] = []
        self._dirty: set = set()
        self._processing: set = set()
        self._failures: Dict[object, int] = {}
        self._waiting: List = []  # heap of (ready_at, seq, item)
        self._seq = itertools.count()
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.now_fn = now_fn
        self._shutdown = False

    # -- plain queue

    def add(self, item) -> None:
        with self._lock:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # requeued by done()
            self._queue.append(item)
            self._lock.notify()

    def get(self, timeout: float = 0.0) -> Optional[object]:
        with self._lock:
            self._flush_waiting_locked()
            if not self._queue and timeout > 0:
                self._lock.wait(timeout)
                self._flush_waiting_locked()
            if not self._queue:
                return None
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item) -> None:
        with self._lock:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._lock.notify()

    # -- rate-limited add

    def num_requeues(self, item) -> int:
        with self._lock:
            return self._failures.get(item, 0)

    def add_rate_limited(self, item) -> None:
        """Queue after the item's exponential backoff delay
        (rate_limiting_queue.go AddRateLimited + ItemExponentialFailureRateLimiter)."""
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
            delay = min(self.base_delay * (2 ** n), self.max_delay)
            heapq.heappush(self._waiting, (self.now_fn() + delay, next(self._seq), item))

    def forget(self, item) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def _flush_waiting_locked(self) -> None:
        now = self.now_fn()
        while self._waiting and self._waiting[0][0] <= now:
            _, _, item = heapq.heappop(self._waiting)
            if item not in self._dirty and item not in self._processing:
                self._dirty.add(item)
                self._queue.append(item)

    def flush_waiting(self) -> None:
        with self._lock:
            self._flush_waiting_locked()
            self._lock.notify_all()

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)


def chunk_size_for(n: int, parallelism: int) -> int:
    """max(1, min(√n, n/parallelism+1)) — the scheduler Parallelizer's
    chunking (parallelize/parallelism.go:41 chunkSizeFor)."""
    return max(1, min(int(n ** 0.5), n // parallelism + 1))


def parallelize_until(workers: int, pieces: int, do_work: Callable[[int], None],
                      chunk_size: Optional[int] = None) -> None:
    """workqueue.ParallelizeUntil: run do_work(0..pieces-1) over a worker
    pool in chunks. Sequential when workers<=1 or the work is tiny (the
    Python analog: threads only pay off for released-GIL work)."""
    if pieces <= 0:
        return
    if chunk_size is None:
        chunk_size = chunk_size_for(pieces, max(workers, 1))
    if workers <= 1 or pieces <= chunk_size:
        for i in range(pieces):
            do_work(i)
        return
    chunks = [(s, min(s + chunk_size, pieces)) for s in range(0, pieces, chunk_size)]
    idx_lock = threading.Lock()
    pos = itertools.count()

    def _worker():
        while True:
            with idx_lock:
                i = next(pos)
            if i >= len(chunks):
                return
            start, end = chunks[i]
            for j in range(start, end):
                do_work(j)

    threads = [threading.Thread(target=_worker, daemon=True) for _ in range(min(workers, len(chunks)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
