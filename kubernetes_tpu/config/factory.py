"""Config → running Scheduler (cmd/kube-scheduler/app Setup analog:
server.go:300 — decode config, build registries/profiles, construct)."""

from __future__ import annotations

from typing import Optional

from ..apiserver.store import ClusterStore
from ..scheduler.extender import build_extenders
from ..scheduler.scheduler import Scheduler
from .types import KubeSchedulerConfiguration, expand_profile, load_config


def scheduler_from_config(
    store: ClusterStore,
    cfg: Optional[KubeSchedulerConfiguration] = None,
    raw: Optional[dict] = None,
    registry=None,
    out_of_tree_registry: Optional[dict] = None,
    scheduler_cls=None,
    **scheduler_kwargs,
) -> Scheduler:
    """Build a Scheduler from a KubeSchedulerConfiguration (or its raw dict
    form).  ``out_of_tree_registry`` merges extra plugin factories, the
    app.WithPlugin hook (server.go:293)."""
    if cfg is None:
        cfg = load_config(raw)
    if out_of_tree_registry:
        from ..framework.registry import in_tree_registry

        merged = in_tree_registry()
        for name, factory in out_of_tree_registry.items():
            if name in merged:
                raise ValueError(f"plugin {name!r} already registered")
            merged[name] = factory
        registry = merged

    profiles = {
        p.scheduler_name: {
            "plugin_config": expand_profile(p),
            "plugin_args": p.plugin_config,
            "registry": registry,
        }
        for p in cfg.profiles
    }
    cls = scheduler_cls or Scheduler
    return cls(
        store,
        profiles=profiles,
        percentage_of_nodes_to_score=cfg.percentage_of_nodes_to_score,
        pod_initial_backoff=cfg.pod_initial_backoff_seconds,
        pod_max_backoff=cfg.pod_max_backoff_seconds,
        extenders=build_extenders(cfg.extenders),
        **scheduler_kwargs,
    )
