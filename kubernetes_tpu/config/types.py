"""Scheduler component configuration API.

Analog of pkg/scheduler/apis/config/types.go (:41 KubeSchedulerConfiguration,
:102 KubeSchedulerProfile, :129 Plugins/PluginSet) with v1beta3 defaulting
(apis/config/v1beta3/defaults.go:104-160) and MultiPoint expansion
(runtime/framework.go:430).  The on-disk form is a plain dict (YAML/JSON
decodes to it); ``load_config`` is the scheme decode+default+validate path
(scheduler_perf_test.go:584 loadSchedulerConfig analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..framework.interface import EXTENSION_POINTS
from ..framework.registry import DEFAULT_PLUGINS

API_VERSION = "kubescheduler.config.k8s.io/v1beta3"
API_VERSION_V1BETA2 = "kubescheduler.config.k8s.io/v1beta2"
SUPPORTED_VERSIONS = (API_VERSION, API_VERSION_V1BETA2)

# name used when a profile doesn't set one (v1beta3/defaults.go)
DEFAULT_SCHEDULER_NAME = "default-scheduler"

# camelCase extension-point names as they appear in config files → internal
_POINT_NAMES = {
    "queueSort": "queue_sort",
    "preEnqueue": "pre_enqueue",
    "preFilter": "pre_filter",
    "filter": "filter",
    "postFilter": "post_filter",
    "preScore": "pre_score",
    "score": "score",
    "reserve": "reserve",
    "permit": "permit",
    "preBind": "pre_bind",
    "bind": "bind",
    "postBind": "post_bind",
}
_MULTI_POINT = "multiPoint"

# which points carry weights (only score does)
_WEIGHTED_POINTS = {"score"}

# default weights used when MultiPoint enables a scoring plugin without an
# explicit weight (default_plugins.go:32-51)
_DEFAULT_SCORE_WEIGHTS = {name: w for name, w in DEFAULT_PLUGINS["score"]}


@dataclass
class PluginEntry:
    name: str
    weight: int = 0


@dataclass
class PluginSet:
    enabled: List[PluginEntry] = field(default_factory=list)
    disabled: List[PluginEntry] = field(default_factory=list)


@dataclass
class Profile:
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    # point (internal name) -> PluginSet; "multiPoint" handled at expansion
    plugins: Dict[str, PluginSet] = field(default_factory=dict)
    multi_point: PluginSet = field(default_factory=PluginSet)
    plugin_config: Dict[str, dict] = field(default_factory=dict)  # plugin name -> args


@dataclass
class Extender:
    """HTTP extender config (apis/config/types.go:246 Extender)."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    enable_https: bool = False
    node_cache_capable: bool = False
    ignorable: bool = False
    managed_resources: Tuple[str, ...] = ()
    # in-process escape hatch: tests can hand a callable extender directly
    instance: Optional[object] = None


@dataclass
class KubeSchedulerConfiguration:
    parallelism: int = 16
    percentage_of_nodes_to_score: int = 0  # 0 = adaptive
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    profiles: List[Profile] = field(default_factory=lambda: [Profile()])
    extenders: List[Extender] = field(default_factory=list)
    api_version: str = API_VERSION
    # leaderElection (component-base/config LeaderElectionConfiguration)
    leader_elect: bool = True
    leader_elect_lease_duration: float = 15.0
    leader_elect_renew_deadline: float = 10.0
    leader_elect_retry_period: float = 2.0
    # clientConnection envelope (qps/burst; scheduler_perf uses 5000/5000)
    client_qps: float = 50.0
    client_burst: int = 100


class ConfigError(ValueError):
    pass


def _parse_duration(v) -> float:
    """metav1.Duration string ('15s', '2m30s', '100ms') or number → seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    units = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
    total, num = 0.0, ""
    i = 0
    while i < len(s):
        c = s[i]
        if c.isdigit() or c == ".":
            num += c
            i += 1
            continue
        for u in ("ms", "h", "m", "s"):
            if s.startswith(u, i):
                if not num:
                    raise ConfigError(f"invalid duration {v!r}")
                total += float(num) * units[u]
                num = ""
                i += len(u)
                break
        else:
            raise ConfigError(f"invalid duration {v!r}")
    if num:  # bare number tail
        total += float(num)
    return total


# ---------------------------------------------------------------------------
# decode


def _decode_plugin_set(raw: dict) -> PluginSet:
    ps = PluginSet()
    for e in raw.get("enabled", []) or []:
        if isinstance(e, str):
            ps.enabled.append(PluginEntry(e))
        else:
            ps.enabled.append(PluginEntry(e["name"], int(e.get("weight", 0))))
    for e in raw.get("disabled", []) or []:
        name = e if isinstance(e, str) else e["name"]
        ps.disabled.append(PluginEntry(name))
    return ps


def load_config(raw: Optional[dict]) -> KubeSchedulerConfiguration:
    """Decode a config dict (the YAML object form), apply defaults, validate."""
    cfg = KubeSchedulerConfiguration()
    raw = raw or {}
    if "apiVersion" in raw and raw["apiVersion"] not in SUPPORTED_VERSIONS:
        raise ConfigError(f"unsupported apiVersion {raw['apiVersion']!r}")
    # v1beta2 → internal conversion: same field surface for what this
    # framework models; v1beta2 predates multiPoint, which simply won't
    # appear in such configs (apis/config/v1beta2/conversion.go)
    cfg.api_version = raw.get("apiVersion", API_VERSION)

    le = raw.get("leaderElection") or {}
    cfg.leader_elect = bool(le.get("leaderElect", cfg.leader_elect))
    cfg.leader_elect_lease_duration = float(
        _parse_duration(le.get("leaseDuration", cfg.leader_elect_lease_duration)))
    cfg.leader_elect_renew_deadline = float(
        _parse_duration(le.get("renewDeadline", cfg.leader_elect_renew_deadline)))
    cfg.leader_elect_retry_period = float(
        _parse_duration(le.get("retryPeriod", cfg.leader_elect_retry_period)))

    cc = raw.get("clientConnection") or {}
    cfg.client_qps = float(cc.get("qps", cfg.client_qps))
    cfg.client_burst = int(cc.get("burst", cfg.client_burst))
    cfg.parallelism = int(raw.get("parallelism", cfg.parallelism))
    cfg.percentage_of_nodes_to_score = int(
        raw.get("percentageOfNodesToScore", cfg.percentage_of_nodes_to_score)
    )
    cfg.pod_initial_backoff_seconds = float(
        raw.get("podInitialBackoffSeconds", cfg.pod_initial_backoff_seconds)
    )
    cfg.pod_max_backoff_seconds = float(
        raw.get("podMaxBackoffSeconds", cfg.pod_max_backoff_seconds)
    )

    if "profiles" in raw and raw["profiles"]:
        cfg.profiles = []
        for rp in raw["profiles"]:
            p = Profile(scheduler_name=rp.get("schedulerName", DEFAULT_SCHEDULER_NAME))
            for raw_point, internal in _POINT_NAMES.items():
                if raw_point in (rp.get("plugins") or {}):
                    p.plugins[internal] = _decode_plugin_set(rp["plugins"][raw_point])
            if _MULTI_POINT in (rp.get("plugins") or {}):
                p.multi_point = _decode_plugin_set(rp["plugins"][_MULTI_POINT])
            for pc in rp.get("pluginConfig", []) or []:
                p.plugin_config[pc["name"]] = pc.get("args", {}) or {}
            cfg.profiles.append(p)

    if "extenders" in raw:
        for re_ in raw["extenders"]:
            cfg.extenders.append(
                Extender(
                    url_prefix=re_.get("urlPrefix", ""),
                    filter_verb=re_.get("filterVerb", ""),
                    prioritize_verb=re_.get("prioritizeVerb", ""),
                    bind_verb=re_.get("bindVerb", ""),
                    preempt_verb=re_.get("preemptVerb", ""),
                    weight=int(re_.get("weight", 1)),
                    enable_https=bool(re_.get("enableHTTPS", False)),
                    node_cache_capable=bool(re_.get("nodeCacheCapable", False)),
                    ignorable=bool(re_.get("ignorable", False)),
                    managed_resources=tuple(
                        m["name"] if isinstance(m, dict) else m
                        for m in re_.get("managedResources", [])
                    ),
                )
            )

    validate_config(cfg)
    return cfg


# ---------------------------------------------------------------------------
# validation (apis/config/validation/validation.go)


def validate_config(cfg: KubeSchedulerConfiguration) -> None:
    if cfg.parallelism <= 0:
        raise ConfigError("parallelism must be greater than 0")
    if not (0 <= cfg.percentage_of_nodes_to_score <= 100):
        raise ConfigError("percentageOfNodesToScore must be in [0, 100]")
    if cfg.pod_initial_backoff_seconds <= 0:
        raise ConfigError("podInitialBackoffSeconds must be greater than 0")
    if cfg.pod_max_backoff_seconds < cfg.pod_initial_backoff_seconds:
        raise ConfigError("podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
    if not cfg.profiles:
        raise ConfigError("at least one profile is required")
    names = [p.scheduler_name for p in cfg.profiles]
    if len(set(names)) != len(names):
        raise ConfigError("duplicated scheduler name in profiles")
    for p in cfg.profiles:
        if not p.scheduler_name:
            raise ConfigError("schedulerName is needed")
        for point, ps in p.plugins.items():
            if point not in EXTENSION_POINTS:
                raise ConfigError(f"unknown extension point {point!r}")
            seen = set()
            for e in ps.enabled:
                if e.name in seen:
                    raise ConfigError(f"duplicated enabled plugin {e.name!r} at {point}")
                seen.add(e.name)
    for ext in cfg.extenders:
        if ext.instance is None and not ext.url_prefix:
            raise ConfigError("extender urlPrefix is required")
        if ext.weight <= 0:
            raise ConfigError("extender weight must be positive")


# ---------------------------------------------------------------------------
# expansion: defaults + profile overrides -> framework plugin_config


def expand_profile(profile: Profile) -> Dict[str, List[Tuple[str, int]]]:
    """Merge the default plugin set with the profile's per-point
    enable/disable and MultiPoint shorthand (runtime/framework.go:430).

    Order semantics (the reference's expandMultiPointPlugins + mergePlugins):
    defaults first (minus disabled), then profile-enabled appended in config
    order; '*' in disabled clears the whole default set for that point.
    """
    out: Dict[str, List[Tuple[str, int]]] = {}

    # MultiPoint: a plugin listed there joins every point it implements — at
    # config level we can't introspect implementations, so MultiPoint entries
    # are offered to every point and the Framework keeps only those whose
    # instance actually implements the point's method (registry factories
    # produce one instance per name, so this is safe and cheap).
    mp_enabled = [(e.name, e.weight) for e in profile.multi_point.enabled]
    mp_disabled = {e.name for e in profile.multi_point.disabled}

    for point in EXTENSION_POINTS:
        defaults = list(DEFAULT_PLUGINS.get(point, []))
        ps = profile.plugins.get(point)
        disabled = {e.name for e in ps.disabled} if ps else set()
        if "*" in disabled or "*" in mp_disabled:
            merged: List[Tuple[str, int]] = []
        else:
            merged = [
                (n, w) for (n, w) in defaults if n not in disabled and n not in mp_disabled
            ]
        if ps:
            have = {n for n, _ in merged}
            for e in ps.enabled:
                w = e.weight
                if point in _WEIGHTED_POINTS and w == 0:
                    w = _DEFAULT_SCORE_WEIGHTS.get(e.name, 1)
                if e.name in have:
                    # re-enabling overrides weight and moves to the back
                    merged = [(n, ww) for (n, ww) in merged if n != e.name]
                merged.append((e.name, w))
        for name, w in mp_enabled:
            if name not in {n for n, _ in merged}:
                ww = w
                if point in _WEIGHTED_POINTS and ww == 0:
                    ww = _DEFAULT_SCORE_WEIGHTS.get(name, 1)
                merged.append((name, ww))
        out[point] = merged
    return out
