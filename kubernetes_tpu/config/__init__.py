"""Scheduler component-config API (KubeSchedulerConfiguration)."""

from .types import (
    API_VERSION,
    ConfigError,
    DEFAULT_SCHEDULER_NAME,
    Extender,
    KubeSchedulerConfiguration,
    PluginEntry,
    PluginSet,
    Profile,
    expand_profile,
    load_config,
    validate_config,
)
from .factory import scheduler_from_config

__all__ = [
    "API_VERSION",
    "ConfigError",
    "DEFAULT_SCHEDULER_NAME",
    "Extender",
    "KubeSchedulerConfiguration",
    "PluginEntry",
    "PluginSet",
    "Profile",
    "expand_profile",
    "load_config",
    "validate_config",
    "scheduler_from_config",
]
