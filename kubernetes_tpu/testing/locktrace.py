"""Dynamic lock-order / blocking-under-lock tracer (the ``go test -race``
discipline for the concurrent device path, in the shape Python affords).

The repo's concurrent surface — N scheduler replicas sharing one
DeviceService, the serving threads of the HTTP binding, the multi-batch
in-flight ring, lease-fencing housekeeping — is guarded by a handful of
per-class locks. Two whole families of bugs are invisible to unit tests
there: *lock-order inversions* (thread 1 takes A then B, thread 2 takes B
then A: a deadlock that only fires under the right interleaving) and
*blocking work under a hot lock* (a device sync, an HTTP round-trip, or a
retry sleep held under the DeviceService lock starves every peer replica's
heartbeat until their leases fence).

This module makes both observable at test time:

  * ``make_lock(name)`` / ``make_rlock(name)`` are the lock FACTORY the
    concurrent classes construct their locks through
    (``backend/service.py``, ``queue/scheduling_queue.py``,
    ``cache/cache.py``, ``apiserver/store.py``). With ``KTPU_LOCKTRACE``
    unset they return plain ``threading`` primitives — zero overhead, the
    production path is byte-identical. Under ``KTPU_LOCKTRACE=1`` they
    return traced wrappers that record, per thread, the stack of held lock
    names and fold every (held → acquired) pair into a global lock-order
    graph.

  * ``tracer().cycles()`` returns every order-inversion cycle in that
    graph — the chaos/active-active suites run with tracing on and assert
    it is empty (``assert_clean()``).

  * ``note_blocking(kind, detail)`` marks the known blocking seams (device
    dispatch, socket IO, retry sleeps, WAL fsync). Fired while the thread
    holds any traced lock it records a blocking-under-lock event; the
    deliberate, reviewed holds pass ``allowed="reason"`` and land in a
    separate ledger — an event in ``blocking_violations`` is always a bug.

Determinism note: the tracer observes the interleavings a test actually
drives, so it catches *potential* deadlocks (the A→B plus B→A edges) even
when the run never wedged — edges accumulate across threads and calls, the
cycle check is over the whole graph, not one schedule.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

_ENV = "KTPU_LOCKTRACE"


def enabled() -> bool:
    """Tracing requested via the environment. Read per call (tests flip it
    with monkeypatch.setenv); the cost is one dict lookup and it sits only
    at lock CONSTRUCTION time and inside ``note_blocking``."""
    return os.environ.get(_ENV, "") not in ("", "0")


def _call_site() -> str:
    """file:line of the nearest caller outside this module."""
    for frame in reversed(traceback.extract_stack(limit=12)[:-1]):
        if not frame.filename.endswith("locktrace.py"):
            return f"{os.path.basename(frame.filename)}:{frame.lineno}"
    return "?"


class LockTracer:
    """Global acquisition recorder: per-thread held-lock stacks feeding one
    process-wide lock-order graph + blocking-event ledgers."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        # (held, acquired) -> {"count", "site"}: "site" is the first place
        # the edge was observed (enough to find the nested acquire)
        self.edges: Dict[Tuple[str, str], Dict[str, object]] = {}
        self.acquisitions: Dict[str, int] = {}
        self.blocking_violations: List[dict] = []
        self.blocking_allowed: List[dict] = []

    # ------------------------------------------------------------ per-thread

    def held(self) -> List[str]:
        """This thread's stack of held traced-lock names (outermost first;
        reentrant RLock acquisitions appear once per acquire)."""
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def on_acquire(self, name: str) -> None:
        held = self.held()
        new_edges = [(h, name) for h in set(held) if h != name]
        # stack extraction is the expensive part: do it outside _mu and only
        # when some edge looks unseen (GIL-atomic optimistic read; a racing
        # first-observer just means one discarded extraction)
        site = None
        if new_edges and any(e not in self.edges for e in new_edges):  # ktpu: unguarded-ok(optimistic membership probe; the locked section below re-checks and a racing first-observer only costs one discarded stack extraction)
            site = _call_site()
        with self._mu:
            self.acquisitions[name] = self.acquisitions.get(name, 0) + 1
            for edge in new_edges:
                rec = self.edges.get(edge)
                if rec is None:
                    self.edges[edge] = {"count": 1,
                                        "site": site or _call_site()}
                else:
                    rec["count"] += 1
        held.append(name)

    def on_release(self, name: str) -> None:
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def on_blocking(self, kind: str, detail: str,
                    allowed: Optional[str] = None) -> None:
        held = self.held()
        if not held:
            return
        rec = {"kind": kind, "detail": detail,
               "locks": list(dict.fromkeys(held)),
               "site": _call_site(), "allowed": allowed}
        with self._mu:
            (self.blocking_allowed if allowed
             else self.blocking_violations).append(rec)

    # --------------------------------------------------------------- queries

    def cycles(self) -> List[List[str]]:
        """Every elementary order-inversion cycle in the lock-order graph
        (names in traversal order; a cycle means two threads CAN deadlock
        by taking the cycle's locks in opposite orders)."""
        with self._mu:
            adj: Dict[str, List[str]] = {}
            for a, b in self.edges:
                adj.setdefault(a, []).append(b)
        out: List[List[str]] = []
        seen_cycles = set()
        for start in sorted(adj):
            # DFS from each node; report cycles that return to `start` so
            # each cycle is found once (rotated to its smallest member)
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in adj.get(node, ()):
                    if nxt == start:
                        lo = path.index(min(path))
                        canon = tuple(path[lo:] + path[:lo])
                        if canon not in seen_cycles:
                            seen_cycles.add(canon)
                            out.append(list(canon))
                    elif nxt not in path and nxt > start:
                        # only walk nodes > start: every cycle is reported
                        # from its smallest member exactly once
                        stack.append((nxt, path + [nxt]))
        return out

    def report(self) -> dict:
        with self._mu:
            return {
                "acquisitions": dict(self.acquisitions),
                "edges": {f"{a} -> {b}": dict(v)
                          for (a, b), v in sorted(self.edges.items())},
                "blockingViolations": list(self.blocking_violations),
                "blockingAllowed": list(self.blocking_allowed),
            }


_tracer = LockTracer()


def tracer() -> LockTracer:
    return _tracer


def reset() -> None:
    """Fresh tracer (test isolation). Locks constructed earlier keep
    reporting into the new tracer — the wrappers resolve ``tracer()`` per
    call, never capture it."""
    global _tracer
    _tracer = LockTracer()


def assert_clean() -> None:
    """Raise AssertionError naming every lock-order cycle and every
    non-allowed blocking-under-lock event observed so far — the chaos
    suites' one-line postcondition."""
    t = tracer()
    problems = []
    for cyc in t.cycles():
        problems.append("lock-order cycle: " + " -> ".join(cyc + [cyc[0]]))
    for ev in t.blocking_violations:
        problems.append(
            f"blocking under lock: {ev['kind']} ({ev['detail']}) at "
            f"{ev['site']} while holding {ev['locks']}")
    if problems:
        raise AssertionError("locktrace found:\n  " + "\n  ".join(problems))


# ------------------------------------------------------------------ wrappers


class TracedLock:
    """threading.Lock/RLock wrapper reporting acquisitions to the tracer.
    Context-manager and acquire/release compatible; anything else proxies
    to the wrapped primitive."""

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            tracer().on_acquire(self.name)
        return ok

    def release(self) -> None:
        tracer().on_release(self.name)
        self._inner.release()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def make_lock(name: str):
    """Factory for a class's mutex: plain ``threading.Lock`` in production,
    a traced wrapper under KTPU_LOCKTRACE=1. ``name`` is the lock's node in
    the order graph — one name per protected component."""
    inner = threading.Lock()
    return TracedLock(name, inner) if enabled() else inner


def make_rlock(name: str):
    """``make_lock`` for reentrant locks (reentrant re-acquisition records
    no self-edge; the held stack tracks each level so release balances)."""
    inner = threading.RLock()
    return TracedLock(name, inner) if enabled() else inner


def note_blocking(kind: str, detail: str = "",
                  allowed: Optional[str] = None) -> None:
    """Mark a blocking operation (device dispatch, socket IO, sleep, fsync)
    at its call site. Free when tracing is off (one env read); under
    tracing it records an event IF the calling thread holds any traced
    lock. ``allowed="why"`` documents a reviewed deliberate hold — those
    land in a separate ledger and never fail ``assert_clean()``."""
    if not enabled():
        return
    tracer().on_blocking(kind, detail, allowed=allowed)
