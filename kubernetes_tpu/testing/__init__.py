"""Test infrastructure (the client-go fake clientset + reaction-hook role,
kubernetes/fake/clientset_generated.go + testing/fixture.go).
"""

from .faults import Fault, FaultPlan
from .reactors import ReactionError, with_reactors

__all__ = ["Fault", "FaultPlan", "ReactionError", "with_reactors"]
