"""Test infrastructure (the client-go fake clientset + reaction-hook role,
kubernetes/fake/clientset_generated.go + testing/fixture.go).
"""

from .reactors import ReactionError, with_reactors

__all__ = ["ReactionError", "with_reactors"]
