"""Test infrastructure (the client-go fake clientset + reaction-hook role,
kubernetes/fake/clientset_generated.go + testing/fixture.go) — plus the
lock-order/race tracer the production lock factories route through
(locktrace; a plain ``threading`` primitive unless KTPU_LOCKTRACE=1).
"""

from . import locktrace
from .faults import Fault, FaultPlan
from .reactors import ReactionError, with_reactors

__all__ = ["Fault", "FaultPlan", "ReactionError", "locktrace",
           "with_reactors"]
