"""Reaction hooks: inject failures/observations into store writes
(client-go testing/fixture.go's PrependReactor pattern).

``with_reactors(store)`` wraps a ClusterStore's mutating methods so tests can
intercept verbs — return True to swallow the call, raise to inject an error,
return False/None to let the real method run:

    tracker = with_reactors(store)
    tracker.prepend("bind", lambda verb, args: raise_(Conflict("boom")))
"""

from __future__ import annotations

import functools
from typing import Callable, List, Tuple

VERBS = (
    "create_pod", "update_pod", "delete_pod", "bind",
    "create_node", "update_node", "delete_node",
    "create_lease", "update_lease", "bind_pv",
    "create_object", "update_object", "delete_object",
)


class ReactionError(Exception):
    """Raised by tests through a reactor to simulate server errors."""


class ReactorTracker:
    def __init__(self, store):
        self.store = store
        self.reactors: List[Tuple[str, Callable]] = []
        self.calls: List[Tuple[str, tuple]] = []  # observed (verb, args)
        self._wrap_all()

    def prepend(self, verb: str, fn: Callable) -> None:
        """fn(verb, args) -> truthy to swallow the call; may raise."""
        if verb != "*" and verb not in VERBS:
            raise ValueError(f"unknown verb {verb!r}")
        self.reactors.insert(0, (verb, fn))

    def _wrap_all(self) -> None:
        for verb in VERBS:
            original = getattr(self.store, verb)

            def make(verb=verb, original=original):
                @functools.wraps(original)
                def wrapped(*args, **kwargs):
                    self.calls.append((verb, args))
                    for want, fn in list(self.reactors):
                        if want in ("*", verb) and fn(verb, args):
                            return None
                    return original(*args, **kwargs)

                return wrapped

            setattr(self.store, verb, make())


def with_reactors(store) -> ReactorTracker:
    return ReactorTracker(store)


def raise_(exc: Exception):
    """Helper for lambda reactors: ``lambda v, a: raise_(ReactionError())``."""
    raise exc
