"""Scripted device-fault injection (the chaosmonkey Do/Setup analog for
the device-service seam, test/e2e/chaosmonkey/chaosmonkey.go).

A ``FaultPlan`` is a deterministic script of transport/service failures
consumed in order, wired into two interception points:

  * client side (``WireClient``/``GrpcClient``): a fault fires BEFORE the
    request touches the network — ``drop`` raises the same transient error
    a refused connection would, ``delay`` raises the read-timeout error a
    slow service would (no wall-clock sleep: the injected latency is
    compared against the client's read deadline), ``error`` raises a
    transient error N times (error-once / error-N).
  * server side (``serve``'s handler): ``error`` answers 503 (transient on
    the client's taxonomy), ``crash`` replaces the served DeviceService
    with a FRESH instance — new process epoch, empty DeviceState — and
    severs the connection without a response, exactly what a sidecar
    segfault+restart looks like from the client; ``conflict`` answers the
    409 + ``conflict: true`` cross-client race verdict (HA taxonomy).

HA-fabric primitives (per-ENDPOINT scoping comes from attaching one plan
per endpoint client): ``partition()`` persistently drops the batch-path
verbs while Health still answers (the asymmetric partition a health-only
detector never catches), ``slow()`` injects persistent per-call latency
(below the read deadline = laggy-but-live, at/above = dead), ``kill()``
persistently drops everything, and ``heal()`` lifts persistent faults.

Stream-level primitives (the pipelined-transport failure modes — K batches
in flight, replies matched by batchId):

  * ``torn(op)`` — server side: the request is PROCESSED (the service
    commits) but the connection is severed before the reply leaves — the
    lost-response case whose only safe recovery is the idempotent-batchId
    replay. Distinct from ``crash``: the service survives with its state.
  * ``dup_reply(op)`` — reply side: the reply is DELIVERED TWICE into the
    pipelined reply router (a retransmit duplicate); the router must drop
    the second copy by batchId, never double-process.
  * ``reorder(op)`` — reply side: the next TWO replies swap delivery order
    across pipeline lanes (each lane receives the OTHER call's reply), so
    the router's match-by-batchId is exercised for real, not incidentally.

Reply-side faults live in their own queue (side=``reply``) and are
consumed by the pipelined transport's reply router (``next_reply``), never
by ``raise_injected_fault`` — a request-side script cannot accidentally
swallow them.

Every consumed fault is appended to ``log`` so tests assert the script
actually fired. Thread-safe: handler threads and the scheduling thread
consume concurrently.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

APPLY_DELTAS = "apply_deltas"
SCHEDULE_BATCH = "schedule_batch"
ANY = "*"

CLIENT = "client"
SERVER = "server"
REPLY = "reply"


class _Rendezvous:
    """Two-party reply swap: each party deposits its reply and receives the
    OTHER party's. The first arrival waits (bounded) for the second; if the
    partner never comes — the script fired but only one call happened — the
    party falls back to its own reply so a test bug reads as an assertion
    failure, not a hang."""

    def __init__(self, timeout_s: float = 10.0):
        self.cv = threading.Condition()
        self.slots: List[object] = []
        self.timeout_s = timeout_s

    def swap(self, reply):
        with self.cv:
            idx = len(self.slots)
            self.slots.append(reply)
            if idx == 0:
                self.cv.wait_for(lambda: len(self.slots) >= 2,
                                 timeout=self.timeout_s)
                return self.slots[1] if len(self.slots) >= 2 else reply
            self.cv.notify_all()
            return self.slots[0]


@dataclasses.dataclass
class Fault:
    kind: str            # "error" | "delay" | "drop" | "crash" | "conflict"
    #                    # | "torn" | "dup" | "reorder"
    count: int = 1       # calls this fault applies to; -1 = persistent
    seconds: float = 0.0  # injected latency ("delay" only)
    status: int = 503    # HTTP status for server-side "error"
    rendezvous: object = None  # "reorder" only: the two-party reply swap

    @property
    def persistent(self) -> bool:
        return self.count < 0


class FaultPlan:
    def __init__(self):
        self._lock = threading.Lock()
        # (side, op) -> FIFO of pending faults; ANY matches either op
        self._faults: Dict[Tuple[str, str], List[Fault]] = {}
        self.log: List[Tuple[str, str, str]] = []  # (side, op, kind)

    # ------------------------------------------------------------ authoring

    def inject(self, op: str, fault: Fault, side: str = CLIENT) -> "FaultPlan":
        with self._lock:
            queue = self._faults.setdefault((side, op), [])
            if any(f.persistent for f in queue):
                # a persistent fault never leaves the head of its queue,
                # so anything injected behind it would silently never
                # fire — reject the script instead of losing its intent
                raise ValueError(
                    f"({side}, {op}) already has a persistent fault; "
                    f"heal() it before injecting more")
            queue.append(fault)
        return self

    def error_once(self, op: str = ANY, side: str = CLIENT) -> "FaultPlan":
        return self.inject(op, Fault("error"), side=side)

    def error_n(self, n: int, op: str = ANY, side: str = CLIENT) -> "FaultPlan":
        return self.inject(op, Fault("error", count=n), side=side)

    def delay(self, seconds: float, op: str = ANY, count: int = 1) -> "FaultPlan":
        return self.inject(op, Fault("delay", count=count, seconds=seconds))

    def drop(self, op: str = ANY, count: int = 1) -> "FaultPlan":
        return self.inject(op, Fault("drop", count=count))

    def crash(self, op: str = ANY) -> "FaultPlan":
        return self.inject(op, Fault("crash"), side=SERVER)

    def conflict(self, op: str = ANY, count: int = 1) -> "FaultPlan":
        """Server answers 409 + ``conflict: true`` — the cross-client race
        verdict, scriptable without staging a real two-replica collision."""
        return self.inject(op, Fault("conflict", count=count), side=SERVER)

    # ------------------------------------------------- stream-level primitives

    def torn(self, op: str = ANY, count: int = 1) -> "FaultPlan":
        """Torn mid-stream disconnect: the server PROCESSES the request
        (state committed) but the connection dies before the reply leaves.
        The client sees a transport error for work that actually happened —
        recovery is the transport retry hitting the idempotent-batchId
        replay, never a re-commit."""
        return self.inject(op, Fault("torn", count=count), side=SERVER)

    def dup_reply(self, op: str = ANY, count: int = 1) -> "FaultPlan":
        """Duplicated delivery: the reply router receives the same reply
        twice (a retransmit duplicate on the stream). The router must drop
        the second copy by batchId."""
        return self.inject(op, Fault("dup", count=count), side=REPLY)

    def reorder(self, op: str = ANY) -> "FaultPlan":
        """Reordered replies: the next TWO calls' replies swap delivery
        lanes — each pipeline lane receives the OTHER call's reply, so only
        batchId matching can pair results with requests."""
        return self.inject(op, Fault("reorder", count=2,
                                     rendezvous=_Rendezvous()), side=REPLY)

    # ------------------------------------------------- HA-fabric primitives

    def partition(self, *ops: str) -> "FaultPlan":
        """Asymmetric network partition of ONE endpoint (attach this plan
        to that endpoint's client): batch traffic fails PERSISTENTLY while
        the Health verb still answers — the failure mode where a naive
        health-probe-only detector never fails over. Defaults to both
        batch-path verbs; pass explicit ops to narrow (e.g. only
        ``SCHEDULE_BATCH`` so delta pushes still land). ``heal()`` lifts
        it."""
        for op in (ops or (APPLY_DELTAS, SCHEDULE_BATCH)):
            self.inject(op, Fault("drop", count=-1))
        return self

    def slow(self, seconds: float, op: str = ANY) -> "FaultPlan":
        """Persistently slow endpoint: every matching call carries
        ``seconds`` of injected latency (deterministic — compared against
        the client's read deadline, never slept). Below the deadline the
        calls succeed slow (a laggy-but-live standby must NOT trigger
        failover); at/above it every call times out like a dead one."""
        return self.inject(op, Fault("delay", count=-1, seconds=seconds))

    def kill(self) -> "FaultPlan":
        """Endpoint death: every client-side call — Health included —
        fails persistently, what a killed sidecar process looks like from
        its clients. ``heal()`` is the restart-less recovery (partition
        healed / process back on the same epoch)."""
        return self.inject(ANY, Fault("drop", count=-1))

    def heal(self, op: Optional[str] = None,
             side: Optional[str] = None) -> "FaultPlan":
        """Remove pending faults (all of them by default, or only the
        given op/side): the partition heals, the slow replica catches up,
        the killed process answers again. Healing a specific op while a
        WILDCARD fault still covers it raises — a silent no-op there
        would leave the script believing the op recovered while every
        call keeps matching the ``*`` queue."""
        with self._lock:
            matched = False
            for key in list(self._faults):
                s, o = key
                if (op is None or o == op) and (side is None or s == side):
                    del self._faults[key]
                    matched = True
            if op is not None and op != ANY and not matched:
                wild = [key for key in self._faults
                        if key[1] == ANY and (side is None or key[0] == side)
                        and self._faults[key]]
                if wild:
                    raise ValueError(
                        f"heal(op={op!r}) matched no per-op fault, but a "
                        f"wildcard (op='*') fault still covers it — heal "
                        f"the wildcard (heal() / heal(op='*')) or inject "
                        f"per-op faults instead of kill()")
        return self

    # ------------------------------------------------------------ consuming

    def _take(self, side: str, op: str) -> Optional[Fault]:
        with self._lock:
            for key in ((side, op), (side, ANY)):
                queue = self._faults.get(key)
                if not queue:
                    continue
                fault = queue[0]
                if not fault.persistent:  # persistent faults never expire
                    fault.count -= 1
                    if fault.count <= 0:
                        queue.pop(0)
                self.log.append((side, op, fault.kind))
                return fault
            return None

    def next_client(self, op: str) -> Optional[Fault]:
        return self._take(CLIENT, op)

    def next_server(self, op: str) -> Optional[Fault]:
        return self._take(SERVER, op)

    def next_reply(self, op: str) -> Optional[Fault]:
        """Reply-side faults (dup/reorder), consumed by the pipelined
        transport's reply router only."""
        return self._take(REPLY, op)

    def pending(self) -> int:
        """Finite faults not yet consumed (persistent ones never drain,
        so they are excluded — scripts assert exact finite consumption)."""
        with self._lock:
            return sum(max(f.count, 0)
                       for q in self._faults.values() for f in q)
