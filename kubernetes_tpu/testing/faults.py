"""Scripted device-fault injection (the chaosmonkey Do/Setup analog for
the device-service seam, test/e2e/chaosmonkey/chaosmonkey.go).

A ``FaultPlan`` is a deterministic script of transport/service failures
consumed in order, wired into two interception points:

  * client side (``WireClient``/``GrpcClient``): a fault fires BEFORE the
    request touches the network — ``drop`` raises the same transient error
    a refused connection would, ``delay`` raises the read-timeout error a
    slow service would (no wall-clock sleep: the injected latency is
    compared against the client's read deadline), ``error`` raises a
    transient error N times (error-once / error-N).
  * server side (``serve``'s handler): ``error`` answers 503 (transient on
    the client's taxonomy), ``crash`` replaces the served DeviceService
    with a FRESH instance — new process epoch, empty DeviceState — and
    severs the connection without a response, exactly what a sidecar
    segfault+restart looks like from the client; ``conflict`` answers the
    409 + ``conflict: true`` cross-client race verdict (HA taxonomy).

Every consumed fault is appended to ``log`` so tests assert the script
actually fired. Thread-safe: handler threads and the scheduling thread
consume concurrently.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

APPLY_DELTAS = "apply_deltas"
SCHEDULE_BATCH = "schedule_batch"
ANY = "*"

CLIENT = "client"
SERVER = "server"


@dataclasses.dataclass
class Fault:
    kind: str            # "error" | "delay" | "drop" | "crash"
    count: int = 1       # calls this fault applies to before expiring
    seconds: float = 0.0  # injected latency ("delay" only)
    status: int = 503    # HTTP status for server-side "error"


class FaultPlan:
    def __init__(self):
        self._lock = threading.Lock()
        # (side, op) -> FIFO of pending faults; ANY matches either op
        self._faults: Dict[Tuple[str, str], List[Fault]] = {}
        self.log: List[Tuple[str, str, str]] = []  # (side, op, kind)

    # ------------------------------------------------------------ authoring

    def inject(self, op: str, fault: Fault, side: str = CLIENT) -> "FaultPlan":
        with self._lock:
            self._faults.setdefault((side, op), []).append(fault)
        return self

    def error_once(self, op: str = ANY, side: str = CLIENT) -> "FaultPlan":
        return self.inject(op, Fault("error"), side=side)

    def error_n(self, n: int, op: str = ANY, side: str = CLIENT) -> "FaultPlan":
        return self.inject(op, Fault("error", count=n), side=side)

    def delay(self, seconds: float, op: str = ANY, count: int = 1) -> "FaultPlan":
        return self.inject(op, Fault("delay", count=count, seconds=seconds))

    def drop(self, op: str = ANY, count: int = 1) -> "FaultPlan":
        return self.inject(op, Fault("drop", count=count))

    def crash(self, op: str = ANY) -> "FaultPlan":
        return self.inject(op, Fault("crash"), side=SERVER)

    def conflict(self, op: str = ANY, count: int = 1) -> "FaultPlan":
        """Server answers 409 + ``conflict: true`` — the cross-client race
        verdict, scriptable without staging a real two-replica collision."""
        return self.inject(op, Fault("conflict", count=count), side=SERVER)

    # ------------------------------------------------------------ consuming

    def _take(self, side: str, op: str) -> Optional[Fault]:
        with self._lock:
            for key in ((side, op), (side, ANY)):
                queue = self._faults.get(key)
                if not queue:
                    continue
                fault = queue[0]
                fault.count -= 1
                if fault.count <= 0:
                    queue.pop(0)
                self.log.append((side, op, fault.kind))
                return fault
            return None

    def next_client(self, op: str) -> Optional[Fault]:
        return self._take(CLIENT, op)

    def next_server(self, op: str) -> Optional[Fault]:
        return self._take(SERVER, op)

    def pending(self) -> int:
        with self._lock:
            return sum(f.count for q in self._faults.values() for f in q)
